"""Label documents with ParagraphVectors and classify unseen text — the
dl4j-examples ParagraphVectorsClassifierExample analog.

Run: python examples/doc2vec_classification.py
Env: EXAMPLES_SMOKE=1 shrinks sizes and forces CPU.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
if SMOKE:  # the smoke run must be hermetic: never touch a real device
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.nlp import ParagraphVectors
from deeplearning4j_tpu.nlp.tokenization import LabelledDocument


def synthetic_docs(n):
    rs = np.random.RandomState(11)
    topics = {
        "weather": ["rain", "cloud", "storm", "wind", "sun", "cold"],
        "finance": ["stock", "market", "price", "trade", "bank", "rate"],
        "health": ["doctor", "sleep", "diet", "heart", "run", "rest"],
    }
    docs = []
    for _ in range(n):
        label = list(topics)[rs.randint(3)]
        words = topics[label]
        docs.append(LabelledDocument(
            " ".join(words[rs.randint(len(words))] for _ in range(10)),
            label))
    return docs


def main():
    docs = synthetic_docs(150 if SMOKE else 1000)
    pv = ParagraphVectors(layer_size=24 if SMOKE else 100, window=3,
                          min_word_frequency=2, negative=5,
                          use_hierarchic_softmax=False,
                          epochs=6 if SMOKE else 12,
                          sequence_algorithm="dbow", learning_rate=0.05,
                          seed=9)
    pv.fit(docs)
    probes = {"weather": "storm wind rain cloud",
              "finance": "market trade price stock",
              "health": "sleep diet heart doctor"}
    correct = 0
    for truth, text in probes.items():
        pred = pv.predict(text)
        print(f"  '{text}' -> {pred} (truth: {truth})")
        correct += pred == truth
    print(f"probe accuracy: {correct}/3")
    # the sentinel signals TRAINING HAPPENED (weights moved), never
    # prediction luck — a correct model with unlucky probes must not
    # read as "trained zero steps". syn1neg starts at exactly zero and
    # only moves with training steps; syn0's random init would always
    # pass a norm check
    trained = int(np.linalg.norm(np.asarray(pv.syn1neg)) > 0)
    print("TRAINED iterations:", len(docs) * trained)
    return correct


if __name__ == "__main__":
    main()
