"""Train from a live cross-process stream — the dl4j-streaming
Kafka/Camel route analog (CamelKafkaRouteBuilder.java:16,
kafka/NDArrayPublisher.java), using the in-repo TCP broker.

A producer PROCESS generates minibatches and publishes them to a broker
topic; this process subscribes and trains while the frames arrive, with
bounded-buffer backpressure throttling the producer if training lags.

Run: python examples/streaming_training.py
Env: EXAMPLES_SMOKE=1 shrinks sizes for the test-suite smoke run.
"""

import os
import subprocess
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
if SMOKE:  # the smoke run must be hermetic: never touch a real device
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.streaming import NDArrayRoute, StreamingBroker

# the producer runs in its OWN python process: only the publisher client
# and numpy are imported there — it never touches jax or the model
_PRODUCER = r"""
import sys
import numpy as np
from deeplearning4j_tpu.streaming import NDArrayPublisher

port, n_batches, batch = (int(a) for a in sys.argv[1:4])
rs = np.random.RandomState(0)
with NDArrayPublisher("127.0.0.1", port, "spiral") as pub:
    for i in range(n_batches):
        # two-class spiral, generated on the fly: the "external source"
        theta = rs.rand(batch) * 3 * np.pi
        cls = rs.randint(0, 2, batch)
        r = theta / (3 * np.pi)
        x = np.stack([r * np.cos(theta + np.pi * cls),
                      r * np.sin(theta + np.pi * cls)], 1)
        x = (x + rs.randn(batch, 2) * 0.02).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[cls]
        pub.publish_arrays(x, y)
    pub.end()
print("producer: published", n_batches, "batches", flush=True)
"""


def main():
    n_batches = 8 if SMOKE else 400
    batch = 64
    broker = StreamingBroker(port=0).start()
    try:
        route = NDArrayRoute("127.0.0.1", broker.port, "spiral",
                             buffer_batches=8)
        env = dict(os.environ,
                   PYTHONPATH=os.pathsep.join(sys.path))
        producer = subprocess.Popen(
            [sys.executable, "-c", _PRODUCER, str(broker.port),
             str(n_batches), str(batch)], env=env)

        conf = (NeuralNetConfiguration.builder()
                .seed(7).updater(Adam(learning_rate=3e-3))
                .list(DenseLayer(n_out=64, activation="relu"),
                      DenseLayer(n_out=64, activation="relu"),
                      OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(2)).build())
        net = MultiLayerNetwork(conf).init()

        def unblock_on_producer_crash():
            # a producer that dies without sending END would leave fit()
            # blocked on the queue forever; close the stream in its stead
            if producer.wait() != 0:
                route.iterator().end()

        threading.Thread(target=unblock_on_producer_crash,
                         daemon=True).start()
        try:
            net.fit(route.iterator())  # trains WHILE the producer publishes
            assert producer.wait(120) == 0
        finally:
            if producer.poll() is None:  # crashed-consumer path: don't
                producer.kill()          # leak the child process
                producer.wait()

        # held-out accuracy on freshly generated spiral points
        rs = np.random.RandomState(9)
        theta = rs.rand(512) * 3 * np.pi
        cls = rs.randint(0, 2, 512)
        r = theta / (3 * np.pi)
        x = np.stack([r * np.cos(theta + np.pi * cls),
                      r * np.sin(theta + np.pi * cls)], 1).astype(np.float32)
        pred = np.asarray(net.output(x)).argmax(1)
        acc = float((pred == cls).mean())
        print(f"trained on {net.iteration} streamed batches; "
              f"held-out accuracy {acc:.3f}")
        print(f"TRAINED iterations: {net.iteration}")
        assert net.iteration == n_batches
        if not SMOKE:
            assert acc > 0.85, acc
    finally:
        broker.stop()


if __name__ == "__main__":
    main()
