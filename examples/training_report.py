"""Train with the stats pipeline attached and export a self-contained
HTML report — the dl4j-examples UI/HistogramIterationListener analog
(file-based: a pod worker has no browser).

Run: python examples/training_report.py   (writes /tmp/dl4j_tpu_report.html)
Env: EXAMPLES_SMOKE=1 shrinks sizes and forces CPU.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
if SMOKE:  # the smoke run must be hermetic: never touch a real device
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.ui import (
    ChartHistogram,
    ChartLine,
    ChartMatrix,
    ComponentTable,
    ComponentText,
    InMemoryStatsStorage,
    StatsListener,
    render_html_file,
)
from deeplearning4j_tpu.ui.stats import TYPE_ID


def main():
    storage = InMemoryStatsStorage()
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(learning_rate=0.01))
            .list(DenseLayer(n_out=32, activation="relu"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(StatsListener(storage, session_id="report",
                                    reporting_frequency=1,
                                    collect_histograms=True))
    rs = np.random.RandomState(0)
    labels = rs.randint(0, 3, 128)
    ds = DataSet((rs.randn(128, 4) + labels[:, None]).astype(np.float32),
                 np.eye(3, dtype=np.float32)[labels])
    for _ in range(15 if SMOKE else 60):
        net.fit(ds)

    updates = storage.get_all_updates_after("report", TYPE_ID)
    iters = [u["data"]["iteration"] for u in updates]
    scores = [u["data"]["score"] for u in updates]
    ev = net.evaluate(ds)
    hist_data = updates[-1]["data"]["param_histograms"]["0/W"]
    edges = np.linspace(hist_data["min"], hist_data["max"],
                        len(hist_data["counts"]) + 1)
    hist = ChartHistogram(title="layer 0 weights")
    for i, c in enumerate(hist_data["counts"]):
        hist.add_bin(edges[i], edges[i + 1], c)
    components = [
        ComponentText(text="Training report"),
        ChartLine(title="score").add_series("train", iters, scores),
        hist,
        ChartMatrix(title="confusion matrix",
                    values=[[int(v) for v in row]
                            for row in ev.confusion],
                    row_labels=["0", "1", "2"], col_labels=["0", "1", "2"]),
        ComponentTable(header=["metric", "value"],
                       content=[["accuracy", f"{ev.accuracy():.4f}"],
                                ["f1", f"{ev.f1():.4f}"],
                                ["final score", f"{scores[-1]:.4f}"]]),
    ]
    out = "/tmp/dl4j_tpu_report.html"
    render_html_file(components, out, title="training report")
    print("report written to", out,
          f"({os.path.getsize(out)} bytes)")
    print("TRAINED iterations:", net.iteration)


if __name__ == "__main__":
    main()
