"""Crash-and-resume training with the elastic recovery subsystem.

A training run is killed hard (os._exit — no cleanup, the moral
equivalent of SIGKILL / a preempted TPU VM) partway through, then
restarted; `FaultTolerantTrainer.run()` restores the newest checkpoint and
continues from the first un-trained batch. The resumed parameters are
bit-identical to an uninterrupted run's — verified at the end.

Run: python examples/elastic_training.py
Env: EXAMPLES_SMOKE=1 forces CPU for the test-suite smoke run (the
workload is already tiny; nothing needs shrinking).
"""

import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
if SMOKE:  # the smoke run must be hermetic: never touch a real device
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.parallel import CheckpointStore, FaultTolerantTrainer

EPOCHS = 2
N_BATCHES = 6
CRASH_AT_ITERATION = 8  # mid-epoch-2


def build_net():
    conf = (NeuralNetConfiguration.builder()
            .seed(42)
            .updater(Adam(learning_rate=0.01))
            .list(DenseLayer(n_out=32, activation="relu"),
                  OutputLayer(n_out=5, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(10))
            .build())
    return MultiLayerNetwork(conf).init()


def batches():
    rs = np.random.RandomState(7)
    return [DataSet(rs.randn(32, 10).astype(np.float32),
                    np.eye(5, dtype=np.float32)[rs.randint(0, 5, 32)])
            for _ in range(N_BATCHES)]


def factory():
    return ListDataSetIterator(batches(), batch_size=32)


class DieHard(TrainingListener):
    """Simulates preemption: the process vanishes mid-training."""

    def iteration_done(self, model, iteration):
        if iteration == CRASH_AT_ITERATION:
            print(f"!! killed hard at iteration {iteration}", flush=True)
            os._exit(137)


def train(ckpt_dir: str, crash: bool) -> MultiLayerNetwork:
    net = build_net()
    if crash:
        net.set_listeners(DieHard())
    trainer = FaultTolerantTrainer(net, CheckpointStore(ckpt_dir),
                                   frequency=3)
    return trainer.run(factory, epochs=EPOCHS)


def main():
    # child mode: run one (possibly crashing) training process
    if len(sys.argv) == 3:
        train(sys.argv[1], crash=sys.argv[2] == "crash")
        return

    with tempfile.TemporaryDirectory() as d:
        env = dict(os.environ)
        if SMOKE:
            env["JAX_PLATFORMS"] = "cpu"
        # 1) a run that dies hard mid-epoch-2
        p = subprocess.run([sys.executable, os.path.abspath(__file__),
                            d, "crash"], env=env)
        print(f"crashed run exit code: {p.returncode} (expected 137)")
        # the example exists to exercise the resume path: a child that
        # died for some other reason (or finished!) must fail loudly here
        assert p.returncode == 137, p.returncode
        assert CheckpointStore(d).latest() is not None, "no checkpoint saved"
        # 2) the restarted job: resumes at the first un-trained batch
        final = train(d, crash=False)
        # 3) prove exactness against an uninterrupted run
        with tempfile.TemporaryDirectory() as d2:
            reference = train(d2, crash=False)
        same = np.array_equal(
            np.asarray(final.params_flat(), np.float32),
            np.asarray(reference.params_flat(), np.float32))
        print(f"resumed == uninterrupted (bitwise): {same}")
        assert same
        print("TRAINED iterations:", final.iteration)


if __name__ == "__main__":
    main()
