"""Long-context attention: flash-kernel training + sequence parallelism.

Two capabilities in one runnable demo:
1. Train a causal self-attention network with ``helper="auto"`` — on TPU
   the Pallas flash kernel serves the layer (O(T) training memory,
   measured 3.1x over stock at T=4096); elsewhere the stock XLA path runs.
2. Shard the SEQUENCE axis of attention across a device mesh with ring
   attention (lax.ppermute K/V rotation) and with Ulysses all-to-all, and
   check both match single-device attention.

Run: python examples/long_context_attention.py
Env: EXAMPLES_SMOKE=1 -> CPU, T=256, 4 virtual devices for the SP part.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
import jax

if SMOKE:  # hermetic: CPU with a virtual 4-device mesh for the SP demo
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 4)
    except AttributeError:  # jax < 0.5: only the XLA_FLAGS spelling exists
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4")

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.attention import (
    SelfAttentionLayer,
    scaled_dot_attention,
)
from deeplearning4j_tpu.nn.conf.layers.recurrent import RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam

T = 256 if SMOKE else 2048
F = 64 if SMOKE else 128


def train_with_auto_helper():
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Adam(learning_rate=1e-3))
            .list(SelfAttentionLayer(n_out=F, n_heads=4, causal=True,
                                     helper="auto", activation="identity"),
                  RnnOutputLayer(n_out=8, activation="softmax",
                                 loss="mcxent"))
            .set_input_type(InputType.recurrent(F, T)).build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    x = rs.randn(2, T, F).astype(np.float32)
    y = np.eye(8, dtype=np.float32)[rs.randint(0, 8, (2, T))]
    ds = DataSet(x, y)
    s0 = net.score(ds)
    epochs = 4 if SMOKE else 10
    net.fit(ds, epochs=epochs)
    s1 = net.score(ds)
    print(f"causal attention T={T} (helper=auto, "
          f"{jax.default_backend()}): score {s0:.4f} -> {s1:.4f}")
    assert s1 < s0
    return net.iteration


def sequence_parallel_demo():
    n = min(8, len(jax.devices()))
    if n < 2:
        print(f"sequence-parallel demo skipped: {n} device(s)")
        return
    from jax.sharding import Mesh

    from deeplearning4j_tpu.parallel.sequence import (
        ring_attention,
        ulysses_attention,
    )

    mesh = Mesh(np.asarray(jax.devices()[:n]), ("seq",))
    rs = np.random.RandomState(1)
    B, H, d = 2, n, 32
    Tsp = 16 * n
    q = jnp.asarray(rs.randn(B, H, Tsp, d), jnp.float32)
    k = jnp.asarray(rs.randn(B, H, Tsp, d), jnp.float32)
    v = jnp.asarray(rs.randn(B, H, Tsp, d), jnp.float32)
    dense = scaled_dot_attention(q, k, v, causal=True)
    for name, fn in (("ring", ring_attention), ("ulysses",
                                                ulysses_attention)):
        out = fn(q, k, v, mesh=mesh, axis="seq", causal=True)
        err = float(jnp.max(jnp.abs(out - dense)))
        print(f"{name} attention over {n} devices: max |diff| vs dense "
              f"= {err:.2e}")
        assert err < 1e-4


def main():
    iters = train_with_auto_helper()
    sequence_parallel_demo()
    print("TRAINED iterations:", iters)


if __name__ == "__main__":
    main()
