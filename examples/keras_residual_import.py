"""Import a branched (residual) Keras model and keep training it — the
dl4j-examples ImportKeras flow extended to functional DAGs
(KerasModel.java:419-495 / layers/KerasMerge.java parity).

Builds a small residual CNN in Keras, saves legacy h5, imports it as a
ComputationGraph, checks forward parity against keras.predict, then
fine-tunes the imported graph on synthetic data.

Run: python examples/keras_residual_import.py
Env: EXAMPLES_SMOKE=1 shrinks sizes for the test-suite smoke run.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
if SMOKE:  # the smoke run must be hermetic: never touch a real device
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np


def main():
    os.environ.setdefault("CUDA_VISIBLE_DEVICES", "")
    import keras
    from keras import layers

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.modelimport import import_keras_model_and_weights
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    inp = keras.Input((16, 16, 3), name="in0")
    x = layers.Conv2D(8, (3, 3), padding="same", activation="relu",
                      name="c1")(inp)
    y = layers.Conv2D(8, (3, 3), padding="same", name="c2")(x)
    z = layers.Add(name="residual_add")([x, y])
    z = layers.Activation("relu", name="act")(z)
    z = layers.GlobalAveragePooling2D(name="gap")(z)
    out = layers.Dense(4, activation="softmax", name="head")(z)
    km = keras.Model(inp, out)

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "residual.h5")
        km.save(path)
        net = import_keras_model_and_weights(path)
    assert isinstance(net, ComputationGraph)

    import jax

    rs = np.random.RandomState(0)
    xb = rs.randn(8, 16, 16, 3).astype(np.float32)
    expected = np.asarray(km.predict(xb, verbose=0))
    # TPU default matmul precision is bf16-multiply; the parity check
    # needs full precision or the comparison measures the MXU rounding,
    # not the import
    with jax.default_matmul_precision("highest"):
        got = np.asarray(net.output(xb))
    err = float(np.abs(got - expected).max())
    print(f"imported {len(net.conf.vertices)}-vertex graph; "
          f"forward parity max err {err:.2e}")
    assert err < 1e-4

    # keep training the imported graph (transfer-learning style)
    n = 64 if SMOKE else 512
    yb = np.eye(4, dtype=np.float32)[rs.randint(0, 4, n)]
    data = DataSet(rs.randn(n, 16, 16, 3).astype(np.float32), yb)
    s0 = net.score(data)
    for _ in range(2 if SMOKE else 20):
        net.fit(data)
    s1 = net.score(data)
    print(f"fine-tune on imported graph: score {s0:.4f} -> {s1:.4f}")
    assert s1 < s0
    print(f"TRAINED iterations: {net.iteration}")


if __name__ == "__main__":
    main()
