"""Data-parallel LeNet training over the device mesh — the dl4j-examples
ParallelWrapper MultiGpuLenetMnistExample analog (one mesh instead of
replica threads).

Run: python examples/lenet_mesh_dataparallel.py
Env: EXAMPLES_SMOKE=1 shrinks sizes and forces a 4-device CPU mesh.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
if SMOKE:
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 4)
    except AttributeError:  # jax < 0.5: only the XLA_FLAGS spelling exists
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4")

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.models import LeNet
from deeplearning4j_tpu.parallel import ParallelWrapper, data_mesh


def main():
    net = LeNet(num_labels=10).init()
    mesh = data_mesh()  # every visible device
    n_dev = mesh.devices.size
    # stream minibatches are PER-WORKER: each averaging round consumes
    # n_dev * averaging_frequency batches, so size the corpus to whole
    # rounds or the trailing partial round is dropped (with a warning)
    pw = ParallelWrapper(net, mesh=mesh, averaging_frequency=1)
    batch = 64
    n = 512 if SMOKE else (60000 // (batch * n_dev)) * batch * n_dev

    def image_batches(**kw):
        # MNIST iterator yields flat [B, 784] (the reference's contract);
        # the zoo LeNet takes NHWC images
        return [DataSet(ds.features.reshape(-1, 28, 28, 1), ds.labels)
                for ds in MnistDataSetIterator(**kw)]

    pw.fit(image_batches(batch_size=batch, num_examples=n),
           epochs=1 if SMOKE else 3)
    ev = net.evaluate(image_batches(batch_size=512, train=False,
                                    num_examples=min(n, 10000)))
    print(f"devices: {n_dev}")
    print(ev.stats())
    print("TRAINED iterations:", net.iteration)
    return ev.accuracy()


if __name__ == "__main__":
    main()
