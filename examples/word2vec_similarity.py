"""Train Word2Vec on a text corpus and query word similarity — the
dl4j-examples Word2VecRawTextExample analog.

Run: python examples/word2vec_similarity.py [corpus.txt]
Without a corpus file a small synthetic two-topic corpus is generated.
Env: EXAMPLES_SMOKE=1 shrinks sizes.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
if SMOKE:  # the smoke run must be hermetic: never touch a real device
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.nlp import CollectionSentenceIterator, Word2Vec
from deeplearning4j_tpu.nlp.serde import write_word2vec_binary



def synthetic_corpus(n):
    rs = np.random.RandomState(7)
    day = ["day", "sun", "light", "bright", "warm", "sky"]
    night = ["night", "moon", "dark", "star", "cold", "quiet"]
    out = []
    for _ in range(n):
        topic = day if rs.rand() < 0.5 else night
        out.append(" ".join(topic[rs.randint(len(topic))]
                            for _ in range(12)))
    return out


def main():
    if len(sys.argv) > 1:
        with open(sys.argv[1], encoding="utf-8") as f:
            sentences = [ln.strip() for ln in f if ln.strip()]
    else:
        sentences = synthetic_corpus(400 if SMOKE else 5000)
    w2v = Word2Vec(layer_size=64 if not SMOKE else 24, window=5,
                   min_word_frequency=2, negative=5,
                   use_hierarchic_softmax=False,
                   epochs=3 if SMOKE else 5, learning_rate=0.05, seed=42)
    w2v.fit(CollectionSentenceIterator(sentences))
    probe = "sun" if w2v.has_word("sun") else \
        w2v.vocab.vocab_words()[0].word
    print(f"nearest({probe}):")
    for word, sim in w2v.words_nearest(probe, 5):
        print(f"  {word:>12}  {sim:.3f}")
    out = "/tmp/word_vectors.bin"
    write_word2vec_binary(w2v, out)
    print("vectors saved to", out)
    trained = int(np.linalg.norm(np.asarray(w2v.syn0)) > 0)
    print("TRAINED iterations:", len(sentences) * trained)


if __name__ == "__main__":
    main()
