"""Freeze a trained feature stack and retrain a new head — the
dl4j-examples TransferLearning (EditLastLayerOthersFrozen) analog.

Run: python examples/transfer_learning.py
Env: EXAMPLES_SMOKE=1 shrinks sizes.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
if SMOKE:  # the smoke run must be hermetic: never touch a real device
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import TransferLearning
from deeplearning4j_tpu.nn.updater import Adam



def main():
    rs = np.random.RandomState(0)
    n = 256 if SMOKE else 2048
    # source task: 4-class problem
    labels4 = rs.randint(0, 4, n)
    x = (rs.randn(n, 8) + labels4[:, None]).astype(np.float32)
    base_conf = (NeuralNetConfiguration.builder()
                 .seed(1).updater(Adam(learning_rate=0.01))
                 .list(DenseLayer(n_out=32, activation="relu"),
                       DenseLayer(n_out=16, activation="relu"),
                       OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                 .set_input_type(InputType.feed_forward(8)).build())
    base = MultiLayerNetwork(base_conf).init()
    ds4 = DataSet(x, np.eye(4, dtype=np.float32)[labels4])
    for _ in range(15 if SMOKE else 60):
        base.fit(ds4)
    print("source-task score:", round(base.score_value, 4))

    # target task: binary relabeling, freeze the feature stack
    labels2 = (labels4 >= 2).astype(int)
    ds2 = DataSet(x, np.eye(2, dtype=np.float32)[labels2])
    transferred = (TransferLearning.Builder(base)
                   .set_feature_extractor(1)     # freeze layers 0..1
                   .remove_output_layer()
                   .add_layer(OutputLayer(n_out=2, activation="softmax",
                                          loss="mcxent"))
                   .build())
    frozen_before = np.asarray(transferred.params["0"]["W"]).copy()
    for _ in range(15 if SMOKE else 60):
        transferred.fit(ds2)
    frozen_after = np.asarray(transferred.params["0"]["W"])
    ev = transferred.evaluate(ds2)
    print("target-task accuracy:", round(ev.accuracy(), 3))
    print("frozen layer untouched:", np.array_equal(frozen_before,
                                                    frozen_after))
    print("TRAINED iterations:", transferred.iteration)
    return ev.accuracy()


if __name__ == "__main__":
    main()
