"""Train an MLP on MNIST and evaluate — the dl4j-examples
MLPMnistSingleLayerExample analog.

Run: python examples/mnist_mlp.py  (TPU when available; CPU otherwise)
Env: EXAMPLES_SMOKE=1 shrinks sizes for the test-suite smoke run.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
if SMOKE:  # the smoke run must be hermetic: never touch a real device
    import jax
    jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener



def main():
    n = 2048 if SMOKE else 60000
    epochs = 1 if SMOKE else 5
    conf = (NeuralNetConfiguration.builder()
            .seed(123)
            .updater(Adam(learning_rate=1e-3))
            .list(DenseLayer(n_out=256, activation="relu"),
                  DenseLayer(n_out=128, activation="relu"),
                  OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(ScoreIterationListener(20))
    train = MnistDataSetIterator(batch_size=128, num_examples=n)
    net.fit(train, epochs=epochs)
    test = MnistDataSetIterator(batch_size=512, train=False,
                                num_examples=min(n, 10000))
    ev = net.evaluate(test)
    print(ev.stats())
    print("TRAINED iterations:", net.iteration)
    return ev.accuracy()


if __name__ == "__main__":
    main()
