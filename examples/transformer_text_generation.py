"""Char-level language modeling with the TransformerLM — the
dl4j-examples GravesLSTMCharModellingExample flow, transformer edition:
train on a small corpus, then generate text with KV-cache streaming
decode (one compiled device-side loop; see models/zoo.greedy_generate).

Run: python examples/transformer_text_generation.py
Env: EXAMPLES_SMOKE=1 shrinks sizes for the test-suite smoke run.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(os.environ.get("EXAMPLES_SMOKE"))
if SMOKE:  # the smoke run must be hermetic: never touch a real device
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models import TransformerLM, greedy_generate

# a tiny synthetic "language": one repeated sentence, so a small model
# can memorize real character-level structure
SENTENCE = "the quick brown fox jumps over the lazy dog and runs "


def main():
    text = SENTENCE * (20 if SMOKE else 400)
    chars = sorted(set(text))
    V = len(chars)
    c2i = {c: i for i, c in enumerate(chars)}
    ids = np.asarray([c2i[c] for c in text], np.int64)

    T = 32 if SMOKE else 64
    n_seq = 32 if SMOKE else 256
    rs = np.random.RandomState(0)
    starts = rs.randint(0, len(ids) - T - 1, n_seq)
    seq = np.stack([ids[s:s + T + 1] for s in starts])
    eye = np.eye(V, dtype=np.float32)
    ds = DataSet(eye[seq[:, :-1]], eye[seq[:, 1:]])

    m = TransformerLM(num_labels=V, max_length=T, d_model=128, n_heads=4,
                      n_blocks=2, seed=7).init()
    for _ in range(8 if SMOKE else 600):
        m.fit(ds)
    print(f"trained; final score {m.score_value:.4f}")

    prompt_text = "the quick "
    prompt = np.asarray([[c2i[c] for c in prompt_text]] * 1, np.int64)
    gen = greedy_generate(m, prompt, steps=24, vocab=V,
                          device_loop=not SMOKE)
    out = "".join(chars[i] for i in gen[0])
    print(f"prompt {prompt_text!r} -> generated {out!r}")
    print(f"TRAINED iterations: {m.iteration}")
    if not SMOKE:
        # the model must continue the memorized sentence structure
        assert out.startswith("brown fox jumps"), out


if __name__ == "__main__":
    main()
