"""Benchmark driver: one JSON line with the headline metric.

Headline (BASELINE.json "metric"): ResNet50-zoo images/sec/chip, measured by
training the zoo ResNet50 ComputationGraph on synthetic ImageNet-shaped data
on the default jax device (the real TPU chip under the driver; CPU when
forced). Sub-metrics (LeNet-MNIST img/s, TextGenLSTM tokens/s) ride along as
extra keys in the same JSON object.

Methodology (round 5): every throughput number is the MEDIAN of k
marginal-timed windows, with every window recorded beside it — no
best-of-N anywhere. The headline's windows are additionally interleaved
across the whole run (one window between sub-benchmarks) because the
tunneled chip's far-side contention swings throughput ~3.5x on a minutes
timescale (profiles/README.md): back-to-back windows sample one
contention state; spread windows + median estimate steady state without
cherry-picking. Model batch sizes were picked by an interleaved on-chip
sweep (profiles/batch_sweep.py).

vs_baseline: the reference publishes no numbers (BASELINE.md — "published":
{}), and its Java/Maven stack cannot run here. The denominator is therefore
the north-star *target* from BASELINE.json: >=70% of nd4j-cuda per-device
ResNet50 throughput, with the nd4j-cuda-8.0-era figure estimated at 120
img/s on the 2017 GPUs the reference targeted (K80/GTX1080 class) => target
84 img/s. vs_baseline = measured / 84.0, i.e. 1.0 means the north star is
met; >1 beats it.

Usage: python bench.py [model]   (model: resnet50 | vgg16 | lenet | lstm |
transformer | word2vec | doc2vec | attention | all; default all, headline = resnet50)
"""

from __future__ import annotations

import json
import sys
import threading
import time

import numpy as np

NORTH_STAR_RESNET50_IMG_S = 84.0  # 70% of est. 120 img/s nd4j-cuda


def _sync(x):
    """Force execution to completion via a host fetch of a scalar that is
    data-dependent on ``x``. jax.block_until_ready is NOT sufficient on the
    tunneled TPU backend (it returns before device execution finishes, which
    silently turns timing loops into dispatch-rate measurements); a host
    transfer cannot complete before the producing program has."""
    import jax
    import jax.numpy as jnp

    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(jnp.sum(jnp.ravel(leaf)[:1]))


# The marginal window (t2 - t1) must be far above perf_counter resolution
# (~ns) and above scheduler jitter, or the computed per-step cost is noise:
# BENCH_r03 recorded LSTM "3.2e12 tokens/s" because a ~zero window hit a
# floor clamp. Windows below this are auto-resolved by doubling the step
# count; if that fails, refuse to report rather than publish garbage.
MIN_MARGINAL_WINDOW_S = 0.05
MAX_MARGINAL_STEPS = 20480


class MarginalTimer:
    """Marginal-timing harness for one compiled training step.

    Inputs live on device (synthetic-data benchmarking convention: an input
    pipeline overlaps transfers with compute; the metric is the chip's
    training throughput, BASELINE 'img/s/chip'). One WINDOW times two runs
    of different step counts; the per-step cost is (t2 - t1) / (n2 - n1) —
    cancelling the constant dispatch/queueing slack of the remote-device
    pipeline, which otherwise inflates short windows. The step count is
    doubled at calibration until the marginal window is well above timer
    resolution.

    Built as an object (not one closed function) so the headline bench can
    take windows INTERLEAVED across the whole ~15-minute run: the far-side
    chip contention swings throughput ~3.5x on a minutes timescale
    (profiles/README.md variance table), so back-to-back windows all
    sample the same contention state, while spread windows + median
    estimate steady state without cherry-picking."""

    def __init__(self, net, x, y, steps: int):
        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        self._tree_map = jax.tree_util.tree_map
        self.batch = x.shape[0]
        self.xd, self.yd = jnp.asarray(x), jnp.asarray(y)
        key = (self.xd.shape, self.yd.shape, False, False, False)
        self._step = net._get_step(key)
        self._rng = jax.random.PRNGKey(0)
        # the step donates params/opt/state buffers: keep pristine trees
        # and hand each run its own copies (made OUTSIDE the timed region).
        # Copies — not the live net's trees — so the net is untouched.
        self._tree0 = self._tree_map(
            lambda a: a.copy(),
            (net.params, net.updater_state, net.state))
        warm = self._tree_map(lambda a: a.copy(), self._tree0)
        params, _, _, _, loss = self._step(
            *warm, self._rng, jnp.float32(0), self.xd, self.yd, None,
            None, {})
        _sync(params)
        assert bool(jnp.isfinite(loss)), "non-finite loss in benchmark"
        self.steps = self._calibrate(steps)

    def _run(self, n):
        jnp = self._jnp
        params, opt, state = self._tree_map(lambda a: a.copy(), self._tree0)
        _sync(params)
        t0 = time.perf_counter()
        for i in range(n):
            params, opt, state, _, _ = self._step(
                params, opt, state, self._rng, jnp.float32(i + 1),
                self.xd, self.yd, None, None, {})
        _sync(params)
        return time.perf_counter() - t0

    def _calibrate(self, steps):
        while True:
            dt = self._run(2 * steps) - self._run(steps)
            if dt >= MIN_MARGINAL_WINDOW_S:
                return steps
            if steps >= MAX_MARGINAL_STEPS:
                raise RuntimeError(
                    f"marginal timing window is {dt * 1e3:.3f} ms over "
                    f"{steps} extra steps — below the "
                    f"{MIN_MARGINAL_WINDOW_S * 1e3:.0f} ms resolution "
                    "floor; refusing to report a throughput number from "
                    "noise")
            steps *= 2

    def window(self):
        """One marginal-timed throughput sample (img/s), or None if the
        window landed below timer resolution (discarded, not clamped)."""
        t1 = self._run(self.steps)
        t2 = self._run(2 * self.steps)
        dt = t2 - t1
        if dt < MIN_MARGINAL_WINDOW_S:
            return None
        return self.batch / (dt / self.steps)


def _median_of_windows(timer: "MarginalTimer", k: int):
    """(median, windows): k marginal windows, median as the reported
    value, EVERY window kept for the record — no best-of-N selection."""
    windows = [w for w in (timer.window() for _ in range(k))
               if w is not None]
    if not windows:
        raise RuntimeError(
            "every marginal window fell below timer resolution — "
            "refusing to report a throughput number from noise")
    return float(np.median(windows)), [round(w, 1) for w in windows]


def _steady_state_img_s(net, x, y, steps: int, k_windows: int = 5):
    """(median img/s, all window samples) — see MarginalTimer."""
    return _median_of_windows(MarginalTimer(net, x, y, steps), k_windows)


def _imagenet_model_timer(model_cls, *, batch, steps, seed,
                          compute_dtype=None, image=224, labels=1000):
    """Shared synthetic-ImageNet training timer for zoo CNNs."""
    net = model_cls(num_labels=labels, dtype="float32",
                    compute_dtype=compute_dtype).init()
    rs = np.random.RandomState(seed)
    x = rs.randn(batch, image, image, 3).astype(np.float32)
    y = np.eye(labels, dtype=np.float32)[rs.randint(0, labels, batch)]
    return MarginalTimer(net, x, y, steps)


# chip-swept defaults (profiles/chip_session_results.json batch_sweep_r5,
# interleaved rounds so contention hits all configs equally): ResNet50
# bf16 peaked at batch 128 (median 7494 img/s ~= 49% MFU vs 5768 at the
# old batch 64); VGG16 at batch 128 (1516 vs 1134 at the old batch 32)
RESNET50_BATCH = 128
VGG16_BATCH = 128

# MFU bookkeeping: FLOP audit (profiles/flop_audit.py, round-5 corrected
# — multiply+add counted separately, same convention as the peak figure).
# NB the zoo ResNet50 is the reference's stride-2-stage-2a variant, ~2x
# lighter than canonical torchvision ResNet50; round 4's 12.8 G/img figure
# double-counted it and overstated MFU 2x (profiles/README.md).
RESNET50_TRAIN_FLOP_PER_IMG = 6.6e9
VGG16_TRAIN_FLOP_PER_IMG = 89.35e9
PEAK_BF16_FLOP_S = 197e12


def bench_resnet50(batch: int = RESNET50_BATCH, steps: int = 20,
                   image: int = 224, compute_dtype=None, k_windows: int = 5):
    """ResNet50 training throughput (median, windows) (BASELINE config #2)."""
    from deeplearning4j_tpu.models import ResNet50

    timer = _imagenet_model_timer(ResNet50, batch=batch, steps=steps,
                                  seed=0, compute_dtype=compute_dtype,
                                  image=image)
    return _median_of_windows(timer, k_windows)


def bench_vgg16(batch: int = VGG16_BATCH, steps: int = 10,
                k_windows: int = 5):
    """VGG16 training throughput (median, windows) (BASELINE config #5's
    model; the ParallelWrapper half of that config needs >1 chip — its
    semantics are covered by the multichip dryrun, the single-chip model
    cost here)."""
    from deeplearning4j_tpu.models import VGG16

    timer = _imagenet_model_timer(VGG16, batch=batch, steps=steps, seed=4,
                                  compute_dtype="bfloat16")
    return _median_of_windows(timer, k_windows)


def bench_lenet(batch: int = 512, steps: int = 80, k_windows: int = 5):
    """LeNet-MNIST training throughput (median, windows) (BASELINE #1)."""
    from deeplearning4j_tpu.models import LeNet

    net = LeNet(num_labels=10).init()
    rs = np.random.RandomState(1)
    x = rs.randn(batch, 28, 28, 1).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, batch)]
    return _steady_state_img_s(net, x, y, steps, k_windows)


def bench_lstm(batch: int = 64, seq: int = 50, vocab: int = 77,
               steps: int = 60, k_windows: int = 5):
    """GravesLSTM char-RNN training throughput (median tokens/s, windows)
    (BASELINE config #3)."""
    from deeplearning4j_tpu.models import TextGenerationLSTM

    net = TextGenerationLSTM(num_labels=vocab, max_length=seq).init()
    rs = np.random.RandomState(2)
    idx = rs.randint(0, vocab, (batch, seq))
    x = np.eye(vocab, dtype=np.float32)[idx]
    y = np.eye(vocab, dtype=np.float32)[rs.randint(0, vocab, (batch, seq))]
    med, windows = _steady_state_img_s(net, x, y, steps, k_windows)
    return med * seq, [round(w * seq, 1) for w in windows]


def bench_transformer_lm(batch: int = 32, seq: int = 512, vocab: int = 256,
                         steps: int = 10, k_windows: int = 5):
    """Causal TransformerLM training throughput, tokens/s (beyond-parity
    model: pre-norm residual blocks whose attention routes through the
    Pallas flash kernel; bf16 compute)."""
    from deeplearning4j_tpu.models import TransformerLM

    net = TransformerLM(num_labels=vocab, max_length=seq, d_model=256,
                        n_heads=8, n_blocks=4, seed=0,
                        compute_dtype="bfloat16").init()
    rs = np.random.RandomState(6)
    idx = rs.randint(0, vocab, (batch, seq + 1))
    x = np.eye(vocab, dtype=np.float32)[idx[:, :-1]]
    y = np.eye(vocab, dtype=np.float32)[idx[:, 1:]]
    med, windows = _steady_state_img_s(net, x, y, steps, k_windows)
    return med * seq, [round(w * seq, 1) for w in windows]


def bench_attention(B: int = 4, H: int = 8, T: int = 4096, d: int = 128,
                    steps: int = 30):
    """Pallas flash-attention kernel vs stock XLA attention (the
    accelerated-kernel stage, SURVEY §7 stage 4). Chained serial timing:
    each call consumes the previous output, so queue pipelining cannot hide
    per-call latency. Returns (stock_ms, flash_ms)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.layers.attention import (
        scaled_dot_attention,
    )
    from deeplearning4j_tpu.ops.pallas_attention import flash_attention

    stock = jax.jit(lambda q, k, v: scaled_dot_attention(q, k, v,
                                                         causal=True))
    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    return (_attn_chained_ms(stock, B, H, T, d, steps, "attention"),
            _attn_chained_ms(flash, B, H, T, d, steps, "attention"))


def _attn_chained_ms(g, B, H, T, d, steps, label):
    """Shared chained-serial attention timer: each call consumes the
    previous output (q := g(q, k, v)) so queue pipelining cannot hide
    per-call latency; refuses windows below timer resolution."""
    import jax.numpy as jnp

    rs = np.random.RandomState(7)
    q0 = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
    k = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
    v = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
    _sync(g(q0, k, v))  # compile + warm
    t0 = time.perf_counter()
    o = q0
    for _ in range(steps):
        o = g(o, k, v)
    _sync(o)
    total = time.perf_counter() - t0
    if total < MIN_MARGINAL_WINDOW_S:
        raise RuntimeError(
            f"{label} timing window {total * 1e3:.3f} ms is below the "
            f"{MIN_MARGINAL_WINDOW_S * 1e3:.0f} ms resolution floor — "
            "harness bug; refusing to report")
    return total / steps * 1000


def bench_attention_bwd(B: int = 4, H: int = 8, T: int = 2048, d: int = 128,
                        steps: int = 20):
    """Fwd+bwd (training) leg of the attention bench. The stock backward
    materialises the [B,H,T,T] score matrix (~2 GB at T=4096 — fits in
    HBM at this batch, measured, but pays the O(T^2) traffic); the flash
    backward (recompute-by-block Pallas kernels) keeps O(T) memory and
    measured 3.1x faster at T=4096 (10.7 vs 33.2 ms). Returns
    (stock_ms, flash_ms)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.layers.attention import (
        scaled_dot_attention,
    )
    from deeplearning4j_tpu.ops.pallas_attention import flash_attention

    def grad_of(f):
        return jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(f(q, k, v) ** 2), argnums=0))

    stock = grad_of(lambda q, k, v: scaled_dot_attention(q, k, v,
                                                         causal=True))
    flash = grad_of(lambda q, k, v: flash_attention(q, k, v, causal=True))
    return (_attn_chained_ms(stock, B, H, T, d, steps, "attention bwd"),
            _attn_chained_ms(flash, B, H, T, d, steps, "attention bwd"))


def bench_paged_attn(B: int = 8, H: int = 8, d: int = 128,
                     page_size: int = 16, steps: int = 16):
    """Paged-attention decode read: the Pallas block-table kernel vs the
    stock gather-then-attend XLA backend (the ``PagedAttentionHelper``
    seam, nn/conf/layers/paged_attention.py), at a short (128-token) and
    a long (2048-token) context, f32 and int8 pools. Decode shape: q is
    ONE token per slot, so the gather the stock path materialises per
    read is pure overhead the kernel deletes — tokens/s here is
    ``B * calls / wall``. Chained serial timing (each call's output is
    the next call's query) so queue pipelining cannot hide latency.
    Off-TPU the kernel leg runs in interpret mode — the parity
    configuration, not a perf path — and the geometry shrinks to keep
    the interpreter affordable; the context lengths stay 128/2048
    either way."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.layers.paged_attention import (
        paged_attend)

    if jax.default_backend() != "tpu":
        B, H, d, steps = 2, 2, 64, 4

    def quantize(t):
        m = jnp.max(jnp.abs(t), axis=-1)
        scale = (m / 127.0).astype(jnp.float32)
        safe = jnp.where(scale > 0, scale, 1.0).astype(t.dtype)
        q8 = jnp.clip(jnp.round(t / safe[..., None]),
                      -127, 127).astype(jnp.int8)
        return q8, scale

    def chain_tokens_s(g, q, args, n):
        _sync(g(q, *args))  # compile + warm
        while True:
            t0 = time.perf_counter()
            o = q
            for _ in range(n):
                o = g(o, *args)
            _sync(o)
            total = time.perf_counter() - t0
            if total >= MIN_MARGINAL_WINDOW_S:
                return B * n / total
            n *= 2  # below timer resolution: widen the window

    out = {}
    rs = np.random.RandomState(11)
    for ctx in (128, 2048):
        NP = ctx // page_size
        P = B * NP + 1  # + the garbage page
        q = jnp.asarray(rs.randn(B, H, 1, d), jnp.float32)
        kf = jnp.asarray(rs.randn(P, H, page_size, d), jnp.float32)
        vf = jnp.asarray(rs.randn(P, H, page_size, d), jnp.float32)
        # distinct pages per slot, decode position at the full context
        bt = jnp.asarray(rs.permutation(P - 1)[:B * NP].reshape(B, NP)
                         + 1, jnp.int32)
        pos = jnp.full((B,), ctx - 1, jnp.int32)
        for quant in (False, True):
            if quant:
                kp, ksp = quantize(kf)
                vp, vsp = quantize(vf)
            else:
                kp, vp, ksp, vsp = kf, vf, None, None
            key = f"paged_attn_t{ctx}" + ("_int8" if quant else "")
            rates = {}
            for name, backend in (("xla", "xla"), ("kernel", "pallas")):
                # pools/tables are jit ARGUMENTS (device-resident, as in
                # serving) — closing over them would bake them into the
                # program as constants
                g = jax.jit(lambda qq, kkp, vvp, bbt, ppos, kks, vvs,
                            _b=backend: paged_attend(
                                _b, qq, kkp, vvp, bbt, ppos,
                                kscales=kks, vscales=vvs))
                rates[name] = chain_tokens_s(
                    g, q, (kp, vp, bt, pos, ksp, vsp), steps)
                out[f"{key}_{name}_tokens_s"] = rates[name]
            out[f"{key}_kernel_speedup"] = rates["kernel"] / rates["xla"]
    return out


def bench_fit_e2e(batch: int = 1, n_examples: int = 96, reps: int = 5):
    """LeNet-MNIST ``fit()`` wall clock, END TO END — the user-facing path
    the marginal timer deliberately cancels out of the chip metrics: per
    minibatch, one Python dispatch, one host->device transfer, and one
    listener round-trip. Measures the same iterator through the unfused
    per-minibatch path (``fused_steps=1``) and the fused K-step driver
    (``fused_steps=None`` — the shipping default), and reports the ratio.

    Config notes: per-minibatch overhead is CONSTANT per step while compute
    scales with the batch, so the metric uses a small batch where the
    quantity under test is visible above compute (at batch 512 the dispatch
    slack is <1% of a step and the metric would measure conv throughput
    again — bench_lenet already does that). A score-reading listener is
    attached to both legs so the per-iteration score round-trip (one device
    fetch per step unfused, one per block fused) is part of the timing.
    Median of ``reps`` timed epochs per leg, all samples recorded."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.optimize.listeners import TrainingListener

    class _ScoreReader(TrainingListener):
        def iteration_done(self, model, iteration):
            float(model.score_value)

    rs = np.random.RandomState(1)
    x = rs.randn(n_examples, 28, 28, 1).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, n_examples)]
    iterator = ListDataSetIterator(DataSet(x, y), batch_size=batch)

    def leg(fused_steps):
        net = LeNet(num_labels=10).init()
        net.set_listeners(_ScoreReader())
        net.fit(iterator, epochs=1, fused_steps=fused_steps)  # compile warm
        samples = []
        for _ in range(reps):
            t0 = time.perf_counter()
            net.fit(iterator, epochs=1, fused_steps=fused_steps)
            samples.append(n_examples / (time.perf_counter() - t0))
        return float(np.median(samples)), [round(s, 1) for s in samples]

    unfused, unfused_samples = leg(1)
    fused, fused_samples = leg(None)
    return {
        "fit_e2e_unfused_img_s": _sane("fit_e2e_img_s", unfused),
        "fit_e2e_unfused_samples": unfused_samples,
        "fit_e2e_img_s": _sane("fit_e2e_img_s", fused),
        "fit_e2e_samples": fused_samples,
        "fit_e2e_fused_speedup": fused / unfused,
    }


def bench_guard_overhead(batch: int = 128, n_examples: int = 1024,
                         reps: int = 5):
    """Numerical-health guard cost on the fused fit path (optimize/health
    .py, acceptance: <2%). Times an identical LeNet fused-fit epoch with
    the guard ON (all-finite reduction + identity-select fused into the
    step, skip flags riding the block fetch, HealthPolicy.observe on host)
    vs OFF, and reports the throughput delta as a percentage.

    Config notes: unlike fit_e2e this uses a compute-visible batch — the
    guard's cost model is O(num_params) reads against O(num_params *
    batch) step compute plus one extra small host fetch per K-step block,
    so a tiny batch would measure the guard against dispatch slack instead
    of against the compute it is amortized by. No listeners on either leg:
    the guarded no-listener path pays its stats fetch, the unguarded one
    keeps the device-side score contract, exactly as users get by
    default. Median of ``reps`` timed epochs per leg, all recorded."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.optimize.health import HealthPolicy

    rs = np.random.RandomState(4)
    x = rs.randn(n_examples, 28, 28, 1).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, n_examples)]
    iterator = ListDataSetIterator(DataSet(x, y), batch_size=batch)

    def leg(guarded):
        net = LeNet(num_labels=10).init()
        # a fresh policy per fit: thresholds high enough that the guard
        # only ever measures its fast path (nothing in this data skips)
        guard = ((lambda: HealthPolicy(skip_threshold=10 ** 9,
                                       spike_factor=1e18))
                 if guarded else (lambda: None))
        net.fit(iterator, epochs=1, health_guard=guard())  # compile warm
        samples = []
        for _ in range(reps):
            t0 = time.perf_counter()
            net.fit(iterator, epochs=1, health_guard=guard())
            _sync(net.params)
            samples.append(n_examples / (time.perf_counter() - t0))
        return float(np.median(samples)), [round(s, 1) for s in samples]

    off, off_samples = leg(False)
    on, on_samples = leg(True)
    return {
        "guard_off_img_s": _sane("guard_off_img_s", off),
        "guard_off_samples": off_samples,
        "guard_on_img_s": _sane("guard_on_img_s", on),
        "guard_on_samples": on_samples,
        "guard_overhead_pct": (off - on) / off * 100.0,
    }


def bench_eval_e2e(batch: int = 1, n_examples: int = 96, reps: int = 5):
    """LeNet-MNIST ``evaluate()`` wall clock, END TO END — the eval twin of
    bench_fit_e2e. The per-batch path pays, per minibatch, one Python
    dispatch, one host->device transfer, one FULL logit fetch back to host,
    and a numpy confusion build; the fused path (the shipping default)
    scans K batches per dispatch, scatter-adds into a device accumulator,
    and fetches ONE [C, C] count matrix per epoch. Same small-batch
    rationale as fit_e2e: the overheads under test are constant per step,
    so a big batch would bury them under conv throughput (bench_lenet's
    job). Median of ``reps`` timed epochs per leg, all samples recorded."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models import LeNet

    rs = np.random.RandomState(2)
    x = rs.randn(n_examples, 28, 28, 1).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, n_examples)]
    iterator = ListDataSetIterator(DataSet(x, y), batch_size=batch)
    net = LeNet(num_labels=10).init()

    def leg(fused):
        iterator.reset()
        net.evaluate(iterator, fused=fused)  # compile warm
        samples = []
        for _ in range(reps):
            iterator.reset()
            t0 = time.perf_counter()
            net.evaluate(iterator, fused=fused)
            samples.append(n_examples / (time.perf_counter() - t0))
        return float(np.median(samples)), [round(s, 1) for s in samples]

    unfused, unfused_samples = leg(False)
    fused, fused_samples = leg(True)
    return {
        "eval_e2e_unfused_img_s": _sane("eval_e2e_img_s", unfused),
        "eval_e2e_unfused_samples": unfused_samples,
        "eval_e2e_img_s": _sane("eval_e2e_img_s", fused),
        "eval_e2e_samples": fused_samples,
        "eval_e2e_fused_speedup": fused / unfused,
    }


def _serve_latency_quantiles(lat_ms, prefix):
    """p50/p99 over a latency sample via the metrics histogram — the
    registry's nearest-rank quantile is the single percentile
    implementation for bench AND serving. (The inline index math it
    replaces, ``lat_ms[int(len(lat_ms) * 0.99)]``, read one rank past
    the nearest-rank p99 at these sample counts — and past the END of
    the list whenever the count is a multiple of 100.)"""
    from deeplearning4j_tpu.metrics.registry import Histogram

    h = Histogram(reservoir=max(1, len(lat_ms)))
    for v in lat_ms:
        h.observe(v)
    return {f"{prefix}_p50_ms": h.quantile(0.5),
            f"{prefix}_p99_ms": h.quantile(0.99)}


def bench_inference_serve(n_requests: int = 256, max_batch: int = 64,
                          max_wait_ms: float = 2.0):
    """Coalescing inference server latency/throughput: ``n_requests``
    single-image LeNet requests pushed through ``submit()`` as fast as the
    host can produce them (the serving worst case — every request is 1 row,
    so ALL batching is the coalescer's doing). Reports requests/s plus p50
    and p99 request latency (submit -> future resolution, measured by a
    done-callback timestamp) and the dispatch count the coalescer needed."""
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    rs = np.random.RandomState(3)
    xs = rs.randn(n_requests, 1, 28, 28, 1).astype(np.float32)
    net = LeNet(num_labels=10).init()
    with ParallelInference(net, max_batch=max_batch,
                           max_wait_ms=max_wait_ms) as inf:
        inf.submit(xs[0]).result(timeout=120)  # compile warm (1-row bucket)
        inf.output(xs[:max_batch, 0])          # warm the full-batch bucket
        base = inf.dispatch_count
        done_at = [None] * n_requests
        t_submit = [None] * n_requests

        def make_cb(i):
            def cb(_fut):
                done_at[i] = time.perf_counter()
            return cb

        t0 = time.perf_counter()
        futs = []
        for i in range(n_requests):
            t_submit[i] = time.perf_counter()
            f = inf.submit(xs[i])
            f.add_done_callback(make_cb(i))
            futs.append(f)
        for f in futs:
            f.result(timeout=120)
        total = time.perf_counter() - t0
        dispatches = inf.dispatch_count - base
    lat_ms = sorted((d - s) * 1e3 for d, s in zip(done_at, t_submit))
    return {
        "inference_serve_req_s": _sane("inference_serve_req_s",
                                       n_requests / total),
        **_serve_latency_quantiles(lat_ms, "inference_serve"),
        "inference_serve_dispatches": float(dispatches),
    }


def bench_serve_chaos(n_requests: int = 256, max_batch: int = 64,
                      max_wait_ms: float = 2.0,
                      transient_rate: float = 0.10):
    """The serving path under fault injection: the ``inference_serve``
    workload with a ``ChaosPolicy`` failing ``transient_rate`` of
    dispatches transiently. Measures what resilience costs AND proves the
    zero-loss contract at bench scale — every future must resolve or fail
    typed. Reports req/s, p50/p99 latency over SUCCESSFUL requests
    (retried requests pay their backoffs in the tail), and the fraction
    that still failed typed once the retry budget was spent."""
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                        ResilienceError,
                                                        RetryPolicy)

    rs = np.random.RandomState(3)
    xs = rs.randn(n_requests, 1, 28, 28, 1).astype(np.float32)
    net = LeNet(num_labels=10).init()
    chaos = ChaosPolicy(seed=7, transient_rate=transient_rate)
    retry = RetryPolicy(max_attempts=4, base_s=1e-4, cap_s=2e-3, seed=0)
    with ParallelInference(net, max_batch=max_batch,
                           max_wait_ms=max_wait_ms,
                           max_pending=2 * n_requests, retry=retry,
                           chaos=chaos) as inf:
        inf.submit(xs[0]).result(timeout=120)  # compile warm (1-row bucket)
        inf.output(xs[:max_batch, 0])          # warm the full-batch bucket
        chaos.injected_transient = 0           # don't count warmup faults
        done_at = [None] * n_requests
        t_submit = [None] * n_requests

        def make_cb(i):
            def cb(_fut):
                done_at[i] = time.perf_counter()
            return cb

        t0 = time.perf_counter()
        futs = []
        for i in range(n_requests):
            t_submit[i] = time.perf_counter()
            f = inf.submit(xs[i])
            f.add_done_callback(make_cb(i))
            futs.append(f)
        ok, failed_typed = [], 0
        for i, f in enumerate(futs):
            try:
                f.result(timeout=120)
                ok.append(i)
            except ResilienceError:
                failed_typed += 1
        total = time.perf_counter() - t0
        st = inf.stats()
    lost = n_requests - len(ok) - failed_typed
    if lost:  # the zero-loss contract is the point of the metric
        raise RuntimeError(f"{lost} futures neither resolved nor failed "
                           "typed under chaos")
    lat_ms = sorted((done_at[i] - t_submit[i]) * 1e3 for i in ok)
    return {
        "serve_chaos_req_s": _sane("serve_chaos_req_s",
                                   n_requests / total),
        **_serve_latency_quantiles(lat_ms, "serve_chaos"),
        "serve_chaos_typed_failure_frac": failed_typed / n_requests,
        "serve_chaos_retries": float(st["retried"]),
        "serve_chaos_injected_faults": float(chaos.injected_transient),
    }


def bench_serve_fleet(n_requests: int = 96, repeats: int = 3,
                      window: int = 8, vocab: int = 17):
    """Replica-fleet generation serving under chaos: ``n_requests`` mixed
    greedy+sampled requests per pass through a ``ReplicaFleet`` of
    ``GenerationServer`` replicas, a bounded client window (``window``
    outstanding, typed sheds retried with backoff — the HTTP-client
    contract), each replica carrying its own seeded ``ChaosPolicy`` at
    ~10% injected faults (transient dispatch failures, stalls,
    slow-decode) PLUS one explicit mid-stream ``kill_replica`` per timed
    pass. Measures aggregate req/s at replicas=1 vs replicas=2 on the
    SAME workload and asserts the fleet scales >= 1.7x.

    The scaling is an availability win, not a FLOPs win (the bench box
    may be one core): a lone replica takes the full outage on every kill
    — restart backoff, re-prefill, re-decode of re-dispatched requests —
    while the two-replica fleet routes around the death at nearly full
    throughput and re-dispatches the victim's in-flight work to the
    survivor. Every completion is checked BIT-identical to its serial
    reference (the fold_in key schedule makes regeneration exact on any
    replica) and the zero-lost-futures ledger is asserted from the fleet
    counters — both in-bench, not in a separate test."""
    from deeplearning4j_tpu.models.zoo import (TransformerLM,
                                               greedy_generate,
                                               sample_generate)
    from deeplearning4j_tpu.parallel.fleet import READY, ReplicaFleet
    from deeplearning4j_tpu.parallel.generation import GenerationServer
    from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                        ResilienceError)

    net = TransformerLM(num_labels=vocab, max_length=16, d_model=16,
                        n_heads=2, n_blocks=1, seed=3).init()
    rng = np.random.default_rng(42)
    shapes = [(3, 4), (5, 5), (4, 6)]  # (plen, steps): bounded programs
    specs = []
    for i in range(n_requests):
        plen, steps = shapes[i % len(shapes)]
        p = rng.integers(1, vocab, size=plen).astype(np.int64)
        specs.append((p, steps, 0.0, 0, 0) if i % 2 == 0
                     else (p, steps, 0.9, 5, 2000 + i))
    refs = [greedy_generate(net, p[None], steps, vocab)[0]
            if temp == 0.0 else
            sample_generate(net, p[None], steps, vocab, temperature=temp,
                            top_k=top_k, seed=seed)[0]
            for p, steps, temp, top_k, seed in specs]

    def factory(rid):
        # ~10% of dispatches faulted, deterministic per replica slot
        chaos = ChaosPolicy(seed=1000 + rid, transient_rate=0.04,
                            stall_rate=0.03, stall_s=0.05,
                            slow_rate=0.03, slow_factor=2.0)
        return GenerationServer(net, vocab, slots=4, chaos=chaos)

    def submit_retry(fl, spec):
        p, steps, temp, top_k, seed = spec
        t_end = time.monotonic() + SUB_BENCH_TIMEOUT_S
        while True:
            try:
                return fl.submit(p, steps, temperature=temp, top_k=top_k,
                                 seed=seed,
                                 deadline_s=SUB_BENCH_TIMEOUT_S)
            except ResilienceError:
                # typed shed (replica restarting): back off and resubmit
                if time.monotonic() > t_end:
                    raise
                time.sleep(0.01)

    def run_pass(fl, kill):
        sem = threading.BoundedSemaphore(window)
        done_at = [None] * n_requests
        t_submit = [None] * n_requests

        def make_cb(i):
            def cb(_fut):
                done_at[i] = time.perf_counter()
                sem.release()
            return cb

        t0 = time.perf_counter()
        futs = []
        for i, spec in enumerate(specs):
            sem.acquire()
            t_submit[i] = time.perf_counter()
            f = submit_retry(fl, spec)
            f.add_done_callback(make_cb(i))
            futs.append(f)
            if kill and i == n_requests // 3:
                # mid-stream replica death: in-flight work re-dispatches
                fl.kill_replica(0)
        outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S) for f in futs]
        total = time.perf_counter() - t0
        bad = sum(1 for o, ref in zip(outs, refs)
                  if not np.array_equal(np.asarray(o), ref))
        if bad:  # bit-exact across redispatch is the point of the metric
            raise RuntimeError(
                f"{bad}/{n_requests} fleet completions differ from their "
                "serial references under chaos")
        lat_ms = sorted((d - s) * 1e3
                        for d, s in zip(done_at, t_submit))
        return total, lat_ms

    results = {}
    for nrep in (1, 2):
        fl = ReplicaFleet(factory, replicas=nrep,
                          max_pending=2 * n_requests,
                          replica_max_pending=2 * n_requests,
                          restart_backoff_s=0.5)
        try:
            run_pass(fl, kill=False)  # warm every program, both paths
            total = 0.0
            lat_ms = None
            for _ in range(repeats):
                t, lat_ms = run_pass(fl, kill=True)
                total += t
            # let the supervised restart land (the backoff may outlive a
            # fast pass) so the counters prove the full death->respawn arc
            t_end = time.monotonic() + 30.0
            st = fl.stats()
            while (st["restarts"] < 1
                   or any(r["state"] != READY for r in st["replicas"])):
                if time.monotonic() > t_end:
                    break
                time.sleep(0.02)
                st = fl.stats()
        finally:
            fl.close()
        # zero-lost-futures ledger: every accepted request completed;
        # typed sheds the client retried are rejected_submits, and
        # nothing may be left parked, in flight, failed, or expired
        lost = st["submitted"] - st["completed"] - st["rejected_submits"]
        if lost or st["inflight"] or st["parked"] or st["failed"] \
                or st["expired"]:
            raise RuntimeError(
                f"fleet leaked {lost} futures (inflight {st['inflight']}"
                f", parked {st['parked']}, failed {st['failed']}, "
                f"expired {st['expired']}) under chaos")
        if st["deaths"] < 1 or st["restarts"] < 1:
            raise RuntimeError(
                "the explicit kill_replica never exercised the "
                f"restart path (deaths {st['deaths']}, restarts "
                f"{st['restarts']})")
        results[nrep] = (repeats * n_requests / total, lat_ms, st)

    req_s_1, _, _ = results[1]
    req_s_2, lat_ms, st2 = results[2]
    scaling = req_s_2 / req_s_1
    if scaling < 1.7:
        raise RuntimeError(
            f"fleet 1->2 replica scaling {scaling:.2f}x under chaos — "
            "below the 1.7x bar the health-weighted router exists to "
            "clear")
    return {
        "serve_fleet_req_s": _sane("serve_fleet_req_s", req_s_2),
        "serve_fleet_1rep_req_s": _sane("serve_fleet_1rep_req_s",
                                        req_s_1),
        "serve_fleet_scaling": scaling,
        **_serve_latency_quantiles(lat_ms, "serve_fleet"),
        "serve_fleet_deaths": float(st2["deaths"]),
        "serve_fleet_restarts": float(st2["restarts"]),
        "serve_fleet_redispatched": float(st2["redispatched"]),
    }


def bench_serve_federated(n_requests: int = 64, repeats: int = 2,
                          window: int = 24, vocab: int = 17,
                          n_crash: int = 6, crash_steps: int = 20):
    """Cross-host fleet federation: generation serving over N fleet-host
    *processes* (each a ReplicaFleet behind the framed socket RPC)
    fronted by one FleetFederation router. Three legs over the same two
    spawned host processes:

    1. federation over H0 only (timed),
    2. federation over H0+H1 (timed) — asserts aggregate req/s scaling
       >= 1.7x; the hosts' decode loops are stall-chaos dominated
       (sleep-bound, not FLOPs-bound), so two processes must deliver
       near-2x even on a one-core bench box,
    3. crash drill: a fresh federation over both hosts, SIGKILL H1
       mid-stream once the router holds published KV snapshots, and
       assert IN-BENCH that every completion is bit-exact vs its serial
       reference (the victims resume on H0 via cross-host snapshot
       adoption — ``handoff_resumes >= 1`` proves at least one adopted
       rather than replayed from token 0), that zero futures were lost,
       and that the federated ledger balances."""
    import tempfile

    from deeplearning4j_tpu.models.zoo import (TransformerLM,
                                               greedy_generate,
                                               sample_generate)
    from deeplearning4j_tpu.parallel.federation import (FleetFederation,
                                                        spawn_host)
    from deeplearning4j_tpu.parallel.resilience import ResilienceError

    net = TransformerLM(num_labels=vocab, max_length=32, d_model=16,
                        n_heads=2, n_blocks=1, seed=3).init()
    rng = np.random.default_rng(42)
    # deeper requests than the in-process fleet bench: each decode step
    # stalls, so length amortizes the per-request RPC + routing overhead
    # and keeps both hosts' slots full behind the client window
    shapes = [(3, 8), (5, 9), (4, 10)]

    def mk_specs(n, steps=None):
        specs = []
        for i in range(n):
            plen, st = shapes[i % len(shapes)]
            p = rng.integers(1, vocab, size=plen).astype(np.int64)
            specs.append((p, steps or st, 0.0, 0, 0) if i % 2 == 0
                         else (p, steps or st, 0.9, 5, 2000 + i))
        return specs

    def mk_refs(specs):
        return [greedy_generate(net, p[None], st, vocab)[0]
                if temp == 0.0 else
                sample_generate(net, p[None], st, vocab, temperature=temp,
                                top_k=top_k, seed=seed)[0]
                for p, st, temp, top_k, seed in specs]

    specs = mk_specs(n_requests)
    refs = mk_refs(specs)

    def submit_retry(fed, spec):
        p, st, temp, top_k, seed = spec
        t_end = time.monotonic() + SUB_BENCH_TIMEOUT_S
        while True:
            try:
                return fed.submit(p, st, temperature=temp, top_k=top_k,
                                  seed=seed,
                                  deadline_s=SUB_BENCH_TIMEOUT_S)
            except ResilienceError:
                if time.monotonic() > t_end:
                    raise
                time.sleep(0.01)

    def run_pass(fed):
        sem = threading.BoundedSemaphore(window)
        done_at = [None] * n_requests
        t_submit = [None] * n_requests

        def make_cb(i):
            def cb(_fut):
                done_at[i] = time.perf_counter()
                sem.release()
            return cb

        t0 = time.perf_counter()
        futs = []
        for i, spec in enumerate(specs):
            sem.acquire()
            t_submit[i] = time.perf_counter()
            f = submit_retry(fed, spec)
            f.add_done_callback(make_cb(i))
            futs.append(f)
        outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S) for f in futs]
        total = time.perf_counter() - t0
        bad = sum(1 for o, ref in zip(outs, refs)
                  if not np.array_equal(np.asarray(o), ref))
        if bad:
            raise RuntimeError(
                f"{bad}/{n_requests} federated completions differ from "
                "their serial references")
        lat_ms = sorted((d - s) * 1e3
                        for d, s in zip(done_at, t_submit))
        return total, lat_ms

    hb_dir = tempfile.mkdtemp(prefix="fed_bench_hb_")
    spec_base = {"heartbeat_dir": hb_dir, "heartbeat_interval": 0.05,
                 "builder_kwargs": {
                     "replicas": 1, "slots": 4, "snapshot_every": 1,
                     "max_length": 32, "steps_per_dispatch": 1,
                     "chaos": {"stall_rate": 1.0, "stall_s": 0.02}}}
    hh0 = spawn_host(dict(spec_base, hid="h0"))
    hh1 = spawn_host(dict(spec_base, hid="h1"))
    try:
        results = {}
        for nhosts, handles in ((1, [hh0]), (2, [hh0, hh1])):
            fed = FleetFederation(handles, heartbeat_dir=hb_dir,
                                  max_pending=2 * n_requests)
            try:
                run_pass(fed)  # warm every host program, both paths
                total = 0.0
                lat_ms = None
                for _ in range(repeats):
                    t, lat_ms = run_pass(fed)
                    total += t
                st = fed.stats()["federation"]
                lost = (st["submitted"] - st["completed"]
                        - st["rejected_submits"])
                if lost or st["inflight"] or st["parked"] \
                        or st["failed"] or st["expired"]:
                    raise RuntimeError(
                        f"federation ({nhosts} host) leaked {lost} "
                        f"futures (inflight {st['inflight']}, parked "
                        f"{st['parked']}, failed {st['failed']}, "
                        f"expired {st['expired']})")
            finally:
                fed.close()
            results[nhosts] = (repeats * n_requests / total, lat_ms)

        # ---- leg 3: whole-process SIGKILL mid-stream -----------------
        crash_specs = mk_specs(n_crash, steps=crash_steps)
        crash_refs = mk_refs(crash_specs)
        fed = FleetFederation([hh0, hh1], heartbeat_dir=hb_dir,
                              max_pending=2 * n_crash)
        try:
            futs = [submit_retry(fed, sp) for sp in crash_specs]
            t_end = time.monotonic() + 60.0
            while fed.stats()["federation"]["snapshots"] < 2:
                if time.monotonic() > t_end:
                    raise RuntimeError(
                        "hosts never published KV snapshots to the "
                        "router — nothing to adopt on crash")
                time.sleep(0.01)
            hh1.kill()   # SIGKILL the whole process: no flush, no goodbye
            outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S) for f in futs]
            bad = sum(1 for o, ref in zip(outs, crash_refs)
                      if not np.array_equal(np.asarray(o), ref))
            if bad:
                raise RuntimeError(
                    f"{bad}/{n_crash} completions differ from serial "
                    "after the host SIGKILL — cross-host migration is "
                    "not bit-exact")
            st = fed.stats()["federation"]
            lost = (st["submitted"] - st["completed"]
                    - st["rejected_submits"])
            if lost or st["inflight"] or st["parked"] or st["failed"] \
                    or st["expired"]:
                raise RuntimeError(
                    f"federation leaked {lost} futures across the host "
                    f"SIGKILL (inflight {st['inflight']}, parked "
                    f"{st['parked']}, failed {st['failed']}, expired "
                    f"{st['expired']})")
            if st["deaths"] < 1:
                raise RuntimeError("the SIGKILL was never detected as a "
                                   "host death")
            if st["handoff_resumes"] < 1:
                raise RuntimeError(
                    "no victim resumed from an adopted snapshot "
                    f"(resumes {st['handoff_resumes']}, fallbacks "
                    f"{st['handoff_fallbacks']}) — the crash drill must "
                    "exercise cross-host adoption, not just token-0 "
                    "replay")
            crash_st = st
        finally:
            fed.close()
    finally:
        hh0.terminate()
        if hh1.alive:
            hh1.kill()

    req_s_1, _ = results[1]
    req_s_2, lat_ms = results[2]
    scaling = req_s_2 / req_s_1
    if scaling < 1.7:
        raise RuntimeError(
            f"federation 1->2 host scaling {scaling:.2f}x — below the "
            "1.7x bar on a stall-dominated workload")
    return {
        "serve_federated_req_s": _sane("serve_federated_req_s", req_s_2),
        "serve_federated_1host_req_s": _sane("serve_federated_1host_req_s",
                                             req_s_1),
        "serve_federated_scaling": scaling,
        **_serve_latency_quantiles(lat_ms, "serve_federated"),
        "serve_federated_deaths": float(crash_st["deaths"]),
        "serve_federated_handoff_resumes": float(
            crash_st["handoff_resumes"]),
        "serve_federated_redispatched": float(crash_st["redispatched"]),
    }


def bench_serve_handoff(n_requests: int = 64, vocab: int = 17,
                        steps: int = 48, kill_at_tokens: int = 80):
    """Crash-durable serving: what does a mid-stream replica death COST?
    Two legs over the same fleet geometry and the same deterministic kill
    trigger — token-0 redispatch (``snapshot_every=0``, the pre-handoff
    behavior) vs crash-durable (``snapshot_every=1``: periodic KV-page
    snapshots ride each request's future and the fleet adopts the newest
    one on the survivor). ``n_requests`` mixed greedy+sampled requests of
    ``steps`` tokens stream through 2 replicas x 2 slots; replica 0 is
    killed once its live streams are ``kill_at_tokens`` deep, so the
    token-0 leg must regenerate every one of those tokens while the
    handoff leg resumes at position N and recomputes only the
    since-last-snapshot tail.

    Recomputed work is measured from the ledger, not wall clock: the sum
    of ``tokens_generated`` over every server the factory ever created,
    minus the tokens the completed requests actually needed. Gates (all
    raise, never publish): every completion bit-exact vs its serial
    reference in BOTH legs, the zero-lost-futures ledger in both legs,
    resumes only in the handoff leg, and handoff recompute <= 10% of the
    token-0 baseline's."""
    from deeplearning4j_tpu.models.zoo import (TransformerLM,
                                               greedy_generate,
                                               sample_generate)
    from deeplearning4j_tpu.parallel.fleet import READY, ReplicaFleet
    from deeplearning4j_tpu.parallel.generation import GenerationServer
    from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                        ResilienceError)

    net = TransformerLM(num_labels=vocab, max_length=16, d_model=16,
                        n_heads=2, n_blocks=1, seed=3).init()
    rng = np.random.default_rng(42)
    plens = (3, 5, 4)  # mixed lengths over a bounded program set
    specs = []
    for i in range(n_requests):
        p = rng.integers(1, vocab, size=plens[i % 3]).astype(np.int64)
        specs.append((p, steps, 0.0, 0, 0) if i % 2 == 0
                     else (p, steps, 0.9, 5, 2000 + i))
    refs = [greedy_generate(net, p[None], s, vocab)[0]
            if temp == 0.0 else
            sample_generate(net, p[None], s, vocab, temperature=temp,
                            top_k=top_k, seed=seed)[0]
            for p, s, temp, top_k, seed in specs]

    def submit_retry(fl, spec):
        p, s, temp, top_k, seed = spec
        t_end = time.monotonic() + SUB_BENCH_TIMEOUT_S
        while True:
            try:
                return fl.submit(p, s, temperature=temp, top_k=top_k,
                                 seed=seed,
                                 deadline_s=SUB_BENCH_TIMEOUT_S)
            except ResilienceError:
                if time.monotonic() > t_end:
                    raise
                time.sleep(0.01)

    def run_leg(snapshot_every):
        created = []

        def factory(rid):
            # the stall keeps streams long enough for the kill trigger
            # to land mid-generation deterministically
            chaos = ChaosPolicy(seed=1000 + rid, stall_rate=1.0,
                                stall_s=0.003)
            srv = GenerationServer(net, vocab, slots=2, page_size=4,
                                   snapshot_every=snapshot_every,
                                   steps_per_dispatch=1, chaos=chaos)
            created.append(srv)
            return srv

        fl = ReplicaFleet(factory, replicas=2,
                          max_pending=2 * n_requests,
                          replica_max_pending=2 * n_requests,
                          restart_backoff_s=0.05)
        try:
            for sp in specs[:6]:  # warm every program on both replicas
                submit_retry(fl, sp).result(timeout=SUB_BENCH_TIMEOUT_S)
            useful_warm = sum(sp[1] for sp in specs[:6])
            warm0 = (fl.stats()["replicas"][0]["server"]
                     or {}).get("tokens_generated", 0)
            t0 = time.perf_counter()
            futs = [submit_retry(fl, sp) for sp in specs]
            # kill replica 0 once its live streams are provably deep:
            # the token-0 leg then pays for every resident token
            t_kill = time.monotonic() + SUB_BENCH_TIMEOUT_S / 2
            while True:
                srv0 = fl.stats()["replicas"][0]["server"] or {}
                if (srv0.get("active_slots", 0) >= 2
                        and (srv0.get("tokens_generated", 0) - warm0
                             >= kill_at_tokens)):
                    break
                if time.monotonic() > t_kill:
                    break
                time.sleep(0.002)
            fl.kill_replica(0)
            outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S) for f in futs]
            total = time.perf_counter() - t0
            # let the supervised restart land before reading the ledger
            t_end = time.monotonic() + 30.0
            st = fl.stats()
            while any(r["state"] != READY for r in st["replicas"]):
                if time.monotonic() > t_end:
                    break
                time.sleep(0.02)
                st = fl.stats()
        finally:
            fl.close()
        bad = sum(1 for o, ref in zip(outs, refs)
                  if not np.array_equal(np.asarray(o), ref))
        if bad:
            raise RuntimeError(
                f"{bad}/{n_requests} completions differ from their "
                f"serial references (snapshot_every={snapshot_every})")
        lost = st["submitted"] - st["completed"] - st["rejected_submits"]
        if lost or st["inflight"] or st["parked"] or st["failed"] \
                or st["expired"]:
            raise RuntimeError(
                f"fleet leaked {lost} futures (inflight {st['inflight']}"
                f", parked {st['parked']}, failed {st['failed']}, "
                f"expired {st['expired']}) across the handoff kill")
        if st["deaths"] < 1:
            raise RuntimeError("the kill trigger never fired")
        gen_total = sum(s.stats()["tokens_generated"] for s in created)
        useful = n_requests * steps + useful_warm
        recompute = gen_total - useful
        ho = {"resumes": 0, "tokens_saved": 0, "bytes": 0}
        for s in created:
            h = s.stats()["handoff"]
            for k in ho:
                ho[k] += h[k]
        return (n_requests / total, recompute, st, ho)

    _req_s_0, base_rc, st0, _ho0 = run_leg(0)
    req_s, handoff_rc, st1, ho1 = run_leg(1)
    if st0["handoff_resumes"] != 0:
        raise RuntimeError(
            "the token-0 baseline leg resumed from a snapshot — the legs "
            "are not comparable")
    if st1["handoff_resumes"] < 1 or ho1["resumes"] < 1:
        raise RuntimeError(
            "the crash-durable leg never resumed from a snapshot: the "
            "kill landed outside any snapshotted stream")
    if base_rc < kill_at_tokens // 2:
        raise RuntimeError(
            f"token-0 baseline recomputed only {base_rc} tokens — the "
            "kill did not land mid-stream; the comparison is void")
    if handoff_rc > 0.10 * base_rc:
        raise RuntimeError(
            f"crash-durable leg recomputed {handoff_rc} tokens vs "
            f"{base_rc} at token-0 — above the 10% bar snapshots exist "
            "to clear")
    return {
        "serve_handoff_req_s": _sane("serve_handoff_req_s", req_s),
        "serve_handoff_recompute_tokens": float(handoff_rc),
        "serve_handoff_token0_recompute_tokens": float(base_rc),
        "serve_handoff_recompute_frac": handoff_rc / max(1, base_rc),
        "serve_handoff_resumes": float(st1["handoff_resumes"]),
        "serve_handoff_tokens_saved": float(ho1["tokens_saved"]),
        "serve_handoff_snapshot_bytes": float(ho1["bytes"]),
    }


def bench_serve_disagg(n_requests: int = 24, vocab: int = 17,
                       steps_long: int = 48, steps_short: int = 8,
                       ttft_slo_ms: float = 400.0):
    """Disaggregated prefill/decode tiers: what does splitting the fleet
    buy on time-to-first-token when long decodes hog the slots?

    Four passes over the same warm net and the same long+short request
    mix (two-thirds ``steps_long``-token decodes behind short prompts,
    one-third ``steps_short``-token replies behind long prompts), every
    pass gated bit-exact against serial references and zero-lost on the
    fleet ledger (``submitted == completed + failed + expired +
    rejected``; all raise, never publish):

    1. **co-located baseline** — 2 unified replicas x 2 slots. A slot is
       held for prefill + the entire decode, so fresh requests queue
       behind ``steps_long``-token streams and p99 TTFT blows through
       the SLO. The pass *asserts* the violation: under the same load
       the baseline must fail the SLO the disagg pass holds, else the
       workload is too light and the comparison is void.
    2. **disaggregated** — the same replica/slot budget, but
       ``roles=("prefill", "decode")``: the prefill tier frees its slot
       at export (milliseconds), so p99 TTFT stays under
       ``ttft_slo_ms`` even while the decode tier's queue is deep.
       TTFT and inter-token latency are read from the fleet's two
       SEPARATE registry histograms (``fleet_ttft_ms`` /
       ``fleet_itl_ms``) — never derived from one another.
    3. **mid-handoff chaos** — a fresh tiered fleet; the prefill
       replica is killed once handoffs are staged with prefills still
       in flight. Every request must complete bit-exact, zero lost
       futures.
    4. **decode-tier-dark degraded** — the decode replica is killed
       under a long restart backoff; every request must complete
       co-located on the prefill tier (``degraded_submits`` >= 1)."""
    from deeplearning4j_tpu.models.zoo import (TransformerLM,
                                               greedy_generate,
                                               sample_generate)
    from deeplearning4j_tpu.parallel.fleet import READY, ReplicaFleet
    from deeplearning4j_tpu.parallel.generation import GenerationServer
    from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                        ResilienceError)

    net = TransformerLM(num_labels=vocab, max_length=16, d_model=16,
                        n_heads=2, n_blocks=1, seed=3).init()
    rng = np.random.default_rng(7)
    specs = []
    for i in range(n_requests):
        if i % 3 == 2:  # short reply behind a long prompt
            p = rng.integers(1, vocab,
                             size=(10, 12)[i % 2]).astype(np.int64)
            specs.append((p, steps_short, 0.0, 0, 0))
        else:           # long decode behind a short prompt
            p = rng.integers(1, vocab,
                             size=(3, 5, 4)[i % 3]).astype(np.int64)
            specs.append((p, steps_long, 0.0, 0, 0) if i % 2 == 0
                         else (p, steps_long, 0.9, 5, 3000 + i))
    refs = [greedy_generate(net, p[None], s, vocab)[0]
            if temp == 0.0 else
            sample_generate(net, p[None], s, vocab, temperature=temp,
                            top_k=top_k, seed=seed)[0]
            for p, s, temp, top_k, seed in specs]

    def submit_retry(fl, spec):
        p, s, temp, top_k, seed = spec
        t_end = time.monotonic() + SUB_BENCH_TIMEOUT_S
        while True:
            try:
                return fl.submit(p, s, temperature=temp, top_k=top_k,
                                 seed=seed,
                                 deadline_s=SUB_BENCH_TIMEOUT_S)
            except ResilienceError:
                if time.monotonic() > t_end:
                    raise
                time.sleep(0.01)

    def check_exact(outs, want, tag):
        bad = sum(1 for o, ref in zip(outs, want)
                  if not np.array_equal(np.asarray(o), ref))
        if bad:
            raise RuntimeError(
                f"{tag}: {bad}/{len(outs)} completions differ from "
                "their serial references")

    def check_ledger(st, tag):
        lost = st["submitted"] - st["completed"] - st["rejected_submits"]
        if lost or st["inflight"] or st["parked"] or st["failed"] \
                or st["expired"]:
            raise RuntimeError(
                f"{tag}: fleet leaked {lost} futures (inflight "
                f"{st['inflight']}, parked {st['parked']}, failed "
                f"{st['failed']}, expired {st['expired']})")

    def make_fleet(roles, **fleet_kw):
        def factory(rid):
            # the stall shapes slot residency: a co-located slot is
            # held for ~steps stalls, a prefill-tier slot for ~one
            chaos = ChaosPolicy(seed=1000 + rid, stall_rate=1.0,
                                stall_s=0.004)
            kw = dict(slots=2, page_size=4, steps_per_dispatch=1,
                      chaos=chaos)
            if roles is not None:
                kw["role"] = roles[rid]
            return GenerationServer(net, vocab, **kw)

        fkw = dict(max_pending=2 * n_requests,
                   replica_max_pending=2 * n_requests,
                   restart_backoff_s=0.05)
        fkw.update(fleet_kw)
        if roles is not None:
            fkw["roles"] = roles
        return ReplicaFleet(factory, replicas=2, **fkw)

    def run_latency_leg(roles, tag):
        fl = make_fleet(roles)
        try:
            for sp in specs[:4]:  # absorb compiles outside the window
                submit_retry(fl, sp).result(timeout=SUB_BENCH_TIMEOUT_S)
            t0 = time.perf_counter()
            futs = [submit_retry(fl, sp) for sp in specs]
            outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S) for f in futs]
            total = time.perf_counter() - t0
            st = fl.stats()
            if int(fl.ttft_hist.count) < n_requests \
                    or int(fl.itl_hist.count) < n_requests:
                raise RuntimeError(
                    f"{tag}: latency histograms under-populated "
                    f"(ttft {int(fl.ttft_hist.count)}, itl "
                    f"{int(fl.itl_hist.count)} observations for "
                    f"{n_requests} requests)")
            lat = {"ttft_p50": float(fl.ttft_hist.quantile(0.5)),
                   "ttft_p99": float(fl.ttft_hist.quantile(0.99)),
                   "itl_p50": float(fl.itl_hist.quantile(0.5)),
                   "itl_p99": float(fl.itl_hist.quantile(0.99))}
        finally:
            fl.close()
        check_exact(outs, refs, tag)
        check_ledger(st, tag)
        return n_requests / total, lat, st

    def run_chaos_leg():
        fl = make_fleet(("prefill", "decode"))
        try:
            futs = [submit_retry(fl, sp) for sp in specs]
            # kill the prefill replica mid-handoff: snapshots staged
            # AND prefills still resident, so both the parked and the
            # inflight recovery paths are exercised in one pass
            t_kill = time.monotonic() + SUB_BENCH_TIMEOUT_S / 2
            armed = False
            while True:
                st = fl.stats()
                srv0 = st["replicas"][0]["server"] or {}
                if (st["tier_handoffs"] >= 2
                        and srv0.get("active_slots", 0) >= 1):
                    armed = True
                    break
                if time.monotonic() > t_kill:
                    break
                time.sleep(0.0005)
            if not armed:
                raise RuntimeError(
                    "chaos pass: never observed staged handoffs with "
                    "prefills still in flight — the kill would not "
                    "land mid-handoff")
            fl.kill_replica(0)
            outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S) for f in futs]
            # let the supervised restart land before the ledger read
            t_end = time.monotonic() + 30.0
            st = fl.stats()
            while any(r["state"] != READY for r in st["replicas"]):
                if time.monotonic() > t_end:
                    break
                time.sleep(0.02)
                st = fl.stats()
        finally:
            fl.close()
        check_exact(outs, refs, "chaos pass")
        check_ledger(st, "chaos pass")
        if st["deaths"] < 1:
            raise RuntimeError("chaos pass: the kill never fired")
        return st

    def run_degraded_leg():
        fl = make_fleet(("prefill", "decode"), restart_backoff_s=30.0)
        sub = specs[:8]
        try:
            t_end = time.monotonic() + 30.0
            while any(r["state"] != READY
                      for r in fl.stats()["replicas"]):
                if time.monotonic() > t_end:
                    raise RuntimeError(
                        "degraded pass: fleet never became READY")
                time.sleep(0.01)
            fl.kill_replica(1)  # decode tier dark for the whole pass
            futs = [submit_retry(fl, sp) for sp in sub]
            outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S) for f in futs]
            st = fl.stats()
        finally:
            fl.close()
        check_exact(outs, refs[:len(sub)], "degraded pass")
        check_ledger(st, "degraded pass")
        if st["completed"] < len(sub):
            raise RuntimeError(
                f"degraded pass completed only {st['completed']}/"
                f"{len(sub)} requests with the decode tier dark")
        if st["degraded_submits"] < 1:
            raise RuntimeError(
                "degraded pass: decode tier was dark yet no submit "
                "was served co-located on the prefill tier")
        return st

    colo_req_s, colo_lat, _colo_st = run_latency_leg(
        None, "co-located baseline")
    dis_req_s, dis_lat, dis_st = run_latency_leg(
        ("prefill", "decode"), "disagg pass")
    if dis_st["tier_handoffs"] < n_requests:
        raise RuntimeError(
            f"disagg pass staged only {dis_st['tier_handoffs']} "
            f"handoffs for {n_requests} requests — the tier pipeline "
            "was bypassed")
    if dis_lat["ttft_p99"] >= ttft_slo_ms:
        raise RuntimeError(
            f"disagg p99 TTFT {dis_lat['ttft_p99']:.1f} ms violates "
            f"the {ttft_slo_ms:.0f} ms SLO it exists to hold")
    if colo_lat["ttft_p99"] <= ttft_slo_ms:
        raise RuntimeError(
            f"co-located p99 TTFT {colo_lat['ttft_p99']:.1f} ms "
            f"already meets the {ttft_slo_ms:.0f} ms SLO — load too "
            "light, the disagg win is unmeasured")
    chaos_st = run_chaos_leg()
    deg_st = run_degraded_leg()
    return {
        # colo first: the standalone headline picker takes the LAST
        # sanity-ceiling'd key, and the disagg number is the headline
        "serve_colo_req_s": _sane("serve_colo_req_s", colo_req_s),
        "serve_disagg_req_s": _sane("serve_disagg_req_s", dis_req_s),
        "serve_disagg_ttft_p50_ms": round(dis_lat["ttft_p50"], 2),
        "serve_disagg_ttft_p99_ms": round(dis_lat["ttft_p99"], 2),
        "serve_disagg_itl_p50_ms": round(dis_lat["itl_p50"], 2),
        "serve_disagg_itl_p99_ms": round(dis_lat["itl_p99"], 2),
        "serve_colo_ttft_p50_ms": round(colo_lat["ttft_p50"], 2),
        "serve_colo_ttft_p99_ms": round(colo_lat["ttft_p99"], 2),
        "serve_colo_itl_p50_ms": round(colo_lat["itl_p50"], 2),
        "serve_disagg_ttft_slo_ms": float(ttft_slo_ms),
        "serve_disagg_tier_handoffs": float(dis_st["tier_handoffs"]),
        "serve_disagg_chaos_redispatched":
            float(chaos_st["redispatched"]),
        "serve_disagg_degraded_submits":
            float(deg_st["degraded_submits"]),
    }


def bench_generate_serve(n_requests: int = 64, slots: int = 64,
                         vocab: int = 256, d_model: int = 256,
                         n_blocks: int = 3, repeats: int = 3):
    """Paged continuous-batching generation throughput: ``n_requests``
    concurrent mixed-length greedy requests through ``GenerationServer``
    (page-pool KV-cache, batched wave prefill, ``steps_per_dispatch``
    write-clamped decode micro-steps fused per host round trip) vs the
    SAME requests decoded serially via ``sample_generate`` (one fused
    scan per request — the pre-continuous-batching serving story).

    64 slots, not 16: serial batch-1 decode is weight-bandwidth-bound
    while batched decode is compute-bound, so the speedup keeps growing
    with batch until the GEMMs saturate the core — 16 slots structurally
    caps near 3.5x on one core, 64 clears 4x with margin. Serial and
    server timed passes are INTERLEAVED ``repeats`` times and each side
    takes its best pass, so a background load spike cannot deflate one
    side of the ratio alone (this box is shared and noisy).

    Reports aggregate generated tokens/s for both paths, p50/p99 request
    latency under the server, and the speedup, asserted >= 4x. Every
    server completion is checked BIT-identical to its serial greedy
    reference — zero lost or incorrect completions is part of the
    contract, not a separate test."""
    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.models.zoo import sample_generate
    from deeplearning4j_tpu.parallel.generation import GenerationServer

    rs = np.random.RandomState(9)
    shapes = [(6, 40), (14, 48), (6, 48), (14, 40)]  # (plen, max_tokens)
    reqs = [(rs.randint(0, vocab, shapes[i % 4][0]), shapes[i % 4][1])
            for i in range(n_requests)]
    net = TransformerLM(num_labels=vocab, max_length=64, d_model=d_model,
                        n_heads=8, n_blocks=n_blocks, seed=0).init()
    # right-size the KV cache to the workload: every decode step attends
    # over ALL cache columns (real or padding), a per-slot cost, so a
    # 512-column default pool would bury the batching win under padded
    # attention; 64 covers prompt+generation here with nothing to spare
    for v in net.conf.vertices.values():
        lyr = getattr(v, "layer", None)
        if lyr is not None and hasattr(lyr, "max_cache"):
            lyr.max_cache = 64
    n_tokens = sum(steps for _, steps in reqs)

    # serial baseline: one fused-scan program per (plen, steps) shape —
    # warmed first, so the comparison is steady-state vs steady-state
    for prompt, steps in reqs[:4]:
        sample_generate(net, prompt[None], steps, vocab, temperature=0.0)
    refs = [sample_generate(net, prompt[None], steps, vocab,
                            temperature=0.0)[0] for prompt, steps in reqs]

    srv = GenerationServer(net, vocab, slots=slots, steps_per_dispatch=16,
                           max_pending=max(64, n_requests))
    serial_s = server_s = float("inf")
    try:
        # warm the decode step and the prefill bucket
        for f in [srv.submit(p, 2) for p, _ in reqs[:2]]:
            f.result(timeout=SUB_BENCH_TIMEOUT_S)
        done_at = [None] * n_requests
        t_submit = [None] * n_requests

        def make_cb(i):
            def cb(_fut):
                done_at[i] = time.perf_counter()
            return cb

        for _ in range(repeats):
            t0 = time.perf_counter()
            for prompt, steps in reqs:
                sample_generate(net, prompt[None], steps, vocab,
                                temperature=0.0)
            serial_s = min(serial_s, time.perf_counter() - t0)

            t0 = time.perf_counter()
            futs = []
            for i, (prompt, steps) in enumerate(reqs):
                t_submit[i] = time.perf_counter()
                f = srv.submit(prompt, steps)
                f.add_done_callback(make_cb(i))
                futs.append(f)
            outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S) for f in futs]
            server_s = min(server_s, time.perf_counter() - t0)

            bad = sum(1 for got, ref in zip(outs, refs)
                      if not np.array_equal(got, ref))
            if bad:  # the zero-loss/zero-drift contract is the point
                raise RuntimeError(
                    f"{bad}/{n_requests} continuous-batched completions "
                    "differ from their serial greedy references")
    finally:
        srv.close()

    speedup = serial_s / server_s
    if speedup < 4.0:
        raise RuntimeError(
            f"paged continuous batching {speedup:.2f}x serial decode — "
            "below the 4x bar the page pool + fused decode dispatch "
            "exist to clear")
    lat_ms = sorted((d - s) * 1e3 for d, s in zip(done_at, t_submit))
    return {
        "generate_serve_tokens_s": _sane("generate_serve_tokens_s",
                                         n_tokens / server_s),
        "generate_serve_serial_tokens_s": _sane(
            "generate_serve_serial_tokens_s", n_tokens / serial_s),
        "generate_serve_speedup": speedup,
        **_serve_latency_quantiles(lat_ms, "generate_serve"),
    }


def bench_generate_longtail(slots: int = 8, vocab: int = 256,
                            d_model: int = 128, n_blocks: int = 2):
    """Long-tail paged-serving memory: 16 requests with 16..2048-token
    prompts sharing a 128-token system prefix, decoded under an explicit
    page budget a contiguous ``[slots, max_len]`` KV-cache provably
    cannot fit (the assertion, not a vibe: pool bytes < contiguous
    bytes). Long prompts prefill through bounded Sarathi-style chunks,
    short ones ride the shared-prefix page cache (COW), and the whole
    workload is run TWICE on one server — the second pass rides fully
    cached prefixes and must produce byte-identical completions, so
    sharing/eviction can only save memory, never change output.

    Reports server tokens/s, the resident-KV compression vs contiguous,
    and prefix reuse counters."""
    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.parallel.generation import GenerationServer

    page_size = 16
    max_cache = 2176          # fits prompt 2048 + 16 generated, paged
    max_tokens = 16
    pages = 360               # vs slots * (max_cache/page_size) = 1088
    plens = [16, 32, 64, 128, 256, 512, 1024, 2048]
    net = TransformerLM(num_labels=vocab, max_length=max_cache,
                        d_model=d_model, n_heads=4, n_blocks=n_blocks,
                        seed=0).init()
    for v in net.conf.vertices.values():
        lyr = getattr(v, "layer", None)
        if lyr is not None and hasattr(lyr, "max_cache"):
            lyr.max_cache = max_cache
    rs = np.random.RandomState(11)
    system = rs.randint(0, vocab, 128)
    prompts = []
    for _rep in range(2):
        for plen in plens:
            if plen <= 128:
                prompts.append(system[:plen])
            else:
                prompts.append(np.concatenate(
                    [system, rs.randint(0, vocab, plen - 128)]))
    n_requests = len(prompts)
    n_tokens = n_requests * max_tokens

    srv = GenerationServer(net, vocab, slots=slots, page_size=page_size,
                           pages=pages, steps_per_dispatch=8,
                           max_pending=2 * n_requests)
    try:
        contiguous_bytes = slots * max_cache * srv._page_token_bytes
        pool_bytes = pages * page_size * srv._page_token_bytes
        assert pool_bytes < contiguous_bytes, (
            "longtail bench misconfigured: the page pool must be "
            "smaller than the contiguous design it replaces")
        # warm pass: compiles every chunk bucket + decode, and registers
        # the shared prefix pages
        warm = [f.result(timeout=SUB_BENCH_TIMEOUT_S)
                for f in [srv.submit(p, max_tokens) for p in prompts]]
        t0 = time.perf_counter()
        futs = [srv.submit(p, max_tokens) for p in prompts]
        outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S) for f in futs]
        server_s = time.perf_counter() - t0
        st = srv.stats()
    finally:
        srv.close()

    bad = sum(1 for got, ref in zip(outs, warm)
              if not np.array_equal(got, ref))
    if bad:  # prefix sharing / COW / eviction must never change output
        raise RuntimeError(
            f"{bad}/{n_requests} paged completions differ between the "
            "cold and prefix-cached passes")
    if st["pages"]["prefix_hits"] < n_requests:
        raise RuntimeError(
            f"only {st['pages']['prefix_hits']} prefix-cache hits across "
            f"{2 * n_requests} admissions — the shared 128-token system "
            "prefix should hit on every warm re-admission")
    return {
        "generate_longtail_tokens_s": _sane("generate_longtail_tokens_s",
                                            n_tokens / server_s),
        "generate_longtail_kv_compression": contiguous_bytes / pool_bytes,
        "generate_longtail_prefix_hits": float(
            st["pages"]["prefix_hits"]),
        "generate_longtail_prefix_tokens_reused": float(
            st["pages"]["prefix_tokens_reused"]),
        "generate_longtail_cow_copies": float(
            st["pages"]["cow_copies"]),
    }


def bench_generate_mesh(n_requests: int = 24, vocab: int = 256,
                        d_model: int = 256, n_blocks: int = 2,
                        n_heads: int = 8, slots: int = 12,
                        pages: int = 128, page_size: int = 16,
                        chip_budget_mb: float = 6.0, repeats: int = 2):
    """Tensor-parallel mesh-sharded paged decode: serve a TransformerLM
    whose page pool does NOT fit one chip's KV budget. The pool here is
    ~8 MiB against a {chip_budget_mb} MiB per-chip envelope — single-
    chip serving is over budget, and head-axis sharding is what brings
    the per-chip residency back inside it (pool/tp: under at tp=2, half
    the envelope at tp=4). Both facts are asserted from the server's
    OWN page accounting, not recomputed on faith.

    Runs the same mixed greedy workload at tp=1, tp=2 and tp=4 over the
    forced 8-virtual-device CPU mesh (standalone:
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8 python
    bench.py generate_mesh`` — main() sets the flag for this sub-bench
    when run standalone) and reports tokens/s per tp plus per-chip
    tokens/s. Every tp>1 completion is checked BIT-identical to the
    tp=1 server's — the zero-drift sharding contract is part of the
    bench, not a separate test. On CPU the \"chips\" share one socket,
    so the asserted scaling is the CAPACITY scaling (per-chip bytes =
    pool/tp, exact); wall-clock scaling is a real-mesh property and the
    reported ratios are informational with only a collapse floor
    asserted."""
    import os

    import jax

    flag = "--xla_force_host_platform_device_count=8"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    if len(jax.devices()) < 4:
        raise RuntimeError(
            f"generate_mesh needs >= 4 devices, found "
            f"{len(jax.devices())} — run standalone so XLA_FLAGS="
            f"{flag} lands before the backend initializes, or run on "
            "a real mesh")

    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.parallel.generation import GenerationServer

    rs = np.random.RandomState(13)
    shapes = [(6, 40), (14, 48), (6, 48), (14, 40)]  # (plen, max_tokens)
    reqs = [(rs.randint(0, vocab, shapes[i % 4][0]), shapes[i % 4][1])
            for i in range(n_requests)]
    n_tokens = sum(steps for _, steps in reqs)
    net = TransformerLM(num_labels=vocab, max_length=64, d_model=d_model,
                        n_heads=n_heads, n_blocks=n_blocks, seed=0).init()
    for v in net.conf.vertices.values():
        lyr = getattr(v, "layer", None)
        if lyr is not None and hasattr(lyr, "max_cache"):
            lyr.max_cache = 64

    budget = chip_budget_mb * 2**20

    def run_tp(tp):
        srv = GenerationServer(net, vocab, slots=slots, pages=pages,
                               page_size=page_size, steps_per_dispatch=8,
                               max_pending=max(64, n_requests), tp=tp)
        best = float("inf")
        try:
            st = srv.stats()["pages"]
            pool_bytes = (st["pages_total"] * st["page_size"]
                          * st["bytes_per_token"])
            for f in [srv.submit(p, 2) for p, _ in reqs[:2]]:  # warm
                f.result(timeout=SUB_BENCH_TIMEOUT_S)
            outs = None
            for _ in range(repeats):
                t0 = time.perf_counter()
                futs = [srv.submit(p, steps) for p, steps in reqs]
                outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S)
                        for f in futs]
                best = min(best, time.perf_counter() - t0)
        finally:
            srv.close()
        return pool_bytes, outs, n_tokens / best

    pool_bytes, base_outs, tps = {}, None, {}
    for tp in (1, 2, 4):
        pool_b, outs, tok_s = run_tp(tp)
        pool_bytes[tp] = pool_b
        tps[tp] = tok_s
        if base_outs is None:
            base_outs = outs
        else:
            bad = sum(1 for got, ref in zip(outs, base_outs)
                      if not np.array_equal(got, ref))
            if bad:
                raise RuntimeError(
                    f"{bad}/{n_requests} tp={tp} completions differ "
                    "from the tp=1 server's — head-axis sharding must "
                    "never change an output bit")

    # capacity scaling: the model is over budget single-chip, inside it
    # sharded — measured from the server's own page accounting
    if pool_bytes[1] <= budget:
        raise RuntimeError(
            f"pool {pool_bytes[1] / 2**20:.1f} MiB fits the "
            f"{chip_budget_mb} MiB chip budget single-chip — the bench "
            "must serve a model one chip CANNOT hold; grow pages/"
            "d_model or shrink the budget")
    for tp in (2, 4):
        per_chip = pool_bytes[tp] / tp
        if per_chip > budget:
            raise RuntimeError(
                f"tp={tp} leaves {per_chip / 2**20:.1f} MiB per chip — "
                f"still over the {chip_budget_mb} MiB budget")
    for tp in (2, 4):  # collapse floor only: real scaling needs a mesh
        if tps[tp] < 0.05 * tps[1]:
            raise RuntimeError(
                f"tp={tp} decode collapsed to {tps[tp]:.0f} tokens/s "
                f"vs {tps[1]:.0f} at tp=1 — sharding overhead ate the "
                "dispatch, not just the collectives")

    return {
        "generate_mesh_tp1_tokens_s": _sane(
            "generate_mesh_tp1_tokens_s", tps[1]),
        "generate_mesh_tp2_tokens_s": _sane(
            "generate_mesh_tp2_tokens_s", tps[2]),
        "generate_mesh_tp4_tokens_s": _sane(
            "generate_mesh_tp4_tokens_s", tps[4]),
        "generate_mesh_tp2_tokens_s_per_chip": _sane(
            "generate_mesh_tp2_tokens_s_per_chip", tps[2] / 2),
        "generate_mesh_tp4_tokens_s_per_chip": _sane(
            "generate_mesh_tp4_tokens_s_per_chip", tps[4] / 4),
        "generate_mesh_tp2_scaling": tps[2] / tps[1],
        "generate_mesh_tp4_scaling": tps[4] / tps[1],
        "generate_mesh_pool_mb": pool_bytes[1] / 2**20,
        "generate_mesh_chip_budget_mb": float(chip_budget_mb),
        "generate_mesh_tp4_per_chip_mb": pool_bytes[4] / 4 / 2**20,
    }


def bench_quant_serve(slots: int = 16, vocab: int = 256,
                      d_model: int = 256, n_blocks: int = 2,
                      repeats: int = 2):
    """Int8 paged KV-cache capacity at a FIXED page-byte budget: the same
    budget buys a f32 pool and an int8 pool (values stored int8 with
    per-token-per-head f32 dequant scales), so the int8 server fits
    >= 1.8x the concurrent sequences — asserted from the real allocated
    pools (``GenerationServer`` verifies its byte accounting against the
    arrays XLA materialised), not from a formula. Both servers then run
    the same greedy workload with INTERLEAVED timed passes (best pass
    each, same shared-noisy-box rationale as ``generate_serve``), and
    every int8 completion is gated on greedy agreement vs its f32
    reference — the capacity win does not get to cost correctness.

    Reports tokens/s for both pools, the capacity ratio, resident cache
    bytes, and the mean greedy-agreement score."""
    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.parallel.generation import GenerationServer

    page_size = 16
    max_cache = 64
    net = TransformerLM(num_labels=vocab, max_length=max_cache,
                        d_model=d_model, n_heads=8, n_blocks=n_blocks,
                        seed=0).init()
    for v in net.conf.vertices.values():
        lyr = getattr(v, "layer", None)
        if lyr is not None and hasattr(lyr, "max_cache"):
            lyr.max_cache = max_cache
    rs = np.random.RandomState(13)
    shapes = [(6, 26), (14, 18), (10, 22), (16, 16)]  # all span 2 pages
    reqs = [(rs.randint(0, vocab, shapes[i % 4][0]), shapes[i % 4][1])
            for i in range(2 * slots)]
    n_tokens = sum(steps for _, steps in reqs)

    # ONE byte budget, sized in f32 pages; each server converts it to
    # pages at ITS bytes-per-token (+1 garbage page apiece)
    f32_pages = 2 * slots + 1

    def probe_tok_bytes(kv_dtype):
        probe = GenerationServer(net, vocab, slots=1,
                                 page_size=page_size, pages=2,
                                 kv_dtype=kv_dtype)
        try:
            return probe._page_token_bytes
        finally:
            probe.close()

    f32_tok = probe_tok_bytes(None)
    int8_tok = probe_tok_bytes("int8")
    budget_bytes = f32_pages * page_size * f32_tok
    pages = {None: f32_pages,
             "int8": budget_bytes // (page_size * int8_tok)}
    capacity_ratio = pages["int8"] / pages[None]
    if capacity_ratio < 1.8:
        raise RuntimeError(
            f"int8 KV pool fits only {capacity_ratio:.2f}x the f32 "
            "sequences at the same byte budget — below the 1.8x bar "
            "the per-page scale planes were budgeted for")

    results = {}
    refs = None
    for kv_dtype in (None, "int8"):
        srv = GenerationServer(net, vocab, slots=slots,
                               page_size=page_size,
                               pages=int(pages[kv_dtype]),
                               steps_per_dispatch=8,
                               max_pending=2 * len(reqs),
                               kv_dtype=kv_dtype)
        try:
            st0 = srv.stats()  # also asserts page-byte accounting
            assert st0["pages"]["bytes_per_token"] * page_size \
                * st0["pages"]["pages_total"] <= budget_bytes + \
                page_size * f32_tok, "pool exceeds the byte budget"
            for f in [srv.submit(p, 2) for p, _ in reqs[:2]]:
                f.result(timeout=SUB_BENCH_TIMEOUT_S)
            best = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                futs = [srv.submit(p, s) for p, s in reqs]
                outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S)
                        for f in futs]
                best = min(best, time.perf_counter() - t0)
            st = srv.stats()
        finally:
            srv.close()
        if kv_dtype is None:
            refs = outs
        results[kv_dtype] = (best, outs, st)

    from deeplearning4j_tpu.optimize.quantize import greedy_agreement
    agreements = [greedy_agreement(got, ref)
                  for got, ref in zip(results["int8"][1], refs)]
    mean_agree = float(np.mean(agreements))
    if mean_agree < 0.95:
        raise RuntimeError(
            f"int8 KV greedy agreement {mean_agree:.3f} vs f32 — the "
            "capacity win is not allowed to corrupt decoding")
    f32_s, _, st_f = results[None]
    int8_s, _, st_q = results["int8"]
    return {
        "quant_serve_kv_capacity_x": capacity_ratio,
        "quant_serve_f32_tokens_s": _sane("quant_serve_f32_tokens_s",
                                          n_tokens / f32_s),
        "quant_serve_tokens_s": _sane("quant_serve_tokens_s",
                                      n_tokens / int8_s),
        "quant_serve_greedy_agreement": mean_agree,
        "quant_serve_kv_bytes_per_token": float(
            st_q["pages"]["bytes_per_token"]),
        "quant_serve_f32_kv_bytes_per_token": float(
            st_f["pages"]["bytes_per_token"]),
        "quant_serve_peak_resident_kv_bytes": float(
            st_q["pages"]["peak_resident_kv_bytes"]),
    }


def bench_quant_infer(n_requests: int = 256, max_batch: int = 64,
                      max_wait_ms: float = 2.0):
    """Int8-weight serving throughput: the ``inference_serve`` workload
    through ``ParallelInference(quantize="int8")`` — absmax per-channel
    int8 LeNet weights with the dequant fused into each matmul/conv —
    next to the f32 server, same coalescer settings. Gated on eval
    parity: the two servers' argmax decisions over the whole workload
    must agree on >= 99% of rows (random-weight LeNet logit gaps are
    tight, so this is a strict bound). Reports req/s for both paths."""
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    rs = np.random.RandomState(3)
    xs = rs.randn(n_requests, 1, 28, 28, 1).astype(np.float32)
    net = LeNet(num_labels=10).init()

    def run(quantize):
        with ParallelInference(net, max_batch=max_batch,
                               max_wait_ms=max_wait_ms,
                               max_pending=2 * n_requests,
                               quantize=quantize) as inf:
            inf.submit(xs[0]).result(timeout=120)
            inf.output(xs[:max_batch, 0])
            t0 = time.perf_counter()
            futs = [inf.submit(xs[i]) for i in range(n_requests)]
            rows = [f.result(timeout=120) for f in futs]
            total = time.perf_counter() - t0
        return total, np.concatenate([np.asarray(r) for r in rows])

    f32_s, f32_out = run(None)
    int8_s, int8_out = run("int8")
    agree = float((f32_out.argmax(-1) == int8_out.argmax(-1)).mean())
    if agree < 0.99:
        raise RuntimeError(
            f"int8-weight serving argmax agreement {agree:.3f} vs f32 "
            "— per-channel weight quantization should not move LeNet "
            "decisions at this rate")
    return {
        "quant_infer_f32_req_s": _sane("quant_infer_f32_req_s",
                                       n_requests / f32_s),
        "quant_infer_req_s": _sane("quant_infer_req_s",
                                   n_requests / int8_s),
        "quant_infer_argmax_agreement": agree,
    }


def bench_knn_serve(n_points: int = 1_000_000, d: int = 32,
                    partitions: int = 1024, nprobe: int = 8,
                    n_queries: int = 256, serial_queries: int = 64,
                    deadline_s: float = 10.0, max_wait_ms: float = 20.0):
    """Retrieval serving at the 1M-vector scale, over a clustered corpus
    (mixture of gaussians — the workload shape a partitioned index
    exists for; pure noise spreads every query's neighbors across cells
    and is gated in tests instead). Two int8 ``EmbeddingIndex`` builds
    over the SAME million vectors:

    * the FLAT store carries the coalescing claim: one-row requests are
      queried two ways — a serial ``submit().result()`` loop (each round
      trip pays the assembly window plus a full store sweep) and an
      open-loop burst the coalescer fuses into batched matmul+top_k
      dispatches that amortize the sweep. The assembly window is sized
      ~1% of the batched dispatch cost (20 ms vs ~2 s at this scale) —
      the production tuning for a store this large, and the price a
      one-row-at-a-time client honestly pays against it.
    * the IVF store (k-means partitions + nprobe gather + exact
      re-rank) carries the recall claim, plus the same open-loop
      deadline/ledger discipline.

    This is a gate, not just a read — the bench RAISES unless all of:
    coalesced throughput >= 5x the serial one-row loop, IVF recall@10
    >= 0.95 vs an exact search over the same 1M points, p99 latency
    (measured submit-to-resolution via done-callbacks, no coordinated
    omission) under the per-query deadline on BOTH stores, a zero-lost
    ledger (every admitted future resolves with rows or a typed error),
    and the int8 store holding >= 1.8x the vectors of f32 at equal
    bytes (measured from the real device arrays of twin stores, not a
    formula)."""
    from deeplearning4j_tpu.nearestneighbors.index import EmbeddingIndex
    from deeplearning4j_tpu.parallel.resilience import (CircuitOpen,
                                                        DeadlineExceeded,
                                                        ServerOverloaded)

    rs = np.random.RandomState(0)
    centers = rs.randn(partitions, d).astype(np.float32) * 2.0
    pts = (centers[rs.randint(0, partitions, n_points)]
           + rs.randn(n_points, d).astype(np.float32) * 0.6)
    qs = (pts[rs.choice(n_points, n_queries, replace=False)]
          + rs.randn(n_queries, d).astype(np.float32) * 0.2)

    # store-level capacity: twin FLAT stores over the same rows, ratio
    # read from the actual resident device arrays
    cap_n = 65536
    f32_twin = EmbeddingIndex(pts[:cap_n])
    int8_twin = EmbeddingIndex(pts[:cap_n], store="int8")
    capacity_x = f32_twin.resident_bytes / int8_twin.resident_bytes
    f32_twin.close()
    int8_twin.close()
    if capacity_x < 1.8:
        raise RuntimeError(
            f"int8 store holds only {capacity_x:.2f}x the f32 vectors at "
            "equal bytes — below the 1.8x bar the fused-dequant store "
            "was budgeted for")

    def open_loop(index, k=10):
        """Submit every query one-row with a deadline; resolve all of
        them and return (q/s over resolved, p99 ms, failed, lost)."""
        lat_s = []
        t_sub = {}
        failed = shed = ok = 0
        futs = []
        t0 = time.perf_counter()
        for i in range(n_queries):
            try:
                f = index.submit(qs[i:i + 1], k, deadline_s=deadline_s)
            except (ServerOverloaded, CircuitOpen):
                shed += 1
                continue
            t_sub[id(f)] = time.monotonic()
            f.add_done_callback(
                lambda f: lat_s.append(time.monotonic() - t_sub[id(f)]))
            futs.append(f)
        for f in futs:
            try:
                dd, _ii = f.result(timeout=SUB_BENCH_TIMEOUT_S)
                assert dd.shape == (1, k)
                ok += 1
            except (DeadlineExceeded, ServerOverloaded, CircuitOpen):
                failed += 1
        wall = time.perf_counter() - t0
        lost = n_queries - ok - failed - shed
        if lost:
            raise RuntimeError(
                f"{lost} of {n_queries} queries neither resolved nor "
                "failed typed — the serving ledger leaked futures")
        if ok == 0:
            raise RuntimeError("every query failed — nothing to report")
        p99_ms = float(np.percentile(np.asarray(lat_s) * 1e3, 99))
        if p99_ms >= deadline_s * 1e3:
            raise RuntimeError(
                f"p99 {p99_ms:.0f} ms breached the {deadline_s * 1e3:.0f} "
                "ms deadline — admitted queries not resolving in budget")
        return ok / wall, p99_ms, failed

    # --- flat int8 store: the coalescing gate -----------------------------
    flat = EmbeddingIndex(pts, store="int8", max_batch=n_queries,
                          max_wait_ms=max_wait_ms,
                          max_pending=4 * n_queries)
    try:
        q = 1
        while q <= n_queries:   # warm every pow2 row bucket in play
            flat.search_batch_arrays(qs[:q], 10)
            q *= 2
        t0 = time.perf_counter()
        for i in range(serial_queries):
            flat.submit(qs[i:i + 1], 10).result(
                timeout=SUB_BENCH_TIMEOUT_S)
        serial_q_s = serial_queries / (time.perf_counter() - t0)
        d0 = flat.stats()["dispatches"]
        coalesced_q_s, p99_ms, flat_failed = open_loop(flat)
        dispatches = flat.stats()["dispatches"] - d0
    finally:
        flat.close()
    if coalesced_q_s < 5.0 * serial_q_s:
        raise RuntimeError(
            f"coalesced {coalesced_q_s:.0f} q/s is only "
            f"{coalesced_q_s / serial_q_s:.1f}x the serial one-row loop "
            f"({serial_q_s:.0f} q/s) — below the 5x coalescing bar")

    # --- IVF int8 store: the recall gate ----------------------------------
    t0 = time.perf_counter()
    ivf = EmbeddingIndex(pts, store="int8", partitions=partitions,
                         nprobe=nprobe, train_sample=32768,
                         kmeans_iters=10, seed=0, max_batch=64,
                         max_wait_ms=2.0, max_pending=4 * n_queries)
    build_s = time.perf_counter() - t0
    try:
        recall = ivf.measure_recall(qs[:64], k=10)
        if recall < 0.95:
            raise RuntimeError(
                f"IVF recall@10 {recall:.3f} vs exact over the same "
                f"{n_points} points — below the 0.95 gate")
        q = 1
        while q <= 64:
            ivf.search_batch_arrays(qs[:q], 10)
            q *= 2
        ivf_q_s, ivf_p99_ms, _ivf_failed = open_loop(ivf)
        st = ivf.stats()
    finally:
        ivf.close()

    return {
        "knn_serve_q_s": _sane("knn_serve_q_s", coalesced_q_s),
        "knn_serve_serial_q_s": _sane("knn_serve_serial_q_s", serial_q_s),
        "knn_serve_coalesce_speedup": coalesced_q_s / serial_q_s,
        "knn_serve_ivf_q_s": _sane("knn_serve_ivf_q_s", ivf_q_s),
        "knn_serve_recall": recall,
        "knn_serve_p99_ms": p99_ms,
        "knn_serve_ivf_p99_ms": ivf_p99_ms,
        "knn_serve_int8_capacity_x": capacity_x,
        "knn_serve_build_s": build_s,
        "knn_serve_dispatches": float(dispatches),
        "knn_serve_lost": 0.0,
        "knn_serve_spilled": float(st.get("spilled", 0)),
    }


class _VirtualPassages:
    """Lazy deterministic passage store: doc id -> token ids, computed
    on demand (a 10M-document corpus never materializes — the RAG
    pipeline only ever touches the retrieved ids)."""

    def __init__(self, vocab: int, length: int = 24):
        self.vocab = int(vocab)
        self.length = int(length)

    def __getitem__(self, i: int):
        rs = np.random.RandomState((int(i) * 2654435761) & 0x7FFFFFFF)
        return rs.randint(1, self.vocab, size=self.length).astype(np.int64)


def bench_serve_rag(n_points: int = 10_000_000, d: int = 16,
                    partitions: int = 1024, nprobe: int = 8,
                    vocab: int = 64, n_requests: int = 96,
                    hot_candidates: int = 64, burst: int = 12,
                    max_tokens: int = 8, deadline_s: float = 60.0):
    """Retrieval-augmented generation at the 10M-vector scale: a
    Zipf-skewed query mix over an int8 IVF store drives the two-tier
    ``RagPipeline`` (knn tier -> canonical passage prefix -> generate
    tier) end to end. The passage corpus is a lazy virtual store — only
    retrieved documents ever materialize tokens.

    This is a gate, not just a read — the bench RAISES unless all of:
    IVF recall@10 >= 0.95 vs exact at the FULL 10M point, hot documents
    dedupe prefill through the chunk-hashed prefix cache
    (``prefix_hits``/``prefix_tokens_reused`` > 0 after the hot burst,
    and the hot burst's mean turn latency measurably below an
    equal-shape cold burst's), end-to-end p99 under the request
    deadline SLO with zero expired, and a zero-lost two-tier ledger
    (submitted == completed + failed + expired + rejected, inflight 0,
    every future resolved or typed)."""
    from deeplearning4j_tpu.models.zoo import TransformerLM
    from deeplearning4j_tpu.nearestneighbors.index import EmbeddingIndex
    from deeplearning4j_tpu.parallel.generation import GenerationServer
    from deeplearning4j_tpu.parallel.rag import RagPipeline
    from deeplearning4j_tpu.parallel.resilience import (CircuitOpen,
                                                        DeadlineExceeded,
                                                        ServerOverloaded)

    rs = np.random.RandomState(0)
    centers = rs.randn(partitions, d).astype(np.float32) * 2.0
    pts = np.empty((n_points, d), np.float32)
    CH = 1 << 20
    for s in range(0, n_points, CH):  # chunked: no 2nd 10M f32 transient
        m = min(CH, n_points - s)
        pts[s:s + m] = (centers[rs.randint(0, partitions, m)]
                        + rs.randn(m, d).astype(np.float32) * 0.6)

    t0 = time.perf_counter()
    index = EmbeddingIndex(pts, store="int8", partitions=partitions,
                           nprobe=nprobe, train_sample=32768,
                           kmeans_iters=10, seed=0, max_batch=64,
                           max_wait_ms=2.0, max_pending=4 * n_requests)
    build_s = time.perf_counter() - t0
    try:
        probe_qs = (pts[rs.choice(n_points, 32, replace=False)]
                    + rs.randn(32, d).astype(np.float32) * 0.2)
        recall = index.measure_recall(probe_qs, k=10)
    except Exception:
        index.close()
        raise
    if recall < 0.95:
        index.close()
        raise RuntimeError(
            f"IVF recall@10 {recall:.3f} vs exact over the same "
            f"{n_points} points — below the 0.95 gate")

    # Zipf-skewed document popularity over a hot candidate set: rank r
    # drawn with p(r) ~ 1/r^1.1, so a handful of documents dominate —
    # the regime the prefix-cache document cache exists for
    hot_ids = rs.choice(n_points, hot_candidates, replace=False)
    ranks = np.arange(1, hot_candidates + 1, dtype=np.float64)
    pz = (1.0 / ranks ** 1.1)
    pz /= pz.sum()
    targets = hot_ids[rs.choice(hot_candidates, n_requests, p=pz)]

    passages = _VirtualPassages(vocab, length=24)
    lm = TransformerLM(num_labels=vocab, max_length=128, d_model=16,
                       n_heads=2, n_blocks=1, seed=3).init()
    served = index  # ONE index instance serves the knn tier

    def knn_factory(rid):
        return served

    def gen_factory(rid):
        return GenerationServer(lm, vocab, slots=8, page_size=8)

    rag = RagPipeline(knn_factory, gen_factory, passages, page_size=8,
                      k=2, max_pending=4 * n_requests)
    prompt = np.arange(1, 9, dtype=np.int64)

    def q_for(doc, jitter):
        return pts[doc] + jitter * rs.randn(d).astype(np.float32)

    try:
        # warm the compile path twice: the first request compiles the
        # cold full-prefill bucket + knn programs, the SECOND (same
        # document) compiles the prefix-hit suffix-only prefill bucket
        hot_doc = int(targets[0])
        for _ in range(2):
            rag.submit(prompt, max_tokens,
                       query_vec=q_for(hot_doc, 0.0)).result(
                           timeout=SUB_BENCH_TIMEOUT_S)

        # hot-vs-cold prefill: equal-shape serial bursts; the hot burst
        # re-retrieves ONE document set (prefix pages already resident),
        # the cold burst a fresh document each turn
        t0 = time.perf_counter()
        for _ in range(burst):
            rag.submit(prompt, max_tokens,
                       query_vec=q_for(hot_doc, 0.0)).result(
                           timeout=SUB_BENCH_TIMEOUT_S)
        hot_ms = (time.perf_counter() - t0) * 1e3 / burst
        cold_ids = rs.choice(n_points, burst, replace=False)
        t0 = time.perf_counter()
        for cd in cold_ids:
            rag.submit(prompt, max_tokens,
                       query_vec=q_for(int(cd), 0.0)).result(
                           timeout=SUB_BENCH_TIMEOUT_S)
        cold_ms = (time.perf_counter() - t0) * 1e3 / burst
        st = rag.stats()
        if st["prefix_hits"] <= 0 or st["prefix_tokens_reused"] <= 0:
            raise RuntimeError(
                f"hot documents produced prefix_hits="
                f"{st['prefix_hits']} tokens_reused="
                f"{st['prefix_tokens_reused']} — the document cache "
                "never deduped a prefill")
        if not hot_ms < cold_ms:
            raise RuntimeError(
                f"hot-document turns ({hot_ms:.1f} ms) not below cold "
                f"({cold_ms:.1f} ms) — prefix reuse saved no prefill")

        # open-loop Zipf mix under the deadline SLO
        lat_s = []
        t_sub = {}
        failed = shed = ok = 0
        futs = []
        t0 = time.perf_counter()
        for i in range(n_requests):
            try:
                f = rag.submit(prompt, max_tokens,
                               query_vec=q_for(int(targets[i]), 0.05),
                               deadline_s=deadline_s)
            except (ServerOverloaded, CircuitOpen):
                shed += 1
                continue
            t_sub[id(f)] = time.monotonic()
            f.add_done_callback(
                lambda f: lat_s.append(time.monotonic() - t_sub[id(f)]))
            futs.append(f)
        for f in futs:
            try:
                out = f.result(timeout=SUB_BENCH_TIMEOUT_S)
                assert 1 <= len(out) <= max_tokens
                ok += 1
            except (DeadlineExceeded, ServerOverloaded, CircuitOpen):
                failed += 1
        wall = time.perf_counter() - t0
        lost = n_requests - ok - failed - shed
        if lost:
            raise RuntimeError(
                f"{lost} of {n_requests} requests neither resolved nor "
                "failed typed — the two-tier ledger leaked futures")
        if ok == 0:
            raise RuntimeError("every request failed — nothing to report")
        p99_ms = float(np.percentile(np.asarray(lat_s) * 1e3, 99))
        if p99_ms >= deadline_s * 1e3:
            raise RuntimeError(
                f"p99 {p99_ms:.0f} ms breached the {deadline_s * 1e3:.0f} "
                "ms deadline SLO")
        st = rag.stats()
        if st["expired"] != 0:
            raise RuntimeError(
                f"{st['expired']} requests expired inside the "
                f"{deadline_s}s SLO — deadline propagation is eating "
                "budget")
        if st["inflight"] != 0 or st["submitted"] != (
                st["completed"] + st["failed"] + st["expired"]
                + st["rejected"]):
            raise RuntimeError(
                f"two-tier ledger unbalanced: {st['submitted']} submitted "
                f"vs {st['completed']}+{st['failed']}+{st['expired']}"
                f"+{st['rejected']} resolved, {st['inflight']} in flight")
        prefix_hits = st["prefix_hits"]
        prefix_reused = st["prefix_tokens_reused"]
    finally:
        rag.close()

    return {
        "serve_rag_req_s": _sane("serve_rag_req_s", ok / wall),
        "serve_rag_p99_ms": p99_ms,
        "serve_rag_recall": recall,
        "serve_rag_hot_ms": hot_ms,
        "serve_rag_cold_ms": cold_ms,
        "serve_rag_prefill_savings_x": cold_ms / hot_ms,
        "serve_rag_prefix_hits": float(prefix_hits),
        "serve_rag_prefix_tokens_reused": float(prefix_reused),
        "serve_rag_points": float(n_points),
        "serve_rag_build_s": build_s,
        "serve_rag_lost": 0.0,
    }


def bench_serve_soak(duration_s: float = 8.0, lo: float = 1200.0,
                     hi: float = 1550.0, ramp_s: float = 3.0,
                     spike_add: float = 500.0, spike_at: float = 4.5,
                     spike_dur: float = 1.0, max_batch: int = 128,
                     slo_p99_ms: float = 1500.0,
                     min_req_s: float = 1400.0, seed: int = 0):
    """Closed-loop soak of the coalescing inference path under a seeded
    open-arrival load: a non-homogeneous Poisson process (linear ramp
    ``lo``->``hi`` req/s with a rectangular spike riding on top) drives
    single-image LeNet requests through ``ParallelInference`` while a
    queue-driven ``Autoscaler`` grows/shrinks the coalescer pool from
    observed backlog. Latency is measured from the SCHEDULED arrival
    (no coordinated omission: a stalled server inflates the tail, it
    cannot pace the generator down).

    This is an SLO gate, not just a throughput read — the bench RAISES
    unless all of: p99 under ``slo_p99_ms``, zero lost futures
    (submitted == completed + failed, the ledger the whole serving
    stack promises), zero failed at this admission headroom, and
    sustained throughput >= ``min_req_s``.

    Floor calibration: a bare submit loop saturates this coalescer at
    ~2300 single-row req/s, but that number has no pacing, no per-
    request latency capture, and no ledger — the honest end-to-end
    ceiling THROUGH the generator (scheduled sleeps, submit/record
    bookkeeping, registry publication, all GIL-serialized against the
    serving threads) measures 1700-2050 req/s across runs on this
    shared box, flat across 1-4 coalescers (host-bound, not device-
    bound). The offered profile averages ~1550 — under the noisy ceiling's
    LOW end, with ~10% further headroom — so the gate measures the
    serving path rather than the box's contention-of-the-minute,
    the spike still drives a real backlog through the autoscaler,
    and the floor sits ~10% under the offered average: box noise does not flake the gate, while a
    per-request regression in the submit/publication hot path still
    trips it. Deterministic under ``seed``: same arrival schedule,
    same request indices."""
    from deeplearning4j_tpu.metrics.autoscale import (Autoscaler,
                                                      CoalescerTarget)
    from deeplearning4j_tpu.metrics.loadgen import (LoadGenerator,
                                                    ramp_profile,
                                                    spike_profile)
    from deeplearning4j_tpu.metrics.registry import MetricsRegistry
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    rs = np.random.RandomState(3)
    xs = rs.randn(64, 1, 28, 28, 1).astype(np.float32)
    net = LeNet(num_labels=10).init()
    registry = MetricsRegistry()
    base = ramp_profile(lo, hi, ramp_s)
    burst = spike_profile(0.0, spike_add, spike_at, spike_dur)
    with ParallelInference(net, max_batch=max_batch, max_wait_ms=2.0,
                           max_pending=65536,
                           registry=registry) as inf:
        # warm every pow-2 coalescer bucket: a mid-soak XLA compile
        # would be a fake tail-latency event
        inf.submit(xs[0]).result(timeout=120)
        b = 2
        while b <= max_batch:
            inf.output(np.repeat(xs[0], b, axis=0))
            b *= 2
        lg = LoadGenerator(lambda i: inf.submit(xs[i % len(xs)]),
                           seed=seed, registry=registry)
        scaler = Autoscaler([CoalescerTarget(inf)], high_depth=64,
                            low_depth=8, up_ticks=2, down_ticks=10,
                            cooldown_s=1.0, registry=registry)
        scaler.start(interval_s=0.2)
        try:
            res = lg.run_open(lambda t: base(t) + burst(t), duration_s,
                              rate_max=hi + spike_add,
                              timeout_s=SUB_BENCH_TIMEOUT_S)
        finally:
            scaler.stop()
        st = inf.stats()
    if res.lost:  # the zero-lost-futures ledger is the point
        raise RuntimeError(
            f"soak leaked {res.lost} futures (submitted "
            f"{res.submitted}, completed {res.completed}, failed "
            f"{res.failed})")
    if res.failed:
        raise RuntimeError(
            f"{res.failed} soak requests failed typed ({res.errors}) "
            "despite admission headroom — serving regression")
    if st["completed"] < res.completed:
        raise RuntimeError(
            "registry ledger disagrees with the load generator: "
            f"inference completed {st['completed']} < soak completed "
            f"{res.completed}")
    p50 = res.quantile(0.5)
    p99 = res.quantile(0.99)
    if not p99 < slo_p99_ms:
        raise RuntimeError(
            f"soak p99 {p99:.1f} ms breaches the {slo_p99_ms:.0f} ms "
            "SLO — backlog never drained")
    if res.achieved_req_s < min_req_s:
        raise RuntimeError(
            f"soak sustained {res.achieved_req_s:.0f} req/s — below "
            f"the {min_req_s:.0f} req/s floor")
    ups = sum(1 for d in scaler.decisions if d.action == "scale_up")
    downs = sum(1 for d in scaler.decisions if d.action == "scale_down")
    return {
        "serve_soak_req_s": _sane("serve_soak_req_s",
                                  res.achieved_req_s),
        "serve_soak_offered_req_s": _sane(
            "serve_soak_offered_req_s", res.submitted / duration_s),
        "serve_soak_p50_ms": p50,
        "serve_soak_p99_ms": p99,
        "serve_soak_submitted": float(res.submitted),
        "serve_soak_lost": float(res.lost),
        "serve_soak_scale_ups": float(ups),
        "serve_soak_scale_downs": float(downs),
        "serve_soak_final_workers": float(inf.coalescer_workers),
        "serve_soak_dispatches": float(st["dispatches"]),
    }


def bench_serve_restart(n_requests: int = 72, vocab: int = 17,
                        rate_req_s: float = 120.0, seed: int = 0):
    """Rolling supervised restart under load: a two-replica generation
    fleet serves a seeded Poisson arrival stream while one replica's
    decode loop thread is KILLED in place mid-stream (chaos lands a
    ``LoopKilled`` during a drain-migrate pass) and the runtime's
    ``LoopSupervisor`` restarts the same server — no fleet respawn, no
    replacement replica, the rolling-restart primitive the unified
    runtime exists to make safe.

    Three gates, all in-bench:

    * zero lost futures — the fleet parks the victim's in-flight work
      and redispatches it, so every accepted request completes; the
      ledger (submitted == completed + rejected_submits, nothing left
      in flight / parked / failed / expired) is asserted from the fleet
      counters;
    * bit-exact completions — every output matches its serial greedy
      reference, across the redispatch (the fold_in key schedule makes
      regeneration exact on any replica);
    * bounded tail — latency is measured from the SCHEDULED Poisson
      arrival (no coordinated omission), and the restart pass's p99
      must stay within 2x of the steady-state pass's p99 on the same
      schedule."""
    from deeplearning4j_tpu.models.zoo import TransformerLM, greedy_generate
    from deeplearning4j_tpu.parallel.fleet import READY, ReplicaFleet
    from deeplearning4j_tpu.parallel.generation import GenerationServer
    from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                        ResilienceError)

    net = TransformerLM(num_labels=vocab, max_length=16, d_model=16,
                        n_heads=2, n_blocks=1, seed=3).init()
    rng = np.random.default_rng(42 + seed)
    shapes = [(3, 4), (5, 5), (4, 6)]  # (plen, steps): bounded programs
    specs = [(rng.integers(1, vocab,
                           size=shapes[i % len(shapes)][0]).astype(np.int64),
              shapes[i % len(shapes)][1])
             for i in range(n_requests)]
    refs = [greedy_generate(net, p[None], steps, vocab)[0]
            for p, steps in specs]
    gaps = rng.exponential(1.0 / rate_req_s, size=n_requests)

    chaos_by_rid = {}

    def factory(rid):
        # the kill is drawn ONLY on a drain/migration pass, so steady
        # serving is chaos-free and the two passes differ by exactly
        # the one injected loop death
        chaos_by_rid[rid] = ChaosPolicy(seed=1000 + rid,
                                        kill_during_drain_rate=1.0)
        return GenerationServer(net, vocab, slots=4,
                                chaos=chaos_by_rid[rid])

    def submit_retry(fl, spec):
        p, steps = spec
        t_end = time.monotonic() + SUB_BENCH_TIMEOUT_S
        while True:
            try:
                return fl.submit(p, steps, deadline_s=SUB_BENCH_TIMEOUT_S)
            except ResilienceError:
                if time.monotonic() > t_end:
                    raise
                time.sleep(0.01)

    def run_pass(fl, srv0, restart_mid):
        restarts0 = srv0._runtime.restarts
        done_at = [None] * n_requests
        roller = None

        def make_cb(i):
            def cb(_fut):
                done_at[i] = time.perf_counter()
            return cb

        t0 = time.perf_counter()
        futs = []
        sched = []
        due = t0
        for i, spec in enumerate(specs):
            due += gaps[i]
            delay = due - time.perf_counter()
            if delay > 0:  # a lagging server never paces arrivals down
                time.sleep(delay)
            sched.append(due)
            f = submit_retry(fl, spec)
            f.add_done_callback(make_cb(i))
            futs.append(f)
            if restart_mid and i == n_requests // 3:
                # in-place rolling restart: the migrate pass arms the
                # chaos kill, the supervisor restarts the SAME server
                roller = threading.Thread(
                    target=lambda: srv0.drain(timeout=30, migrate=True),
                    daemon=True)
                roller.start()
        outs = [f.result(timeout=SUB_BENCH_TIMEOUT_S) for f in futs]
        total = time.perf_counter() - t0
        if roller is not None:
            roller.join(timeout=30)
        bad = sum(1 for o, ref in zip(outs, refs)
                  if not np.array_equal(np.asarray(o), ref))
        if bad:
            raise RuntimeError(
                f"{bad}/{n_requests} completions differ from their serial "
                "references across the supervised restart")
        if restart_mid:
            t_end = time.monotonic() + 30.0
            while srv0._runtime.restarts <= restarts0:
                if time.monotonic() > t_end:
                    raise RuntimeError(
                        "the chaos kill never produced a supervised "
                        "restart — the rolling-restart path was not "
                        "exercised")
                time.sleep(0.02)
            if chaos_by_rid[0].injected_drain_kill < 1:
                raise RuntimeError("drain-kill chaos armed but never drew")
        lat_ms = sorted((d - s) * 1e3 for d, s in zip(done_at, sched))
        return total, lat_ms

    fl = ReplicaFleet(factory, replicas=2, max_pending=2 * n_requests,
                      replica_max_pending=2 * n_requests,
                      restart_backoff_s=0.05)
    try:
        with fl._cond:
            srv0 = fl._replicas[0].server
        # warm every program on both replicas
        run_pass(fl, srv0, restart_mid=False)
        steady_total, steady_lat = run_pass(fl, srv0, restart_mid=False)
        restart_total, restart_lat = run_pass(fl, srv0, restart_mid=True)
        loop_restarts = srv0._runtime.restarts
        # the restarted replica must be back in service before the
        # ledger read, or in-flight bookkeeping muddies the counters
        t_end = time.monotonic() + 30.0
        st = fl.stats()
        while any(r["state"] != READY for r in st["replicas"]):
            if time.monotonic() > t_end:
                break
            time.sleep(0.02)
            st = fl.stats()
    finally:
        fl.close()
    lost = st["submitted"] - st["completed"] - st["rejected_submits"]
    if lost or st["inflight"] or st["parked"] or st["failed"] \
            or st["expired"]:
        raise RuntimeError(
            f"rolling restart leaked {lost} futures (inflight "
            f"{st['inflight']}, parked {st['parked']}, failed "
            f"{st['failed']}, expired {st['expired']})")
    p99_steady = _serve_latency_quantiles(
        steady_lat, "x")["x_p99_ms"]
    p99_restart = _serve_latency_quantiles(
        restart_lat, "x")["x_p99_ms"]
    if p99_steady > 0 and p99_restart > 2.0 * p99_steady:
        raise RuntimeError(
            f"restart-pass p99 {p99_restart:.1f} ms exceeds 2x the "
            f"steady-state p99 {p99_steady:.1f} ms — the supervised "
            "restart is not transparent enough")
    return {
        "serve_restart_req_s": _sane("serve_restart_req_s",
                                     n_requests / restart_total),
        "serve_restart_steady_req_s": _sane(
            "serve_restart_steady_req_s", n_requests / steady_total),
        "serve_restart_p99_ms": p99_restart,
        "serve_restart_steady_p99_ms": p99_steady,
        "serve_restart_p99_ratio": (p99_restart / p99_steady
                                    if p99_steady > 0 else 0.0),
        "serve_restart_loop_restarts": float(loop_restarts),
        "serve_restart_redispatched": float(st["redispatched"]),
    }


def bench_metrics_overhead(n_requests: int = 1024, max_batch: int = 128,
                           reps: int = 5):
    """Registry publication cost on the two hot serving paths
    (acceptance: <2%, the guard_overhead discipline). Each leg runs an
    identical workload twice — once against the real leaf-locked
    ``MetricsRegistry``, once against the no-op ``NullRegistry`` — and
    reports the throughput delta as a percentage.

    Leg 1 is the ``inference_serve`` worst case (every request one
    LeNet row, all batching the coalescer's): counter incs + latency
    histogram per request. Leg 2 is continuous-batching generation on
    a deliberately SMALL TransformerLM — decode steps are cheap, so
    the per-dispatch publication cost is measured against the least
    compute it could hide behind. Median of ``reps`` timed passes per
    leg, all samples recorded; the bench RAISES past the 2% gate."""
    from deeplearning4j_tpu.metrics.registry import (MetricsRegistry,
                                                     NullRegistry)
    from deeplearning4j_tpu.models import LeNet, TransformerLM
    from deeplearning4j_tpu.parallel.generation import GenerationServer
    from deeplearning4j_tpu.parallel.inference import ParallelInference

    rs = np.random.RandomState(3)
    xs = rs.randn(256, 1, 28, 28, 1).astype(np.float32)
    net = LeNet(num_labels=10).init()

    def inf_leg(make_reg):
        with ParallelInference(net, max_batch=max_batch,
                               max_wait_ms=2.0,
                               max_pending=4 * n_requests,
                               registry=make_reg()) as inf:
            inf.submit(xs[0]).result(timeout=120)
            inf.output(xs[:max_batch, 0])
            samples = []
            for _ in range(reps):
                t0 = time.perf_counter()
                futs = [inf.submit(xs[i % len(xs)])
                        for i in range(n_requests)]
                for f in futs:
                    f.result(timeout=120)
                samples.append(n_requests / (time.perf_counter() - t0))
        return float(np.median(samples)), [round(s, 1) for s in samples]

    vocab = 256
    lm = TransformerLM(num_labels=vocab, max_length=64, d_model=64,
                       n_heads=4, n_blocks=2, seed=0).init()
    for v in lm.conf.vertices.values():
        lyr = getattr(v, "layer", None)
        if lyr is not None and hasattr(lyr, "max_cache"):
            lyr.max_cache = 64
    shapes = [(6, 24), (14, 32), (6, 32), (14, 24)]
    reqs = [(rs.randint(0, vocab, shapes[i % 4][0]), shapes[i % 4][1])
            for i in range(32)]
    n_tokens = sum(steps for _, steps in reqs)

    def gen_leg(make_reg):
        srv = GenerationServer(lm, vocab, slots=16, steps_per_dispatch=8,
                               max_pending=128, registry=make_reg())
        try:
            for f in [srv.submit(p, 2) for p, _ in reqs[:2]]:
                f.result(timeout=SUB_BENCH_TIMEOUT_S)
            samples = []
            for _ in range(reps):
                t0 = time.perf_counter()
                futs = [srv.submit(p, steps) for p, steps in reqs]
                for f in futs:
                    f.result(timeout=SUB_BENCH_TIMEOUT_S)
                samples.append(n_tokens / (time.perf_counter() - t0))
        finally:
            srv.close()
        return float(np.median(samples)), [round(s, 1) for s in samples]

    out = {}
    for prefix, leg, unit_key in (("metrics", inf_leg, "req_s"),
                                  ("metrics_gen", gen_leg, "tokens_s")):
        off, off_samples = leg(NullRegistry)
        on, on_samples = leg(MetricsRegistry)
        pct = (off - on) / off * 100.0
        if pct > 2.0:
            raise RuntimeError(
                f"{prefix} publication overhead {pct:.2f}% — above the "
                "2% gate the boundary-only-writes design exists to "
                "clear")
        out[f"{prefix}_off_{unit_key}"] = _sane(
            f"{prefix}_off_{unit_key}", off)
        out[f"{prefix}_off_samples"] = off_samples
        out[f"{prefix}_on_{unit_key}"] = _sane(
            f"{prefix}_on_{unit_key}", on)
        out[f"{prefix}_on_samples"] = on_samples
        out[f"{prefix}_overhead_pct"] = pct
    return out


def bench_word2vec(n_sentences: int = 50000, epochs: int = 1):
    """SkipGram words/s on a synthetic 1M-word corpus, 30k vocab (BASELINE
    config #4; corpus sized so fixed host/dispatch overheads are amortised
    — a 40k-word corpus measured overhead, not throughput).

    Measures BOTH backends — the native C hot loop (the reference's own
    architecture, its SkipGram hot op being a libnd4j kernel) and the
    device scatter path — as separate recorded medians;
    'word2vec_words_s' is the better of the two, because they are
    different IMPLEMENTATIONS a user picks between per environment (the
    native path rides one host core and collapses under host load; the
    device path rides the chip and collapses under tunnel contention),
    not samples of one implementation. The measured reference-rate
    baseline is profiles/chip_session_results.json 'w2v_native_baseline'
    (profiles/w2v_baseline.py — same corpus, same config)."""
    from deeplearning4j_tpu.nlp import CollectionSentenceIterator, Word2Vec

    rs = np.random.RandomState(3)
    vocab = [f"w{i}" for i in range(30000)]
    zipf = rs.zipf(1.3, size=n_sentences * 20)
    zipf = np.minimum(zipf - 1, len(vocab) - 1)
    sentences = [" ".join(vocab[z] for z in zipf[i * 20:(i + 1) * 20])
                 for i in range(n_sentences)]
    total_words = n_sentences * 20 * epochs
    out = {}
    for key, backend in (("word2vec_native_words_s", "auto"),
                         ("word2vec_device_words_s", "device")):
        w2v = Word2Vec(layer_size=128, window=5, min_word_frequency=2,
                       negative=5, use_hierarchic_softmax=False,
                       epochs=epochs, batch_size=8192, backend=backend)
        w2v.build_vocab(sentences)
        w2v.reset_weights()
        # steady-state convention (same as MarginalTimer): one warmup fit
        # compiles the epoch program; the timed fit re-trains from fresh
        # weights on identical shapes, so the measurement is throughput,
        # not XLA compile. (The native path has no compile; warmup then
        # only pays the corpus tokenization cache-warm.)
        w2v.fit(CollectionSentenceIterator(sentences))
        # median of 3 timed fits, all recorded (same median-of-windows
        # methodology as the chip metrics: the native path rides ONE host
        # core whose contention swings it like the tunnel swings the chip)
        samples = []
        for _ in range(3):
            w2v.reset_weights()
            t0 = time.perf_counter()
            w2v.fit(CollectionSentenceIterator(sentences))
            if not isinstance(w2v.syn0, np.ndarray):
                # device path: force execution completion. The native
                # path is a synchronous C call on host arrays — _sync
                # would instead measure a 9 MB table UPLOAD.
                _sync(w2v.syn0)
            samples.append(total_words / (time.perf_counter() - t0))
        out[key] = _sane("word2vec_words_s", float(np.median(samples)))
        out[f"{key}_samples"] = [round(v, 1) for v in samples]
    # fails loudly if a backend leg is renamed/missing (see the loop keys)
    out["word2vec_words_s"] = max(out["word2vec_native_words_s"],
                                  out["word2vec_device_words_s"])
    return out


def bench_doc2vec(n_docs: int = 4000, epochs: int = 1):
    """DBOW words/s (reference: dl4j-examples ParagraphVectors workloads).
    Measures both backends like bench_word2vec (separate medians, the
    better one as 'doc2vec_words_s' — different implementations, not
    samples): 'auto' routes to the native DBOW pair kernel, the
    DBOW.java analog."""
    from deeplearning4j_tpu.nlp import ParagraphVectors
    from deeplearning4j_tpu.nlp.tokenization import LabelledDocument

    rs = np.random.RandomState(5)
    vocab = [f"w{i}" for i in range(5000)]
    zipf = np.minimum(rs.zipf(1.3, size=n_docs * 40) - 1, len(vocab) - 1)
    docs = [LabelledDocument(
        " ".join(vocab[z] for z in zipf[i * 40:(i + 1) * 40]), f"doc_{i}")
        for i in range(n_docs)]
    total_words = n_docs * 40 * epochs
    out = {}
    for key, backend in (("doc2vec_native_words_s", "auto"),
                         ("doc2vec_device_words_s", "device")):
        pv = ParagraphVectors(layer_size=100, window=5,
                              min_word_frequency=2, negative=5,
                              use_hierarchic_softmax=False, epochs=epochs,
                              sequence_algorithm="dbow", seed=11,
                              backend=backend)
        pv.build_vocab_from_documents(docs)
        pv.reset_weights()
        pv.fit(docs)          # warmup: compiles the epoch program
        samples = []
        for _ in range(3):    # median of 3, as in bench_word2vec
            pv.syn0 = None
            pv.reset_weights()
            t0 = time.perf_counter()
            pv.fit(docs)
            if not isinstance(pv.syn0, np.ndarray):
                _sync(pv.syn0)  # device path only; native is synchronous
            samples.append(total_words / (time.perf_counter() - t0))
        out[key] = _sane("doc2vec_words_s", float(np.median(samples)))
        out[f"{key}_samples"] = [round(v, 1) for v in samples]
    out["doc2vec_words_s"] = max(out["doc2vec_native_words_s"],
                                 out["doc2vec_device_words_s"])
    return out


# Physically-possible ceilings per metric (an order of magnitude above any
# plausible single-chip result): a number past one of these is a harness
# bug, and publishing it poisons every number beside it. Refuse instead.
SANITY_CEILING = {
    "lenet_mnist_img_s": 1e8,
    "fit_e2e_img_s": 1e8,
    "eval_e2e_img_s": 1e8,
    "guard_on_img_s": 1e8,
    "guard_off_img_s": 1e8,
    "inference_serve_req_s": 1e8,
    "serve_soak_req_s": 1e8,
    "serve_soak_offered_req_s": 1e8,
    "metrics_off_req_s": 1e8,
    "metrics_on_req_s": 1e8,
    "metrics_gen_off_tokens_s": 1e9,
    "metrics_gen_on_tokens_s": 1e9,
    "serve_chaos_req_s": 1e8,
    "serve_fleet_req_s": 1e8,
    "serve_fleet_1rep_req_s": 1e8,
    "serve_federated_req_s": 1e8,
    "serve_federated_1host_req_s": 1e8,
    "serve_handoff_req_s": 1e8,
    "serve_restart_req_s": 1e8,
    "serve_restart_steady_req_s": 1e8,
    "serve_disagg_req_s": 1e8,
    "serve_colo_req_s": 1e8,
    "generate_serve_tokens_s": 1e9,
    "generate_serve_serial_tokens_s": 1e9,
    "generate_longtail_tokens_s": 1e9,
    "generate_mesh_tp1_tokens_s": 1e9,
    "generate_mesh_tp2_tokens_s": 1e9,
    "generate_mesh_tp4_tokens_s": 1e9,
    "generate_mesh_tp2_tokens_s_per_chip": 1e9,
    "generate_mesh_tp4_tokens_s_per_chip": 1e9,
    "quant_serve_tokens_s": 1e9,
    "quant_serve_f32_tokens_s": 1e9,
    "quant_infer_req_s": 1e8,
    "quant_infer_f32_req_s": 1e8,
    "knn_serve_q_s": 1e8,
    "knn_serve_serial_q_s": 1e8,
    "knn_serve_ivf_q_s": 1e8,
    "serve_rag_req_s": 1e6,
    "paged_attn_t128_xla_tokens_s": 1e9,
    "paged_attn_t128_kernel_tokens_s": 1e9,
    "paged_attn_t128_int8_xla_tokens_s": 1e9,
    "paged_attn_t128_int8_kernel_tokens_s": 1e9,
    "paged_attn_t2048_xla_tokens_s": 1e9,
    "paged_attn_t2048_kernel_tokens_s": 1e9,
    "paged_attn_t2048_int8_xla_tokens_s": 1e9,
    "paged_attn_t2048_int8_kernel_tokens_s": 1e9,
    "vgg16_bf16_img_s": 1e5,
    "textgen_lstm_tokens_s": 1e9,
    "transformer_lm_tokens_s": 1e9,
    "word2vec_words_s": 1e8,
    "doc2vec_words_s": 1e8,
    "resnet50_bf16_img_s": 1e5,
    "resnet50_img_per_sec_per_chip": 1e5,
}


def _sane(name: str, value: float) -> float:
    ceiling = SANITY_CEILING[name]
    if not value < ceiling:
        raise RuntimeError(
            f"benchmark '{name}' produced {value:.4g}, above the physical "
            f"ceiling {ceiling:.0g} — harness bug; refusing to publish")
    return value


# unit per metric key — single source for stderr logging AND the JSON
# "unit" field when a sub-metric is run standalone
METRIC_UNIT = {
    "lenet_mnist_img_s": "img/s",
    "fit_e2e_img_s": "img/s",
    "fit_e2e_unfused_img_s": "img/s",
    "fit_e2e_fused_speedup": "x",
    "eval_e2e_img_s": "img/s",
    "eval_e2e_unfused_img_s": "img/s",
    "eval_e2e_fused_speedup": "x",
    "guard_on_img_s": "img/s",
    "guard_off_img_s": "img/s",
    "guard_overhead_pct": "%",
    "inference_serve_req_s": "req/s",
    "inference_serve_p50_ms": "ms",
    "inference_serve_p99_ms": "ms",
    "inference_serve_dispatches": "",
    "serve_soak_req_s": "req/s",
    "serve_soak_offered_req_s": "req/s",
    "serve_soak_p50_ms": "ms",
    "serve_soak_p99_ms": "ms",
    "serve_soak_submitted": "",
    "serve_soak_lost": "",
    "serve_soak_scale_ups": "",
    "serve_soak_scale_downs": "",
    "serve_soak_final_workers": "",
    "serve_soak_dispatches": "",
    "metrics_off_req_s": "req/s",
    "metrics_on_req_s": "req/s",
    "metrics_overhead_pct": "%",
    "metrics_gen_off_tokens_s": "tokens/s",
    "metrics_gen_on_tokens_s": "tokens/s",
    "metrics_gen_overhead_pct": "%",
    "serve_chaos_req_s": "req/s",
    "serve_chaos_p50_ms": "ms",
    "serve_chaos_p99_ms": "ms",
    "serve_chaos_typed_failure_frac": "",
    "serve_chaos_retries": "",
    "serve_chaos_injected_faults": "",
    "serve_fleet_req_s": "req/s",
    "serve_fleet_1rep_req_s": "req/s",
    "serve_fleet_scaling": "x",
    "serve_fleet_p50_ms": "ms",
    "serve_fleet_p99_ms": "ms",
    "serve_fleet_deaths": "",
    "serve_fleet_restarts": "",
    "serve_fleet_redispatched": "",
    "serve_federated_req_s": "req/s",
    "serve_federated_1host_req_s": "req/s",
    "serve_federated_scaling": "x",
    "serve_federated_p50_ms": "ms",
    "serve_federated_p99_ms": "ms",
    "serve_federated_deaths": "",
    "serve_federated_handoff_resumes": "",
    "serve_federated_redispatched": "",
    "serve_restart_req_s": "req/s",
    "serve_restart_steady_req_s": "req/s",
    "serve_restart_p99_ms": "ms",
    "serve_restart_steady_p99_ms": "ms",
    "serve_restart_p99_ratio": "x",
    "serve_restart_loop_restarts": "",
    "serve_restart_redispatched": "",
    "serve_handoff_req_s": "req/s",
    "serve_handoff_recompute_tokens": "tokens",
    "serve_handoff_token0_recompute_tokens": "tokens",
    "serve_handoff_recompute_frac": "",
    "serve_handoff_resumes": "",
    "serve_handoff_tokens_saved": "tokens",
    "serve_handoff_snapshot_bytes": "B",
    "serve_disagg_req_s": "req/s",
    "serve_colo_req_s": "req/s",
    "serve_disagg_ttft_p50_ms": "ms",
    "serve_disagg_ttft_p99_ms": "ms",
    "serve_disagg_itl_p50_ms": "ms",
    "serve_disagg_itl_p99_ms": "ms",
    "serve_colo_ttft_p50_ms": "ms",
    "serve_colo_ttft_p99_ms": "ms",
    "serve_colo_itl_p50_ms": "ms",
    "serve_disagg_ttft_slo_ms": "ms",
    "serve_disagg_tier_handoffs": "",
    "serve_disagg_chaos_redispatched": "",
    "serve_disagg_degraded_submits": "",
    "generate_serve_tokens_s": "tokens/s",
    "generate_serve_serial_tokens_s": "tokens/s",
    "generate_serve_speedup": "x",
    "generate_serve_p50_ms": "ms",
    "generate_serve_p99_ms": "ms",
    "generate_longtail_tokens_s": "tokens/s",
    "generate_longtail_kv_compression": "x",
    "generate_longtail_prefix_hits": "hits",
    "generate_longtail_prefix_tokens_reused": "tokens",
    "generate_longtail_cow_copies": "copies",
    "generate_mesh_tp1_tokens_s": "tokens/s",
    "generate_mesh_tp2_tokens_s": "tokens/s",
    "generate_mesh_tp4_tokens_s": "tokens/s",
    "generate_mesh_tp2_tokens_s_per_chip": "tokens/s/chip",
    "generate_mesh_tp4_tokens_s_per_chip": "tokens/s/chip",
    "generate_mesh_tp2_scaling": "x",
    "generate_mesh_tp4_scaling": "x",
    "generate_mesh_pool_mb": "MiB",
    "generate_mesh_chip_budget_mb": "MiB",
    "generate_mesh_tp4_per_chip_mb": "MiB",
    "quant_serve_kv_capacity_x": "x",
    "quant_serve_tokens_s": "tokens/s",
    "quant_serve_f32_tokens_s": "tokens/s",
    "quant_serve_greedy_agreement": "",
    "quant_serve_kv_bytes_per_token": "B",
    "quant_serve_f32_kv_bytes_per_token": "B",
    "quant_serve_peak_resident_kv_bytes": "B",
    "quant_infer_req_s": "req/s",
    "quant_infer_f32_req_s": "req/s",
    "quant_infer_argmax_agreement": "",
    "knn_serve_q_s": "q/s",
    "knn_serve_serial_q_s": "q/s",
    "knn_serve_ivf_q_s": "q/s",
    "knn_serve_coalesce_speedup": "x",
    "knn_serve_recall": "",
    "knn_serve_p99_ms": "ms",
    "knn_serve_ivf_p99_ms": "ms",
    "knn_serve_int8_capacity_x": "x",
    "knn_serve_build_s": "s",
    "knn_serve_dispatches": "",
    "knn_serve_lost": "",
    "knn_serve_spilled": "",
    "serve_rag_req_s": "req/s",
    "serve_rag_p99_ms": "ms",
    "serve_rag_recall": "",
    "serve_rag_hot_ms": "ms",
    "serve_rag_cold_ms": "ms",
    "serve_rag_prefill_savings_x": "x",
    "serve_rag_prefix_hits": "",
    "serve_rag_prefix_tokens_reused": "",
    "serve_rag_points": "",
    "serve_rag_build_s": "s",
    "serve_rag_lost": "",
    "vgg16_bf16_img_s": "img/s",
    "textgen_lstm_tokens_s": "tokens/s",
    "transformer_lm_tokens_s": "tokens/s",
    "word2vec_words_s": "words/s",
    "word2vec_native_words_s": "words/s",
    "word2vec_device_words_s": "words/s",
    "doc2vec_words_s": "words/s",
    "doc2vec_native_words_s": "words/s",
    "doc2vec_device_words_s": "words/s",
    "resnet50_bf16_img_s": "img/s",
    "resnet50_img_per_sec_per_chip": "img/s",
    "attention_t4096_stock_ms": "ms",
    "attention_t4096_flash_ms": "ms",
    "attention_flash_speedup": "x",
    "paged_attn_t128_xla_tokens_s": "tokens/s",
    "paged_attn_t128_kernel_tokens_s": "tokens/s",
    "paged_attn_t128_kernel_speedup": "x",
    "paged_attn_t128_int8_xla_tokens_s": "tokens/s",
    "paged_attn_t128_int8_kernel_tokens_s": "tokens/s",
    "paged_attn_t128_int8_kernel_speedup": "x",
    "paged_attn_t2048_xla_tokens_s": "tokens/s",
    "paged_attn_t2048_kernel_tokens_s": "tokens/s",
    "paged_attn_t2048_kernel_speedup": "x",
    "paged_attn_t2048_int8_xla_tokens_s": "tokens/s",
    "paged_attn_t2048_int8_kernel_tokens_s": "tokens/s",
    "paged_attn_t2048_int8_kernel_speedup": "x",
    "attention_bwd_t2048_stock_ms": "ms",
    "attention_bwd_t2048_flash_ms": "ms",
    "attention_bwd_flash_speedup": "x",
    "attention_bwd_t4096_stock_ms": "ms",
    "attention_bwd_t4096_flash_ms": "ms",
    "attention_bwd_t4096_speedup": "x",
}


# Hard per-benchmark wall-clock cap. A wedged device tunnel makes even
# jax.devices() block forever; a benchmark that cannot finish in this time
# is not producing a number anyway, and hanging the round-end bench run is
# strictly worse than reporting the failure. First-compile of the biggest
# model through the remote-compile tunnel is minutes-class — 20 min is an
# order of magnitude of headroom, not a tight budget.
SUB_BENCH_TIMEOUT_S = 1200


# extras snapshot for the hard-exit path: completed metrics are flushed as
# a JSON line even when a later benchmark wedges beyond recovery
_COMPLETED_EXTRAS: dict = {}


class _Watchdog:
    """Two-layer wall-clock cap (unix, main thread):

    1. SIGALRM raises TimeoutError at the deadline — recoverable, lets the
       remaining sub-benchmarks run. Only works for hangs that return to
       the interpreter (CPython runs signal handlers at bytecode
       boundaries).
    2. A daemon Timer thread fires 60s later as the backstop for the hang
       SIGALRM cannot break: the main thread parked inside a C call (PJRT
       client init dialing a dead tunnel never returns to Python). It
       flushes completed metrics as the JSON line and os._exit(1)s —
       loud partial data beats an eternal hang."""

    GRACE_S = 60

    def __init__(self, seconds: int, label: str):
        self.seconds = seconds
        self.label = label

    def __enter__(self):
        import signal
        import threading

        def on_alarm(signum, frame):
            raise TimeoutError(
                f"{self.label} exceeded {self.seconds}s wall clock — "
                "wedged device/tunnel?")

        def hard_exit():
            import os
            print(f"# {self.label} HARD TIMEOUT after "
                  f"{self.seconds + self.GRACE_S}s — main thread wedged in "
                  "a C call (dead tunnel); flushing partial results",
                  file=sys.stderr, flush=True)
            print(json.dumps({"metric": "bench_aborted_hard_timeout",
                              "value": float("nan"), "unit": "",
                              "vs_baseline": float("nan"),
                              "aborted_in": self.label,
                              **_COMPLETED_EXTRAS}), flush=True)
            os._exit(1)

        self._prev = signal.signal(signal.SIGALRM, on_alarm)
        signal.alarm(self.seconds)
        self._timer = threading.Timer(self.seconds + self.GRACE_S,
                                      hard_exit)
        self._timer.daemon = True
        self._timer.start()
        return self

    def __exit__(self, *exc):
        import signal
        self._timer.cancel()
        signal.alarm(0)
        signal.signal(signal.SIGALRM, self._prev)
        return False


def _sub_metric(extras, key, fn, digits: int = 1):
    """Run one sub-benchmark, isolated: a single wedged/failed sub-metric
    must not take down the whole round-end JSON line (flaky tunnels are a
    measured reality) — it is logged to stderr and omitted, never faked.
    ``fn`` returns either one value (recorded under ``key``, sanity-
    checked), a (median, windows) pair (median sanity-checked under
    ``key``, every window recorded under ``key_windows``), or a dict of
    {metric: value} (each scalar sanity-checked when it has a ceiling;
    lists recorded verbatim)."""
    try:
        with _Watchdog(SUB_BENCH_TIMEOUT_S, key):
            out = fn()
        if isinstance(out, tuple):
            med, windows = out
            out = {key: round(_sane(key, med), digits),
                   f"{key}_windows": windows}
        if isinstance(out, dict):
            for k, v in out.items():
                if isinstance(v, list):
                    extras[k] = v
                else:
                    if k in SANITY_CEILING:
                        v = _sane(k, v)
                    extras[k] = round(v, 3)
                print(f"# {k} {extras[k]} {METRIC_UNIT.get(k, '')}",
                      file=sys.stderr)
        else:
            extras[key] = round(_sane(key, out), digits)
            print(f"# {key} {extras[key]} {METRIC_UNIT[key]}",
                  file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — isolate sub-benchmarks
        print(f"# {key} FAILED: {type(e).__name__}: {e}", file=sys.stderr)
        extras[f"{key}_error"] = f"{type(e).__name__}: {e}"[:200]
    _COMPLETED_EXTRAS.update(extras)  # hard-timeout flush sees these
    return extras.get(key)


def _attention_metrics():
    stock_ms, flash_ms = bench_attention()
    return {"attention_t4096_stock_ms": stock_ms,
            "attention_t4096_flash_ms": flash_ms,
            "attention_flash_speedup": stock_ms / flash_ms}


def _attention_bwd_metrics():
    bs, bf = bench_attention_bwd()
    return {"attention_bwd_t2048_stock_ms": bs,
            "attention_bwd_t2048_flash_ms": bf,
            "attention_bwd_flash_speedup": bs / bf}


def _attention_bwd_long_metrics():
    # long-T leg, its own sub-metric so a failure here cannot discard the
    # already-measured T=2048 numbers: the regime the Pallas backward
    # exists for (O(T) memory; round-4 fix lets it compile here)
    bs4, bf4 = bench_attention_bwd(T=4096)
    return {"attention_bwd_t4096_stock_ms": bs4,
            "attention_bwd_t4096_flash_ms": bf4,
            "attention_bwd_t4096_speedup": bs4 / bf4}


class _HeadlineSampler:
    """ResNet50 f32 headline via windows INTERLEAVED across the whole
    bench run. Far-side chip contention swings throughput ~3.5x on a
    minutes timescale (profiles/README.md); a single end-of-run sample
    mostly measured the tunnel's worst minute (VERDICT r4 weak #1). The
    compiled timer is built once up front; one marginal window is taken
    between sub-benchmarks; the headline is the MEDIAN of all windows and
    every window is recorded — no best-of-N selection anywhere."""

    WINDOW_TIMEOUT_S = 600

    def __init__(self):
        self.timer = None
        self.windows = []
        self.init_error = None

    def start(self):
        from deeplearning4j_tpu.models import ResNet50

        try:
            with _Watchdog(SUB_BENCH_TIMEOUT_S, "resnet50_headline_init"):
                self.timer = _imagenet_model_timer(
                    ResNet50, batch=RESNET50_BATCH, steps=20, seed=0)
        except Exception as e:  # noqa: BLE001 — retried loudly at finish
            self.init_error = e
            print(f"# headline timer init FAILED (will retry at end): "
                  f"{type(e).__name__}: {e}", file=sys.stderr)

    def sample(self, label: str):
        if self.timer is None:
            return
        try:
            with _Watchdog(self.WINDOW_TIMEOUT_S, f"headline@{label}"):
                w = self.timer.window()
            if w is not None:
                self.windows.append(w)
                print(f"# headline window @{label}: {w:.1f} img/s",
                      file=sys.stderr)
                _COMPLETED_EXTRAS["resnet50_f32_windows_img_s"] = [
                    round(x, 1) for x in self.windows]
        except Exception as e:  # noqa: BLE001 — one bad window is data loss,
            # not run loss
            print(f"# headline window @{label} FAILED: {e}", file=sys.stderr)

    def finish(self, min_windows: int = 3):
        """Median of all collected windows; takes more back-to-back if the
        interleaved run produced too few. Raises (loudly) if the chip
        never produced a single window — the round then has no honest
        primary number and a missing key must not be quiet."""
        if self.timer is None:
            with _Watchdog(SUB_BENCH_TIMEOUT_S, "resnet50_headline_init"):
                from deeplearning4j_tpu.models import ResNet50

                self.timer = _imagenet_model_timer(
                    ResNet50, batch=RESNET50_BATCH, steps=20, seed=0)
        tries = 0
        while len(self.windows) < min_windows and tries < 2 * min_windows:
            self.sample(f"finish{tries}")
            tries += 1
        if not self.windows:
            raise RuntimeError(
                "no headline window could be measured"
                + (f" (init error: {self.init_error})"
                   if self.init_error else ""))
        return float(np.median(self.windows)), [round(w, 1)
                                                for w in self.windows]


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    valid = ("all", "resnet50", "vgg16", "lenet", "lstm", "transformer",
             "word2vec", "doc2vec", "attention", "paged_attn",
             "fit_e2e", "eval_e2e",
             "guard_overhead", "metrics_overhead", "inference_serve",
             "serve_chaos", "serve_fleet", "serve_federated",
             "serve_handoff", "serve_disagg",
             "serve_soak", "serve_restart",
             "generate_serve", "generate_longtail", "generate_mesh",
             "quant_serve", "quant_infer", "knn_serve", "serve_rag")
    if which not in valid:
        sys.exit(f"Unknown model '{which}'; choose one of {valid}")
    # the mesh bench needs virtual devices BEFORE the backend
    # initializes: standalone, plant the flag here (first thing, ahead
    # of any jax-importing package import); under "all" the bench
    # checks the device count itself and fails loudly if the backend
    # came up single-device
    if which == "generate_mesh":
        import os as _os
        _flag = "--xla_force_host_platform_device_count=8"
        if _flag not in _os.environ.get("XLA_FLAGS", ""):
            _os.environ["XLA_FLAGS"] = (
                _os.environ.get("XLA_FLAGS", "") + " " + _flag).strip()
    # persistent XLA compile cache: repeated bench runs skip the
    # tens-of-seconds remote cold compile per model (13.7 s -> 2.4 s
    # measured for a LeNet cold start). The repo-local default applies
    # only when the user has not already chosen a cache location via
    # DL4J_TPU_COMPILE_CACHE (honored at package import).
    import os

    import deeplearning4j_tpu as d4j

    if not os.environ.get("DL4J_TPU_COMPILE_CACHE"):
        d4j.enable_compile_cache(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".xla_cache"))
    extras = {}
    # informational, never gating: the graftcheck finding trajectory
    # (total / baselined / unbaselined) so BENCH_r06+ shows whether the
    # audited-unsafe list is shrinking or quietly growing
    try:
        from deeplearning4j_tpu.analysis import run_check
        _rep = run_check()
        extras["analysis_findings"] = len(_rep.findings)
        extras["analysis_unbaselined"] = len(_rep.unbaselined)
        print(f"# analysis_findings {len(_rep.findings)} "
              f"({len(_rep.unbaselined)} unbaselined, "
              f"{len(_rep.baselined)} baselined)", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — the bench must never die on it
        print(f"# analysis_findings FAILED: {e}", file=sys.stderr)
    headline = _HeadlineSampler() if which in ("all", "resnet50") else None
    if headline is not None:
        headline.start()
        headline.sample("start")
    if which in ("all", "lenet"):
        _sub_metric(extras, "lenet_mnist_img_s", bench_lenet)
        headline and headline.sample("post-lenet")
    if which in ("all", "fit_e2e"):
        _sub_metric(extras, "fit_e2e", bench_fit_e2e)
        headline and headline.sample("post-fit-e2e")
    if which in ("all", "eval_e2e"):
        _sub_metric(extras, "eval_e2e", bench_eval_e2e)
        headline and headline.sample("post-eval-e2e")
    if which in ("all", "guard_overhead"):
        _sub_metric(extras, "guard_overhead", bench_guard_overhead)
        headline and headline.sample("post-guard-overhead")
    if which in ("all", "metrics_overhead"):
        _sub_metric(extras, "metrics_overhead", bench_metrics_overhead)
        headline and headline.sample("post-metrics-overhead")
    if which in ("all", "inference_serve"):
        _sub_metric(extras, "inference_serve", bench_inference_serve)
        headline and headline.sample("post-inference-serve")
    if which in ("all", "serve_chaos"):
        _sub_metric(extras, "serve_chaos", bench_serve_chaos)
        headline and headline.sample("post-serve-chaos")
    if which in ("all", "serve_fleet"):
        _sub_metric(extras, "serve_fleet", bench_serve_fleet)
        headline and headline.sample("post-serve-fleet")
    if which in ("all", "serve_federated"):
        _sub_metric(extras, "serve_federated", bench_serve_federated)
        headline and headline.sample("post-serve-federated")
    if which in ("all", "serve_handoff"):
        _sub_metric(extras, "serve_handoff", bench_serve_handoff)
        headline and headline.sample("post-serve-handoff")
    if which in ("all", "serve_disagg"):
        _sub_metric(extras, "serve_disagg", bench_serve_disagg)
        headline and headline.sample("post-serve-disagg")
    if which in ("all", "serve_soak"):
        _sub_metric(extras, "serve_soak", bench_serve_soak)
        headline and headline.sample("post-serve-soak")
    if which in ("all", "serve_restart"):
        _sub_metric(extras, "serve_restart", bench_serve_restart)
        headline and headline.sample("post-serve-restart")
    if which in ("all", "generate_serve"):
        _sub_metric(extras, "generate_serve", bench_generate_serve)
    if which in ("all", "generate_longtail"):
        _sub_metric(extras, "generate_longtail", bench_generate_longtail)
    if which in ("all", "generate_mesh"):
        _sub_metric(extras, "generate_mesh", bench_generate_mesh)
        headline and headline.sample("post-generate-serve")
    if which in ("all", "quant_serve"):
        _sub_metric(extras, "quant_serve", bench_quant_serve)
    if which in ("all", "quant_infer"):
        _sub_metric(extras, "quant_infer", bench_quant_infer)
        headline and headline.sample("post-quant")
    if which in ("all", "knn_serve"):
        _sub_metric(extras, "knn_serve", bench_knn_serve)
        headline and headline.sample("post-knn-serve")
    if which in ("all", "serve_rag"):
        _sub_metric(extras, "serve_rag", bench_serve_rag)
        headline and headline.sample("post-serve-rag")
    if which in ("all", "vgg16"):
        _sub_metric(extras, "vgg16_bf16_img_s", bench_vgg16, digits=2)
        if extras.get("vgg16_bf16_img_s"):
            extras["vgg16_bf16_mfu_pct"] = round(
                100 * extras["vgg16_bf16_img_s"] * VGG16_TRAIN_FLOP_PER_IMG
                / PEAK_BF16_FLOP_S, 1)
        headline and headline.sample("post-vgg16")
    if which in ("all", "lstm"):
        _sub_metric(extras, "textgen_lstm_tokens_s", bench_lstm)
        headline and headline.sample("post-lstm")
    if which in ("all", "transformer"):
        _sub_metric(extras, "transformer_lm_tokens_s", bench_transformer_lm)
        headline and headline.sample("post-transformer")
    if which in ("all", "word2vec"):
        _sub_metric(extras, "word2vec_words_s", bench_word2vec)
        headline and headline.sample("post-word2vec")
    if which in ("all", "doc2vec"):
        _sub_metric(extras, "doc2vec_words_s", bench_doc2vec)
        headline and headline.sample("post-doc2vec")
    if which in ("all", "attention"):
        _sub_metric(extras, "attention", _attention_metrics)
    if which in ("all", "paged_attn"):
        _sub_metric(extras, "paged_attn", bench_paged_attn)
        headline and headline.sample("post-attention")
        _sub_metric(extras, "attention_bwd", _attention_bwd_metrics)
        _sub_metric(extras, "attention_bwd_long",
                    _attention_bwd_long_metrics)
        headline and headline.sample("post-attention-bwd")
    if which in ("all", "resnet50"):
        _sub_metric(extras, "resnet50_bf16_img_s",
                    lambda: bench_resnet50(compute_dtype="bfloat16"),
                    digits=2)
        if extras.get("resnet50_bf16_img_s"):
            extras["resnet50_bf16_mfu_pct"] = round(
                100 * extras["resnet50_bf16_img_s"]
                * RESNET50_TRAIN_FLOP_PER_IMG / PEAK_BF16_FLOP_S, 1)
        # the headline metric stays exception-un-wrapped: if ResNet50 f32
        # cannot run, the round has no honest primary number and the
        # failure must be loud, not a quietly missing key. It still gets
        # the watchdog — a loud timeout beats an eternal hang.
        v, windows = headline.finish()
        v = _sane("resnet50_img_per_sec_per_chip", v)
        extras["resnet50_f32_windows_img_s"] = windows
        result = {
            "metric": "resnet50_img_per_sec_per_chip",
            "value": round(v, 2),
            "unit": "img/s",
            "vs_baseline": round(v / NORTH_STAR_RESNET50_IMG_S, 3),
            **extras,
        }
    else:
        # prefer the canonical headline key of the requested sub-bench
        # (word2vec_words_s etc. — inserted LAST after its backend legs),
        # falling back to the first recorded scalar
        canonical = [k for k in extras
                     if k in SANITY_CEILING and not k.endswith("_error")
                     and isinstance(extras[k], (int, float))]
        k = canonical[-1] if canonical else next(
            (k for k, v in extras.items()
             if not k.endswith("_error") and isinstance(v, (int, float))),
            None)
        v = extras.get(k)
        if k is None:
            sys.exit("all requested benchmarks failed")
        result = {"metric": k, "value": v,
                  "unit": METRIC_UNIT.get(k, ""),
                  "vs_baseline": float("nan")}
    print(json.dumps(result))


if __name__ == "__main__":
    main()
