"""ComputationGraph tests.

Ports the intent of the reference's CompGraph suites: gradient checks
(gradientcheck/GradientCheckTestsComputationGraph.java), basic graph tests
(nn/graph/ComputationGraphTestRNN.java / TestComputationGraphNetwork.java) —
topo/cycle validation, multi-input/output fit, vertex ops, serialization
round-trip, skip-connection training.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import (
    DuplicateToTimeSeriesVertex,
    ElementWiseVertex,
    L2NormalizeVertex,
    L2Vertex,
    LastTimeStepVertex,
    MergeVertex,
    ReshapeVertex,
    ScaleVertex,
    ShiftVertex,
    StackVertex,
    SubsetVertex,
    UnstackVertex,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.convolution import (
    ConvolutionLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.updater import Adam, Sgd


def _rs(seed=0):
    return np.random.RandomState(seed)


def _onehot(idx, n):
    return np.eye(n, dtype=np.float64)[idx]


def _simple_graph(updater=None, dtype="float64"):
    """x -> dense a, dense b -> merge -> out (2-branch merge)."""
    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(updater or Sgd(learning_rate=0.1))
            .dtype(dtype)
            .graph_builder()
            .add_inputs("in")
            .add_layer("a", DenseLayer(n_out=5, activation="tanh"), "in")
            .add_layer("b", DenseLayer(n_out=4, activation="relu"), "in")
            .add_vertex("merge", MergeVertex(), "a", "b")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "merge")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(6))
            .build())
    return ComputationGraph(conf).init()


class TestGraphStructure:
    def test_topo_sort_and_nin_inference(self):
        net = _simple_graph()
        conf = net.conf
        assert conf.topo_order.index("a") < conf.topo_order.index("merge")
        assert conf.topo_order.index("b") < conf.topo_order.index("merge")
        assert conf.topo_order.index("merge") < conf.topo_order.index("out")
        # nIn inferred through merge: 5 + 4 = 9
        assert conf.vertices["out"].layer.n_in == 9
        assert conf.vertices["a"].layer.n_in == 6

    def test_cycle_detection(self):
        b = (NeuralNetConfiguration.builder().graph_builder()
             .add_inputs("in")
             .add_layer("a", DenseLayer(n_in=3, n_out=3), "b")
             .add_layer("b", DenseLayer(n_in=3, n_out=3), "a")
             .set_outputs("b"))
        with pytest.raises(ValueError, match="[Cc]ycle"):
            b.build()

    def test_dangling_input_rejected(self):
        b = (NeuralNetConfiguration.builder().graph_builder()
             .add_inputs("in")
             .add_layer("a", DenseLayer(n_in=3, n_out=3), "nope")
             .set_outputs("a"))
        with pytest.raises(ValueError, match="not a network input"):
            b.build()

    def test_duplicate_name_rejected(self):
        b = (NeuralNetConfiguration.builder().graph_builder()
             .add_inputs("in")
             .add_layer("a", DenseLayer(n_in=3, n_out=3), "in"))
        with pytest.raises(ValueError, match="[Dd]uplicate"):
            b.add_layer("a", DenseLayer(n_in=3, n_out=3), "in")


class TestGraphGradients:
    """CompGraph gradient checks (reference:
    GradientCheckTestsComputationGraph.java)."""

    def test_merge_graph_gradients(self):
        net = _simple_graph()
        rs = _rs(1)
        x = rs.randn(4, 6)
        y = _onehot(rs.randint(0, 3, 4), 3)
        assert check_gradients(net, x, y, eps=1e-6, max_rel_error=1e-5)

    def test_elementwise_add_skip_connection_gradients(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(7).updater(Sgd(learning_rate=0.1)).dtype("float64")
                .graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=5, activation="tanh"), "in")
                .add_layer("d2", DenseLayer(n_out=5, activation="tanh"), "d1")
                .add_vertex("add", ElementWiseVertex(op="add"), "d1", "d2")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "add")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        net = ComputationGraph(conf).init()
        rs = _rs(2)
        x = rs.randn(3, 4)
        y = _onehot(rs.randint(0, 2, 3), 2)
        assert check_gradients(net, x, y, eps=1e-6, max_rel_error=1e-5)

    def test_multi_input_multi_output_gradients(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(3).updater(Sgd(learning_rate=0.1)).dtype("float64")
                .graph_builder()
                .add_inputs("in1", "in2")
                .add_layer("d1", DenseLayer(n_out=4, activation="tanh"), "in1")
                .add_layer("d2", DenseLayer(n_out=4, activation="tanh"), "in2")
                .add_vertex("merge", MergeVertex(), "d1", "d2")
                .add_layer("shared", DenseLayer(n_out=6, activation="tanh"),
                           "merge")
                .add_layer("out1", OutputLayer(n_out=2, activation="softmax",
                                               loss="mcxent"), "shared")
                .add_layer("out2", OutputLayer(n_out=3, activation="identity",
                                               loss="mse"), "shared")
                .set_outputs("out1", "out2")
                .set_input_types(InputType.feed_forward(3),
                                 InputType.feed_forward(5))
                .build())
        net = ComputationGraph(conf).init()
        rs = _rs(4)
        x = [rs.randn(3, 3), rs.randn(3, 5)]
        y = [_onehot(rs.randint(0, 2, 3), 2), rs.randn(3, 3)]
        assert check_gradients(net, x, y, eps=1e-6, max_rel_error=1e-5)

    def test_lstm_last_time_step_gradients(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(5).updater(Sgd(learning_rate=0.1)).dtype("float64")
                .graph_builder()
                .add_inputs("in")
                .add_layer("lstm", LSTM(n_out=4, activation="tanh"), "in")
                .add_vertex("last", LastTimeStepVertex(mask_input="in"), "lstm")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "last")
                .set_outputs("out")
                .set_input_types(InputType.recurrent(3))
                .build())
        net = ComputationGraph(conf).init()
        rs = _rs(6)
        x = rs.randn(2, 5, 3)
        y = _onehot(rs.randint(0, 2, 2), 2)
        mask = np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], np.float64)
        assert check_gradients(net, x, y, input_mask=mask, eps=1e-6,
                               max_rel_error=1e-5)


class TestVertexOps:
    def _run_vertex(self, vertex, inputs):
        out, _ = vertex.forward({}, {}, [np.asarray(a) for a in inputs])
        return np.asarray(out)

    def test_elementwise_ops(self):
        a = np.array([[1.0, 2.0]])
        b = np.array([[3.0, -1.0]])
        assert np.allclose(self._run_vertex(ElementWiseVertex(op="add"),
                                            [a, b]), [[4, 1]])
        assert np.allclose(self._run_vertex(ElementWiseVertex(op="subtract"),
                                            [a, b]), [[-2, 3]])
        assert np.allclose(self._run_vertex(ElementWiseVertex(op="product"),
                                            [a, b]), [[3, -2]])
        assert np.allclose(self._run_vertex(ElementWiseVertex(op="average"),
                                            [a, b]), [[2, 0.5]])
        assert np.allclose(self._run_vertex(ElementWiseVertex(op="max"),
                                            [a, b]), [[3, 2]])

    def test_subset_vertex_inclusive(self):
        x = np.arange(12.0).reshape(2, 6)
        out = self._run_vertex(SubsetVertex(from_index=1, to_index=3), [x])
        assert out.shape == (2, 3)
        assert np.allclose(out, x[:, 1:4])

    def test_stack_unstack_roundtrip(self):
        a = _rs(0).randn(2, 3)
        b = _rs(1).randn(2, 3)
        stacked = self._run_vertex(StackVertex(), [a, b])
        assert stacked.shape == (4, 3)
        back = self._run_vertex(UnstackVertex(from_index=1, stack_size=2),
                                [stacked])
        assert np.allclose(back, b)

    def test_scale_shift(self):
        x = np.ones((2, 2))
        assert np.allclose(self._run_vertex(ScaleVertex(scale=3.0), [x]), 3.0)
        assert np.allclose(self._run_vertex(ShiftVertex(shift=-1.5), [x]), -0.5)

    def test_l2_vertex(self):
        a = np.array([[3.0, 0.0], [0.0, 0.0]])
        b = np.array([[0.0, 4.0], [0.0, 0.0]])
        out = self._run_vertex(L2Vertex(), [a, b])
        assert out.shape == (2, 1)
        assert np.allclose(out[0, 0], 5.0, atol=1e-3)

    def test_l2_normalize_vertex(self):
        x = np.array([[3.0, 4.0]])
        out = self._run_vertex(L2NormalizeVertex(), [x])
        assert np.allclose(out, [[0.6, 0.8]], atol=1e-4)

    def test_reshape_vertex(self):
        x = np.arange(24.0).reshape(2, 12)
        out = self._run_vertex(ReshapeVertex(shape=(3, 4)), [x])
        assert out.shape == (2, 3, 4)

    def test_last_time_step_noncontiguous_mask(self):
        """Interior-zero masks must pick the last *nonzero* step (reference:
        rnn/LastTimeStepVertex uses the final nonzero index)."""
        x = np.arange(2 * 4 * 3, dtype=np.float64).reshape(2, 4, 3)
        mask = np.array([[1, 0, 1, 0], [1, 1, 0, 0]], np.float64)
        v = LastTimeStepVertex(mask_input="in")
        out, _ = v.forward({}, {}, [x], ctx={"input_masks": {"in": mask}})
        assert np.allclose(out[0], x[0, 2])  # last active = index 2
        assert np.allclose(out[1], x[1, 1])

    def test_duplicate_to_time_series(self):
        x = np.array([[1.0, 2.0]])
        ref = np.zeros((1, 5, 7))
        v = DuplicateToTimeSeriesVertex(input_name="seq")
        out, _ = v.forward({}, {}, [x], ctx={"input_arrays": {"seq": ref},
                                             "input_masks": {}})
        assert out.shape == (1, 5, 2)
        assert np.allclose(out[0, 3], [1.0, 2.0])


class TestGraphTraining:
    def test_skip_connection_cnn_trains(self):
        """Residual-style CNN (the ResNet building block) trains: loss drops."""
        conf = (NeuralNetConfiguration.builder()
                .seed(42).updater(Adam(learning_rate=1e-2)).dtype("float32")
                .graph_builder()
                .add_inputs("in")
                .add_layer("c1", ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                                  padding=(1, 1),
                                                  activation="relu"), "in")
                .add_layer("c2", ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                                  padding=(1, 1),
                                                  activation="identity"), "c1")
                .add_vertex("res", ElementWiseVertex(op="add"), "c1", "c2")
                .add_layer("pool", SubsamplingLayer(kernel_size=(2, 2),
                                                    stride=(2, 2)), "res")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "pool")
                .set_outputs("out")
                .set_input_types(InputType.convolutional(8, 8, 2))
                .build())
        net = ComputationGraph(conf).init()
        rs = _rs(9)
        x = rs.randn(16, 8, 8, 2).astype(np.float32)
        y = _onehot(rs.randint(0, 3, 16), 3).astype(np.float32)
        first, _ = net.do_step(x, y)
        for _ in range(30):
            last, _ = net.do_step(x, y)
        assert last < first * 0.7

    def test_multi_io_fit_with_multidataset(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(11).updater(Adam(learning_rate=1e-2)).dtype("float32")
                .graph_builder()
                .add_inputs("in1", "in2")
                .add_layer("d1", DenseLayer(n_out=8, activation="relu"), "in1")
                .add_layer("d2", DenseLayer(n_out=8, activation="relu"), "in2")
                .add_vertex("m", MergeVertex(), "d1", "d2")
                .add_layer("out1", OutputLayer(n_out=2, activation="softmax",
                                               loss="mcxent"), "m")
                .add_layer("out2", OutputLayer(n_out=1, activation="identity",
                                               loss="mse"), "m")
                .set_outputs("out1", "out2")
                .set_input_types(InputType.feed_forward(4),
                                 InputType.feed_forward(3))
                .build())
        net = ComputationGraph(conf).init()
        rs = _rs(12)
        mds = MultiDataSet([rs.randn(8, 4).astype(np.float32),
                            rs.randn(8, 3).astype(np.float32)],
                           [_onehot(rs.randint(0, 2, 8), 2).astype(np.float32),
                            rs.randn(8, 1).astype(np.float32)])
        s0 = net.score(mds)
        net.fit(mds, epochs=40)
        assert net.score(mds) < s0 * 0.8
        outs = net.output(*mds.features)
        assert outs[0].shape == (8, 2)
        assert outs[1].shape == (8, 1)

    def test_rnn_graph_tbptt(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(21).updater(Adam(learning_rate=5e-3)).dtype("float32")
                .graph_builder()
                .add_inputs("in")
                .add_layer("lstm", LSTM(n_out=6, activation="tanh"), "in")
                .add_layer("out", RnnOutputLayer(n_out=2, activation="softmax",
                                                 loss="mcxent"), "lstm")
                .set_outputs("out")
                .set_input_types(InputType.recurrent(3))
                .t_bptt_lengths(4)
                .build())
        net = ComputationGraph(conf).init()
        rs = _rs(13)
        x = rs.randn(2, 12, 3).astype(np.float32)
        y = _onehot(rs.randint(0, 2, (2, 12)).ravel(), 2).reshape(
            2, 12, 2).astype(np.float32)
        ds = DataSet(x, y)
        s0 = net.score(ds)
        for _ in range(25):
            net.fit(ds)
        assert net.score(ds) < s0

    def test_evaluate_single_output(self):
        net = _simple_graph(updater=Adam(learning_rate=1e-2), dtype="float32")
        rs = _rs(14)
        x = rs.randn(30, 6).astype(np.float32)
        labels = rs.randint(0, 3, 30)
        y = _onehot(labels, 3).astype(np.float32)
        net.fit(DataSet(x, y), epochs=60)
        ev = net.evaluate(DataSet(x, y))
        assert ev.accuracy() > 0.5


class TestGraphSerialization:
    def test_json_roundtrip(self):
        net = _simple_graph()
        from deeplearning4j_tpu.nn.conf.graph_conf import \
            ComputationGraphConfiguration

        s = net.conf.to_json()
        conf2 = ComputationGraphConfiguration.from_json(s)
        assert conf2.topo_order == net.conf.topo_order
        assert conf2.network_outputs == net.conf.network_outputs
        assert conf2.vertices["out"].layer.n_in == 9
        net2 = ComputationGraph(conf2).init()
        assert net2.params_flat().size == net.params_flat().size

    def test_model_zip_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.utils.model_serializer import (
            load_model,
            save_model,
        )

        net = _simple_graph(updater=Adam(learning_rate=1e-2), dtype="float32")
        rs = _rs(15)
        x = rs.randn(8, 6).astype(np.float32)
        y = _onehot(rs.randint(0, 3, 8), 3).astype(np.float32)
        net.fit(DataSet(x, y), epochs=3)
        p = str(tmp_path / "graph.zip")
        save_model(net, p)
        net2 = load_model(p)
        assert np.allclose(net.params_flat(), net2.params_flat())
        assert np.allclose(np.asarray(net.output(x)),
                           np.asarray(net2.output(x)), atol=1e-6)
        # restored model continues training
        s0 = net2.score(DataSet(x, y))
        net2.fit(DataSet(x, y), epochs=5)
        assert net2.score(DataSet(x, y)) < s0

    def test_flat_params_roundtrip(self):
        net = _simple_graph()
        flat = net.params_flat()
        flat2 = flat * 2.0
        net.set_params_flat(flat2)
        assert np.allclose(net.params_flat(), flat2)


class TestRemat:
    def test_remat_matches_plain_training_and_rematerializes(self):
        """jax.checkpoint vertices: numerically identical training, and
        the compiled HLO actually carries rematerialized computations."""
        import jax

        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.models import TransformerLM

        V, T = 7, 8
        rs = np.random.RandomState(3)
        idx = rs.randint(0, V, (4, T + 1))
        x = np.eye(V, dtype=np.float32)[idx[:, :-1]]
        y = np.eye(V, dtype=np.float32)[idx[:, 1:]]

        def train(remat):
            m = TransformerLM(num_labels=V, max_length=T, d_model=16,
                              n_heads=2, n_blocks=2, seed=9,
                              remat=remat).init()
            for _ in range(3):
                m.fit(DataSet(x, y))
            return m

        a, b = train(False), train(True)
        np.testing.assert_allclose(
            np.asarray(b.params_flat()), np.asarray(a.params_flat()),
            rtol=1e-5, atol=1e-6)

        # the jaxpr of the remat'd loss gradient contains remat calls
        m = TransformerLM(num_labels=V, max_length=T, d_model=16,
                          n_heads=2, n_blocks=1, seed=9, remat=True).init()
        def loss(params):
            val, _ = m._loss(params, m.state, [x], [y], None, None,
                             train=True, rng=jax.random.PRNGKey(0))
            return val
        jaxpr = str(jax.make_jaxpr(jax.grad(loss))(m.params))
        assert "remat" in jaxpr or "checkpoint" in jaxpr
