"""Cross-host fleet federation tests (parallel/federation.py).

Covers the federation contract end to end on localhost sockets:
health-scored routing over N in-process FleetHosts with bit-exact
completions, typed shedding when no host can accept, heartbeat gossip
marking a host SUSPECT on missed beats BEFORE any TCP error surfaces,
host-down/heal cycles with degraded-mode entry and auto-clear,
drain-migrate across host boundaries, framed-RPC structural validation
(oversize and corrupt frames rejected typed on both sides), the
ChaosPolicy network fault modes with their legacy-sequence pinning, the
federated stats block, per-host metrics label injection — and the
headline drill: SIGKILL of an entire fleet-host *process* mid-stream
with bit-exact resumed completions via cross-host snapshot adoption and
a balanced federated ledger.

Tier split: the wire/chaos/shed tests are pure-Python-fast and ride
tier-1; every test that builds a real fleet (XLA compiles per host) or
spawns a host process is ALSO marked ``slow`` — tier-1 runs within ~2%
of its own 870 s timeout cap, so the drills run via ``-m federation``
(or the slow set) instead of inflating the default gate.
"""

import os
import socket
import time
from contextlib import contextmanager

import numpy as np
import pytest

from deeplearning4j_tpu.metrics.exposition import render_text
from deeplearning4j_tpu.models.zoo import (TransformerLM, greedy_generate,
                                           sample_generate)
from deeplearning4j_tpu.parallel.elastic import Heartbeat
from deeplearning4j_tpu.parallel.federation import (
    DEAD, READY, SUSPECT, FederationProtocolError, FleetFederation,
    FleetHost, HostUnavailable, _read_msg, _send_msg,
    build_generation_fleet, spawn_host)
from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                    ResilienceError,
                                                    TransientDispatchError)
from deeplearning4j_tpu.streaming.broker import FrameTooLarge, read_frame

V = 17


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(num_labels=V, max_length=32, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


def _mixed_specs(n, rng, steps=6):
    shapes = [(3, steps), (5, steps - 1), (4, steps + 1)]
    specs = []
    for i in range(n):
        plen, st = shapes[i % len(shapes)]
        p = rng.integers(1, V, size=plen).astype(np.int64)
        if i % 2 == 0:
            specs.append((p, st, 0.0, 0, 0))
        else:
            specs.append((p, st, 0.9, 5, 2000 + i))
    return specs


def _serial_refs(lm, specs):
    refs = []
    for p, steps, temp, top_k, seed in specs:
        if temp == 0.0:
            refs.append(greedy_generate(lm, p[None], steps, V)[0])
        else:
            refs.append(sample_generate(lm, p[None], steps, V,
                                        temperature=temp, top_k=top_k,
                                        seed=seed)[0])
    return refs


def _submit_all(fed, specs, deadline_s=240.0):
    futs = []
    for p, steps, temp, top_k, seed in specs:
        while True:
            try:
                futs.append(fed.submit(p, steps, temperature=temp,
                                       top_k=top_k, seed=seed,
                                       deadline_s=deadline_s))
                break
            except ResilienceError:
                time.sleep(0.02)
    return futs


def _assert_ledger(fed):
    st = fed.stats()["federation"]
    assert st["submitted"] == (st["completed"] + st["failed"]
                               + st["expired"] + st["rejected_submits"]), st
    assert st["inflight"] == 0 and st["parked"] == 0, st
    return st


@contextmanager
def host_pair(hb_dir=None, hids=("h0", "h1"), **fleet_kw):
    """Two in-process FleetHosts over their own single-replica fleets —
    real localhost sockets, no subprocess."""
    fleet_kw.setdefault("replicas", 1)
    fleet_kw.setdefault("max_length", 32)
    fleets, hosts = [], []
    try:
        for hid in hids:
            fl = build_generation_fleet(**fleet_kw)
            hb = (os.path.join(hb_dir, f"{hid}.heartbeat")
                  if hb_dir else None)
            fleets.append(fl)
            hosts.append(FleetHost(fl, hid=hid, heartbeat_path=hb,
                                   heartbeat_interval=0.05))
        yield hosts
    finally:
        for h in hosts:
            h.close()
        for fl in fleets:
            fl.close()


def _wait(pred, timeout=60.0, tick=0.02, msg="condition"):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return
        time.sleep(tick)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------- routing

@pytest.mark.federation
class TestFederationRouting:
    @pytest.mark.slow
    def test_routing_bit_exact_and_balanced(self, lm):
        """Mixed greedy+sampled traffic over two hosts: every completion
        bit-exact vs serial, both hosts share the load, ledger balances.
        Rides the same federation to pin the stats-block contract and
        the synchronous validation errors (one host pair serves all
        three claims — fleet builds dominate this suite's runtime)."""
        rng = np.random.default_rng(0)
        specs = _mixed_specs(10, rng)
        refs = _serial_refs(lm, specs)
        with host_pair() as hosts:
            with FleetFederation(hosts) as fed:
                st = fed.stats()
                assert list(st["federation"].keys()) == [
                    "hosts", "ready", "suspect", "deaths", "reconnects",
                    "submitted", "rejected_submits", "completed",
                    "failed", "expired", "redispatched", "migrated",
                    "handoff_resumes", "handoff_fallbacks", "snapshots",
                    "parked", "inflight", "degraded_mode"]
                assert st["federation"]["hosts"] == 2
                assert st["federation"]["ready"] == 2
                assert {b["hid"] for b in st["hosts"]} == {"h0", "h1"}
                with pytest.raises(ValueError):
                    fed.submit(np.array([[1, 2]]), 4)   # 2-D prompt
                with pytest.raises(ValueError):
                    fed.submit(np.array([1, 2]), 4, deadline_s=-1.0)
                futs = _submit_all(fed, specs)
                for fut, ref in zip(futs, refs):
                    got = fut.result(timeout=240)
                    assert np.array_equal(got, ref)
                st = _assert_ledger(fed)
                assert st["completed"] == 10
                per = {b["hid"]: b for b in fed.stats()["hosts"]}
                assert per["h0"]["dispatched"] > 0
                assert per["h1"]["dispatched"] > 0

    def test_submit_sheds_typed_when_no_host(self):
        """A federation whose only endpoint refuses connections sheds
        typed at submit — and the shed request counts rejected, keeping
        the ledger balanced."""
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        dead_port = probe.getsockname()[1]
        probe.close()
        fed = FleetFederation([("h0", dead_port)],
                              reconnect_backoff_s=10.0)
        try:
            with pytest.raises(HostUnavailable):
                fed.submit(np.array([1, 2, 3]), 4)
            st = fed.stats()["federation"]
            assert st["rejected_submits"] == 1
            _assert_ledger(fed)
        finally:
            fed.close()


# ----------------------------------------------------------------- gossip

@pytest.mark.federation
@pytest.mark.slow
class TestFederationGossip:
    def test_heartbeat_suspect_before_tcp_error(self, tmp_path, lm):
        """The ISSUE headline gossip drill: a host whose heartbeat goes
        stale (wedged process — sockets still open, NO TCP error ever
        fires) is marked SUSPECT and routed around; when beats resume it
        auto-clears back to READY."""
        hb = str(tmp_path)
        with host_pair(hb_dir=hb) as hosts:
            h1 = hosts[1]
            with FleetFederation(hosts, heartbeat_dir=hb,
                                 suspect_after_s=0.3, dead_after_s=600.0,
                                 gossip_tick_s=0.03) as fed:
                _wait(lambda: fed.stats()["federation"]["ready"] == 2,
                      msg="both hosts READY")
                h1.heartbeat.stop()   # the 'wedge': beats stop, sockets live
                _wait(lambda: fed.stats()["federation"]["suspect"] == 1,
                      msg="h1 SUSPECT on missed beats")
                st = fed.stats()
                assert st["federation"]["deaths"] == 0   # no TCP error
                per = {b["hid"]: b for b in st["hosts"]}
                assert per["h1"]["state"] == SUSPECT
                assert per["h1"]["suspect_reason"] == "heartbeat"
                # traffic routes around the suspect host
                before = per["h1"]["dispatched"]
                specs = _mixed_specs(2, np.random.default_rng(1))
                for fut, ref in zip(_submit_all(fed, specs),
                                    _serial_refs(lm, specs)):
                    assert np.array_equal(fut.result(timeout=240), ref)
                per = {b["hid"]: b for b in fed.stats()["hosts"]}
                assert per["h1"]["dispatched"] == before
                assert per["h0"]["dispatched"] >= 2
                # beats resume -> auto-clear, no reconnect needed
                h1.heartbeat = Heartbeat(h1.heartbeat.path,
                                         interval=0.05).start()
                _wait(lambda: fed.stats()["federation"]["suspect"] == 0,
                      msg="h1 recovered on fresh beats")
                assert fed.stats()["federation"]["deaths"] == 0
                _assert_ledger(fed)

    def test_host_down_heal_and_degraded_mode(self, lm):
        """In-process whole-host death: the federation enters degraded
        mode (gauge + typed transition, fleet-style), serves everything
        on the survivor, then auto-clears when a replacement host comes
        up on the same endpoint and the reconnect loop heals the link —
        the same path a healed network partition takes."""
        rng = np.random.default_rng(2)
        fl_new = None
        h_new = None
        with host_pair() as hosts:
            h0, h1 = hosts
            with FleetFederation(hosts, reconnect_backoff_s=0.05,
                                 gossip_tick_s=0.03) as fed:
                try:
                    port1 = h1.port
                    h1.kill()
                    _wait(lambda: fed.stats()["federation"]["degraded_mode"],
                          msg="degraded mode entered")
                    gauge = {g["name"]: g for g in
                             fed.metrics._snapshot_families()}
                    assert gauge["fed_degraded_mode"]["samples"][0][1] == 1.0
                    specs = _mixed_specs(2, rng)
                    for fut, ref in zip(_submit_all(fed, specs),
                                        _serial_refs(lm, specs)):
                        assert np.array_equal(fut.result(timeout=240), ref)
                    per = {b["hid"]: b for b in fed.stats()["hosts"]}
                    assert per["h0"]["completed"] >= 2
                    # replacement host on the SAME endpoint: the
                    # reconnect loop heals without operator action
                    fl_new = build_generation_fleet(replicas=1,
                                                    max_length=32)
                    h_new = FleetHost(fl_new, hid="h1", port=port1)
                    _wait(lambda: not
                          fed.stats()["federation"]["degraded_mode"],
                          msg="degraded mode cleared on heal")
                    st = fed.stats()["federation"]
                    assert st["reconnects"] >= 1 and st["deaths"] >= 1
                    _assert_ledger(fed)
                finally:
                    if h_new is not None:
                        h_new.close()
                    if fl_new is not None:
                        fl_new.close()

    def test_drain_migrate_across_hosts(self, lm):
        """retire_host(migrate=True) hands a host's in-flight work back
        to the router as RequestMigrated (+ newest snapshots) and the
        requests finish bit-exact on the surviving host."""
        rng = np.random.default_rng(3)
        specs = _mixed_specs(4, rng, steps=14)
        refs = _serial_refs(lm, specs)
        with host_pair(snapshot_every=1, steps_per_dispatch=1,
                       chaos={"stall_rate": 1.0, "stall_s": 0.01}) as hosts:
            with FleetFederation(hosts, gossip_tick_s=0.03) as fed:
                futs = _submit_all(fed, specs)
                _wait(lambda: any(b["inflight"] > 0 and b["hid"] == "h0"
                                  for b in fed.stats()["hosts"]),
                      msg="h0 has in-flight work")
                assert fed.retire_host("h0", migrate=True, timeout=30)
                for fut, ref in zip(futs, refs):
                    assert np.array_equal(fut.result(timeout=240), ref)
                st = _assert_ledger(fed)
                assert st["migrated"] >= 1
                per = {b["hid"]: b for b in fed.stats()["hosts"]}
                assert per["h0"]["state"] == "retired"


# ------------------------------------------------------------ crash drill

@pytest.mark.federation
@pytest.mark.slow
class TestFederationCrash:
    def test_sigkill_whole_process_bit_exact(self, tmp_path, lm):
        """The acceptance drill, as a test: two fleet-host *processes*
        behind one router; SIGKILL one mid-stream once the router holds
        published snapshots; every completion bit-exact (cross-host
        snapshot adoption for the victims), zero lost futures, balanced
        federated ledger, handoff_resumes counted."""
        hb = str(tmp_path)
        spec = {"heartbeat_dir": hb, "heartbeat_interval": 0.05,
                "builder_kwargs": {
                    "replicas": 1, "snapshot_every": 1, "max_length": 32,
                    "steps_per_dispatch": 1,
                    "chaos": {"stall_rate": 1.0, "stall_s": 0.02}}}
        hh0 = spawn_host(dict(spec, hid="h0"))
        hh1 = spawn_host(dict(spec, hid="h1"))
        fed = None
        try:
            fed = FleetFederation([hh0, hh1], heartbeat_dir=hb,
                                  suspect_after_s=0.5, dead_after_s=600.0)
            rng = np.random.default_rng(4)
            specs = _mixed_specs(6, rng, steps=20)
            refs = _serial_refs(lm, specs)
            futs = _submit_all(fed, specs)
            _wait(lambda: fed.stats()["federation"]["snapshots"] >= 2,
                  timeout=120, msg="router holds published snapshots")
            hh1.kill()          # SIGKILL: no flush, no goodbye
            assert not hh1.alive
            for fut, ref in zip(futs, refs):
                got = fut.result(timeout=240)
                assert np.array_equal(got, ref)
            st = _assert_ledger(fed)
            assert st["completed"] == 6
            assert st["deaths"] >= 1
            assert st["handoff_resumes"] >= 1
            assert st["degraded_mode"] is True
        finally:
            if fed is not None:
                fed.close()
            hh0.terminate()
            if hh1.alive:
                hh1.kill()


# ------------------------------------------------------------ wire safety

@pytest.mark.federation
class TestFederationWire:
    def _pair(self):
        a, b = socket.socketpair()
        return a, b

    def test_read_msg_roundtrip(self):
        a, b = self._pair()
        try:
            _send_msg(a, {"op": "stats", "id": 7}, b"payload")
            hdr, blob = _read_msg(b)
            assert hdr == {"op": "stats", "id": 7} and blob == b"payload"
        finally:
            a.close(); b.close()

    def test_read_msg_rejects_oversize_typed(self):
        """A length header above the cap is rejected typed BEFORE any
        allocation — the poisoned-length defense, federation side."""
        a, b = self._pair()
        try:
            a.sendall((1 << 30).to_bytes(4, "big"))
            with pytest.raises(FrameTooLarge):
                _read_msg(b, max_frame_bytes=1 << 20)
        finally:
            a.close(); b.close()

    def test_broker_read_frame_rejects_oversize_typed(self):
        """Same discipline on the streaming broker's framed reader."""
        a, b = self._pair()
        try:
            # op(1) topic_len(2) topic payload_len(4): oversize payload
            import struct as _s
            a.sendall(_s.pack(">cH", b"P", 1) + b"t"
                      + _s.pack(">I", 1 << 29))
            with pytest.raises(FrameTooLarge):
                read_frame(b, max_frame_bytes=1 << 20)
        finally:
            a.close(); b.close()

    def test_corrupt_header_rejected_typed(self):
        a, b = self._pair()
        try:
            hdr = b"\x00\x00\x00\x10" + b"not json at all!"
            a.sendall((len(hdr)).to_bytes(4, "big") + hdr)
            with pytest.raises(FederationProtocolError):
                _read_msg(b)
        finally:
            a.close(); b.close()

    def test_chaos_corrupt_draw_breaks_frame_typed(self):
        """A frame_corrupt_rate draw mangles the frame in flight; the
        receiver rejects it typed (FederationProtocolError), never
        crashes, never mis-parses."""
        a, b = self._pair()
        try:
            ch = ChaosPolicy(seed=3, frame_corrupt_rate=1.0)
            _send_msg(a, {"op": "stats", "id": 1}, chaos=ch)
            assert ch.injected_frame_corrupt == 1
            with pytest.raises(FederationProtocolError):
                _read_msg(b)
        finally:
            a.close(); b.close()

    def test_host_answers_protocol_error_and_closes(self):
        """A FleetHost that receives a structurally invalid frame
        answers with a typed protocol_error frame and drops the
        connection — the stream can no longer be trusted. The fleet is
        a bare stub: the corrupt frame is rejected before any op could
        dispatch into it (and a real fleet build costs seconds)."""
        from deeplearning4j_tpu.metrics.registry import MetricsRegistry
        host = FleetHost(object(), hid="hx", registry=MetricsRegistry())
        try:
            s = socket.create_connection(("127.0.0.1", host.port),
                                         timeout=10)
            hdr = b"\xff\xff\xff\xf0" + b"x" * 12   # header_len overrun
            s.sendall((len(hdr)).to_bytes(4, "big") + hdr)
            reply = _read_msg(s)
            assert reply is not None
            assert reply[0]["op"] == "protocol_error"
            assert reply[0]["etype"] == "FederationProtocolError"
            assert _read_msg(s) is None   # connection closed after
            s.close()
        finally:
            host.close()


# ----------------------------------------------------------- chaos modes

@pytest.mark.federation
class TestFederationChaos:
    def test_network_faults_deterministic(self):
        def run():
            sleeps = []
            ch = ChaosPolicy(seed=9, conn_refused_rate=0.3,
                             partition_rate=0.2, partition_s=0.0,
                             frame_corrupt_rate=0.2,
                             sleep=sleeps.append)
            seq = []
            for _ in range(120):
                try:
                    ch.net_connect_fault()
                    seq.append("ok")
                except ConnectionRefusedError:
                    seq.append("refused")
                seq.append(ch.net_fault_mode(64))
            return seq, ch

        s1, c1 = run()
        s2, c2 = run()
        assert s1 == s2
        assert c1.injected_conn_refused == c2.injected_conn_refused > 0
        assert c1.injected_partition == c2.injected_partition > 0
        assert c1.injected_frame_corrupt == c2.injected_frame_corrupt > 0

    def test_partition_window_and_slow_link(self):
        sleeps = []
        ch = ChaosPolicy(seed=1, partition_rate=1.0, partition_s=30.0,
                         sleep=sleeps.append)
        assert not ch.net_partitioned()
        assert ch.net_fault_mode(100) == "partition"
        assert ch.net_partitioned()   # window armed
        a, b = socket.socketpair()
        try:
            with pytest.raises(OSError):
                _send_msg(a, {"op": "stats"}, chaos=ch)
        finally:
            a.close(); b.close()
        slow = ChaosPolicy(seed=1, slow_link_factor=3.0,
                           sleep=sleeps.append)
        assert slow.net_fault_mode(ChaosPolicy.LINK_BYTES_PER_S) is None
        assert slow.injected_slow_link == 1
        assert sleeps and abs(sleeps[-1] - 2.0) < 1e-9

    def test_legacy_sequences_pinned(self):
        """Zero-rate network knobs draw NOTHING from the chaos RNG: a
        seeded policy's replica-fault sequence is byte-identical with
        the new parameters present and the net hooks interleaved."""
        def pattern(**kw):
            ch = ChaosPolicy(seed=11, transient_rate=0.3, hard_rate=0.1,
                             **kw)
            fn = ch.wrap(lambda: "ok")
            seq = []
            for _ in range(200):
                if kw:
                    ch.net_connect_fault()          # rate 0: no draw
                    assert ch.net_fault_mode(64) is None
                    assert not ch.net_partitioned()
                try:
                    seq.append(fn() is not None)
                except TransientDispatchError:
                    seq.append("transient")
                except RuntimeError:
                    seq.append("hard")
            return seq

        assert pattern() == pattern(conn_refused_rate=0.0,
                                    partition_rate=0.0, partition_s=5.0,
                                    slow_link_factor=1.0,
                                    frame_corrupt_rate=0.0)


# -------------------------------------------------------------- metrics

@pytest.mark.federation
@pytest.mark.metrics
@pytest.mark.slow
class TestFederationMetrics:
    def test_one_scrape_shows_every_host(self, lm):
        """metrics_sources() exposes the router registry plus each
        host's last gossiped families under an injected host= label, so
        a single exposition page covers the whole federation — and
        KerasBackendServer.metrics_text composes model= on top of
        host= for a federated target (same pair, one fleet build)."""
        from deeplearning4j_tpu.modelimport.server import \
            KerasBackendServer
        with host_pair() as hosts:
            with FleetFederation(hosts, stats_every_s=0.05,
                                 gossip_tick_s=0.03) as fed:
                specs = _mixed_specs(4, np.random.default_rng(5))
                for fut in _submit_all(fed, specs):
                    fut.result(timeout=240)
                _wait(lambda: len(fed.metrics_sources()) == 3,
                      msg="both hosts gossiped families")
                text = render_text(fed.metrics_sources())
                assert "fed_submitted_total 4" in text
                assert 'fleet_submitted_total{host="h0"}' in text
                assert 'fleet_submitted_total{host="h1"}' in text
                assert "fed_degraded_mode 0" in text
                srv = KerasBackendServer()
                with srv._lock:
                    srv._generators["m0"] = fed
                text = srv.metrics_text()
                assert 'fed_submitted_total{model="m0"} 4' in text
                assert ('fleet_submitted_total{model="m0",host="h0"}'
                        in text)
