"""Ring attention (sequence parallel) + tensor parallel equivalence tests.

Core invariant (the distributed==single-device contract of the test suite,
applied to the new parallelism modes): sharded execution must reproduce the
single-device math to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.nn.conf.layers.attention import (
    SelfAttentionLayer,
    scaled_dot_attention,
)
from deeplearning4j_tpu.parallel.sequence import (
    ring_attention,
    sequence_parallel_self_attention,
)
from deeplearning4j_tpu.parallel.tensor import (
    dp_tp_mesh,
    tp_mlp_train_step,
)


def _seq_mesh(n=8):
    return Mesh(np.asarray(jax.devices()[:n]), ("seq",))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device(self, causal):
        rs = np.random.RandomState(0)
        B, H, T, d = 2, 3, 32, 8  # T = 32 over 8 devices -> blocks of 4
        q = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        k = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        v = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        expected = scaled_dot_attention(q, k, v, causal=causal)
        got = ring_attention(q, k, v, mesh=_seq_mesh(), axis="seq",
                             causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_flow_through_ring(self):
        """The ring is differentiable: grads wrt q/k/v match the dense
        attention's grads (ppermute transposes to the reverse rotation)."""
        rs = np.random.RandomState(1)
        B, H, T, d = 1, 2, 16, 4
        q = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        k = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        v = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        mesh = _seq_mesh()

        def ring_loss(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh=mesh, axis="seq",
                                          causal=True) ** 2)

        def dense_loss(q, k, v):
            return jnp.sum(scaled_dot_attention(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       atol=3e-5, rtol=3e-5)

    def test_layer_wrapper_matches_layer_forward(self):
        layer = SelfAttentionLayer(n_in=12, n_out=12, n_heads=3, causal=True)
        layer.finalize(None)
        params = layer.init_params(jax.random.PRNGKey(0), jnp.float32)
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.randn(2, 24, 12), jnp.float32)
        expected, _ = layer.forward(params, {}, x)
        got = sequence_parallel_self_attention(layer, params, x,
                                               mesh=_seq_mesh())
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=2e-5, rtol=2e-5)


class TestSelfAttentionLayer:
    def test_gradcheck_in_network(self):
        from deeplearning4j_tpu.gradientcheck import check_gradients
        from deeplearning4j_tpu.nn.conf.builders import \
            NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers.recurrent import \
            RnnOutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updater import Sgd

        conf = (NeuralNetConfiguration.builder()
                .seed(5).updater(Sgd(learning_rate=0.1)).dtype("float64")
                .list(SelfAttentionLayer(n_out=8, n_heads=2, causal=True),
                      RnnOutputLayer(n_out=3, activation="softmax",
                                     loss="mcxent"))
                .set_input_type(InputType.recurrent(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        rs = np.random.RandomState(3)
        x = rs.randn(2, 6, 4)
        y = np.eye(3)[rs.randint(0, 3, (2, 6))]
        assert check_gradients(net, x, y, eps=1e-6, max_rel_error=1e-5,
                               subset=60)

    def test_key_mask_excludes_padded_positions(self):
        layer = SelfAttentionLayer(n_in=4, n_out=4, n_heads=1)
        layer.finalize(None)
        params = layer.init_params(jax.random.PRNGKey(1), jnp.float32)
        rs = np.random.RandomState(4)
        x = jnp.asarray(rs.randn(1, 5, 4), jnp.float32)
        mask = jnp.asarray([[1, 1, 1, 0, 0]], jnp.float32)
        out_masked, _ = layer.forward(params, {}, x, mask=mask)
        # perturbing a masked position must not change unmasked outputs
        x2 = x.at[0, 4].set(99.0)
        out2, _ = layer.forward(params, {}, x2, mask=mask)
        np.testing.assert_allclose(np.asarray(out_masked[0, :3]),
                                   np.asarray(out2[0, :3]), atol=1e-6)


class TestTensorParallel:
    def test_dp_tp_step_matches_single_device(self):
        """4-device (data=2, model=2) sharded MLP training step == the same
        step computed densely on one device."""
        rs = np.random.RandomState(5)
        B, I, Hd, O = 8, 6, 12, 4
        x = rs.randn(B, I).astype(np.float32)
        y = rs.randn(B, O).astype(np.float32)
        params = {
            "w1": rs.randn(I, Hd).astype(np.float32) * 0.3,
            "b1": np.zeros(Hd, np.float32),
            "w2": rs.randn(Hd, O).astype(np.float32) * 0.3,
            "b2": np.zeros(O, np.float32),
        }

        def loss_fn(out, y):
            return (out - y) ** 2

        mesh = dp_tp_mesh(2, 2)
        step = tp_mlp_train_step(mesh, jax.nn.tanh, loss_fn, lr=0.1)
        new_params, loss = step(
            {k: jnp.asarray(v) for k, v in params.items()},
            jnp.asarray(x), jnp.asarray(y))

        # dense single-device reference
        def dense_loss(p):
            h = jax.nn.tanh(x @ p["w1"] + p["b1"])
            out = h @ p["w2"] + p["b2"]
            return jnp.mean((out - y) ** 2)

        ref_loss, ref_g = jax.value_and_grad(dense_loss)(
            {k: jnp.asarray(v) for k, v in params.items()})
        assert abs(float(loss) - float(ref_loss)) < 1e-5
        for k in params:
            ref_new = np.asarray(params[k]) - 0.1 * np.asarray(ref_g[k])
            np.testing.assert_allclose(np.asarray(new_params[k]), ref_new,
                                       atol=1e-5, rtol=1e-5,
                                       err_msg=f"param {k}")

    def test_tp_trains_to_lower_loss(self):
        rs = np.random.RandomState(6)
        x = rs.randn(16, 5).astype(np.float32)
        y = (x @ rs.randn(5, 2).astype(np.float32))
        params = {"w1": rs.randn(5, 8).astype(np.float32) * 0.3,
                  "b1": np.zeros(8, np.float32),
                  "w2": rs.randn(8, 2).astype(np.float32) * 0.3,
                  "b2": np.zeros(2, np.float32)}
        params = {k: jnp.asarray(v) for k, v in params.items()}
        mesh = dp_tp_mesh(4, 2)
        step = tp_mlp_train_step(mesh, jax.nn.tanh,
                                 lambda o, t: (o - t) ** 2, lr=0.05)
        params, first = step(params, jnp.asarray(x), jnp.asarray(y))
        for _ in range(60):
            params, loss = step(params, jnp.asarray(x), jnp.asarray(y))
        assert float(loss) < float(first) * 0.5


class TestUlyssesAttention:
    """All-to-all sequence parallelism (Jacobs et al. 2023): the second SP
    implementation, head-sharded compute between two all_to_alls."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device(self, causal):
        from deeplearning4j_tpu.parallel.sequence import ulysses_attention

        rs = np.random.RandomState(2)
        B, H, T, d = 2, 8, 32, 4  # H = 8 over 8 devices -> 1 head each
        q = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        k = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        v = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        expected = scaled_dot_attention(q, k, v, causal=causal)
        got = ulysses_attention(q, k, v, mesh=_seq_mesh(), axis="seq",
                                causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_dense(self):
        from deeplearning4j_tpu.parallel.sequence import ulysses_attention

        rs = np.random.RandomState(3)
        B, H, T, d = 1, 8, 16, 4
        q = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        k = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        v = jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
        mesh = _seq_mesh()

        def u_loss(q, k, v):
            return jnp.sum(ulysses_attention(q, k, v, mesh=mesh,
                                             axis="seq", causal=True) ** 2)

        def dense_loss(q, k, v):
            return jnp.sum(scaled_dot_attention(q, k, v, causal=True) ** 2)

        gu = jax.grad(u_loss, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gu, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=2e-5)

    def test_indivisible_heads_rejected(self):
        from deeplearning4j_tpu.parallel.sequence import ulysses_attention

        rs = np.random.RandomState(4)
        q = jnp.asarray(rs.randn(1, 3, 16, 4), jnp.float32)  # 3 heads, 8 dev
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, q, q, mesh=_seq_mesh(), axis="seq")

    def test_layer_wrapper_ulysses_impl(self):
        from deeplearning4j_tpu.nn.conf.layers.attention import (
            SelfAttentionLayer,
        )
        from deeplearning4j_tpu.parallel.sequence import (
            sequence_parallel_self_attention,
        )

        rs = np.random.RandomState(5)
        layer = SelfAttentionLayer(n_in=16, n_out=16, n_heads=8,
                                   causal=True, activation="identity")
        layer.finalize(None)
        params = layer.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rs.randn(2, 32, 16), jnp.float32)
        expected, _ = layer.forward(params, {}, x, train=False)
        got = sequence_parallel_self_attention(layer, params, x,
                                               mesh=_seq_mesh(),
                                               impl="ulysses")
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   atol=2e-5, rtol=2e-5)
