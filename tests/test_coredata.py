"""Core-data layer tests: datavec bridge, CIFAR/LFW, clustering, VPTree,
t-SNE, k-NN server, graph embeddings (ports the intent of
deeplearning4j-core's RecordReaderDataSetIteratorTest, KMeansTest,
VPTreeTest, Test*Tsne, and deeplearning4j-graph's DeepWalk tests)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KDTree, KMeansClustering, VPTree
from deeplearning4j_tpu.datavec import (
    CollectionRecordReader,
    CollectionSequenceRecordReader,
    CSVRecordReader,
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.graph import DeepWalk, Graph, Node2Vec
from deeplearning4j_tpu.nearestneighbors import NearestNeighborsServer
from deeplearning4j_tpu.plot import Tsne


class TestRecordReaders:
    def test_csv_reader_and_classification_iterator(self, tmp_path):
        p = tmp_path / "data.csv"
        p.write_text("h1,h2,h3\n1.0,2.0,0\n3.0,4.0,1\n5.0,6.0,2\n7,8,0\n")
        rr = CSVRecordReader(str(p), skip_lines=1)
        it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=2,
                                         num_classes=3)
        batches = list(it)
        assert len(batches) == 2
        assert batches[0].features.shape == (2, 2)
        assert batches[0].labels.shape == (2, 3)
        assert np.allclose(batches[0].features[0], [1.0, 2.0])
        assert batches[0].labels[1].argmax() == 1

    def test_regression_iterator(self):
        rr = CollectionRecordReader([[1.0, 2.0, 0.5], [3.0, 4.0, 1.5]])
        it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=2,
                                         regression=True)
        ds = next(iter(it))
        assert ds.labels.shape == (2, 1)
        assert np.allclose(ds.labels[:, 0], [0.5, 1.5])

    def test_sequence_iterator_padding_and_masks(self):
        seqs = [
            [[1.0, 0], [2.0, 1], [3.0, 0]],   # len 3
            [[4.0, 1], [5.0, 0]],              # len 2 -> padded
        ]
        rr = CollectionSequenceRecordReader(seqs)
        it = SequenceRecordReaderDataSetIterator(rr, batch_size=2,
                                                 label_index=1,
                                                 num_classes=2)
        ds = next(iter(it))
        assert ds.features.shape == (2, 3, 1)
        assert ds.labels.shape == (2, 3, 2)
        assert np.allclose(ds.features_mask, [[1, 1, 1], [1, 1, 0]])
        assert np.allclose(ds.labels[1, 2], [0, 0])  # masked step zeroed

    def test_multi_dataset_iterator(self):
        r1 = CollectionRecordReader([[1, 2, 0], [3, 4, 1], [5, 6, 2],
                                     [7, 8, 0]])
        it = (RecordReaderMultiDataSetIterator(batch_size=2)
              .add_reader("r", r1)
              .add_input("r", 0, 1)
              .add_output_one_hot("r", 2, 3))
        mds = list(it)
        assert len(mds) == 2
        assert mds[0].features[0].shape == (2, 2)
        assert mds[0].labels[0].shape == (2, 3)


class TestBuiltinDatasets:
    def test_cifar_synthetic_trains(self):
        from deeplearning4j_tpu.datasets.cifar import CifarDataSetIterator
        from deeplearning4j_tpu.nn.conf.builders import \
            NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers.convolution import (
            ConvolutionLayer,
            SubsamplingLayer,
        )
        from deeplearning4j_tpu.nn.conf.layers.core import (
            DenseLayer,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updater import Adam

        it = CifarDataSetIterator(batch_size=64, num_examples=256)
        assert it.synthetic
        ds0 = next(iter(it))
        assert ds0.features.shape == (64, 32, 32, 3)
        assert ds0.features.min() >= 0 and ds0.features.max() <= 1
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Adam(learning_rate=1e-3))
                .list(ConvolutionLayer(n_out=8, kernel_size=(5, 5),
                                       convolution_mode="same",
                                       activation="relu"),
                      SubsamplingLayer(kernel_size=(4, 4), stride=(4, 4)),
                      DenseLayer(n_out=32, activation="relu"),
                      OutputLayer(n_out=10, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.convolutional(32, 32, 3))
                .build())
        net = MultiLayerNetwork(conf).init()
        losses = []
        for _ in range(8):
            it.reset()
            ep = [net.do_step(ds.features, ds.labels)[0] for ds in it]
            losses.append(float(np.mean(ep)))
        assert losses[-1] < losses[0] * 0.7, losses

    def test_lfw_synthetic_shapes(self):
        from deeplearning4j_tpu.datasets.cifar import LFWDataSetIterator

        it = LFWDataSetIterator(batch_size=16, num_examples=64,
                                image_size=32, num_people=5)
        ds = next(iter(it))
        assert ds.features.shape == (16, 32, 32, 3)
        assert ds.labels.shape == (16, 5)


class TestClustering:
    def test_kmeans_recovers_blobs(self):
        rs = np.random.RandomState(0)
        centers = np.array([[0, 0], [10, 0], [0, 10]], np.float32)
        x = np.concatenate([c + rs.randn(50, 2).astype(np.float32)
                            for c in centers])
        km = KMeansClustering(k=3, max_iterations=50, seed=1)
        assign = km.apply_to(x)
        # each true blob maps to one dominant cluster
        for blob in range(3):
            counts = np.bincount(assign[blob * 50:(blob + 1) * 50],
                                 minlength=3)
            assert counts.max() >= 45
        # predicted centers near true centers
        d = np.linalg.norm(km.centers[:, None, :] - centers[None], axis=2)
        assert d.min(axis=0).max() < 1.0

    def test_kdtree_knn_matches_bruteforce(self):
        rs = np.random.RandomState(1)
        pts = rs.randn(200, 3)
        tree = KDTree.build(pts)
        q = rs.randn(3)
        res = tree.knn(q, 5)
        brute = np.argsort(np.linalg.norm(pts - q, axis=1))[:5]
        assert [i for _, i in res] == list(brute)
        d, i = tree.nn(q)
        assert i == brute[0]

    def test_kdtree_insert_and_range(self):
        tree = KDTree(2)
        for i, p in enumerate([[0, 0], [1, 1], [2, 2], [5, 5]]):
            tree.insert(p, i)
        inside = tree.range([0.5, 0.5], [2.5, 2.5])
        assert sorted(inside) == [1, 2]

    def test_vptree_matches_bruteforce(self):
        rs = np.random.RandomState(2)
        pts = rs.randn(300, 4)
        tree = VPTree(pts)
        q = rs.randn(4)
        res = tree.search(q, 7)
        brute = np.argsort(np.linalg.norm(pts - q, axis=1))[:7]
        assert [i for _, i in res] == list(brute)

    def test_vptree_batch_device_path(self):
        rs = np.random.RandomState(3)
        pts = rs.randn(100, 4)
        tree = VPTree(pts)
        qs = rs.randn(5, 4)
        batch = tree.search_batch(qs, 3)
        assert len(batch) == 5
        for qi, results in enumerate(batch):
            brute = np.argsort(np.linalg.norm(pts - qs[qi], axis=1))[:3]
            assert [i for _, i in results] == list(brute)


class TestTsne:
    def test_tsne_separates_clusters(self):
        rs = np.random.RandomState(4)
        a = rs.randn(30, 10) * 0.3
        b = rs.randn(30, 10) * 0.3 + 5.0
        x = np.concatenate([a, b])
        tsne = Tsne(num_dimension=2, perplexity=10, max_iter=250,
                    learning_rate=100.0, seed=0)
        y = tsne.fit(x)
        assert y.shape == (60, 2)
        assert np.isfinite(tsne.kl)
        # cluster separation in the embedding: inter > intra distances
        ca, cb = y[:30].mean(0), y[30:].mean(0)
        intra = max(np.linalg.norm(y[:30] - ca, axis=1).mean(),
                    np.linalg.norm(y[30:] - cb, axis=1).mean())
        assert np.linalg.norm(ca - cb) > 2 * intra


class TestKnnServer:
    def test_server_endpoints(self):
        rs = np.random.RandomState(5)
        pts = rs.randn(50, 3)
        server = NearestNeighborsServer(pts, port=0)
        port = server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            st = json.loads(urllib.request.urlopen(base + "/status").read())
            assert st == {"points": 50, "dims": 3}
            q = pts[7] + 0.001
            req = urllib.request.Request(
                base + "/knn",
                data=json.dumps({"k": 2, "point": q.tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            res = json.loads(urllib.request.urlopen(req).read())["results"]
            assert res[0]["index"] == 7
            req = urllib.request.Request(
                base + "/knnVector",
                data=json.dumps({"k": 1,
                                 "points": [pts[3].tolist(),
                                            pts[9].tolist()]}).encode(),
                headers={"Content-Type": "application/json"})
            res = json.loads(urllib.request.urlopen(req).read())["results"]
            assert res[0][0]["index"] == 3
            assert res[1][0]["index"] == 9
        finally:
            server.stop()


class TestGraphEmbeddings:
    def _two_cliques(self):
        """Two 6-cliques joined by one bridge edge."""
        edges = []
        for base in (0, 6):
            for i in range(6):
                for j in range(i + 1, 6):
                    edges.append((base + i, base + j))
        edges.append((0, 6))
        return Graph.from_edges(12, edges)

    def test_deepwalk_community_structure(self):
        g = self._two_cliques()
        dw = DeepWalk(vector_size=16, window=3, walk_length=20,
                      walks_per_vertex=8, epochs=2, seed=3)
        dw.fit(g)
        assert dw.vertex_vector(0).shape == (16,)
        # same-clique similarity beats cross-clique
        same = np.mean([dw.similarity(1, j) for j in range(2, 6)])
        cross = np.mean([dw.similarity(1, j) for j in range(7, 12)])
        assert same > cross

    def test_node2vec_runs(self):
        g = self._two_cliques()
        nv = Node2Vec(p=0.5, q=2.0, vector_size=8, walk_length=10,
                      walks_per_vertex=4, epochs=1, seed=4)
        nv.fit(g)
        assert nv.vertex_vector(11).shape == (8,)
        near = nv.verts_nearest(3, 3)
        assert len(near) == 3

    def test_random_walks_respect_graph(self):
        from deeplearning4j_tpu.graph import RandomWalkIterator

        g = Graph.from_edges(4, [(0, 1), (1, 2), (2, 3)])
        for walk in RandomWalkIterator(g, walk_length=10, seed=0):
            for a, b in zip(walk, walk[1:]):
                assert b in g.neighbors(a) or a == b


def test_dataset_without_labels_supports_all_helpers():
    """labels=None (pretraining datasets) must survive shuffle, batching,
    splitting and merge instead of dying in numpy."""
    import numpy as np
    from deeplearning4j_tpu.datasets.dataset import DataSet

    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    ds = DataSet(x, None)
    ds.shuffle(seed=0)
    assert ds.labels is None
    tr, te = ds.split_test_and_train(6)
    assert tr.labels is None and te.num_examples() == 4
    batches = list(ds.batch_by(4))
    assert [b.num_examples() for b in batches] == [4, 4, 2]
    assert all(b.labels is None for b in batches)
    merged = DataSet.merge(batches)
    assert merged.labels is None and merged.num_examples() == 10


class TestDeviceBruteForceKnn:
    """TPU-idiomatic k-NN index (one matmul + top_k) vs the reference-style
    VPTree: exact agreement, both metrics, and through the REST server."""

    def _data(self, n=300, d=16, seed=0):
        rs = np.random.RandomState(seed)
        return rs.randn(n, d).astype(np.float32)

    def test_matches_vptree_euclidean(self):
        from deeplearning4j_tpu.nearestneighbors.brute import (
            DeviceBruteForceIndex,
        )

        pts = self._data()
        tree = VPTree(pts)
        idx = DeviceBruteForceIndex(pts)
        q = self._data(5, 16, seed=1)
        for i in range(5):
            ref = tree.search(q[i], 7)
            got = idx.search(q[i], 7)
            assert [r[1] for r in ref] == [g[1] for g in got]
            np.testing.assert_allclose([r[0] for r in ref],
                                       [g[0] for g in got], rtol=1e-4)

    def test_cosine_metric_self_nearest(self):
        from deeplearning4j_tpu.nearestneighbors.brute import (
            DeviceBruteForceIndex,
        )

        pts = self._data(50, 8)
        idx = DeviceBruteForceIndex(pts, metric="cosine")
        d, ii = idx.search_batch_arrays(pts * 3.0, k=1)  # scale-invariant
        np.testing.assert_array_equal(ii[:, 0], np.arange(50))
        assert float(d.max()) < 1e-5

    def test_server_device_backend(self):
        pts = self._data(100, 8)
        server = NearestNeighborsServer(pts, backend="device")
        port = server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            body = json.dumps({"k": 3,
                               "points": pts[:4].tolist()}).encode()
            req = urllib.request.Request(base + "/knnVector", data=body)
            res = json.loads(urllib.request.urlopen(req).read())
            assert [r[0]["index"] for r in res["results"]] == [0, 1, 2, 3]
            one = json.loads(urllib.request.urlopen(urllib.request.Request(
                base + "/knn",
                data=json.dumps({"k": 2,
                                 "point": pts[7].tolist()}).encode())).read())
            assert one["results"][0]["index"] == 7
        finally:
            server.stop()


class TestCurvesDataset:
    """Curves iterator (datasets/curves.py — CurvesDataFetcher.java
    analog, generated offline instead of the S3 curves.ser)."""

    def test_shapes_labels_and_determinism(self):
        from deeplearning4j_tpu.datasets import CurvesDataSetIterator

        it = CurvesDataSetIterator(batch_size=32, num_examples=96)
        batches = list(it)
        assert len(batches) == 3
        ds = batches[0]
        assert ds.features.shape == (32, 784)
        assert ds.features.dtype == np.float32
        # reconstruction convention: labels ARE the features
        np.testing.assert_array_equal(ds.features, ds.labels)
        assert 0.0 <= float(ds.features.min()) and \
            float(ds.features.max()) <= 1.0
        # images are sparse strokes, not noise: a curve lights up only a
        # small fraction of the 784 pixels
        frac_lit = float((ds.features > 0.05).mean())
        assert 0.01 < frac_lit < 0.4
        again = list(CurvesDataSetIterator(batch_size=32, num_examples=96))
        np.testing.assert_array_equal(ds.features, again[0].features)

    def test_autoencoder_pretraining_reduces_error(self):
        """The fetcher's purpose in the reference: unsupervised deep-AE
        pretraining. Reconstruction MSE must drop when training on it."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.datasets import CurvesDataSetIterator
        from deeplearning4j_tpu.nn.conf.builders import \
            NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers.core import (DenseLayer,
                                                            OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updater import Adam

        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Adam(learning_rate=1e-3))
                .list(DenseLayer(n_out=64, activation="relu"),
                      OutputLayer(n_out=784, activation="sigmoid",
                                  loss="mse"))
                .set_input_type(InputType.feed_forward(784)).build())
        net = MultiLayerNetwork(conf).init()
        it = CurvesDataSetIterator(batch_size=64, num_examples=256)
        s0 = net.score(next(iter(it)))
        net.fit(it, epochs=8)
        s1 = net.score(next(iter(CurvesDataSetIterator(
            batch_size=64, num_examples=256))))
        assert np.isfinite(s1) and s1 < s0
