"""PagedAttentionHelper seam: XLA-vs-Pallas(interpret) bit-exactness.

Ports the reference's helper-vs-stock parity discipline (cuDNN
``*Helper`` vs pure ND4J under deeplearning4j-cuda/) to the paged-KV
decode read: the Pallas block-table kernel
(nn/conf/layers/paged_attention.py) must be BITWISE identical to the
stock gather-then-attend backend across f32/int8 pools, greedy and
sampled serving, and the edge geometries the block-table walk can get
wrong — a row's position exactly on a page boundary, a prefill chunk
straddling two pages, and an all-masked chunk whose writes route to
garbage page 0.

Parity is asserted UNDER JIT on both sides — the production
configuration (every serving program is jitted), and the only honest
one: XLA rewrites ``x / const`` to a reciprocal multiply inside any
compiled program, including the interpreted kernel body, so an eager
stock reference would differ from BOTH compiled paths by one ulp at
head dims whose ``sqrt`` is not a power of two.

On the CPU suite the kernel runs in ``interpret=True`` mode (parity
gating only; the TPU bench measures the speedup — bench.py paged_attn).
If the installed jax cannot interpret Pallas TPU kernels on CPU the
module skips cleanly rather than failing collection.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402

try:
    from deeplearning4j_tpu.nn.conf.layers import paged_attention as ppa

    # probe: one tiny interpret-mode call; some jax builds lack Pallas
    # TPU-interpret support on CPU entirely
    ppa.paged_attend(
        "pallas",
        jnp.zeros((1, 1, 1, 8), jnp.float32),
        jnp.zeros((2, 1, 8, 8), jnp.float32),
        jnp.zeros((2, 1, 8, 8), jnp.float32),
        jnp.ones((1, 2), jnp.int32),
        jnp.zeros((1,), jnp.int32),
    )
except Exception as e:  # noqa: BLE001 — any failure means "no interpret"
    pytest.skip(f"Pallas interpret mode unavailable on this host: {e}",
                allow_module_level=True)

from deeplearning4j_tpu.nn.conf.layers.attention import (  # noqa: E402
    SelfAttentionLayer)

pytestmark = pytest.mark.pallas

V = 17


def _layer(backend, n_heads=4, ps_cap=32):
    lyr = SelfAttentionLayer(n_in=32, n_out=32, n_heads=n_heads,
                             causal=True, max_cache=ps_cap,
                             paged_attention=backend, bias_init=0.0)
    return lyr


def _paged_state(rs, *, pages, ps, NP, B, H=4, d=8, quant=False):
    """A pool with random resident content, distinct per-row pages (page
    0 reserved as the garbage sink), and a [B, NP] block table."""
    if quant:
        state = {
            "kpages": jnp.asarray(rs.randint(
                -127, 128, (pages, H, ps, d)), jnp.int8),
            "vpages": jnp.asarray(rs.randint(
                -127, 128, (pages, H, ps, d)), jnp.int8),
            "kscales": jnp.asarray(rs.rand(pages, H, ps) * 0.05,
                                   jnp.float32),
            "vscales": jnp.asarray(rs.rand(pages, H, ps) * 0.05,
                                   jnp.float32),
        }
    else:
        state = {
            "kpages": jnp.asarray(rs.randn(pages, H, ps, d), jnp.float32),
            "vpages": jnp.asarray(rs.randn(pages, H, ps, d), jnp.float32),
        }
    perm = rs.permutation(pages - 1)[:B * NP] + 1
    state["block_table"] = jnp.asarray(perm.reshape(B, NP), jnp.int32)
    return state


class TestLayerParity:
    """jit(xla layer) vs jit(pallas layer): output AND updated pool
    bitwise equal, across the edge geometries the kernel must match."""

    def _run_both(self, state, x, mask=None, seed=0):
        l_xla = _layer("xla")
        l_pal = _layer("pallas")
        params = l_xla.init_params(jax.random.PRNGKey(seed))

        def fwd(lyr):
            if mask is None:
                return jax.jit(lambda p, s, xx: lyr.forward(p, s, xx))(
                    params, state, x)
            return jax.jit(
                lambda p, s, xx, m: lyr.forward(p, s, xx, mask=m))(
                params, state, x, mask)

        (out_x, st_x) = fwd(l_xla)
        (out_p, st_p) = fwd(l_pal)
        np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_x))
        for k in st_x:
            np.testing.assert_array_equal(np.asarray(st_p[k]),
                                          np.asarray(st_x[k]))
        return out_x, st_x

    @pytest.mark.parametrize("quant", [False, True])
    def test_decode_at_page_boundary(self, quant):
        """cache_pos exactly on a page boundary: the freshest resident
        token is the last slot of the previous page and the write lands
        at offset 0 of the next — both sides of the boundary walk."""
        rs = np.random.RandomState(0)
        ps, NP, B = 8, 4, 3
        state = _paged_state(rs, pages=B * NP + 1, ps=ps, NP=NP, B=B,
                             quant=quant)
        # rows pinned to offsets {0, ps, 2*ps}: page-boundary-exact
        state["cache_pos"] = jnp.asarray([0, ps, 2 * ps], jnp.int32)
        x = jnp.asarray(rs.randn(B, 1, 32), jnp.float32)
        self._run_both(state, x)

    @pytest.mark.parametrize("quant", [False, True])
    def test_prefill_chunk_straddles_two_pages(self, quant):
        rs = np.random.RandomState(1)
        ps, NP, B, T = 8, 4, 2, 6
        state = _paged_state(rs, pages=B * NP + 1, ps=ps, NP=NP, B=B,
                             quant=quant)
        # offset 5 + 6 tokens crosses into the next page at offset 8
        state["cache_pos"] = jnp.asarray([5, ps + 5], jnp.int32)
        x = jnp.asarray(rs.randn(B, T, 32), jnp.float32)
        self._run_both(state, x)

    @pytest.mark.parametrize("quant", [False, True])
    def test_all_masked_chunk_routes_to_garbage_page(self, quant):
        """A fully-masked row's chunk writes pool page 0 (the garbage
        sink) and leaves its REAL pages untouched — under both backends,
        bitwise."""
        rs = np.random.RandomState(2)
        ps, NP, B, T = 8, 4, 2, 4
        state = _paged_state(rs, pages=B * NP + 1, ps=ps, NP=NP, B=B,
                             quant=quant)
        state["cache_pos"] = jnp.asarray([3, 9], jnp.int32)
        x = jnp.asarray(rs.randn(B, T, 32), jnp.float32)
        mask = jnp.asarray([[0, 0, 0, 0], [1, 1, 0, 0]], jnp.float32)
        _, st = self._run_both(state, x, mask=mask)
        # row 0 (all masked): its own pages hold their prior content
        bt = np.asarray(state["block_table"])
        for key in ("kpages", "vpages"):
            np.testing.assert_array_equal(
                np.asarray(st[key])[bt[0]],
                np.asarray(state[key])[bt[0]])
            # and the garbage page moved (the masked columns landed there)
            assert not np.array_equal(np.asarray(st[key])[0],
                                      np.asarray(state[key])[0])

    def test_decode_with_garbage_page_refs_in_table(self):
        """Unallocated tail entries of a block table legitimately point
        at page 0; the causal mask keeps them out of the attend."""
        rs = np.random.RandomState(3)
        ps, NP, B = 8, 4, 2
        state = _paged_state(rs, pages=B * NP + 1, ps=ps, NP=NP, B=B)
        bt = np.asarray(state["block_table"]).copy()
        bt[:, 2:] = 0  # only the first two pages are real
        state["block_table"] = jnp.asarray(bt)
        state["cache_pos"] = jnp.asarray([7, 2 * ps - 1], jnp.int32)
        x = jnp.asarray(rs.randn(B, 1, 32), jnp.float32)
        self._run_both(state, x)


class TestBackendSelection:
    def test_auto_resolution_per_platform(self):
        geo = dict(page_size=16, head_dim=128, n_pages=32)
        assert ppa.resolve_paged_backend(
            "auto", platform="tpu", **geo) == "pallas"
        assert ppa.resolve_paged_backend(
            "auto", platform="cpu", **geo) == "xla"
        # forced knobs bypass supports() entirely
        assert ppa.resolve_paged_backend(
            "pallas", platform="cpu", **geo) == "pallas"
        assert ppa.resolve_paged_backend(
            "xla", platform="tpu", **geo) == "xla"

    def test_supports_geometry_gates(self):
        ok = dict(platform="tpu")
        assert ppa.supports(page_size=16, head_dim=128, n_pages=32, **ok)
        # sublane / lane alignment
        assert not ppa.supports(page_size=10, head_dim=128, n_pages=32,
                                **ok)
        assert not ppa.supports(page_size=16, head_dim=8, n_pages=32,
                                **ok)
        # VMEM scratch ceiling (same family as ops/pallas_attention)
        assert ppa.supports(page_size=16, head_dim=128, n_pages=256,
                            **ok)
        assert not ppa.supports(page_size=16, head_dim=128, n_pages=512,
                                **ok)
        # off-TPU: interpret mode is never a serving win
        assert not ppa.supports(page_size=16, head_dim=128, n_pages=32,
                                platform="cpu")

    def test_unknown_backend_raises(self):
        with pytest.raises(ValueError, match="unknown paged_attention"):
            ppa.resolve_paged_backend("cudnn", page_size=16, head_dim=64,
                                      n_pages=4)
        with pytest.raises(ValueError, match="unknown paged_attention"):
            ppa.get_paged_helper("auto")  # must be RESOLVED first

    def test_traced_choice_raises(self):
        """The retrace hazard the graftcheck fixture pins: a backend
        chosen on a traced value must fail loudly at trace time."""

        def bad(x):
            return ppa.resolve_paged_backend(
                x, page_size=16, head_dim=64, n_pages=4)

        with pytest.raises(TypeError, match="static host config"):
            jax.jit(bad)(jnp.float32(1.0))


class TestDebugOverflowAssert:
    """The per-dispatch host-sync capacity check is debug-opt-in only
    (the hot path must not pay a device->host sync; admission lives in
    the caller's page accounting)."""

    def _overflowing_call(self):
        rs = np.random.RandomState(4)
        ps, NP, B = 8, 2, 1
        lyr = _layer("xla")
        params = lyr.init_params(jax.random.PRNGKey(0))
        state = _paged_state(rs, pages=B * NP + 1, ps=ps, NP=NP, B=B)
        state["cache_pos"] = jnp.asarray([NP * ps - 1], jnp.int32)
        x = jnp.asarray(rs.randn(B, 2, 32), jnp.float32)  # 1 past cap
        return lyr.forward(params, state, x)

    def test_silent_by_default(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_PAGED_DEBUG", raising=False)
        self._overflowing_call()  # no host sync, no raise

    def test_debug_mode_asserts(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_PAGED_DEBUG", "1")
        with pytest.raises(ValueError, match="paged KV overflow"):
            self._overflowing_call()


@pytest.fixture(scope="module")
def lm():
    from deeplearning4j_tpu.models.zoo import TransformerLM

    return TransformerLM(num_labels=V, max_length=16, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


class TestServerParity:
    """End-to-end serving parity: a paged_attention="pallas" server must
    emit the exact token streams of the stock server, greedy AND
    sampled, and tag its program-cache keys with the backend so the
    families never share traces."""

    def _serve(self, lm, backend, reqs):
        from deeplearning4j_tpu.parallel.generation import GenerationServer

        srv = GenerationServer(lm, V, slots=3, paged_attention=backend)
        try:
            assert srv._pa == backend
            futs = [srv.submit(p, s, temperature=t, top_k=k, seed=seed)
                    for p, s, t, k, seed in reqs]
            outs = [f.result(timeout=120) for f in futs]
            cached = [key for key in lm._output_cache
                      if key and key[0] in ("gen_decode", "gen_prefill")]
        finally:
            srv.close()
        return outs, cached

    def test_greedy_and_sampled_token_parity(self, lm):
        rs = np.random.RandomState(5)
        reqs = [(rs.randint(0, V, 3), 6, 0.0, 0, 0),
                (rs.randint(0, V, 5), 5, 0.8, 5, 7),
                (rs.randint(0, V, 9), 4, 1.2, 0, 11)]
        outs_x, keys_x = self._serve(lm, "xla", reqs)
        outs_p, keys_p = self._serve(lm, "pallas", reqs)
        for got, ref in zip(outs_p, outs_x):
            np.testing.assert_array_equal(got, ref)
        # backend-tagged program cache: each family traced its OWN
        # programs — the tag is the last key element
        assert all(k[-1] == "xla" for k in keys_x)
        assert any(k[-1] == "pallas" for k in keys_p)

    def test_knob_restored_on_close(self, lm):
        from deeplearning4j_tpu.parallel.generation import GenerationServer

        layers = [l for _, l in lm._stream_layers()
                  if hasattr(l, "paged_attention")]
        before = [l.paged_attention for l in layers]
        srv = GenerationServer(lm, V, slots=2, paged_attention="pallas")
        assert all(l.paged_attention == "pallas" for l in layers)
        srv.close()
        assert [l.paged_attention for l in layers] == before

    def test_invalid_knob_rejected(self, lm):
        from deeplearning4j_tpu.parallel.generation import GenerationServer

        with pytest.raises(ValueError, match="paged_attention"):
            GenerationServer(lm, V, slots=2, paged_attention="cudnn")

    @pytest.mark.parametrize("backend", ["xla", "pallas"])
    def test_int8_greedy_parity_between_backends(self, backend, lm):
        """int8 pools through each backend agree with the OTHER backend's
        int8 stream bitwise (the quantization delta itself is covered by
        test_quantize.py — here both families see identical pools)."""
        from deeplearning4j_tpu.parallel.generation import GenerationServer

        prompt = np.array([2, 5, 7, 1], np.int64)
        srv = GenerationServer(lm, V, slots=2, kv_dtype="int8",
                               paged_attention=backend)
        try:
            out = srv.submit(prompt, 5).result(timeout=120)
        finally:
            srv.close()
        if not hasattr(type(self), "_int8_ref"):
            type(self)._int8_ref = {}
        type(self)._int8_ref[backend] = out
        if len(type(self)._int8_ref) == 2:
            np.testing.assert_array_equal(type(self)._int8_ref["xla"],
                                          type(self)._int8_ref["pallas"])
