"""Int8 quantization tests (ISSUE-10 acceptance surface).

Two quantized execution paths, both default-OFF:
- per-output-channel int8 WEIGHTS with the dequant fused into each
  matmul/conv (optimize/quantize.py + layer ``QUANT_PARAMS`` opt-ins),
  gated on eval parity (``confusion_delta``);
- int8 paged/streaming KV-CACHE with per-token-per-head scales
  (``kv_dtype="int8"`` on GenerationServer / ``init_paged_carry``),
  gated on greedy agreement vs the f32 reference.

Everything with quantization off must stay BIT-exact — asserted here
against the same serial references the f32 serving tests pin.
"""

import time
from contextlib import contextmanager

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import TransformerLM, greedy_generate
from deeplearning4j_tpu.optimize.quantize import (confusion_delta,
                                                  dequantize_array,
                                                  greedy_agreement,
                                                  quantize_array,
                                                  quantize_net,
                                                  quantize_params)
from deeplearning4j_tpu.parallel.generation import GenerationServer

V = 17


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(num_labels=V, max_length=16, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


@pytest.fixture(scope="module")
def greedy_refs(lm):
    rs = np.random.RandomState(4)
    shapes = [(3, 6), (5, 4), (9, 5), (3, 5), (5, 6), (9, 4)]
    reqs = [(rs.randint(0, V, p), s) for p, s in shapes]
    refs = [greedy_generate(lm, p[None], s, V)[0] for p, s in reqs]
    return reqs, refs


@contextmanager
def serving(*args, **kwargs):
    srv = GenerationServer(*args, **kwargs)
    try:
        yield srv
    finally:
        srv.close()


@pytest.mark.quant
class TestWeightQuantization:
    def test_roundtrip_error_bound(self):
        """q * scale reconstructs within half a quantization step per
        output channel; all-zero channels reconstruct exactly."""
        rs = np.random.RandomState(0)
        for shape in [(7, 5), (3, 3, 2, 4), (16, 16)]:
            w = (rs.randn(*shape) * rs.uniform(0.01, 10)).astype(np.float32)
            w[..., -1] = 0.0  # an all-zero output channel
            q, scale = quantize_array(w)
            q, scale = np.asarray(q), np.asarray(scale)
            assert q.dtype == np.int8 and scale.dtype == np.float32
            assert scale.shape == (shape[-1],)
            rt = dequantize_array(q, scale)
            step = scale.reshape((1,) * (w.ndim - 1) + (-1,))
            assert np.all(np.abs(rt - w) <= 0.5001 * np.maximum(step, 1e-12))
            np.testing.assert_array_equal(rt[..., -1], 0.0)

    def test_quantize_params_targets_and_scales(self, lm):
        """Only QUANT_PARAMS weights quantize: attention projections and
        dense W go int8 with f32 ``*_scale`` siblings; biases, norms and
        embeddings are untouched — and the source net's params are not
        mutated."""
        before = {k: {p: np.asarray(a) for p, a in v.items()}
                  for k, v in lm.params.items() if isinstance(v, dict)}
        qparams, scales = quantize_params(lm)
        assert scales  # at least the attention block quantized
        n_int8 = 0
        for key, lp in qparams.items():
            if not isinstance(lp, dict):
                continue
            for pname, arr in lp.items():
                if pname.endswith("_scale"):
                    continue
                if np.asarray(arr).dtype == np.int8:
                    n_int8 += 1
                    assert pname + "_scale" in lp
                    assert pname in scales[key]
                elif pname in ("b", "gamma", "beta"):
                    np.testing.assert_array_equal(np.asarray(arr),
                                                  before[key][pname])
        assert n_int8 == sum(len(v) for v in scales.values()) > 0
        # source untouched (no int8 leaked into the original tree)
        for key, lp in lm.params.items():
            if isinstance(lp, dict):
                for pname, arr in lp.items():
                    assert not pname.endswith("_scale")
                    assert np.asarray(arr).dtype != np.int8

    def test_bad_mode_rejected(self, lm):
        with pytest.raises(ValueError, match="int8"):
            quantize_net(lm, "int4")

    def test_lenet_eval_parity(self):
        """LeNet via the zoo ``quantize="int8"`` knob: int8 weights keep
        classification decisions — confusion delta vs f32 stays inside
        the gate on a synthetic eval set."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.models.zoo import LeNet

        net = LeNet(num_labels=10, seed=1).init()
        qnet = LeNet(num_labels=10, seed=1, quantize="int8").init()
        rs = np.random.RandomState(2)
        x = rs.randn(64, 28, 28, 1).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 64)]
        ev_f = net.evaluate(DataSet(x, y))
        ev_q = qnet.evaluate(DataSet(x, y))
        assert confusion_delta(ev_f, ev_q) <= 0.05
        # and the raw outputs are numerically close, not just argmax-equal
        of = np.asarray(net.output(x))
        oq = np.asarray(qnet.output(x))
        np.testing.assert_allclose(of, oq, atol=5e-2)

    def test_keras_import_quantize_knob(self, tmp_path):
        """An imported-then-quantized Keras model serves through the same
        fused-dequant path: eval parity vs the f32 import."""
        keras = pytest.importorskip("keras")
        from keras import layers

        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.modelimport import \
            import_keras_sequential_model_and_weights

        m = keras.Sequential([
            keras.Input((6,)),
            layers.Dense(12, activation="relu"),
            layers.Dense(3, activation="softmax"),
        ])
        path = str(tmp_path / "mlp.h5")
        m.save(path)
        net = import_keras_sequential_model_and_weights(path)
        qnet = import_keras_sequential_model_and_weights(path,
                                                         quantize="int8")
        rs = np.random.RandomState(3)
        x = rs.randn(48, 6).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 48)]
        assert confusion_delta(net.evaluate(DataSet(x, y)),
                               qnet.evaluate(DataSet(x, y))) <= 0.05

    def test_parallel_inference_int8_and_source_untouched(self, lm):
        """ParallelInference(quantize="int8") serves quantized weights;
        the caller's net keeps serving bit-exact f32."""
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        rs = np.random.RandomState(5)
        ids = rs.randint(0, V, (4, 8))
        import jax
        x = np.asarray(jax.nn.one_hot(ids, V, dtype=np.float32))
        ref = np.asarray(lm.output(x))
        with ParallelInference(lm, workers=2, quantize="int8") as inf:
            got = np.asarray(inf.output(x))
        assert got.shape == ref.shape
        assert (got.argmax(-1) == ref.argmax(-1)).mean() >= 0.9
        # f32 source still bit-exact after the quantized server existed
        np.testing.assert_array_equal(np.asarray(lm.output(x)), ref)


@pytest.mark.quant
class TestInt8KVCache:
    def test_bad_kv_dtype_rejected(self, lm):
        with pytest.raises(ValueError, match="kv_dtype"):
            GenerationServer(lm, V, slots=2, kv_dtype="fp8")

    def test_greedy_agreement_and_capacity(self, lm, greedy_refs):
        """Mixed-length concurrent requests through an int8 pool agree
        with the serial f32 greedy references, and the per-token KV
        footprint shrinks >= 1.8x vs the f32 pool at identical config."""
        reqs, refs = greedy_refs
        with serving(lm, V, slots=3, kv_dtype="int8") as srv:
            futs = [srv.submit(p, s) for p, s in reqs]
            outs = [f.result(timeout=120) for f in futs]
            st_q = srv.stats()
        for got, ref in zip(outs, refs):
            assert greedy_agreement(got, ref) >= 0.95
        assert st_q["completed"] == len(reqs) and st_q["failed"] == 0
        assert st_q["pages"]["kv_cache_dtype"] == "int8"
        with serving(lm, V, slots=3) as srv:
            st_f = srv.stats()
        assert st_f["pages"]["kv_cache_dtype"] == "float32"
        ratio = st_f["pages"]["bytes_per_token"] \
            / st_q["pages"]["bytes_per_token"]
        assert ratio >= 1.8, f"int8 KV shrinks only {ratio:.2f}x"

    def test_f32_default_stays_bit_exact(self, lm, greedy_refs):
        """Quantization off = the seed behavior, bit for bit."""
        reqs, refs = greedy_refs
        with serving(lm, V, slots=3) as srv:
            outs = [srv.submit(p, s).result(timeout=120) for p, s in reqs]
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(got, ref)

    def test_cow_preserves_scales(self, lm):
        """Prefix sharing + copy-on-write under int8: divergent
        continuations off a shared prefix page stay correct (the COW
        page copy must duplicate the scale planes with the values), and
        a second identical pass reproduces the first exactly."""
        rs = np.random.RandomState(7)
        base = rs.randint(0, V, 8)  # spans a full page -> shareable
        prompts = [np.concatenate([base, [t]]) for t in (1, 2, 3)]
        refs = [greedy_generate(lm, p[None], 5, V)[0] for p in prompts]
        with serving(lm, V, slots=3, kv_dtype="int8") as srv:
            outs = [srv.submit(p, 5).result(timeout=120) for p in prompts]
            outs2 = [srv.submit(p, 5).result(timeout=120) for p in prompts]
            st = srv.stats()
        for got, ref in zip(outs, refs):
            assert greedy_agreement(got, ref) >= 0.95
        for a, b in zip(outs, outs2):
            np.testing.assert_array_equal(a, b)
        assert st["pages"]["prefix_hits"] > 0
        assert st["pages"]["cow_copies"] > 0

    def test_no_recompile_on_churn_int8(self):
        """The zero-retrace property survives quantization: one decode
        program, one prefill bucket, one page copy — then occupancy
        churn over int8 pages adds ZERO compiled programs (the scale
        planes ride the same traced pool structure)."""
        net = TransformerLM(num_labels=V, max_length=16, d_model=8,
                            n_heads=2, n_blocks=1, seed=9).init()
        rs = np.random.RandomState(0)
        with serving(net, V, slots=3, min_prefill_bucket=4,
                     kv_dtype="int8") as srv:
            base = len(net._output_cache)
            warm = [srv.submit(rs.randint(0, V, 3), 5),
                    srv.submit(rs.randint(0, V, 7), 2)]
            for f in warm:
                f.result(timeout=120)
            warmed = len(net._output_cache)
            assert warmed - base == 3
            churn = [(4, 3), (2, 7), (6, 1), (8, 4), (3, 2), (5, 6)]
            futs = []
            for plen, mt in churn:
                futs.append(srv.submit(rs.randint(0, V, plen), mt))
                time.sleep(0.02)
            for f, (_plen, mt) in zip(futs, churn):
                assert f.result(timeout=120).shape == (mt,)
            assert len(net._output_cache) == warmed

    def test_pages_telemetry_gauges(self, lm):
        """The pool's quantization posture is on the Prometheus surface:
        occupancy/peak/geometry gauges render with live values."""
        from deeplearning4j_tpu.metrics.exposition import render_text
        from deeplearning4j_tpu.metrics.registry import MetricsRegistry

        reg = MetricsRegistry()
        with serving(lm, V, slots=2, kv_dtype="int8", registry=reg) as srv:
            st = srv.stats()
            text = render_text([({}, reg)])
        for name in ("generation_pages_total", "generation_pages_in_use",
                     "generation_pages_shared",
                     "generation_peak_resident_kv_bytes",
                     "generation_kv_bytes_per_token",
                     "generation_kv_cache_int8"):
            assert name in text, f"missing gauge {name}"
        assert f"generation_pages_total {st['pages']['pages_total']}" \
            in text
        assert "generation_kv_cache_int8 1" in text
        assert ("generation_kv_bytes_per_token "
                f"{st['pages']['bytes_per_token']}") in text

    def test_streaming_carry_int8(self, lm):
        """The dense (non-paged) streaming carry also supports int8:
        token-by-token decode through ``init_streaming_carry(...,
        kv_dtype="int8")`` tracks the full forward's decisions."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.zoo import lm_stream_forward

        rs = np.random.RandomState(11)
        ids = rs.randint(0, V, (2, 10))
        oh = np.asarray(jax.nn.one_hot(ids, V, dtype=jnp.float32))
        full = np.asarray(lm.output(oh))
        fwd = lm_stream_forward(lm)
        carry = {}
        for name, layer in lm._stream_layers():
            if hasattr(layer, "init_paged_carry"):
                carry[name] = layer.init_streaming_carry(
                    2, kv_dtype="int8")
            else:
                carry[name] = layer.init_streaming_carry(2)
        outs = []
        for t in range(ids.shape[1]):
            o, carry = fwd(lm.params, lm.state, oh[:, t:t + 1], carry)
            outs.append(np.asarray(o))
        stream = np.concatenate(outs, axis=1)
        agree = (stream.argmax(-1) == full.argmax(-1)).mean()
        assert agree >= 0.9


@pytest.mark.quant
class TestAccuracyGates:
    def test_confusion_delta(self):
        a = np.array([[5, 0], [0, 5]])
        assert confusion_delta(a, a.copy()) == 0.0
        b = np.array([[4, 1], [0, 5]])  # one example moved cells
        assert confusion_delta(a, b) == pytest.approx(0.1)
        with pytest.raises(ValueError, match="example counts"):
            confusion_delta(a, np.array([[9, 1], [0, 5]]))
        with pytest.raises(ValueError, match="shapes"):
            confusion_delta(a, np.zeros((3, 3), int))

    def test_greedy_agreement(self):
        assert greedy_agreement([1, 2, 3], [1, 2, 3]) == 1.0
        assert greedy_agreement([1, 2, 3], [1, 2, 4]) == pytest.approx(2 / 3)
        # missing tail counts as disagreement
        assert greedy_agreement([1, 2], [1, 2, 3]) == pytest.approx(2 / 3)
        assert greedy_agreement([], []) == 1.0
