"""Model zoo tests (reference: deeplearning4j-zoo/src/test TestInstantiation).

Every zoo model must build (config + shape inference), initialise, and run a
forward pass; the small ones must train. Reduced input sizes keep the CPU
suite fast; full-size instantiation is covered by bench.py on TPU.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.models import (
    AlexNet,
    FaceNetNN4Small2,
    GoogLeNet,
    InceptionResNetV1,
    LeNet,
    ResNet50,
    SimpleCNN,
    TextGenerationLSTM,
    TransformerLM,
    VGG16,
    VGG19,
    zoo_models,
)


def test_registry_complete():
    names = set(zoo_models())
    assert names == {"alexnet", "facenetnn4small2", "googlenet",
                     "inceptionresnetv1", "lenet", "resnet50", "simplecnn",
                     "textgenlstm", "transformerlm", "vgg16", "vgg19"}


@pytest.mark.parametrize("cls,kw,x_shape", [
    (LeNet, {}, (2, 28, 28, 1)),
    # slow: ~18s compile; LeNet + the LSTM keep the forward+train path in
    # tier-1 (see the tier-1 duration budget note in conftest.py)
    pytest.param(SimpleCNN, {}, (2, 48, 48, 1), marks=pytest.mark.slow),
    (TextGenerationLSTM, {"num_labels": 11, "max_length": 8}, (2, 8, 11)),
])
def test_small_models_forward_and_train(cls, kw, x_shape):
    m = cls(**kw)
    net = m.init()
    rs = np.random.RandomState(0)
    x = rs.randn(*x_shape).astype(np.float32)
    n_out = net.conf.layers[-1].n_out if hasattr(net, "layers") else None
    if x.ndim == 3:  # rnn: per-timestep labels
        y = np.eye(n_out, dtype=np.float32)[
            rs.randint(0, n_out, x.shape[:2])]
    else:
        y = np.eye(n_out, dtype=np.float32)[rs.randint(0, n_out, x.shape[0])]
    out = np.asarray(net.output(x))
    assert out.shape[0] == x.shape[0]
    first, _ = net.do_step(x, y)
    for _ in range(8):
        last, _ = net.do_step(x, y)
    assert np.isfinite(last) and last < first * 1.5


@pytest.mark.parametrize("cls,shape,n_params_min", [
    (AlexNet, (64, 64, 3), 1_000_000),
    (VGG16, (32, 32, 3), 10_000_000),
    (VGG19, (32, 32, 3), 15_000_000),
    # slow: the three heaviest compiles (~15-24s each); the four tier-1
    # params above/below exercise the same build-graph/init/forward path
    # (see the tier-1 duration budget note in conftest.py)
    pytest.param(ResNet50, (64, 64, 3), 20_000_000,
                 marks=pytest.mark.slow),
    pytest.param(GoogLeNet, (64, 64, 3), 5_000_000,
                 marks=pytest.mark.slow),
    (FaceNetNN4Small2, (64, 64, 3), 1_000_000),
    pytest.param(InceptionResNetV1, (96, 96, 3), 15_000_000,
                 marks=pytest.mark.slow),
])
def test_big_models_instantiate_and_forward(cls, shape, n_params_min):
    """Reduced input sizes (zoo models accept input_shape overrides like the
    reference's setInputShape)."""
    m = cls(num_labels=10, input_shape=shape)
    if cls is AlexNet:
        # AlexNet's fixed stride stack needs the full 224 input
        m = cls(num_labels=10)
        shape = m.input_shape
    net = m.init()
    assert net.num_params() > n_params_min
    x = np.random.RandomState(1).randn(2, *shape).astype(np.float32)
    # train-mode forward: BN uses batch stats — inference-mode stats are
    # meaningless before training (esp. ResNet50's reference Normal(0,0.5)
    # init, which saturates a 50-layer stack)
    out = np.asarray(net.output(x, train=True))
    assert out.shape == (2, 10)
    assert np.all(np.isfinite(out))
    assert np.allclose(out.sum(axis=1), 1.0, atol=1e-4)  # softmax head


def test_resnet50_residual_structure():
    conf = ResNet50(num_labels=10, input_shape=(64, 64, 3)).conf()
    # 16 residual joins: 4 conv blocks + 12 identity blocks
    from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertex
    adds = [v for v in conf.vertices.values()
            if isinstance(v, ElementWiseVertex)]
    assert len(adds) == 16


def test_zoo_model_serialization_roundtrip(tmp_path):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.utils.model_serializer import (
        load_model,
        save_model,
    )

    net = LeNet(num_labels=10).init()
    rs = np.random.RandomState(3)
    x = rs.randn(4, 28, 28, 1).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 4)]
    net.fit(DataSet(x, y), epochs=2)
    p = str(tmp_path / "lenet.zip")
    save_model(net, p)
    net2 = load_model(p)
    assert np.allclose(np.asarray(net.output(x)), np.asarray(net2.output(x)),
                       atol=1e-6)


def test_init_pretrained_raises_clearly():
    with pytest.raises(NotImplementedError, match="network access"):
        LeNet().init_pretrained()


def test_transformer_lm_learns_next_token():
    """Beyond-parity TransformerLM: causal attention + pre-norm residual
    blocks learn a deterministic cyclic-sequence next-token task."""
    V, T = 11, 16
    m = TransformerLM(num_labels=V, max_length=T, d_model=32, n_heads=4,
                      n_blocks=2, seed=5).init()
    rs = np.random.RandomState(0)
    from deeplearning4j_tpu.datasets.dataset import DataSet

    # token t+1 = (token t + 1) mod V, random start per sequence
    starts = rs.randint(0, V, 64)
    seq = (starts[:, None] + np.arange(T + 1)[None, :]) % V
    x = np.eye(V, dtype=np.float32)[seq[:, :-1]]
    y = np.eye(V, dtype=np.float32)[seq[:, 1:]]
    ds = DataSet(x, y)
    s0 = m.score(ds)
    for _ in range(200):
        m.fit(ds)
    s1 = m.score(ds)
    assert s1 < s0 * 0.5, (s0, s1)
    pred = np.asarray(m.output(x)).argmax(-1)
    acc = float((pred == seq[:, 1:]).mean())
    assert acc > 0.9, acc


def test_transformer_lm_causality():
    """Changing a future token must not change past predictions."""
    V, T = 7, 12
    m = TransformerLM(num_labels=V, max_length=T, d_model=16, n_heads=2,
                      n_blocks=1, seed=3).init()
    rs = np.random.RandomState(1)
    idx = rs.randint(0, V, (2, T))
    x1 = np.eye(V, dtype=np.float32)[idx]
    idx2 = idx.copy()
    idx2[:, -1] = (idx2[:, -1] + 1) % V  # perturb ONLY the last token
    x2 = np.eye(V, dtype=np.float32)[idx2]
    o1 = np.asarray(m.output(x1))
    o2 = np.asarray(m.output(x2))
    np.testing.assert_allclose(o1[:, :-1], o2[:, :-1], atol=1e-5)
    assert np.abs(o1[:, -1] - o2[:, -1]).max() > 1e-6


def test_transformer_streaming_matches_full_forward():
    """KV-cache incremental decode == full forward, token by token: the
    streaming path (rnn_time_step seeding kcache/vcache/cache_pos carry)
    must reproduce the full causal forward's logits at every position."""
    V, T = 9, 10
    m = TransformerLM(num_labels=V, max_length=T, d_model=16, n_heads=2,
                      n_blocks=2, seed=8).init()
    rs = np.random.RandomState(4)
    idx = rs.randint(0, V, (3, T))
    x = np.eye(V, dtype=np.float32)[idx]
    full = np.asarray(m.output(x))                 # [B, T, V]

    m.rnn_clear_previous_state()
    stream = []
    for t in range(T):
        out = m.rnn_time_step(x[:, t:t + 1, :])    # one token at a time
        stream.append(np.asarray(out)[:, 0])
    stream = np.stack(stream, axis=1)
    np.testing.assert_allclose(stream, full, atol=1e-5, rtol=1e-4)

    # a fresh stream after clearing starts from scratch (prefix parity)
    m.rnn_clear_previous_state()
    out0 = np.asarray(m.rnn_time_step(x[:, :4, :]))  # 4-token prompt chunk
    np.testing.assert_allclose(out0, full[:, :4], atol=1e-5, rtol=1e-4)


def test_transformer_generation_follows_learned_rule():
    """Train on the +1 mod V cyclic language, then greedy-generate with
    the KV cache: continuations must follow the rule."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import greedy_generate

    V, T = 11, 16
    m = TransformerLM(num_labels=V, max_length=T, d_model=32, n_heads=4,
                      n_blocks=2, seed=5).init()
    rs = np.random.RandomState(0)
    starts = rs.randint(0, V, 64)
    seq = (starts[:, None] + np.arange(T + 1)[None, :]) % V
    x = np.eye(V, dtype=np.float32)[seq[:, :-1]]
    y = np.eye(V, dtype=np.float32)[seq[:, 1:]]
    ds = DataSet(x, y)
    for _ in range(200):
        m.fit(ds)

    prompt = seq[:4, :6]                           # 6-token prompts
    gen = greedy_generate(m, prompt, steps=8, vocab=V)
    expected = (prompt[:, -1:] + 1 + np.arange(8)[None, :]) % V
    assert (gen == expected).mean() > 0.9, (gen[0], expected[0])


def test_streaming_cache_overflow_raises():
    V = 5
    m = TransformerLM(num_labels=V, max_length=4, d_model=8, n_heads=2,
                      n_blocks=1, seed=1).init()
    # shrink the attention cache to 4 positions
    for v in m.conf.vertices.values():
        lyr = getattr(v, "layer", None)
        if lyr is not None and hasattr(lyr, "max_cache"):
            lyr.max_cache = 4
    x = np.eye(V, dtype=np.float32)[np.zeros((1, 3), np.int64)]
    m.rnn_clear_previous_state()
    m.rnn_time_step(x)                 # 3 of 4 slots used
    with pytest.raises(ValueError, match="KV cache overflow"):
        m.rnn_time_step(x)             # 3 more would exceed 4


@pytest.mark.parametrize("device_loop", [True, False])
def test_sample_generate_temperature_and_topk(device_loop):
    """temperature=0 == greedy; sampled tokens vary with seed but stay
    inside the top-k support set — both the device lax.scan path and the
    host-driven rnn_time_step path."""
    from deeplearning4j_tpu.models import greedy_generate, sample_generate

    V, T = 13, 12
    m = TransformerLM(num_labels=V, max_length=T, d_model=16, n_heads=2,
                      n_blocks=1, seed=6).init()
    rs = np.random.RandomState(1)
    prompt = rs.randint(0, V, (2, 4))
    kw = dict(vocab=V, device_loop=device_loop)

    g = greedy_generate(m, prompt, steps=6, vocab=V)
    s0 = sample_generate(m, prompt, steps=6, temperature=0.0, **kw)
    np.testing.assert_array_equal(g, s0)  # temp 0 IS greedy

    a = sample_generate(m, prompt, steps=6, temperature=1.5, seed=1, **kw)
    b = sample_generate(m, prompt, steps=6, temperature=1.5, seed=2, **kw)
    c = sample_generate(m, prompt, steps=6, temperature=1.5, seed=1, **kw)
    np.testing.assert_array_equal(a, c)   # deterministic in seed
    assert (a != b).any()                 # varies across seeds

    # top_k=1 is greedy regardless of temperature
    k1 = sample_generate(m, prompt, steps=6, temperature=2.0, top_k=1,
                         seed=3, **kw)
    np.testing.assert_array_equal(k1, g)

    with pytest.raises(ValueError, match="top_k"):
        sample_generate(m, prompt, steps=2, top_k=V + 1, **kw)
    with pytest.raises(ValueError, match="temperature"):
        sample_generate(m, prompt, steps=2, temperature=-0.5, **kw)
