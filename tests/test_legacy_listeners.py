"""Legacy visual listeners (ports the intent of FlowIterationListenerTest
and the HistogramIterationListener smoke tests from deeplearning4j-ui)."""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.ui import (
    ConvolutionalIterationListener,
    FlowIterationListener,
    HistogramIterationListener,
)


def _dense_net():
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(learning_rate=0.01))
            .list(DenseLayer(n_out=8, activation="relu"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _fit_some(net, iters=6):
    rs = np.random.RandomState(0)
    labels = rs.randint(0, 2, 16)
    ds = DataSet((rs.randn(16, 4) + labels[:, None]).astype(np.float32),
                 np.eye(2, dtype=np.float32)[labels])
    for _ in range(iters):
        net.fit(ds)


class TestHistogramListener:
    def test_writes_report_with_all_params(self, tmp_path):
        net = _dense_net()
        net.set_listeners(HistogramIterationListener(str(tmp_path),
                                                     frequency=3))
        _fit_some(net)
        page = (tmp_path / "histograms.html").read_text()
        for name in ("0/W", "0/b", "1/W", "1/b"):
            assert name in page
        assert "score" in page


class TestFlowListener:
    def test_topology_table(self, tmp_path):
        net = _dense_net()
        net.set_listeners(FlowIterationListener(str(tmp_path), frequency=2))
        _fit_some(net, iters=2)
        page = (tmp_path / "flow.html").read_text()
        assert "DenseLayer" in page and "OutputLayer" in page
        assert "MultiLayerNetwork" in page


class TestConvListener:
    def test_feature_map_heatmaps(self, tmp_path):
        conf = (NeuralNetConfiguration.builder()
                .seed(2).updater(Adam(learning_rate=0.01))
                .list(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                       activation="relu"),
                      OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        rs = np.random.RandomState(1)
        probe = rs.randn(1, 8, 8, 1).astype(np.float32)
        net.set_listeners(ConvolutionalIterationListener(
            str(tmp_path), probe, frequency=1, max_maps=2))
        ds = DataSet(rs.randn(4, 8, 8, 1).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rs.randint(0, 2, 4)])
        net.fit(ds)
        page = (tmp_path / "activations.html").read_text()
        assert "ChartMatrix" in page
        assert "layer 0 map 0" in page and "layer 0 map 1" in page

    def test_works_with_computation_graph(self, tmp_path):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = (NeuralNetConfiguration.builder()
                .seed(3).updater(Adam(learning_rate=0.01))
                .graph_builder()
                .add_inputs("in")
                .add_layer("conv", ConvolutionLayer(n_out=3,
                                                    kernel_size=(3, 3),
                                                    activation="relu"),
                           "in")
                .add_layer("out", OutputLayer(n_out=2, activation="softmax",
                                              loss="mcxent"), "conv")
                .set_outputs("out")
                .set_input_types(InputType.convolutional(8, 8, 1))
                .build())
        net = ComputationGraph(conf).init()
        rs = np.random.RandomState(2)
        probe = rs.randn(1, 8, 8, 1).astype(np.float32)
        net.set_listeners(ConvolutionalIterationListener(
            str(tmp_path), probe, frequency=1, max_maps=1))
        ds = DataSet(rs.randn(4, 8, 8, 1).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rs.randint(0, 2, 4)])
        net.fit(ds)
        page = (tmp_path / "activations.html").read_text()
        assert "ChartMatrix" in page
