"""Multi-process distributed proof: 2 OS processes, one global mesh.

The reference proves cluster semantics clusterlessly via Spark local[N]
(spark/dl4j-spark/src/test/.../BaseSparkTest.java:46,89). The JAX analog:
spawn 2 real processes, `jax.distributed.initialize` them against a local
coordinator (via parallel/distributed.py — the multi-host half of the comm
backend), build a 2-device global ``data`` mesh (1 CPU device per process),
train the SAME network on a data-sharded global batch, and assert the
result equals single-process training on the full batch. GSPMD inserts the
cross-process psum for the loss mean — the pmean step literally runs over
the gloo inter-process transport.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid, port, outdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]

    from deeplearning4j_tpu.parallel import distributed as dist
    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=2, process_id=pid)
    assert dist.global_device_count() == 2
    assert dist.local_device_count() == 1
    assert dist.process_index() == pid
    assert dist.is_coordinator() == (pid == 0)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updater import Sgd

    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Sgd(learning_rate=0.1))
            .list(DenseLayer(n_out=16, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()

    # deterministic batch, constructed identically in both processes; each
    # process owns rows [pid*8, (pid+1)*8) of the global [16, 6] batch
    rs = np.random.RandomState(0)
    x = rs.randn(16, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)]

    mesh = Mesh(np.array(jax.devices()), ("data",))
    sh = NamedSharding(mesh, P("data"))
    for step in range(4):
        xg = jax.make_array_from_process_local_data(
            sh, x[pid * 8:(pid + 1) * 8], global_shape=x.shape)
        yg = jax.make_array_from_process_local_data(
            sh, y[pid * 8:(pid + 1) * 8], global_shape=y.shape)
        net.do_step(xg, yg)

    np.save(f"{outdir}/params_{pid}.npy", np.asarray(net.params_flat()))
    print("WORKER_OK", pid)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_pmean_training_equals_single_process(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    port = _free_port()
    env = dict(os.environ, PYTHONPATH=REPO)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(port), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"WORKER_OK {pid}" in out

    p0 = np.load(tmp_path / "params_0.npy")
    p1 = np.load(tmp_path / "params_1.npy")
    # both processes hold identical replicated params after the pmean steps
    np.testing.assert_array_equal(p0, p1)

    # single-process training on the full concatenated batch must match
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updater import Sgd

    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Sgd(learning_rate=0.1))
            .list(DenseLayer(n_out=16, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    x = rs.randn(16, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)]
    for _ in range(4):
        net.do_step(x, y)
    single = np.asarray(net.params_flat())
    np.testing.assert_allclose(p0, single, atol=1e-6)
