"""Crash-durable generation tests (parallel/handoff.py).

Covers the KV-snapshot/live-migration contract end to end on the CPU
mesh: snapshot export of a live request (resident KV pages, block-table
row, stream position, RNG fold-in state, accepted tokens) with a
versioned checksummed wire format, adoption into a DIFFERENT server
resuming at position N bit-exactly (greedy and sampled, f32 and int8
pools), corrupted-checksum detection falling back to token-0 replay,
fleet failover resuming from the newest harvested snapshot after a
mid-stream replica kill (zero lost futures), drain-migrate handoff on
both the plain server and ``retire_replica(migrate=True)``, the
preempt-resume path, the seeded ChaosPolicy handoff fault modes, and
the zero-retrace property under repeated adoption.
"""

import time
from contextlib import contextmanager

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import (TransformerLM, greedy_generate,
                                           sample_generate)
from deeplearning4j_tpu.parallel.fleet import RETIRED, ReplicaFleet
from deeplearning4j_tpu.parallel.generation import GenerationServer
from deeplearning4j_tpu.parallel.handoff import (WIRE_VERSION, KVSnapshot,
                                                 RequestMigrated,
                                                 SnapshotInvalid,
                                                 SnapshotUnavailable,
                                                 SnapshotUnsupported,
                                                 adopt_request,
                                                 corrupt_snapshot,
                                                 downgrade_snapshot,
                                                 export_request)
from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                    ResilienceError,
                                                    ServerOverloaded,
                                                    TransientDispatchError)

V = 17


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(num_labels=V, max_length=16, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


@contextmanager
def serving(*args, **kwargs):
    srv = GenerationServer(*args, **kwargs)
    try:
        yield srv
    finally:
        srv.close()


@contextmanager
def fleet_of(factory, replicas=2, **kw):
    fl = ReplicaFleet(factory, replicas=replicas, **kw)
    try:
        yield fl
    finally:
        fl.close()


def _mixed_specs(n, rng, shapes=((3, 4), (5, 5), (4, 6))):
    specs = []
    for i in range(n):
        plen, steps = shapes[i % len(shapes)]
        p = rng.integers(1, V, size=plen).astype(np.int64)
        if i % 2 == 0:
            specs.append((p, steps, 0.0, 0, 0))
        else:
            specs.append((p, steps, 0.9, 5, 2000 + i))
    return specs


def _serial_refs(lm, specs):
    refs = []
    for p, steps, temp, top_k, seed in specs:
        if temp == 0.0:
            refs.append(greedy_generate(lm, p[None], steps, V)[0])
        else:
            refs.append(sample_generate(lm, p[None], steps, V,
                                        temperature=temp, top_k=top_k,
                                        seed=seed)[0])
    return refs


def _submit_with_backoff(fleet, spec, deadline_s=240.0, budget_s=60.0):
    p, steps, temp, top_k, seed = spec
    t_end = time.monotonic() + budget_s
    while True:
        try:
            return fleet.submit(p, steps, temperature=temp, top_k=top_k,
                                seed=seed, deadline_s=deadline_s)
        except ResilienceError:
            if time.monotonic() > t_end:
                raise
            time.sleep(0.02)


def _run_to_snapshot(lm, spec, **server_kw):
    """Run one request to completion on a periodically-snapshotting
    server; return (completed tokens, last published KVSnapshot)."""
    p, steps, temp, top_k, seed = spec
    kw = dict(slots=2, page_size=4, snapshot_every=4,
              steps_per_dispatch=2)
    kw.update(server_kw)
    with serving(lm, V, **kw) as srv:
        fut = srv.submit(p, steps, temperature=temp, top_k=top_k,
                         seed=seed)
        out = np.asarray(fut.result(timeout=120))
        st = srv.stats()["handoff"]
    snap = getattr(fut, "_kv_snapshot", None)
    assert snap is not None, "snapshot_every published no snapshot"
    assert st["snapshots"] >= 1 and st["bytes"] > 0
    return out, snap


GREEDY = (np.array([1, 2, 3, 4], np.int64), 12, 0.0, 0, 0)
SAMPLED = (np.array([1, 2, 3, 4], np.int64), 12, 0.9, 5, 77)


@pytest.mark.handoff
class TestSnapshotRoundTrip:
    def test_greedy_f32_resume_bitexact(self, lm):
        """A mid-stream snapshot adopted into a DIFFERENT server resumes
        at position N and finishes byte-identical to the uninterrupted
        greedy stream — no token is recomputed differently."""
        p = GREEDY[0]
        ref = greedy_generate(lm, p[None], 12, V)[0]
        out, snap = _run_to_snapshot(lm, GREEDY)
        np.testing.assert_array_equal(out, ref)
        assert 0 < snap.count < 12          # genuinely mid-stream
        assert snap.version == WIRE_VERSION
        assert list(snap.tokens) == list(ref[:snap.count])
        with serving(lm, V, slots=2, page_size=4) as dst:
            res = adopt_request(dst, snap).result(timeout=120)
            st = dst.stats()["handoff"]
        np.testing.assert_array_equal(np.asarray(res), ref)
        assert st["resumes"] == 1
        assert st["tokens_saved"] == snap.count
        assert st["fallbacks"] == 0

    def test_sampled_f32_resume_bitexact(self, lm):
        """The fold_in key schedule is server-state-free, so a SAMPLED
        stream resumes bit-exactly on the adopting server too."""
        p, steps, temp, top_k, seed = SAMPLED
        ref = sample_generate(lm, p[None], steps, V, temperature=temp,
                              top_k=top_k, seed=seed)[0]
        out, snap = _run_to_snapshot(lm, SAMPLED)
        np.testing.assert_array_equal(out, ref)
        with serving(lm, V, slots=2, page_size=4) as dst:
            res = adopt_request(dst, snap).result(timeout=120)
        np.testing.assert_array_equal(np.asarray(res), ref)

    def test_int8_resume_bitexact_and_wire_ratio(self, lm):
        """An int8 pool snapshots its quantized pages + scale planes:
        adoption reproduces the uninterrupted int8 stream bit-exactly,
        and the wire image ships >= 2.5x smaller than the f32 one at
        the same stream position."""
        out_q, snap_q = _run_to_snapshot(lm, GREEDY, kv_dtype="int8")
        _out_f, snap_f = _run_to_snapshot(lm, GREEDY)
        assert snap_q.kv_dtype == "int8"
        assert snap_f.count == snap_q.count  # same publish schedule
        assert snap_q.wire_bytes() < snap_f.wire_bytes()
        # page payload (the part that scales with context) shrinks by
        # the int8 + per-row-scale factor; the JSON header is constant
        pf = sum(a.nbytes for _, _, a in _leaves(snap_f))
        pq = sum(a.nbytes for _, _, a in _leaves(snap_q))
        ratio = pf / pq
        assert ratio >= 2.5, f"int8 KV payload only {ratio:.2f}x smaller"
        with serving(lm, V, slots=2, page_size=4, kv_dtype="int8") as dst:
            res = adopt_request(dst, snap_q).result(timeout=120)
        np.testing.assert_array_equal(np.asarray(res), out_q)

    def test_wire_bytes_roundtrip(self, lm):
        """to_bytes/from_bytes is lossless: every header field and every
        payload leaf round-trips, and the checksum re-verifies."""
        _out, snap = _run_to_snapshot(lm, SAMPLED)
        blob = snap.to_bytes()
        assert len(blob) == snap.wire_bytes()
        back = KVSnapshot.from_bytes(blob)
        assert back.verify()
        for f in ("version", "pos", "count", "last", "temperature",
                  "top_k", "seed", "kv_dtype", "page_size",
                  "page_token_bytes", "page_digests"):
            assert getattr(back, f) == getattr(snap, f), f
        assert list(back.tokens) == list(snap.tokens)
        np.testing.assert_array_equal(back.prompt, snap.prompt)
        np.testing.assert_array_equal(back.key, snap.key)
        for (vn, leaf, a), (vn2, leaf2, b) in zip(
                _leaves(snap), _leaves(back)):
            assert (vn, leaf) == (vn2, leaf2)
            np.testing.assert_array_equal(a, b)

    def test_wire_rejects_garbage(self, lm):
        _out, snap = _run_to_snapshot(lm, GREEDY)
        blob = snap.to_bytes()
        with pytest.raises(SnapshotInvalid, match="byte stream"):
            KVSnapshot.from_bytes(b"XXXX" + blob[4:])
        # flip one payload byte: the sha256 gate catches it
        mid = len(blob) // 2
        bad = blob[:mid] + bytes([blob[mid] ^ 0xFF]) + blob[mid + 1:]
        with pytest.raises(SnapshotInvalid):
            KVSnapshot.from_bytes(bad)


def _leaves(snap):
    from deeplearning4j_tpu.parallel.handoff import _leaf_items
    return list(_leaf_items(snap.payload))


@pytest.mark.handoff
class TestWireV3ForwardCompat:
    """The v3 wire generation: sharded-geometry header fields, the
    typed cross-version refusal (BEFORE the checksum — a version skew
    must never masquerade as corruption), and the v2 downgrade bridge
    for fleet tiers still running v2-geometry readers."""

    def test_v3_header_roundtrip_tp1(self, lm):
        """A single-chip server emits v3 with the implied single-chip
        geometry, and the new fields survive the wire round-trip."""
        _out, snap = _run_to_snapshot(lm, GREEDY)
        assert snap.version == WIRE_VERSION == 3
        assert snap.shards == 1
        assert snap.head_layout == "canonical"
        back = KVSnapshot.from_bytes(snap.to_bytes())
        assert back.verify()
        assert (back.shards, back.head_layout) == (1, "canonical")

    def test_v3_rejected_by_v2_reader_typed(self, lm):
        """A v2-geometry reader (``supported=2``) refuses a v3 blob
        with SnapshotUnsupported naming the full geometry tuple —
        never a checksum error, never a silent truncation. Flipping a
        payload byte first proves the refusal fires BEFORE the
        integrity gate even looks."""
        _out, snap = _run_to_snapshot(lm, GREEDY)
        blob = snap.to_bytes()
        with pytest.raises(SnapshotUnsupported, match="geometry") as ei:
            KVSnapshot.from_bytes(blob, supported=2)
        msg = str(ei.value)
        for frag in ("version=3", "shards=1", "head_layout='canonical'",
                     "page_size="):
            assert frag in msg, msg
        assert "checksum" not in msg
        mid = len(blob) - 8                    # corrupt payload bytes
        bad = blob[:mid] + bytes([blob[mid] ^ 0xFF]) + blob[mid + 1:]
        with pytest.raises(SnapshotUnsupported, match="geometry"):
            KVSnapshot.from_bytes(bad, supported=2)

    def test_v2_rejected_by_v3_reader_typed(self, lm):
        """And the mirror image: a v2 blob at this (v3-geometry) reader
        fails the same typed way with the same tuple in the message."""
        _out, snap = _run_to_snapshot(lm, GREEDY)
        blob2 = downgrade_snapshot(snap).to_bytes()
        with pytest.raises(SnapshotUnsupported, match="geometry") as ei:
            KVSnapshot.from_bytes(blob2)
        assert "version=2" in str(ei.value)
        assert "checksum" not in str(ei.value)

    def test_unknown_version_invalid_before_parse(self, lm):
        """A version NO reader generation knows is SnapshotInvalid (not
        Unsupported): nothing about the header can be trusted, and the
        gate fires before the (now stale) checksum can confuse it."""
        _out, snap = _run_to_snapshot(lm, GREEDY)
        snap.version = 99
        with pytest.raises(SnapshotInvalid, match="version"):
            KVSnapshot.from_bytes(snap.to_bytes())

    def test_downgraded_v2_snapshot_adopts_bitexact(self, lm):
        """downgrade_snapshot emits a wire image a v2 reader parses
        (same payload, version-2 header/checksum), and the adopt gate
        keeps a one-generation legacy fallback: the v2 snapshot resumes
        bit-exactly on a live server."""
        p = GREEDY[0]
        ref = greedy_generate(lm, p[None], 12, V)[0]
        out, snap = _run_to_snapshot(lm, GREEDY)
        np.testing.assert_array_equal(out, ref)
        v2 = KVSnapshot.from_bytes(downgrade_snapshot(snap).to_bytes(),
                                   supported=2)
        assert v2.version == 2
        assert v2.verify()
        with serving(lm, V, slots=2, page_size=4) as dst:
            res = adopt_request(dst, v2).result(timeout=120)
            st = dst.stats()["handoff"]
        np.testing.assert_array_equal(np.asarray(res), ref)
        assert st["resumes"] == 1 and st["fallbacks"] == 0


@pytest.mark.handoff
class TestExportAndValidation:
    def test_export_live_request_midstream(self, lm):
        """export_request snapshots a request WHILE it streams; the
        exported state adopts elsewhere and both copies finish
        identical to the serial reference."""
        p = GREEDY[0]
        ref = greedy_generate(lm, p[None], 12, V)[0]
        chaos = ChaosPolicy(seed=5, stall_rate=1.0, stall_s=0.03)
        with serving(lm, V, slots=2, page_size=4, steps_per_dispatch=1,
                     chaos=chaos) as src:
            fut = src.submit(p, 12)
            time.sleep(0.05)                 # a few stalled dispatches in
            snap = export_request(src, fut, timeout=60.0)
            assert 1 <= snap.count <= 12
            with serving(lm, V, slots=2, page_size=4) as dst:
                res = adopt_request(dst, snap).result(timeout=120)
            out = fut.result(timeout=120)
        np.testing.assert_array_equal(np.asarray(out), ref)
        np.testing.assert_array_equal(np.asarray(res), ref)

    def test_export_completed_request_unavailable(self, lm):
        with serving(lm, V, slots=2, page_size=4) as src:
            fut = src.submit(GREEDY[0], 4)
            fut.result(timeout=120)
            with pytest.raises(SnapshotUnavailable):
                export_request(src, fut, timeout=30.0)

    def test_speculative_server_unsupported(self, lm):
        """Draft lookahead pages make a slot's KV non-reconstructible
        mid-round: export refuses typed, and snapshot_every refuses at
        construction."""
        with pytest.raises(ValueError, match="snapshot_every"):
            GenerationServer(lm, V, slots=2, draft_net=lm, spec_k=3,
                             snapshot_every=4)
        with serving(lm, V, slots=2, draft_net=lm, spec_k=3) as src:
            fut = src.submit(GREEDY[0], 4)
            with pytest.raises(SnapshotUnsupported):
                export_request(src, fut)
            fut.result(timeout=120)

    def test_adopt_rejects_corrupt_version_and_geometry(self, lm):
        _out, snap = _run_to_snapshot(lm, GREEDY)
        with serving(lm, V, slots=2, page_size=8) as dst:
            with pytest.raises(SnapshotUnsupported, match="geometry"):
                adopt_request(dst, snap)
        with serving(lm, V, slots=2, page_size=4, kv_dtype="int8") as dst:
            with pytest.raises(SnapshotUnsupported, match="geometry"):
                adopt_request(dst, snap)
        with serving(lm, V, slots=2, page_size=4) as dst:
            snap.version = WIRE_VERSION + 1
            with pytest.raises(SnapshotInvalid, match="version"):
                adopt_request(dst, snap)
            snap.version = WIRE_VERSION
            corrupt_snapshot(snap)
            assert not snap.verify()
            with pytest.raises(SnapshotInvalid, match="checksum"):
                adopt_request(dst, snap)

    def test_adopt_infeasible_sheds_typed(self, lm):
        _out, snap = _run_to_snapshot(lm, GREEDY)
        with serving(lm, V, slots=1, page_size=4, pages=3) as dst:
            with pytest.raises(ServerOverloaded):
                adopt_request(dst, snap)


@pytest.mark.handoff
class TestChaosHandoffModes:
    def test_handoff_faults_deterministic_and_exclusive(self):
        """Same seed -> same corrupt/stall sequence; at most one handoff
        fault per draw; stalls sleep outside the policy lock via the
        injected sleeper."""
        def run():
            sleeps = []
            ch = ChaosPolicy(seed=7, snapshot_corrupt_rate=0.15,
                             handoff_stall_rate=0.25, handoff_stall_s=0.5,
                             sleep=sleeps.append)
            outcomes = [ch.handoff_fault() for _ in range(200)]
            return outcomes, sleeps, ch

        o1, s1, c1 = run()
        o2, s2, c2 = run()
        assert o1 == o2 and s1 == s2
        assert c1.injected_snapshot_corrupt == c2.injected_snapshot_corrupt
        assert c1.injected_handoff_stall == c2.injected_handoff_stall
        assert c1.injected_snapshot_corrupt == sum(o1) > 0
        assert c1.injected_handoff_stall == len(s1) > 0
        assert all(s == 0.5 for s in s1)

    def test_legacy_sequences_pinned(self):
        """Zero-rate handoff knobs draw NOTHING from the chaos RNG: the
        replica-fault sequence of a seeded policy is byte-identical with
        the new parameters present and handoff_fault() interleaved."""
        def pattern(**kw):
            ch = ChaosPolicy(seed=11, transient_rate=0.3, hard_rate=0.1,
                             **kw)
            fn = ch.wrap(lambda: "ok")
            seq = []
            for _ in range(200):
                if kw:
                    assert ch.handoff_fault() is False
                try:
                    seq.append(fn() is not None)
                except TransientDispatchError:
                    seq.append("transient")
                except RuntimeError:
                    seq.append("hard")
            return seq

        assert pattern() == pattern(snapshot_corrupt_rate=0.0,
                                    handoff_stall_rate=0.0)


def _wait_replica_midstream(fl, rid, min_snapshots=4, timeout=90.0):
    """Poll until replica ``rid`` is visibly mid-stream: >= 2 live slots
    AND enough published snapshots that the live slots are covered.
    Event-driven, not sleep-calibrated — compile time on a cold program
    cache just extends the poll."""
    t_end = time.monotonic() + timeout
    while True:
        rep = fl.stats()["replicas"][rid]
        srv = rep["server"] or {}
        ho = srv.get("handoff", {})
        if (srv.get("active_slots", 0) >= 2
                and ho.get("snapshots", 0) >= min_snapshots):
            return
        assert time.monotonic() < t_end, (
            f"replica {rid} never reached a snapshotted mid-stream "
            f"state: {srv.get('active_slots')} active, "
            f"{ho.get('snapshots')} snapshots")
        time.sleep(0.005)


LONG_SHAPES = ((3, 8), (5, 9), (4, 10))


@pytest.mark.handoff
class TestFleetHandoff:
    def _factory(self, lm, **chaos_kw):
        def factory(rid):
            chaos = ChaosPolicy(seed=1000 + rid, **chaos_kw)
            return GenerationServer(lm, V, slots=4, page_size=4,
                                    snapshot_every=1, steps_per_dispatch=1,
                                    chaos=chaos)
        return factory

    def test_midstream_kill_resumes_from_snapshot(self, lm):
        """The headline failover: a replica dies under mid-stream
        requests; the fleet harvests each future's newest snapshot and
        the survivor resumes at position N — zero lost futures, every
        completion bit-exact, recompute saved on the handoff counters."""
        rng = np.random.default_rng(21)
        specs = _mixed_specs(24, rng, shapes=LONG_SHAPES)
        refs = _serial_refs(lm, specs)
        factory = self._factory(lm, stall_rate=1.0, stall_s=0.008)
        with fleet_of(factory, replicas=2, max_pending=64,
                      restart_backoff_s=0.02) as fl:
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            _wait_replica_midstream(fl, 0)    # streams mid-generation...
            fl.kill_replica(0)                # ...die under them
            outs = [f.result(timeout=600) for f in futs]
            st = fl.stats()
        assert len(outs) == 24
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(np.asarray(got), ref)
        assert st["completed"] == 24
        assert st["failed"] == 0 and st["expired"] == 0
        assert st["deaths"] >= 1
        assert st["handoff_resumes"] >= 1, \
            "kill resumed nothing from snapshots"

    def test_corrupted_snapshots_fall_back_to_token0(self, lm):
        """snapshot_corrupt chaos poisons every published snapshot: the
        checksum gate rejects them at adoption, the fleet falls back to
        token-0 replay — still zero lost futures and bit-exact, with the
        fallbacks (not resumes) counter telling the story."""
        rng = np.random.default_rng(22)
        specs = _mixed_specs(16, rng, shapes=LONG_SHAPES)
        refs = _serial_refs(lm, specs)
        factory = self._factory(lm, stall_rate=1.0, stall_s=0.008,
                                snapshot_corrupt_rate=1.0)
        with fleet_of(factory, replicas=2, max_pending=64,
                      restart_backoff_s=0.02) as fl:
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            _wait_replica_midstream(fl, 0)
            fl.kill_replica(0)
            outs = [f.result(timeout=600) for f in futs]
            st = fl.stats()
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(np.asarray(got), ref)
        assert st["completed"] == 16
        assert st["failed"] == 0 and st["expired"] == 0
        assert st["handoff_resumes"] == 0
        assert st["handoff_fallbacks"] >= 1, \
            "corrupted snapshots never hit the fallback path"

    def test_retire_migrate_hands_off_live_streams(self, lm):
        """retire_replica(migrate=True) drains by HANDING OFF: live
        slots snapshot at their exact position, requeue through the
        fleet, and finish on the survivor bit-exactly."""
        rng = np.random.default_rng(23)
        specs = [(rng.integers(1, V, size=4).astype(np.int64), 10,
                  0.0, 0, 0) for _ in range(16)]
        refs = _serial_refs(lm, specs)
        factory = self._factory(lm, stall_rate=1.0, stall_s=0.01)
        with fleet_of(factory, replicas=2, max_pending=64,
                      restart_backoff_s=0.02) as fl:
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            _wait_replica_midstream(fl, 0, min_snapshots=2)
            assert fl.retire_replica(0, timeout=60.0, migrate=True)
            outs = [f.result(timeout=600) for f in futs]
            st = fl.stats()
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(np.asarray(got), ref)
        assert st["completed"] == 16
        assert st["failed"] == 0 and st["expired"] == 0
        assert st["replicas"][0]["state"] == RETIRED
        assert st["handoff_resumes"] >= 1, "migration resumed nothing"


@pytest.mark.handoff
class TestServerMigrateAndPreempt:
    def test_drain_migrate_fails_typed_with_adoptable_snapshots(self, lm):
        """Plain-server drain(migrate=...): every live request fails
        typed with RequestMigrated, its snapshot rides both the sink
        callback and the future — and adopting it elsewhere completes
        the stream bit-exactly."""
        rs = np.random.RandomState(31)
        prompts = [rs.randint(1, V, 4) for _ in range(3)]
        refs = [greedy_generate(lm, p[None], 12, V)[0] for p in prompts]
        chaos = ChaosPolicy(seed=9, stall_rate=1.0, stall_s=0.02)
        collected = []
        with serving(lm, V, slots=4, page_size=4, steps_per_dispatch=1,
                     chaos=chaos) as src:
            futs = [src.submit(p, 12) for p in prompts]
            while src.stats()["active_slots"] < 3:
                time.sleep(0.005)             # wait until all prefilled
            src.drain(timeout=60.0, migrate=collected.append)
            st = src.stats()["handoff"]
        assert st["migrated"] == 3
        assert len(collected) == 3
        with serving(lm, V, slots=4, page_size=4) as dst:
            for fut, ref in zip(futs, refs):
                with pytest.raises(RequestMigrated):
                    fut.result(timeout=0)
                snap = fut._kv_snapshot
                assert snap.verify() and snap.count >= 1
                res = adopt_request(dst, snap).result(timeout=120)
                np.testing.assert_array_equal(np.asarray(res), ref)
            dst_st = dst.stats()["handoff"]
        assert dst_st["resumes"] == 3
        assert dst_st["tokens_saved"] == sum(
            f._kv_snapshot.count for f in futs)

    def test_preempt_snapshots_instead_of_discarding(self, lm):
        """Pool-pressure preemption keeps the decoded stream: the victim
        requeues WITH a snapshot, resumes via the adopt path when pages
        free up, and both requests still finish bit-exactly."""
        rs = np.random.RandomState(25)
        pa = rs.randint(1, V, 12)             # 3 pages of prompt each
        pb = rs.randint(1, V, 12)
        ra = greedy_generate(lm, pa[None], 10, V)[0]
        rb = greedy_generate(lm, pb[None], 10, V)[0]
        # each needs 6 pages end to end; 9 usable < 12 combined
        with serving(lm, V, slots=2, page_size=4, pages=10,
                     prefix_cache=False) as srv:
            fa = srv.submit(pa, 10)
            fb = srv.submit(pb, 10)
            np.testing.assert_array_equal(fa.result(timeout=180), ra)
            np.testing.assert_array_equal(fb.result(timeout=180), rb)
            st = srv.stats()
        assert st["pages"]["preempted"] >= 1
        assert st["handoff"]["preempt_resumes"] >= 1
        assert st["handoff"]["resumes"] >= 1
        assert st["handoff"]["tokens_saved"] >= srv._ps
        assert st["completed"] == 2 and st["failed"] == 0

    def test_no_recompile_on_adoption_churn(self):
        """Zero-retrace survives handoff: snapshotting compiles ONE
        gather program, adoption ONE scatter program — then repeated
        adoptions of fresh snapshots add ZERO compiled programs."""
        net = TransformerLM(num_labels=V, max_length=16, d_model=8,
                            n_heads=2, n_blocks=1, seed=9).init()
        specs = [GREEDY, SAMPLED,
                 (np.array([2, 5, 1, 3], np.int64), 12, 0.0, 0, 0)]
        snaps = []
        for sp in specs:
            out, snap = _run_to_snapshot(net, sp)
            snaps.append((snap, out))
        with serving(net, V, slots=2, page_size=4) as dst:
            res0 = adopt_request(dst, snaps[0][0]).result(timeout=120)
            np.testing.assert_array_equal(np.asarray(res0), snaps[0][1])
            warmed = len(net._output_cache)
            for snap, out in snaps[1:]:
                res = adopt_request(dst, snap).result(timeout=120)
                np.testing.assert_array_equal(np.asarray(res), out)
            assert len(net._output_cache) == warmed
