"""Evaluation unit tests: top-N accuracy vs hand-computed values and curve
JSON serde (ports the intent of EvaluationToolsTests / EvalTest topN and
eval/curves round-trip tests)."""

import numpy as np

from deeplearning4j_tpu.evaluation import (
    Evaluation,
    Histogram,
    PrecisionRecallCurve,
    ROC,
    RocCurve,
)


class TestTopN:
    def test_top_n_matches_hand_computed(self):
        # 4 examples, 4 classes. Probabilities constructed so that:
        #   ex0: true 0, ranked 1st            -> top1 hit, top2 hit
        #   ex1: true 2, ranked 2nd            -> top1 miss, top2 hit
        #   ex2: true 1, ranked 3rd            -> top1 miss, top2 miss
        #   ex3: true 3, ranked 1st            -> top1 hit, top2 hit
        probs = np.array([
            [0.70, 0.10, 0.10, 0.10],
            [0.50, 0.05, 0.40, 0.05],
            [0.50, 0.15, 0.30, 0.05],
            [0.10, 0.20, 0.10, 0.60],
        ])
        labels = np.eye(4)[[0, 2, 1, 3]]
        ev = Evaluation(top_n=2)
        ev.eval(labels, probs)
        assert ev.accuracy() == 0.5           # 2/4 top-1
        assert ev.top_n_accuracy() == 0.75    # 3/4 top-2
        assert f"Top-2" in ev.stats()

    def test_top_n_merge(self):
        probs = np.array([[0.6, 0.3, 0.1], [0.2, 0.3, 0.5]])
        labels = np.eye(3)[[1, 1]]            # ranked 2nd both times
        a = Evaluation(top_n=2).eval(labels[:1], probs[:1])
        b = Evaluation(top_n=2).eval(labels[1:], probs[1:])
        a.merge(b)
        assert a.accuracy() == 0.0
        assert a.top_n_accuracy() == 1.0

    def test_top_n_default_is_accuracy(self):
        probs = np.array([[0.6, 0.4], [0.3, 0.7]])
        labels = np.eye(2)[[0, 0]]
        ev = Evaluation().eval(labels, probs)
        assert ev.top_n_accuracy() == ev.accuracy() == 0.5

    def test_top_n_at_least_num_classes_is_all_correct(self):
        """top_n >= C: the top-N set is all classes, so every example is a
        hit (and argpartition's kth would be out of range) — hand-computed:
        3 examples, 3 classes, top_n=3."""
        probs = np.array([[0.6, 0.3, 0.1],
                          [0.1, 0.2, 0.7],
                          [0.4, 0.4, 0.2]])
        # true classes ranked 3rd, 3rd, tied-1st: top-2 hits only ex2
        labels = np.eye(3)[[2, 0, 1]]
        for n in (3, 5):
            ev = Evaluation(top_n=n).eval(labels, probs)
            assert ev.top_n_correct == 3
            assert ev.top_n_total == 3
            assert ev.top_n_accuracy() == 1.0
        # boundary below: top_n = C-1 = 2 still uses the ranked path
        ev = Evaluation(top_n=2).eval(labels, probs)
        assert ev.top_n_accuracy() == 1 / 3


class TestZeroState:
    def test_per_class_metrics_on_empty_evaluation(self):
        """Explicit class index on a never-evaluated instance (e.g. a
        zero-batch worker in the distributed merge): 0.0, not IndexError on
        the 1x1 placeholder."""
        ev = Evaluation()
        assert ev.precision(2) == 0.0
        assert ev.recall(2) == 0.0
        assert ev.false_positive_rate(2) == 0.0
        assert ev.f1(2) == 0.0

    def test_zero_state_merges_cleanly(self):
        probs = np.array([[0.8, 0.1, 0.1], [0.2, 0.6, 0.2]])
        labels = np.eye(3)[[0, 1]]
        full = Evaluation().eval(labels, probs)
        empty = Evaluation()
        empty.merge(full)
        assert empty.precision(0) == full.precision(0)
        assert empty.recall(1) == full.recall(1)


class TestCurveSerde:
    def _roc(self):
        rs = np.random.RandomState(0)
        scores = rs.rand(200)
        targets = (scores + rs.randn(200) * 0.3 > 0.5).astype(float)
        return ROC().eval(targets, scores)

    def test_roc_curve_roundtrip(self):
        roc = self._roc()
        curve = roc.get_roc_curve()
        back = RocCurve.from_json(curve.to_json())
        assert back == curve
        assert back.calculate_auc() == roc.calculate_auc()

    def test_pr_curve_roundtrip(self):
        roc = self._roc()
        curve = roc.get_precision_recall_curve()
        back = PrecisionRecallCurve.from_json(curve.to_json())
        assert back == curve
        assert abs(back.calculate_auprc() - roc.calculate_auprc()) < 1e-12

    def test_histogram_roundtrip(self):
        h = Histogram(title="w", min=-1.0, max=1.0, counts=[1, 5, 9, 2])
        assert Histogram.from_json(h.to_json()) == h

    def test_histogram_wraps_stats_pipeline_entry(self):
        # same schema StatsListener._histograms emits
        entry = {"counts": [2, 3], "min": -0.5, "max": 0.5}
        h = Histogram.from_stats("0/W", entry)
        assert h.counts == [2, 3] and h.min == -0.5 and h.max == 0.5
        assert Histogram.from_json(h.to_json()) == h

    def test_roc_curve_json_is_strict(self):
        """The +inf sentinel threshold must not leak as bare `Infinity`
        (invalid RFC 8259 — browser JSON.parse would reject the curve)."""
        import json as _json
        roc = ROC().eval(np.array([1.0, 0.0, 1.0]),
                         np.array([0.9, 0.2, 0.7]))
        s = roc.get_roc_curve().to_json()
        assert "Infinity" not in s
        _json.loads(s)  # strict-parseable
        back = RocCurve.from_json(s)
        assert back.thresholds[0] == float("inf")

    def test_wrong_class_rejected(self):
        h = Histogram()
        try:
            RocCurve.from_json(h.to_json())
        except ValueError:
            return
        raise AssertionError("expected ValueError")


class TestEvaluationSerde:
    def test_round_trip_preserves_metrics_and_merge(self):
        rs = np.random.RandomState(3)
        probs = rs.rand(64, 4)
        labels = np.eye(4)[rs.randint(0, 4, 64)]
        ev = Evaluation(labels=["w", "x", "y", "z"], top_n=2)
        ev.eval(labels, probs)
        back = Evaluation.from_json(ev.to_json())
        assert back.accuracy() == ev.accuracy()
        assert back.top_n_accuracy() == ev.top_n_accuracy()
        assert back.label_names == ["w", "x", "y", "z"]
        np.testing.assert_array_equal(back.confusion, ev.confusion)
        # the transport use-case: merge a deserialized remote result
        ev2 = Evaluation(top_n=2).eval(labels, probs)
        ev2.merge(back)
        assert ev2.confusion.sum() == 128

    def test_empty_round_trip(self):
        back = Evaluation.from_json(Evaluation().to_json())
        assert back.accuracy() == 0.0 and back.confusion is None
        # every sibling metric must also survive the empty case
        assert back.precision() == back.recall() == back.f1() == 0.0
        assert isinstance(back.stats(), str)


class TestSimpleResults:
    def test_rank_classification(self):
        from deeplearning4j_tpu.nn.simple import RankClassificationResult
        probs = np.array([[0.1, 0.7, 0.2], [0.5, 0.2, 0.3]])
        r = RankClassificationResult(probs, labels=["a", "b", "c"])
        assert r.max_output() == ["b", "a"]
        assert r.ranked_classes(0) == ["b", "c", "a"]
        assert r.probability(1, "c") == 0.3

    def test_binary_result(self):
        from deeplearning4j_tpu.nn.simple import BinaryClassificationResult
        r = BinaryClassificationResult(np.array([[0.3, 0.7], [0.9, 0.1]]))
        np.testing.assert_array_equal(r.decisions(), [1, 0])
        assert r.positive_count() == 1
        r2 = BinaryClassificationResult([0.2, 0.6, 0.9], threshold=0.8)
        np.testing.assert_array_equal(r2.decisions(), [0, 0, 1])
        try:
            BinaryClassificationResult(np.zeros((4, 3)))
        except ValueError:
            pass
        else:
            raise AssertionError("multiclass input must be rejected")
