"""Observability pipeline tests (ports the intent of ui-model
TestStatsListener / TestStatsStorage and the remote-router round trip)."""

import json
import urllib.request

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.ui import (
    FileStatsStorage,
    InMemoryStatsStorage,
    RemoteUIStatsStorageRouter,
    StatsListener,
    UIServer,
)
from deeplearning4j_tpu.ui.stats import TYPE_ID
from deeplearning4j_tpu.ui.storage import make_record


def _trained_net_with_listener(storage, iters=25, frequency=5):
    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(learning_rate=0.01))
            .list(DenseLayer(n_out=8, activation="relu"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    listener = StatsListener(storage, session_id="s1",
                             reporting_frequency=frequency)
    net.set_listeners(listener)
    rs = np.random.RandomState(0)
    labels = rs.randint(0, 3, 32)
    ds = DataSet((rs.randn(32, 4) + labels[:, None]).astype(np.float32),
                 np.eye(3, dtype=np.float32)[labels])
    for _ in range(iters):
        net.fit(ds)
    return net, listener


class TestStatsListenerStorage:
    def test_updates_recorded_and_queryable(self):
        storage = InMemoryStatsStorage()
        _trained_net_with_listener(storage, iters=25, frequency=5)
        assert storage.list_session_ids() == ["s1"]
        assert storage.list_type_ids("s1") == [TYPE_ID]
        upd = storage.get_all_updates_after("s1", TYPE_ID)
        assert len(upd) == 5  # iterations 5,10,15,20,25
        d = upd[-1]["data"]
        assert np.isfinite(d["score"])
        assert "0/W" in d["param_norms"] and "1/b" in d["param_norms"]
        assert d["param_norms"]["0/W"] > 0
        assert "update_norms" in d  # from 2nd report on
        # static info
        info = storage.get_static_info("s1", TYPE_ID)["data"]
        assert info["model_class"] == "MultiLayerNetwork"
        assert info["num_params"] > 0
        assert info["updater"] == "Adam"

    def test_timestamp_filtering(self):
        storage = InMemoryStatsStorage()
        storage.put_update(make_record("s", "t", "w", {"x": 1},
                                       timestamp=100.0))
        storage.put_update(make_record("s", "t", "w", {"x": 2},
                                       timestamp=200.0))
        assert len(storage.get_all_updates_after("s", "t", 150.0)) == 1
        assert storage.get_latest_update("s", "t")["data"]["x"] == 2

    def test_listener_callbacks(self):
        storage = InMemoryStatsStorage()
        events = []
        storage.register_stats_storage_listener(
            lambda kind, r: events.append(kind))
        storage.put_update(make_record("s", "t", "w", {}))
        storage.put_static_info(make_record("s", "t", "w", {}))
        assert events == ["update", "static"]

    def test_file_storage_persistence(self, tmp_path):
        p = str(tmp_path / "stats.jsonl")
        s1 = FileStatsStorage(p)
        _trained_net_with_listener(s1, iters=10, frequency=5)
        n = s1.num_updates()
        assert n == 2
        s1.close()
        s2 = FileStatsStorage(p)  # reload from disk
        assert s2.num_updates() == n
        assert s2.list_session_ids() == ["s1"]
        s2.close()

    def test_histograms_optional(self):
        storage = InMemoryStatsStorage()
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Adam(learning_rate=0.01))
                .list(DenseLayer(n_out=4, activation="relu"),
                      OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.set_listeners(StatsListener(storage, session_id="h",
                                        reporting_frequency=1,
                                        collect_histograms=True))
        rs = np.random.RandomState(1)
        net.fit(DataSet(rs.randn(8, 3).astype(np.float32),
                        np.eye(2, dtype=np.float32)[rs.randint(0, 2, 8)]))
        h = storage.get_latest_update("h", TYPE_ID)["data"][
            "param_histograms"]
        assert sum(h["0/W"]["counts"]) == 12  # 3*4 weights


class TestUIServer:
    def test_server_endpoints_and_remote_receive(self):
        storage = InMemoryStatsStorage()
        _trained_net_with_listener(storage, iters=10, frequency=5)
        server = UIServer(port=0)
        server.attach(storage)
        server.enable_remote_listener()
        port = server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            sessions = json.loads(
                urllib.request.urlopen(base + "/train/sessions").read())
            assert "s1" in sessions
            ov = json.loads(urllib.request.urlopen(
                base + "/train/overview?sid=s1").read())
            assert len(ov["scores"]) == 2
            assert ov["latest_param_norms"]
            mi = json.loads(urllib.request.urlopen(
                base + "/train/model?sid=s1").read())
            assert mi["model_class"] == "MultiLayerNetwork"
            # remote router -> server sink -> queryable
            router = RemoteUIStatsStorageRouter(base)
            router.put_update(make_record("remote_s", TYPE_ID, "w0",
                                          {"iteration": 1, "score": 0.5}))
            ov2 = json.loads(urllib.request.urlopen(
                base + "/train/overview?sid=remote_s").read())
            assert ov2["scores"] == [0.5]
            # html page served
            page = urllib.request.urlopen(base + "/").read().decode()
            assert "Training overview" in page
        finally:
            server.stop()

    def test_model_system_histogram_pages_from_live_run(self):
        """The TrainModule model/system/histogram tabs render from a live
        training run (reference: deeplearning4j-play TrainModule routes)."""
        storage = InMemoryStatsStorage()
        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Adam(learning_rate=0.01))
                .list(DenseLayer(n_out=4, activation="relu"),
                      OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.set_listeners(StatsListener(storage, session_id="pages",
                                        reporting_frequency=1,
                                        collect_histograms=True))
        rs = np.random.RandomState(1)
        ds = DataSet(rs.randn(8, 3).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rs.randint(0, 2, 8)])
        for _ in range(4):
            net.fit(ds)
        server = UIServer(port=0)
        server.attach(storage)
        port = server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            for path, marker in (("/model", "Model"),
                                 ("/system", "System"),
                                 ("/histograms", "Parameter histograms")):
                page = urllib.request.urlopen(base + path).read().decode()
                assert marker in page
            sysinfo = json.loads(urllib.request.urlopen(
                base + "/train/system?sid=pages").read())
            assert len(sysinfo["iterations"]) == 4
            assert all(m > 0 for m in sysinfo["memory_mb"])
            hist = json.loads(urllib.request.urlopen(
                base + "/train/histograms?sid=pages").read())
            assert hist["iteration"] is not None
            assert sum(hist["param_histograms"]["0/W"]["counts"]) == 12
        finally:
            server.stop()

    def test_tsne_module_upload_and_page(self):
        """TsneModule analog: coords uploaded (HTTP or in-process) render on
        the /tsne page (reference: deeplearning4j-play TsneModule)."""
        server = UIServer(port=0)
        port = server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            body = json.dumps({
                "points": [[0.0, 0.0], [1.0, 2.0], [-1.5, 0.5]],
                "labels": ["a", "b", "c"],
            }).encode()
            req = urllib.request.Request(
                base + "/tsne/upload?sid=emb", data=body,
                headers={"Content-Type": "application/json"})
            assert json.loads(urllib.request.urlopen(req).read())[
                "status"] == "ok"
            sessions = json.loads(urllib.request.urlopen(
                base + "/tsne/sessions").read())
            assert sessions == ["emb"]
            coords = json.loads(urllib.request.urlopen(
                base + "/tsne/coords?sid=emb").read())
            assert coords["points"][1] == [1.0, 2.0]
            assert coords["labels"] == ["a", "b", "c"]
            page = urllib.request.urlopen(base + "/tsne").read().decode()
            assert "t-SNE embedding" in page
        finally:
            server.stop()

    def test_phase_timings_reach_system_page(self):
        """Per-round phase stats (SparkTrainingStats analog): the
        ParallelWrapper round's host-prep/device-round wall times flow
        listener -> storage -> /train/system -> /system page."""
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
        from deeplearning4j_tpu.parallel import ParallelWrapper, data_mesh

        storage = InMemoryStatsStorage()
        conf = (NeuralNetConfiguration.builder()
                .seed(2).updater(Adam(learning_rate=0.01))
                .list(DenseLayer(n_out=4, activation="relu"),
                      OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.set_listeners(StatsListener(storage, session_id="phases",
                                        reporting_frequency=1))
        rs = np.random.RandomState(2)
        W, B = 4, 4
        batches = [DataSet(rs.randn(B, 3).astype(np.float32),
                           np.eye(2, dtype=np.float32)[
                               rs.randint(0, 2, B)])
                   for _ in range(W * 3)]
        pw = ParallelWrapper(net, mesh=data_mesh(W), averaging_frequency=1)
        pw.fit(ListDataSetIterator(batches, batch_size=B))
        assert pw.last_phase_timings["device_round_ms"] > 0
        assert pw.last_phase_timings["averaging"] == "in-device-round"

        server = UIServer(port=0)
        server.attach(storage)
        port = server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            s = json.loads(urllib.request.urlopen(
                base + "/train/system?sid=phases").read())
            assert any(v is not None and v > 0
                       for v in s["host_prep_ms"])
            assert any(v is not None and v > 0
                       for v in s["device_round_ms"])
            page = urllib.request.urlopen(base + "/system").read().decode()
            assert "Training phases" in page
        finally:
            server.stop()

    def test_tsne_eviction_is_least_recently_updated(self):
        """Re-uploading a session refreshes its eviction position: the
        actively updated session must survive while stale ones go."""
        server = UIServer(port=0)
        old_max = server.TSNE_MAX_SESSIONS
        server.TSNE_MAX_SESSIONS = 3
        try:
            pts = [[0.0, 0.0]]
            for sid in ("a", "b", "c"):
                server.upload_tsne(sid, pts)
            server.upload_tsne("a", pts)   # refresh "a": now newest
            server.upload_tsne("d", pts)   # evicts "b", NOT "a"
            assert set(server._tsne) == {"a", "c", "d"}
        finally:
            server.TSNE_MAX_SESSIONS = old_max

    def test_tsne_from_plot_module(self):
        """End-to-end: plot.Tsne output feeds upload_tsne directly."""
        from deeplearning4j_tpu.plot import Tsne

        rs = np.random.RandomState(0)
        x = np.concatenate([rs.randn(10, 8) + 4, rs.randn(10, 8) - 4])
        coords = np.asarray(Tsne(max_iter=30, perplexity=5.0,
                                 seed=3).fit(x))
        server = UIServer(port=0)
        server.upload_tsne("w2v", coords, labels=[str(i) for i in range(20)])
        stored = server._tsne["w2v"]
        assert len(stored["points"]) == 20
        assert all(len(p) == 2 for p in stored["points"])
