"""Tensor-parallel mesh-sharded paged decode tests (parallel/mesh.py +
``GenerationServer(mesh=/tp=)``).

Covers the tp>1 serving contract on the CPU mesh (8 forced virtual
devices): loud typed geometry validation (device divisibility, head
divisibility, axis naming — ``MeshGeometryError`` before any thread
starts), greedy and sampled bit-parity with the single-chip path at
tp=2 and tp=4 for f32 and int8 pools, the Pallas backend fed per-shard
head counts, ZERO decode recompiles under occupancy churn on the mesh
path, cross-TP snapshot handoff (export at tp=2, adopt at tp=4 and
tp=1) resuming bit-exactly, replica-group fleets (2 groups x tp=2) with
a mid-stream kill losing zero futures, and the restore-on-close
discipline: a mesh server's net serves single-chip f32 unchanged after
the server closes.
"""

import time
from contextlib import contextmanager

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import (TransformerLM, greedy_generate,
                                           sample_generate)
from deeplearning4j_tpu.parallel.fleet import ReplicaFleet, device_groups
from deeplearning4j_tpu.parallel.generation import GenerationServer
from deeplearning4j_tpu.parallel.handoff import adopt_request
from deeplearning4j_tpu.parallel.mesh import (MODEL_AXIS, MeshGeometryError,
                                              model_mesh)
from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                    ResilienceError)

pytestmark = pytest.mark.mesh

V = 17


@pytest.fixture(scope="module")
def lm():
    """Four heads so the pool shards cleanly at tp=2 AND tp=4."""
    return TransformerLM(num_labels=V, max_length=16, d_model=16,
                         n_heads=4, n_blocks=1, seed=5).init()


GREEDY = (np.array([1, 2, 3, 4], np.int64), 12, 0.0, 0, 0)
SAMPLED = (np.array([1, 2, 3, 4], np.int64), 12, 0.9, 5, 77)


@pytest.fixture(scope="module")
def refs(lm):
    """Serial single-chip references, computed while no server is live
    (the existing generation suite pins the tp=1 server to these
    bit-exactly, so parity against them IS parity against the
    single-chip serving path)."""
    return {
        "greedy": greedy_generate(lm, GREEDY[0][None], GREEDY[1], V)[0],
        "sampled": sample_generate(lm, SAMPLED[0][None], SAMPLED[1], V,
                                   temperature=SAMPLED[2],
                                   top_k=SAMPLED[3], seed=SAMPLED[4])[0],
    }


@contextmanager
def serving(*args, **kwargs):
    srv = GenerationServer(*args, **kwargs)
    try:
        yield srv
    finally:
        srv.close()


def _serve_one(lm, spec, **kw):
    p, steps, temp, top_k, seed = spec
    with serving(lm, V, slots=2, page_size=4, **kw) as srv:
        fut = srv.submit(p, steps, temperature=temp, top_k=top_k,
                         seed=seed)
        return np.asarray(fut.result(timeout=180))


class TestMeshGeometry:
    """Every bad geometry fails typed and LOUD, naming the numbers."""

    def test_model_mesh_validation(self):
        import jax
        ndev = len(jax.devices())
        assert ndev == 8, "conftest forces 8 virtual CPU devices"
        with pytest.raises(MeshGeometryError, match=">= 1"):
            model_mesh(0)
        with pytest.raises(MeshGeometryError, match="exceeds"):
            model_mesh(ndev + 1)
        with pytest.raises(MeshGeometryError, match="not divisible"):
            model_mesh(3)
        m = model_mesh(2)
        assert m.shape[MODEL_AXIS] == 2

    def test_device_groups_disjoint_and_validated(self):
        import jax
        groups = device_groups(2, 2)
        assert len(groups) == 2 and all(len(g) == 2 for g in groups)
        assert len({d.id for g in groups for d in g}) == 4  # disjoint
        with pytest.raises(MeshGeometryError):
            device_groups(0, 2)
        with pytest.raises(MeshGeometryError):
            device_groups(3, 4, devices=jax.devices())  # 12 > 8

    def test_heads_not_divisible_by_tp(self):
        net = TransformerLM(num_labels=V, max_length=16, d_model=16,
                            n_heads=2, n_blocks=1, seed=7).init()
        with pytest.raises(MeshGeometryError, match="not divisible"):
            GenerationServer(net, V, slots=2, tp=4)

    def test_tp_disagrees_with_mesh(self, lm):
        with pytest.raises(MeshGeometryError, match="disagrees"):
            GenerationServer(lm, V, slots=2, mesh=model_mesh(2), tp=4)

    def test_mesh_without_model_axis(self, lm):
        import jax
        from jax.sharding import Mesh
        data_only = Mesh(np.array(jax.devices()[:2]), ("data",))
        with pytest.raises(MeshGeometryError, match="model"):
            GenerationServer(lm, V, slots=2, mesh=data_only)


@pytest.mark.generation
@pytest.mark.allow_output_recompiles
class TestMeshParity:
    """The tentpole invariant: sharding the page pool head-parallel
    changes WHERE the KV lives, never a single output bit. The only
    collective is an exact all-gather of disjoint per-head contexts
    before the replicated output projection."""

    def test_tp2_greedy_and_sampled_bitexact(self, lm, refs):
        # one server, both sampling modes: greedy and sampled share the
        # sharded decode programs, so a second server would only re-pay
        # the probe+warmup cost
        with serving(lm, V, slots=2, page_size=4, tp=2) as srv:
            for name, spec in (("greedy", GREEDY), ("sampled", SAMPLED)):
                p, steps, temp, top_k, seed = spec
                fut = srv.submit(p, steps, temperature=temp,
                                 top_k=top_k, seed=seed)
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=180)), refs[name])

    def test_tp4_greedy_bitexact(self, lm, refs):
        out = _serve_one(lm, GREEDY, tp=4)
        np.testing.assert_array_equal(out, refs["greedy"])

    def test_tp_int8_parity_with_single_chip_int8(self, lm):
        """int8 scale planes shard on the same head axis as the pages;
        quantized mesh decode matches single-chip int8 exactly. (tp=4
        int8 is covered by the cross-TP handoff test, which adopts into
        an int8 tp=4 server.)"""
        base = _serve_one(lm, GREEDY, kv_dtype="int8")
        out = _serve_one(lm, GREEDY, tp=2, kv_dtype="int8")
        np.testing.assert_array_equal(out, base)

    @pytest.mark.pallas
    def test_tp2_pallas_backend_bitexact(self, lm, refs):
        """The Pallas kernel sees only its LOCAL head shard (grid
        ``(B, H/tp, NP)``) — shard_map hands it per-shard operands with
        no kernel changes. Skips where jax cannot interpret Pallas TPU
        kernels on CPU."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.conf.layers import (
            paged_attention as ppa)
        try:
            ppa.paged_attend(
                "pallas",
                jnp.zeros((1, 1, 1, 8), jnp.float32),
                jnp.zeros((2, 1, 8, 8), jnp.float32),
                jnp.zeros((2, 1, 8, 8), jnp.float32),
                jnp.ones((1, 2), jnp.int32),
                jnp.zeros((1,), jnp.int32))
        except Exception as e:  # noqa: BLE001
            pytest.skip(f"Pallas interpret mode unavailable: {e}")
        out = _serve_one(lm, GREEDY, tp=2, paged_attention="pallas")
        np.testing.assert_array_equal(out, refs["greedy"])


@pytest.mark.generation
class TestMeshScheduling:
    def test_no_recompile_on_occupancy_churn_tp2(self):
        """The zero-retrace property survives sharding: the mesh-keyed
        decode program, one prefill bucket and the COW page-copy warm
        up ONCE, and arbitrary occupancy churn adds ZERO compiled
        programs — block tables and positions stay data on the mesh
        path too."""
        net = TransformerLM(num_labels=V, max_length=16, d_model=8,
                            n_heads=2, n_blocks=1, seed=9).init()
        rs = np.random.RandomState(0)
        with serving(net, V, slots=3, min_prefill_bucket=4,
                     tp=2) as srv:
            base = len(net._output_cache)
            warm = [srv.submit(rs.randint(0, V, 3), 5),
                    srv.submit(rs.randint(0, V, 7), 2)]
            for f in warm:
                f.result(timeout=180)
            warmed = len(net._output_cache)
            assert warmed - base == 3

            churn = [(4, 3), (2, 7), (6, 1), (8, 4), (3, 2), (5, 6)]
            futs = []
            for plen, mt in churn:
                futs.append(srv.submit(rs.randint(0, V, plen), mt))
                time.sleep(0.02)  # stagger: arrive at varied occupancy
            for f, (_plen, mt) in zip(futs, churn):
                assert f.result(timeout=180).shape == (mt,)
            assert len(net._output_cache) == warmed
            st = srv.stats()
        assert st["completed"] == 8
        assert st["decode_steps"] > 0


def _snap_at_tp(lm, spec, tp, **kw):
    p, steps, temp, top_k, seed = spec
    with serving(lm, V, slots=2, page_size=4, snapshot_every=4,
                 steps_per_dispatch=2, tp=tp, **kw) as srv:
        fut = srv.submit(p, steps, temperature=temp, top_k=top_k,
                         seed=seed)
        out = np.asarray(fut.result(timeout=180))
    snap = getattr(fut, "_kv_snapshot", None)
    assert snap is not None, "snapshot_every published no snapshot"
    return out, snap


@pytest.mark.handoff
@pytest.mark.allow_output_recompiles
class TestCrossTPHandoff:
    """The v3 wire contract end to end: export gathers the sharded pool
    to ONE canonical host layout, adopt re-shards to whatever mesh the
    adopting server runs — tp=2 -> tp=4 and tp=2 -> tp=1 resume at
    position N bit-exactly."""

    @pytest.mark.parametrize("kv_dtype", [None, "int8"],
                             ids=["f32", "int8"])
    def test_tp2_export_adopts_at_tp4_and_tp1(self, lm, kv_dtype):
        for spec, dsts in ((GREEDY, (4, 1)), (SAMPLED, (4,))):
            # greedy covers both re-shard directions; sampled pins the
            # RNG schedule across the upshard (the downshard path is
            # spec-independent once greedy has proven it)
            out, snap = _snap_at_tp(lm, spec, tp=2, kv_dtype=kv_dtype)
            assert snap.version == 3
            assert snap.shards == 2          # exporter geometry, FYI
            assert snap.head_layout == "canonical"
            assert 0 < snap.count < spec[1]  # genuinely mid-stream
            for tp_dst in dsts:
                with serving(lm, V, slots=2, page_size=4, tp=tp_dst,
                             kv_dtype=kv_dtype) as dst:
                    res = adopt_request(dst, snap).result(timeout=180)
                    st = dst.stats()["handoff"]
                np.testing.assert_array_equal(np.asarray(res), out)
                assert st["resumes"] == 1 and st["fallbacks"] == 0


def _wait_replica_midstream(fl, rid, min_snapshots=2, timeout=120.0):
    t_end = time.monotonic() + timeout
    while True:
        rep = fl.stats()["replicas"][rid]
        srv = rep["server"] or {}
        ho = srv.get("handoff", {})
        if (srv.get("active_slots", 0) >= 1
                and ho.get("snapshots", 0) >= min_snapshots):
            return
        assert time.monotonic() < t_end, (
            f"replica {rid} never reached a snapshotted mid-stream "
            f"state: {srv.get('active_slots')} active, "
            f"{ho.get('snapshots')} snapshots")
        time.sleep(0.005)


@pytest.mark.fleet
@pytest.mark.allow_output_recompiles
class TestMeshFleet:
    def test_replica_groups_midstream_kill_zero_lost(self, lm):
        """Two replica GROUPS of two devices each behind one fleet —
        each replica is a whole tp=2 mesh server on a disjoint device
        subset. A mid-stream group kill harvests snapshots and the
        surviving group finishes every stream bit-exactly: zero lost
        futures on the ledger."""
        groups = device_groups(2, 2)
        rng = np.random.default_rng(31)
        specs = []
        for i in range(6):
            p = rng.integers(1, V, size=3 + i % 3).astype(np.int64)
            specs.append((p, 8, 0.0, 0, 0) if i % 2 == 0
                         else (p, 8, 0.9, 5, 3000 + i))
        refs = []
        for p, steps, temp, top_k, seed in specs:
            refs.append(greedy_generate(lm, p[None], steps, V)[0]
                        if temp == 0.0 else
                        sample_generate(lm, p[None], steps, V,
                                        temperature=temp, top_k=top_k,
                                        seed=seed)[0])

        def factory(rid):
            mesh = model_mesh(2, devices=groups[rid % len(groups)])
            chaos = ChaosPolicy(seed=1000 + rid, stall_rate=1.0,
                                stall_s=0.005)
            return GenerationServer(lm, V, slots=4, page_size=4,
                                    snapshot_every=1,
                                    steps_per_dispatch=1,
                                    mesh=mesh, chaos=chaos)

        fl = ReplicaFleet(factory, replicas=2, max_pending=64,
                          restart_backoff_s=0.02)
        try:
            futs = []
            for p, steps, temp, top_k, seed in specs:
                t_end = time.monotonic() + 60.0
                while True:
                    try:
                        futs.append(fl.submit(
                            p, steps, temperature=temp, top_k=top_k,
                            seed=seed, deadline_s=300.0))
                        break
                    except ResilienceError:
                        assert time.monotonic() < t_end
                        time.sleep(0.02)
            _wait_replica_midstream(fl, 0)
            fl.kill_replica(0)
            outs = [f.result(timeout=600) for f in futs]
            st = fl.stats()
        finally:
            fl.close()
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(np.asarray(got), ref)
        assert st["completed"] == len(specs)
        assert st["failed"] == 0 and st["expired"] == 0
        assert st["deaths"] >= 1


@pytest.mark.generation
@pytest.mark.allow_output_recompiles
class TestRestoreOnClose:
    def test_close_restores_net_level_mesh_knobs(self, lm, refs):
        """The mesh server's ``paged_mesh`` push is BUILD-scoped (set
        under the trace lock, restored after the trace) and ``close()``
        is the crash-safety net — so between builds, after serving, and
        after close the net's layers read as single-chip config, and
        the same net serves single-chip f32 bit-identically afterwards,
        as if the mesh server had never existed."""
        attn = [lyr for _n, lyr in lm._stream_layers()
                if hasattr(lyr, "init_paged_carry")]
        assert attn, "TransformerLM exposes its paged attention layers"
        with serving(lm, V, slots=2, page_size=4, tp=2,
                     paged_attention="xla") as srv:
            assert srv._mesh is not None
            fut = srv.submit(GREEDY[0], GREEDY[1])
            np.testing.assert_array_equal(
                np.asarray(fut.result(timeout=180)), refs["greedy"])
            # warmed up: the Mesh did not outlive its traces
            for lyr in attn:
                assert lyr.paged_mesh is None
                assert lyr.paged_attention == "xla"  # pushed while live
        for lyr in attn:
            assert lyr.paged_mesh is None
            assert lyr.paged_attention == "auto"     # restored on close
        # the SAME net, single-chip f32, after the mesh server is gone
        out = _serve_one(lm, GREEDY)
        np.testing.assert_array_equal(out, refs["greedy"])
