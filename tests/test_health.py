"""Self-healing training tests (optimize/health.py + the guarded step paths).

The ISSUE-3 acceptance surface: a NaN minibatch mid-stream is skipped on
device with the surviving updates identical between the fused and unfused
paths; a skipped step preserves params/updater-state EXACTLY; the recovery
ladder walks LR backoff -> checkpoint rollback -> DivergenceError; periodic
checkpoints are healthy-gated; the guard composes with ParallelWrapper and
leaves early stopping's invalid-score telemetry untouched.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.config import TerminationReason
from deeplearning4j_tpu.optimize.health import (
    DivergenceError,
    HealthPolicy,
    all_finite,
    resolve_health_policy,
    tree_select,
)
from deeplearning4j_tpu.optimize.listeners import HealthListener
from deeplearning4j_tpu.parallel.elastic import (CheckpointListener,
                                                 CheckpointStore)
from deeplearning4j_tpu.parallel.trainer import (AVERAGING, SHARED_GRADIENTS,
                                                 ParallelWrapper)

from tests.test_fused_fit import TOL, _graph, _max_param_diff, _mln

pytestmark = pytest.mark.health


def _batches(n, batch=16, nan_at=None, seed=0, scale=1.0):
    rs = np.random.RandomState(seed)
    out = []
    for i in range(n):
        x = (rs.randn(batch, 4) * scale).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, batch)]
        if i == nan_at:
            x[0, 0] = np.nan
        out.append(DataSet(x, y))
    return out


def _sgd_mln(seed=12345):
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updater import Sgd
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.05))
            .weight_init("xavier").activation("relu")
            .list(DenseLayer(n_out=16),
                  OutputLayer(n_out=3, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _params_flat(net):
    return np.concatenate([np.asarray(p).ravel()
                           for p in jax.tree_util.tree_leaves(net.params)])


# -------------------------------------------------------- device primitives
class TestDevicePrimitives:
    def test_all_finite(self):
        good = {"w": jnp.ones((3,)), "b": jnp.zeros(())}
        assert bool(all_finite(jnp.float32(1.0), good))
        assert not bool(all_finite(jnp.float32(np.nan), good))
        bad = {"w": jnp.array([1.0, np.inf, 0.0]), "b": jnp.zeros(())}
        assert not bool(all_finite(jnp.float32(1.0), bad))

    def test_tree_select(self):
        new = {"a": jnp.ones((2,))}
        old = {"a": jnp.zeros((2,))}
        np.testing.assert_array_equal(
            np.asarray(tree_select(jnp.bool_(True), new, old)["a"]), 1.0)
        np.testing.assert_array_equal(
            np.asarray(tree_select(jnp.bool_(False), new, old)["a"]), 0.0)

    def test_tree_select_structure_mismatch_passes_new(self):
        # the TBPTT first-segment carry: old is the {} seed
        new = {"h": jnp.ones((2,))}
        assert tree_select(jnp.bool_(False), new, {}) is new

    def test_resolve_health_policy(self):
        assert resolve_health_policy(None) is None
        assert resolve_health_policy(False) is None
        assert isinstance(resolve_health_policy(True), HealthPolicy)
        p = HealthPolicy()
        assert resolve_health_policy(p) is p
        with pytest.raises(TypeError):
            resolve_health_policy("on")


# ------------------------------------------------------------ guarded steps
class TestGuardedStep:
    def test_skipped_step_preserves_params_exactly(self):
        """The acceptance bit-identity: a skipped step is the identity
        update — params, updater state, and iteration RNG alignment all
        pass through unchanged (diff == 0, not just small)."""
        net = _mln()
        before = _params_flat(net)
        # materialize host-side: the jitted step donates the device buffers
        opt_before = [np.asarray(x)
                      for x in jax.tree_util.tree_leaves(net.updater_state)]
        net.fit(_batches(1, nan_at=0)[0],
                health_guard=HealthPolicy(skip_threshold=100))
        assert np.array_equal(before, _params_flat(net))
        for a, b in zip(opt_before,
                        jax.tree_util.tree_leaves(net.updater_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert net.iteration == 1  # the slot is consumed, only the update isn't

    def test_guard_off_poisons_params(self):
        """The failure mode the guard exists for: without it one NaN batch
        destroys the weights."""
        net = _mln()
        net.fit(_batches(1, nan_at=0)[0], health_guard=None)
        assert not np.isfinite(_params_flat(net)).all()

    def test_guard_on_equals_guard_off_on_clean_data(self):
        """On all-finite data the guarded program selects every real
        update. Guarded and unguarded are DIFFERENT compiled programs, so
        agreement is to compile-level rounding (~1e-8 observed), not bitwise
        — bit-exactness of the select itself is pinned by
        test_skipped_step_preserves_params_exactly."""
        it = ListDataSetIterator(_batches(8), batch_size=16)
        on, off = _mln(), _mln()
        on.fit(it, epochs=1, health_guard=HealthPolicy(skip_threshold=100))
        off.fit(it, epochs=1, health_guard=None)
        assert _max_param_diff(on, off) <= TOL

    @pytest.mark.parametrize("k", [1, 4])
    def test_nan_midstream_fused_matches_unfused(self, k):
        """A NaN batch mid-stream: the fused (K>1) and unfused (K=1) guarded
        paths skip the SAME step and agree on every surviving update."""
        batches = _batches(8, nan_at=2, seed=5)
        ref, fus = _mln(), _mln()
        pol_ref = HealthPolicy(skip_threshold=100)
        pol_fus = HealthPolicy(skip_threshold=100)
        ref.fit(ListDataSetIterator(batches, batch_size=16), epochs=1,
                fused_steps=1, health_guard=pol_ref)
        fus.fit(ListDataSetIterator(batches, batch_size=16), epochs=1,
                fused_steps=k, health_guard=pol_fus)
        assert pol_ref.total_skips == pol_fus.total_skips == 1
        assert ref.iteration == fus.iteration == 8
        assert np.isfinite(_params_flat(fus)).all()
        assert _max_param_diff(ref, fus) <= TOL

    def test_skipped_batch_equals_batch_never_seen(self):
        """Under an iteration-clock-free updater (plain SGD; Adam's bias
        correction rides the iteration counter, which a skipped slot still
        advances) the skipped step is a true no-op: training [b0, b1, NaN,
        b3..] under the guard ends bit-identical to training the same
        stream with the NaN batch removed."""
        batches = _batches(6, nan_at=2, seed=9)
        clean = [b for i, b in enumerate(batches) if i != 2]
        guarded, never = _sgd_mln(), _sgd_mln()
        for b in batches:
            guarded.fit(b, health_guard=HealthPolicy(skip_threshold=100))
        for b in clean:  # same guarded program: same shapes, guard on
            never.fit(b, health_guard=HealthPolicy(skip_threshold=100))
        assert guarded.iteration == 6 and never.iteration == 5
        assert _max_param_diff(guarded, never) == 0.0

    def test_graph_guarded_skip(self):
        """ComputationGraph shares the guarded step core."""
        net = _graph()
        before = _params_flat(net)
        pol = HealthPolicy(skip_threshold=100)
        net.fit(ListDataSetIterator(_batches(4, nan_at=1), batch_size=16),
                epochs=1, health_guard=pol)
        assert pol.total_skips == 1
        assert np.isfinite(_params_flat(net)).all()
        assert not np.array_equal(before, _params_flat(net))  # clean steps ran

    def test_raw_nan_score_still_reported(self):
        """The guard protects the weights, not the telemetry: the skipped
        step's raw non-finite loss stays visible to score consumers."""
        net = _mln()
        net.fit(_batches(1, nan_at=0)[0],
                health_guard=HealthPolicy(skip_threshold=100))
        assert not np.isfinite(net.score())


# ----------------------------------------------------------- recovery ladder
class TestRecoveryLadder:
    def test_lr_backoff_first_rung(self):
        """Rung 1: consecutive skips past the threshold halve the LR and
        drop the compiled step programs (the base LR is baked in)."""
        net = _mln()
        lr0 = net.conf.updater.learning_rate
        pol = HealthPolicy(skip_threshold=2, lr_backoff=0.5,
                           max_recoveries=5)
        events = []
        for b in _batches(3, nan_at=None, seed=1):
            b.features[0, 0] = np.nan  # every batch skips
            net.fit(b, health_guard=pol)
            events = [e["action"] for e in pol.events]
            if "lr_backoff" in events:
                break
        assert "lr_backoff" in events
        assert net.conf.updater.learning_rate == pytest.approx(lr0 * 0.5)
        assert len(net._step_cache) == 0  # invalidated for re-trace
        # training continues (recompiles) after the backoff
        net.fit(_batches(1, seed=2)[0], health_guard=pol)
        assert np.isfinite(_params_flat(net)).all()

    def test_spike_triggers_rollback(self, tmp_path):
        """Rung 2: with LR backoff disabled a loss spike rolls the live net
        back to the newest healthy checkpoint in-place."""
        store = CheckpointStore(str(tmp_path), keep=3)
        pol = HealthPolicy(store=store, save_frequency=4, warmup_steps=3,
                           spike_factor=5.0, skip_threshold=100,
                           lr_backoff=None)
        net = _mln()
        for b in _batches(8, seed=3):
            net.fit(b, health_guard=pol)
        assert store.latest() is not None  # healthy-gated periodic saves ran
        # finite but enormous loss -> EMA spike detector fires
        spike = _batches(1, seed=4, scale=400.0)[0]
        net.fit(spike, health_guard=pol)
        actions = [e["action"] for e in pol.events]
        assert actions == ["rollback"]
        rolled = [e for e in pol.events if e["action"] == "rollback"][0]
        assert net.iteration == rolled["restored_iteration"] < 9
        assert rolled["checkpoint_meta"]["healthy"] is True
        assert np.isfinite(_params_flat(net)).all()

    def test_ladder_exhaustion_raises_divergence_error(self):
        """Bounded retries: once max_recoveries is spent the next trigger
        raises instead of thrashing forever."""
        net = _mln()
        pol = HealthPolicy(skip_threshold=2, lr_backoff=0.5,
                           max_recoveries=2)
        with pytest.raises(DivergenceError, match="exhausted"):
            for b in _batches(12, seed=6):
                b.features[0, 0] = np.nan
                net.fit(b, health_guard=pol)
        assert pol.events[-1]["action"] == "raise"
        assert pol.recoveries == 3

    def test_no_rung_available_raises(self):
        """lr_backoff=None and no checkpoint store: the first trigger has
        nowhere to go and must say so rather than loop."""
        net = _mln()
        pol = HealthPolicy(skip_threshold=2, lr_backoff=None)
        with pytest.raises(DivergenceError, match="no recovery rung"):
            for b in _batches(6, seed=7):
                b.features[0, 0] = np.nan
                net.fit(b, health_guard=pol)

    def test_lr_backoff_validation(self):
        with pytest.raises(ValueError):
            HealthPolicy(lr_backoff=1.5)
        with pytest.raises(ValueError):
            HealthPolicy(skip_threshold=0)


# ------------------------------------------------- healthy-gated checkpoints
class TestHealthyGatedCheckpoints:
    def test_unhealthy_window_not_saved(self, tmp_path):
        """A save window containing a skipped step is dropped: the store
        never holds a checkpoint whose window saw non-finite steps."""
        store = CheckpointStore(str(tmp_path), keep=10)
        pol = HealthPolicy(store=store, save_frequency=4, skip_threshold=100)
        net = _mln()
        for b in _batches(4, seed=8):          # clean window -> saved
            net.fit(b, health_guard=pol)
        n_clean = len(store.checkpoints())
        assert n_clean == 1
        for b in _batches(4, nan_at=1, seed=9):  # dirty window -> dropped
            net.fit(b, health_guard=pol)
        assert len(store.checkpoints()) == n_clean
        for b in _batches(4, seed=10):         # clean again -> saved
            net.fit(b, health_guard=pol)
        assert len(store.checkpoints()) == n_clean + 1

    def test_checkpoint_listener_health_gated(self, tmp_path):
        """elastic.CheckpointListener consults the active policy: save
        opportunities inside an unhealthy window are passed over."""
        store = CheckpointStore(str(tmp_path), keep=10)
        listener = CheckpointListener(store, frequency=1)
        net = _mln()
        net.set_listeners(listener)
        net.fit(_batches(1, nan_at=0)[0],
                health_guard=HealthPolicy(skip_threshold=100))
        assert listener.skipped_unhealthy == 1 and listener.saved == 0
        net.set_listeners()
        net.fit(_batches(1)[0], health_guard=None)  # no guard: no gating
        net.set_listeners(listener)
        net.fit(_batches(1, seed=2)[0], health_guard=None)
        assert listener.saved == 1


# ------------------------------------------------------------- observability
class TestHealthListener:
    def test_on_health_reports(self):
        net = _mln()
        hl = HealthListener(log_events=False)
        net.set_listeners(hl)
        pol = HealthPolicy(skip_threshold=100)
        net.fit(ListDataSetIterator(_batches(4, nan_at=1), batch_size=16),
                epochs=1, health_guard=pol)
        skips = [r for r in hl.reports if r["action"] == "skip"]
        assert len(skips) == 1
        assert skips[0]["total_skips"] == 1
        # the policy's own event log matches what listeners saw
        assert [e["action"] for e in pol.events] == \
            [r["action"] for r in hl.reports]


# ------------------------------------------------------------ ParallelWrapper
class TestParallelWrapperGuard:
    @pytest.mark.parametrize("mode", [AVERAGING, SHARED_GRADIENTS])
    def test_guarded_round_skips_nan(self, mode):
        net = _mln()
        pol = HealthPolicy(skip_threshold=100)
        pw = ParallelWrapper(net, workers=4, mode=mode, health_guard=pol)
        pw.fit(_batches(8, nan_at=2), epochs=1)
        assert pol.total_skips >= 1
        assert np.isfinite(_params_flat(net)).all()
        assert np.isfinite(net.score_value)

    def test_guard_on_equals_guard_off_clean(self):
        batches = _batches(8, seed=11)
        on, off = _mln(), _mln()
        ParallelWrapper(on, workers=4, health_guard=True).fit(
            list(batches), epochs=1)
        ParallelWrapper(off, workers=4, health_guard=None).fit(
            list(batches), epochs=1)
        assert _max_param_diff(on, off) == 0.0
        assert on.score_value == pytest.approx(off.score_value, abs=1e-12)


# -------------------------------------------------------------- early stopping
class TestEarlyStoppingInteraction:
    def _es_config(self):
        return EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
            iteration_termination_conditions=[
                InvalidScoreIterationTerminationCondition()],
            score_calculator=DataSetLossCalculator(
                ListDataSetIterator(_batches(2, seed=12), batch_size=16)),
            model_saver=InMemoryModelSaver())

    def test_invalid_score_termination_with_guard_disabled(self):
        """ES defaults to guard OFF; a NaN batch terminates the run through
        InvalidScoreIterationTerminationCondition exactly as before."""
        trainer = EarlyStoppingTrainer(
            self._es_config(), _mln(),
            ListDataSetIterator(_batches(4, nan_at=1, seed=13),
                                batch_size=16))
        assert trainer.health_guard is None  # the documented default
        result = trainer.fit()
        assert result.termination_reason == \
            TerminationReason.ITERATION_TERMINATION_CONDITION
        assert "InvalidScore" in result.termination_details

    def test_guard_protects_weights_but_not_telemetry(self):
        """With a policy passed through, the run STILL terminates on the
        honest NaN score — but the weights survive finite."""
        net = _mln()
        trainer = EarlyStoppingTrainer(
            self._es_config(), net,
            ListDataSetIterator(_batches(4, nan_at=1, seed=13),
                                batch_size=16),
            health_guard=HealthPolicy(skip_threshold=100))
        result = trainer.fit()
        assert result.termination_reason == \
            TerminationReason.ITERATION_TERMINATION_CONDITION
        assert np.isfinite(_params_flat(net)).all()
