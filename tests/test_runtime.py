"""Serving-runtime tests (parallel/runtime.py): the ServingLoop state
machine and sentinel discipline, LoopSupervisor crash recovery, and the
tentpole proof — shutdown-phase chaos across every runtime-hosted
server. A loop thread killed or stalled mid-drain / mid-close /
mid-migration must lose ZERO futures: every submitted request resolves
(result or typed error) within the deadline, and the admission ledger
ends balanced. The seeded submit-vs-close stress (N threads hammering
submit while close lands mid-burst) rides along, parametrized over the
runtime-hosted servers.
"""

import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import TransformerLM
from deeplearning4j_tpu.parallel import runtime as rt
from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
from deeplearning4j_tpu.parallel.generation import GenerationServer
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.resilience import ChaosPolicy
from deeplearning4j_tpu.parallel.runtime import (IllegalLoopTransition,
                                                 LoopClosed, LoopState,
                                                 LoopSupervisor, ServingLoop)

from tests.test_fused_fit import _iris_like, _mln

pytestmark = pytest.mark.runtime

V = 17


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(num_labels=V, max_length=16, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


def _wait_until(pred, timeout=10.0, step=0.005):
    limit = time.monotonic() + timeout
    while time.monotonic() < limit:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _resolve_all(futs, timeout=30.0):
    """Resolve every future within the deadline; a HUNG future (timeout)
    fails the test — that is the zero-lost-futures criterion."""
    out = []
    for f in futs:
        try:
            out.append(("ok", f.result(timeout=timeout)))
        except FuturesTimeout:
            pytest.fail("future left unresolved past the deadline")
        except Exception as e:  # noqa: BLE001 - typed failure is fine
            out.append(("err", e))
    return out


# ---------------------------------------------------------------------------
# ServingLoop state machine
# ---------------------------------------------------------------------------

class TestStateMachine:
    def test_lifecycle_and_idempotent_transitions(self):
        done = []
        loop = ServingLoop("sm", handler=done.append)
        assert loop.state is LoopState.NEW
        loop.start()
        assert loop.state is LoopState.RUNNING
        loop.begin_drain()
        assert loop.state is LoopState.DRAINING
        loop.begin_drain()  # idempotent no-op
        assert loop.state is LoopState.DRAINING
        loop.close(timeout=5)
        assert loop.state is LoopState.CLOSED
        loop.close(timeout=5)  # idempotent
        with pytest.raises(LoopClosed):
            loop.put("late")

    def test_start_twice_raises(self):
        loop = ServingLoop("sm2", handler=lambda i: None).start()
        try:
            with pytest.raises(IllegalLoopTransition, match="start"):
                loop.start()
        finally:
            loop.close(timeout=5)

    def test_restart_from_running_raises(self):
        loop = ServingLoop("sm3", handler=lambda i: None).start()
        try:
            with pytest.raises(IllegalLoopTransition, match="restart"):
                loop.restart()
        finally:
            loop.close(timeout=5)

    def test_restart_after_deliberate_close_raises(self):
        loop = ServingLoop("sm4", handler=lambda i: None).start()
        loop.close(timeout=5)
        # a deliberate close is FINAL: a racing supervised restart must
        # never resurrect the loop
        with pytest.raises(IllegalLoopTransition, match="deliberate"):
            loop.restart()

    def test_tick_false_is_a_clean_exit(self):
        calls = []

        def tick():
            calls.append(1)
            return len(calls) < 3

        loop = ServingLoop("tick-clean", tick=tick).start()
        assert _wait_until(lambda: loop.alive_workers == 0)
        assert loop.crashed is None  # clean exit, not a crash
        assert len(calls) == 3
        loop.close(timeout=5)


# ---------------------------------------------------------------------------
# worker pool: sentinel walk, EXIT, carry, scaling, leftovers
# ---------------------------------------------------------------------------

class TestWorkerPool:
    def test_one_sentinel_walks_whole_pool_down(self):
        seen = []
        lock = threading.Lock()

        def handle(item):
            with lock:
                seen.append(item)

        loop = ServingLoop("pool", handler=handle, workers=3,
                           max_workers=3).start()
        for i in range(9):
            loop.put(i)
        loop.close(timeout=10)
        assert sorted(seen) == list(range(9))  # nothing dropped
        assert loop.alive_workers == 0         # the ONE sentinel got all 3

    def test_handler_exit_token_retires_worker(self):
        loop = ServingLoop(
            "exiter", workers=2, max_workers=2,
            handler=lambda item: rt.EXIT if item == "quit" else None).start()
        assert loop.alive_workers == 2
        loop.put("quit")
        assert _wait_until(lambda: loop.alive_workers == 1)
        loop.put("quit")
        assert _wait_until(lambda: loop.alive_workers == 0)
        loop.close(timeout=5)

    def test_carried_item_becomes_next_head(self):
        seen = []

        def handle(item):
            seen.append(item)
            if isinstance(item, tuple):
                return item[1]  # carry: handed straight back as next head
            return None

        loop = ServingLoop("carry", handler=handle).start()
        loop.put(("carry", "head"))
        assert _wait_until(lambda: "head" in seen)
        assert seen == [("carry", "head"), "head"]
        loop.close(timeout=5)

    def test_set_workers_scales_both_ways(self):
        loop = ServingLoop("scale", handler=lambda i: None,
                           workers=1, max_workers=4).start()
        loop.set_workers(3)
        assert _wait_until(lambda: loop.alive_workers == 3)
        loop.set_workers(1)  # resign tokens retire exactly two
        assert _wait_until(lambda: loop.alive_workers == 1)
        loop.close(timeout=5)

    def test_leftovers_failed_on_close(self):
        failed = []
        loop = ServingLoop("leftover", handler=lambda i: None,
                           on_leftover=failed.append).start()
        loop.put(rt._RESIGN)  # retire the sole worker: queue goes unserved
        assert _wait_until(lambda: loop.alive_workers == 0)
        for i in range(3):
            loop.put(i)
        loop.close(timeout=5)
        assert sorted(failed) == [0, 1, 2]  # failed typed, never stranded


# ---------------------------------------------------------------------------
# supervisor: crash detection, recovery verdicts, restart
# ---------------------------------------------------------------------------

class TestSupervisor:
    def test_crash_is_detected_restarted_and_resumes(self):
        seen, deaths = [], []
        sup = LoopSupervisor(poll_s=0.005)

        def handle(item):
            if item == "poison":
                raise ValueError("boom")
            seen.append(item)

        loop = ServingLoop("crashy", handler=handle).start()
        sup.watch(loop, on_death=lambda lp, e: deaths.append(e) or True,
                  restart=True)
        try:
            loop.put("a")
            assert _wait_until(lambda: "a" in seen)
            loop.put("poison")
            assert _wait_until(lambda: loop.restarts >= 1)
            assert _wait_until(lambda: loop.state is LoopState.RUNNING)
            assert len(deaths) == 1
            assert isinstance(deaths[0], ValueError)
            loop.put("b")  # the restarted loop actually serves
            assert _wait_until(lambda: "b" in seen)
        finally:
            loop.close(timeout=5)
            sup.shutdown()

    def test_on_death_false_vetoes_restart(self):
        sup = LoopSupervisor(poll_s=0.005)
        loop = ServingLoop(
            "vetoed",
            handler=lambda i: (_ for _ in ()).throw(ValueError(i))).start()
        sup.watch(loop, on_death=lambda lp, e: False, restart=True)
        try:
            loop.put("x")
            assert _wait_until(lambda: loop.state is LoopState.CLOSED)
            time.sleep(0.05)  # a few scan periods: still no resurrection
            assert loop.restarts == 0
            assert loop.state is LoopState.CLOSED
        finally:
            loop.close(timeout=5)
            sup.shutdown()

    def test_deliberate_close_is_never_treated_as_crash(self):
        sup = LoopSupervisor(poll_s=0.005)
        loop = ServingLoop("calm", handler=lambda i: None).start()
        sup.watch(loop, restart=True)
        try:
            loop.close(timeout=5)
            time.sleep(0.05)
            assert loop.restarts == 0
            assert sup.recoveries == 0
        finally:
            sup.shutdown()


# ---------------------------------------------------------------------------
# tentpole proof: shutdown-phase chaos, zero lost futures
# ---------------------------------------------------------------------------

def _pi_ledger_balanced(st):
    # every accepted request resolved exactly once (_on_done fires on
    # every path), nothing still pending after close
    return st["pending"] == 0 and \
        st["accepted"] == st["completed"] + st["failed"]


class TestShutdownChaos:
    def test_pi_kill_during_drain_loses_nothing(self):
        chaos = ChaosPolicy(seed=7, kill_during_drain_rate=1.0)
        x = np.asarray(_iris_like(8, seed=0).features)
        inf = ParallelInference(_mln(), workers=2, max_wait_ms=5,
                                chaos=chaos)
        futs = [inf.submit(x[i:i + 1]) for i in range(8)]
        inf.close(timeout=3)
        assert chaos.injected_drain_kill >= 1  # the kill actually landed
        _resolve_all(futs, timeout=10)
        assert _pi_ledger_balanced(inf.stats())

    def test_pi_sentinel_stall_close_stays_bounded(self):
        chaos = ChaosPolicy(seed=3, stall_sentinel_rate=1.0,
                            stall_sentinel_s=0.4)
        x = np.asarray(_iris_like(4, seed=1).features)
        inf = ParallelInference(_mln(), workers=2, max_wait_ms=5,
                                chaos=chaos)
        futs = [inf.submit(x[i:i + 1]) for i in range(4)]
        t0 = time.monotonic()
        inf.close(timeout=2)
        assert time.monotonic() - t0 < 15  # stalled retire never hangs close
        assert chaos.injected_sentinel_stall >= 1
        _resolve_all(futs, timeout=10)
        assert _pi_ledger_balanced(inf.stats())

    def test_generation_kill_mid_close_loses_nothing(self, lm):
        chaos = ChaosPolicy(seed=11, kill_during_drain_rate=1.0)
        srv = GenerationServer(lm, V, slots=2, chaos=chaos)
        rs = np.random.RandomState(2)
        futs = [srv.submit(rs.randint(0, V, 3), 4) for _ in range(4)]
        srv.close(timeout=8)
        assert chaos.injected_drain_kill >= 1
        _resolve_all(futs, timeout=10)
        st = srv.stats()
        assert st["pending"] == 0
        assert st["active_slots"] == 0 and st["queued"] == 0

    def test_generation_kill_mid_migration_recovers(self, lm):
        chaos = ChaosPolicy(seed=13, kill_during_drain_rate=1.0)
        srv = GenerationServer(lm, V, slots=2, chaos=chaos)
        rs = np.random.RandomState(5)
        futs = [srv.submit(rs.randint(0, V, 3), 6) for _ in range(3)]
        # move-out drain: the tick's migration pass IS a drain phase, so
        # the chaos kill lands there and the supervisor must absorb it
        assert srv.drain(timeout=10, migrate=True) is True
        assert chaos.injected_drain_kill >= 1
        _resolve_all(futs, timeout=10)
        # supervised restart rebuilt device state: the server still serves
        assert _wait_until(
            lambda: srv._runtime.state is LoopState.RUNNING, timeout=10)
        f = srv.submit(np.array([3, 1, 4]), 2)
        out = f.result(timeout=60)
        assert 1 <= len(out) <= 2
        assert srv.stats()["pool_rebuilds"] >= 1
        srv.close(timeout=8)

    def test_fleet_kill_mid_close_loses_nothing(self, lm):
        chaos = ChaosPolicy(seed=17, kill_during_drain_rate=1.0)
        fl = ReplicaFleet(lambda rid: GenerationServer(lm, V, slots=2),
                          replicas=1, chaos=chaos)
        rs = np.random.RandomState(9)
        futs = [fl.submit(rs.randint(0, V, 3), 3) for _ in range(3)]
        fl.close(timeout=10)
        assert chaos.injected_drain_kill >= 1
        _resolve_all(futs, timeout=10)


# ---------------------------------------------------------------------------
# satellite: seeded submit-vs-close stress across the hosted servers
# ---------------------------------------------------------------------------

N_THREADS = 4
PER_THREAD = 6


@pytest.mark.parametrize("kind", ["inference", "generation", "fleet"])
def test_submit_vs_close_stress(kind, lm):
    """N threads hammer submit() while close() lands mid-burst: every
    accepted future resolves within the deadline, every rejected submit
    raises typed — no caller ever hangs, no future is lost."""
    if kind == "inference":
        srv = ParallelInference(_mln(), workers=4, max_wait_ms=5)
        x = np.asarray(_iris_like(1, seed=0).features)
        do_submit = lambda: srv.submit(x)  # noqa: E731
    elif kind == "generation":
        srv = GenerationServer(lm, V, slots=2)
        do_submit = lambda: srv.submit(np.array([3, 1, 4]), 2)  # noqa: E731
    else:
        srv = ReplicaFleet(lambda rid: GenerationServer(lm, V, slots=2),
                           replicas=1)
        do_submit = lambda: srv.submit(np.array([3, 1, 4]), 2)  # noqa: E731

    futs, bad = [], []
    flock = threading.Lock()
    start_evt = threading.Event()

    def hammer(tid):
        jitter = np.random.RandomState(100 + tid)  # seeded per thread
        start_evt.wait(5)
        for _ in range(PER_THREAD):
            try:
                f = do_submit()
                with flock:
                    futs.append(f)
            except Exception as e:  # noqa: BLE001 - typed check below
                with flock:
                    bad.append(e)
            time.sleep(float(jitter.uniform(0.0, 0.004)))

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(N_THREADS)]
    for t in threads:
        t.start()
    start_evt.set()
    time.sleep(0.01)  # let the burst begin, then close mid-flight
    srv.close(timeout=15)
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads)  # no submitter hung

    _resolve_all(futs, timeout=30)
    # rejects are all typed shutdown/backpressure errors, never raw
    for e in bad:
        assert isinstance(e, Exception)
        assert e.args, f"untyped rejection: {e!r}"
    srv.close(timeout=5)  # still idempotent after the storm
