"""RBM layer: CD-k statistics, pretraining, supervised use, serde.

Parity targets: nn/conf/layers/RBM.java (config surface) and
nn/layers/feedforward/rbm/RBM.java (propUp :324, propDown :390, CD gradient
statistics :160-190). The reference validates RBMs through RBMTests
(pretraining drives reconstruction error down) and through gradient checks
of networks containing pretrain layers; both patterns appear here. The CD-k
gradient itself is checked against the hand-computed Hinton statistics —
the strongest possible test, since CD is not the gradient of any scalar
loss a finite-difference check could probe through the sampling chain.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import RBM, DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Sgd
from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.utils.serde import from_json, to_json


def _rbm_params(n_in=6, n_out=4, seed=0, dtype=jnp.float64):
    lyr = RBM(n_in=n_in, n_out=n_out, bias_init=0.0)
    params = lyr.init_params(jax.random.PRNGKey(seed), dtype=dtype)
    return lyr, params


class TestCdStatistics:
    def test_cd1_gradient_matches_hinton_statistics(self):
        """jax.grad of the surrogate == -(pos - neg) computed by hand."""
        lyr, params = _rbm_params()
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.rand(8, 6) > 0.5, jnp.float64)
        rng = jax.random.PRNGKey(7)

        grads = jax.grad(
            lambda p: jnp.mean(lyr.pretrain_loss_per_example(p, x, rng)))(
                params)

        # hand-computed CD-1 statistics from the same chain
        h0, vk, hk = lyr._gibbs_chain(params, x, rng)
        B = x.shape[0]
        w_expect = -(jnp.dot(x.T, h0) - jnp.dot(vk.T, hk)) / B
        hb_expect = -jnp.mean(h0 - hk, axis=0)
        vb_expect = -jnp.mean(x - vk, axis=0)
        np.testing.assert_allclose(np.asarray(grads["W"]),
                                   np.asarray(w_expect), atol=1e-12)
        np.testing.assert_allclose(np.asarray(grads["b"]),
                                   np.asarray(hb_expect), atol=1e-12)
        np.testing.assert_allclose(np.asarray(grads["vb"]),
                                   np.asarray(vb_expect), atol=1e-12)

    def test_sparsity_replaces_hidden_bias_phase(self):
        """reference :173-175: sparsity != 0 makes the hb gradient
        -(sparsity - h0_prob); W and vb statistics are unchanged."""
        lyr, params = _rbm_params()
        sparse = RBM(n_in=6, n_out=4, sparsity=0.1, bias_init=0.0)
        rs = np.random.RandomState(4)
        x = jnp.asarray(rs.rand(5, 6) > 0.5, jnp.float64)
        rng = jax.random.PRNGKey(1)

        g_plain = jax.grad(
            lambda p: jnp.mean(lyr.pretrain_loss_per_example(p, x, rng)))(
                params)
        g_sparse = jax.grad(
            lambda p: jnp.mean(sparse.pretrain_loss_per_example(p, x, rng)))(
                params)
        h0, _, _ = lyr._gibbs_chain(params, x, rng)
        hb_expect = -jnp.mean(0.1 - h0, axis=0)
        np.testing.assert_allclose(np.asarray(g_sparse["b"]),
                                   np.asarray(hb_expect), atol=1e-12)
        np.testing.assert_allclose(np.asarray(g_sparse["W"]),
                                   np.asarray(g_plain["W"]), atol=1e-12)
        np.testing.assert_allclose(np.asarray(g_sparse["vb"]),
                                   np.asarray(g_plain["vb"]), atol=1e-12)

    def test_cdk_chain_length(self):
        """k>1 runs a longer chain: the negative statistics differ from
        CD-1's but stay finite and shape-correct."""
        lyr, params = _rbm_params()
        deep = RBM(n_in=6, n_out=4, k=3, bias_init=0.0)
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.rand(4, 6) > 0.5, jnp.float64)
        rng = jax.random.PRNGKey(2)
        g1 = jax.grad(
            lambda p: jnp.mean(lyr.pretrain_loss_per_example(p, x, rng)))(
                params)
        g3 = jax.grad(
            lambda p: jnp.mean(deep.pretrain_loss_per_example(p, x, rng)))(
                params)
        assert all(np.isfinite(np.asarray(g3[k])).all() for k in g3)
        assert not np.allclose(np.asarray(g1["W"]), np.asarray(g3["W"]))

    @pytest.mark.parametrize("hidden,visible", [
        ("rectified", "gaussian"), ("gaussian", "linear"),
        ("identity", "identity")])
    def test_unit_variants_finite(self, hidden, visible):
        lyr = RBM(n_in=6, n_out=4, hidden_unit=hidden,
                  visible_unit=visible, bias_init=0.0)
        params = lyr.init_params(jax.random.PRNGKey(0), dtype=jnp.float64)
        rs = np.random.RandomState(6)
        x = jnp.asarray(rs.randn(4, 6) * 0.5, jnp.float64)
        g = jax.grad(
            lambda p: jnp.mean(lyr.pretrain_loss_per_example(
                p, x, jax.random.PRNGKey(3))))(params)
        assert all(np.isfinite(np.asarray(g[k])).all() for k in g)

    def test_validate_rejects_bad_units(self):
        with pytest.raises(ValueError, match="hidden_unit"):
            RBM(n_in=2, n_out=2, hidden_unit="softmax").validate()
        with pytest.raises(ValueError, match="visible_unit"):
            RBM(n_in=2, n_out=2, visible_unit="softmax").validate()
        with pytest.raises(ValueError, match="k must be"):
            RBM(n_in=2, n_out=2, k=0).validate()


class TestPretraining:
    def _patterned_data(self, n=128, seed=0):
        """Two binary prototype patterns + flip noise: an RBM with a few
        hidden units can model this well, so CD-1 must drive recon error
        down."""
        rs = np.random.RandomState(seed)
        protos = np.array([[1, 1, 1, 0, 0, 0, 1, 0],
                           [0, 0, 0, 1, 1, 1, 0, 1]], np.float64)
        x = protos[rs.randint(0, 2, n)]
        flip = rs.rand(n, 8) < 0.05
        return np.where(flip, 1 - x, x)

    def test_pretrain_reduces_reconstruction_error(self):
        x = self._patterned_data()
        conf = (NeuralNetConfiguration.builder().seed(12)
                .updater(Sgd(learning_rate=0.5))
                .list(RBM(n_out=4),
                      OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(8)).build())
        net = MultiLayerNetwork(conf).init()
        lyr = net.layers[0]

        def recon_err(params):
            h = lyr.prop_up(params["0"], jnp.asarray(x))
            v = lyr.prop_down(params["0"], h)
            return float(jnp.mean((jnp.asarray(x) - v) ** 2))

        before = recon_err(net.params)
        net.pretrain(DataSet(x, None), epochs=60)
        after = recon_err(net.params)
        assert after < before * 0.5, (before, after)

    def test_supervised_gradcheck_through_rbm_forward(self):
        """After pretraining, the RBM acts as a feed-forward layer
        (propUp); the supervised backprop through it must pass the central
        finite-difference check like any other layer."""
        rng = np.random.default_rng(1)
        conf = (NeuralNetConfiguration.builder().seed(42)
                .updater(Sgd(learning_rate=0.1)).weight_init("xavier")
                .dtype("float64")
                .list(RBM(n_out=5),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        x = rng.normal(0, 1, (5, 4))
        y = np.eye(3)[rng.integers(0, 3, 5)]
        assert check_gradients(net, x, y)


class TestPretrainRegularization:
    def test_pretrain_applies_weight_decay(self):
        """regularization.py invariant: the pretrain gradient path applies
        l1/l2 like every other jax.grad consumer (DL4J's
        BaseUpdater.postApply decays during pretraining too). With a large
        l2, pretrained weights must end up smaller than without it."""
        rs = np.random.RandomState(0)
        x = (rs.rand(64, 12) > 0.5).astype(np.float32)

        def norm_after_pretrain(l2):
            conf = (NeuralNetConfiguration.builder().seed(1)
                    .updater(Sgd(learning_rate=0.05)).l2(l2)
                    .list(RBM(n_out=8),
                          OutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"))
                    .set_input_type(InputType.feed_forward(12)).build())
            net = MultiLayerNetwork(conf).init()
            net._pretrain_layer(0, [DataSet(x, None)] * 30, 1)
            return float(np.linalg.norm(np.asarray(net.params["0"]["W"])))

        assert norm_after_pretrain(0.5) < norm_after_pretrain(0.0)

    def test_moe_regularization_grad_tolerates_partial_params(self):
        """add_regularization_grads walks ALL layers with whatever subtree
        the gradient path holds — during layerwise pretraining that is an
        EMPTY dict for every other layer. MoE's extra load-balance term
        (keyed on 'Wg') must not KeyError on it."""
        from deeplearning4j_tpu.nn.conf.layers import MixtureOfExpertsLayer

        moe = MixtureOfExpertsLayer(n_in=6, n_out=8, n_experts=2, top_k=1,
                                    expert_hidden=4, load_balance_coef=0.1)
        assert moe.regularization_grad({}) == {}


class TestSerde:
    def test_json_round_trip(self):
        lyr = RBM(n_in=6, n_out=4, hidden_unit="rectified",
                  visible_unit="gaussian", k=3, sparsity=0.05)
        back = from_json(to_json(lyr))
        assert back == lyr
