"""NLP stack tests (ports the intent of deeplearning4j-nlp tests:
Word2VecTests, ParagraphVectorsTest, GloveTest, vocab/Huffman tests,
TfidfVectorizerTest)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    CollectionSentenceIterator,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    Glove,
    Huffman,
    ParagraphVectors,
    VocabConstructor,
    Word2Vec,
)
from deeplearning4j_tpu.nlp.bagofwords import (
    BagOfWordsVectorizer,
    TfidfVectorizer,
)
from deeplearning4j_tpu.nlp.tokenization import LabelledDocument


def _synthetic_corpus(n=300, seed=0):
    """Two topic clusters: 'day/sun/light/bright' vs 'night/moon/dark/star'.
    Co-occurrence structure is what the embeddings must discover."""
    rs = np.random.RandomState(seed)
    day = ["day", "sun", "light", "bright", "warm", "noon"]
    night = ["night", "moon", "dark", "star", "cold", "midnight"]
    filler = ["the", "a", "is", "was", "and"]
    sentences = []
    for _ in range(n):
        topic = day if rs.rand() < 0.5 else night
        words = []
        for _ in range(rs.randint(5, 9)):
            words.append(topic[rs.randint(len(topic))]
                         if rs.rand() < 0.75
                         else filler[rs.randint(len(filler))])
        sentences.append(" ".join(words))
    return sentences


class TestTokenization:
    def test_default_tokenizer_with_preprocessor(self):
        tf = DefaultTokenizerFactory(CommonPreprocessor())
        toks = tf.create("Hello, World! 123 foo-bar").tokens()
        assert "hello" in toks and "world" in toks
        assert all("," not in t and "!" not in t for t in toks)

    def test_sentence_iterator_reset(self):
        it = CollectionSentenceIterator(["a b", "c d"])
        assert list(it) == ["a b", "c d"]
        assert list(it) == ["a b", "c d"]  # re-iterable


class TestVocab:
    def test_vocab_counts_and_min_frequency(self):
        vc = VocabConstructor(min_word_frequency=2)
        cache = vc.build_vocab(["a a a b b c", "a b d"])
        assert cache.word_frequency("a") == 4
        assert cache.word_frequency("b") == 3
        assert not cache.contains_word("c")  # freq 1 < 2
        assert not cache.contains_word("d")
        # index 0 = most frequent
        assert cache.word_at_index(0) == "a"

    def test_huffman_codes_valid(self):
        """Huffman: prefix-free codes, frequent words get shorter codes
        (reference: Huffman.java:34)."""
        vc = VocabConstructor(min_word_frequency=1)
        cache = vc.build_vocab(
            ["a a a a a a a a b b b b c c d d e f g h i j"])
        wa = cache.word_for("a")
        wj = cache.word_for("j")
        assert len(wa.codes) <= len(wj.codes)
        # prefix-free: no word's code is a prefix of another's
        codes = {"".join(map(str, cache.word_for(w).codes))
                 for w in "abcdefghij"}
        assert len(codes) == 10
        for c1 in codes:
            for c2 in codes:
                if c1 != c2:
                    assert not c2.startswith(c1)
        # points within inner-node space [0, V-1)
        V = cache.num_words()
        for w in "abcdefghij":
            ww = cache.word_for(w)
            assert len(ww.points) == len(ww.codes)
            assert all(0 <= p < V - 1 for p in ww.points)


class TestWord2Vec:
    @pytest.mark.parametrize("hs,negative,lr", [(True, 0, 0.05),
                                                (False, 5, 0.05),
                                                (True, 5, 0.025)])
    def test_skipgram_learns_topic_structure(self, hs, negative, lr):
        # combined HS+NS doubles the per-pair step; with this tiny 17-word
        # vocab the batched scatter update needs the word2vec-default lr and
        # a smaller batch to stay stable (real vocabs spread the rows)
        corpus = _synthetic_corpus()
        w2v = Word2Vec(layer_size=32, window=4, min_word_frequency=3,
                       epochs=4, use_hierarchic_softmax=hs,
                       negative=negative, learning_rate=lr,
                       batch_size=128 if (hs and negative) else 512, seed=7)
        w2v.fit(CollectionSentenceIterator(corpus))
        # within-topic similarity must beat cross-topic
        same = w2v.similarity("day", "sun")
        cross = w2v.similarity("day", "moon")
        assert same > cross, (same, cross)
        assert w2v.similarity("night", "moon") > \
            w2v.similarity("night", "sun")

    def test_native_backend_learns_topic_structure(self):
        """The native C hot loop (native/skipgram.c — the reference's
        AggregateSkipGram stand-in, SkipGram.java:215-272) trains real
        embeddings; backend='native' forces it."""
        from deeplearning4j_tpu.native import skipgram_native_available

        if not skipgram_native_available():
            pytest.skip("no C toolchain")
        corpus = _synthetic_corpus()
        w2v = Word2Vec(layer_size=32, window=4, min_word_frequency=3,
                       epochs=4, use_hierarchic_softmax=False, negative=5,
                       learning_rate=0.05, seed=7, backend="native")
        w2v.fit(CollectionSentenceIterator(corpus))
        assert w2v.similarity("day", "sun") > w2v.similarity("day", "moon")
        assert w2v.similarity("night", "moon") > \
            w2v.similarity("night", "sun")

    def test_native_backend_routing_rules(self):
        """auto: plain NS skip-gram and CBOW route native; HS / device
        pin / oversize windows stay on the device path; native pin on an
        ineligible config raises instead of silently training
        differently."""
        from deeplearning4j_tpu.native import skipgram_native_available

        if not skipgram_native_available():
            pytest.skip("no C toolchain")
        corpus = _synthetic_corpus(60)

        def built(**kw):
            kw.setdefault("window", 2)
            w2v = Word2Vec(layer_size=8, min_word_frequency=1, **kw)
            w2v.build_vocab(corpus)
            w2v.reset_weights()
            return w2v

        assert built(negative=5, use_hierarchic_softmax=False
                     )._use_native_backend()
        assert not built(negative=5, use_hierarchic_softmax=True
                         )._use_native_backend()
        assert not built(negative=5, use_hierarchic_softmax=False,
                         backend="device")._use_native_backend()
        # CBOW is native-eligible too (cbow_train) — up to the kernel's
        # context-buffer window cap
        assert built(negative=5, use_hierarchic_softmax=False,
                     elements_algorithm="cbow")._use_native_backend()
        assert not built(negative=5, use_hierarchic_softmax=False,
                         elements_algorithm="cbow",
                         window=65)._use_native_backend()
        with pytest.raises(ValueError, match="native"):
            built(negative=0, use_hierarchic_softmax=True,
                  backend="native")._use_native_backend()

    def test_native_fallback_reuses_materialized_corpus(self, monkeypatch):
        """Regression: when the native kernel bails (returns None) AFTER
        the corpus walk consumed a one-shot generator, the device
        fallback must train on the materialized index corpus — re-
        iterating the exhausted generator would silently train on
        nothing."""
        import deeplearning4j_tpu.native as native

        corpus = _synthetic_corpus(80)
        w2v = Word2Vec(layer_size=16, window=3, min_word_frequency=2,
                       epochs=1, negative=5, use_hierarchic_softmax=False,
                       learning_rate=0.05, seed=5)
        w2v.build_vocab(corpus)
        w2v.reset_weights()
        before = np.array(w2v.syn0, copy=True)

        monkeypatch.setattr(native, "skipgram_train",
                            lambda *a, **k: None)
        trained_tokens = []
        orig_fit = w2v._fit_element_epochs
        w2v._fit_element_epochs = lambda sents: (
            trained_tokens.append(sum(len(s) for s in sents))
            or orig_fit(sents))

        one_shot = iter(corpus)          # no .reset(): a plain generator
        w2v._fit_native(one_shot)
        assert trained_tokens and trained_tokens[0] > 0
        assert not np.allclose(np.asarray(w2v.syn0), before)
        corpus = _synthetic_corpus()
        w2v = Word2Vec(layer_size=32, window=4, min_word_frequency=3,
                       epochs=6, negative=5, use_hierarchic_softmax=False,
                       elements_algorithm="cbow", learning_rate=0.05, seed=3)
        w2v.fit(CollectionSentenceIterator(corpus))
        assert w2v.similarity("day", "sun") > w2v.similarity("day", "moon")

    def test_words_nearest(self):
        corpus = _synthetic_corpus()
        w2v = Word2Vec(layer_size=32, window=4, min_word_frequency=3,
                       epochs=4, negative=5, seed=11)
        w2v.fit(CollectionSentenceIterator(corpus))
        near = [w for w, _ in w2v.words_nearest("moon", 4)]
        assert len(near) == 4
        assert "moon" not in near
        night_words = {"night", "dark", "star", "cold", "midnight"}
        assert len(night_words & set(near)) >= 1

    def test_tiny_vocab_large_batch_stays_finite_and_learns(self):
        # regression: batch >> vocab means hundreds of duplicate scatter
        # contributions per row per batch; without the DUP_CAP per-row step
        # cap (learning.py _row_mean_scale) the summed stale-gradient update
        # diverged to NaN within a few batches
        rs = np.random.RandomState(42)
        topic_a = ["cat", "dog", "bird", "fish", "horse", "cow"]
        topic_b = ["hammer", "wrench", "drill", "saw", "pliers", "chisel"]
        sentences = [" ".join(rs.choice(topic_a if rs.rand() < 0.5
                                        else topic_b, 8))
                     for _ in range(1500)]
        w2v = Word2Vec(layer_size=32, window=5, min_word_frequency=1,
                       epochs=3, negative=5, use_hierarchic_softmax=False,
                       batch_size=4096, seed=1)
        w2v.fit(CollectionSentenceIterator(sentences))
        assert np.all(np.isfinite(np.asarray(w2v.syn0)))
        near = [w for w, _ in w2v.words_nearest("cat", 5)]
        assert all(w in topic_a for w in near), near

    def test_binary_serde_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.nlp.serde import (
            load_word2vec,
            write_word2vec_binary,
        )

        corpus = _synthetic_corpus(100)
        w2v = Word2Vec(layer_size=16, min_word_frequency=2, epochs=1,
                       negative=3, seed=5)
        w2v.fit(CollectionSentenceIterator(corpus))
        p = str(tmp_path / "vecs.bin")
        write_word2vec_binary(w2v, p)
        m2 = load_word2vec(p, binary=True)
        for w in ("day", "night", "the"):
            if w2v.has_word(w):
                assert np.allclose(w2v.word_vector(w), m2.word_vector(w),
                                   atol=1e-6)
        assert abs(w2v.similarity("day", "sun")
                   - m2.similarity("day", "sun")) < 1e-5

    def test_text_serde_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.nlp.serde import (
            load_word2vec,
            write_word_vectors_text,
        )

        w2v = Word2Vec(layer_size=8, min_word_frequency=1, epochs=1,
                       negative=2, seed=5)
        w2v.fit(CollectionSentenceIterator(["a b c a b", "b c a"]))
        p = str(tmp_path / "vecs.txt")
        write_word_vectors_text(w2v, p)
        m2 = load_word2vec(p, binary=False)
        assert np.allclose(w2v.word_vector("a"), m2.word_vector("a"),
                           atol=1e-5)


class TestSkipGramGradient:
    def test_hs_update_matches_autodiff(self):
        """The closed-form HS update must equal -lr * dLoss/dparams for the
        binary cross-entropy along the huffman path (gradcheck of the fused
        op, parity with the reference's AggregateSkipGram semantics)."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nlp.learning import skipgram_step

        V, D, L = 7, 5, 3
        rs = np.random.RandomState(0)
        syn0 = jnp.asarray(rs.randn(V, D), jnp.float32) * 0.1
        syn1 = jnp.asarray(rs.randn(V, D), jnp.float32) * 0.1
        centers = jnp.asarray([2], jnp.int32)
        points = jnp.asarray([[0, 3, 4]], jnp.int32)
        codes = jnp.asarray([[1.0, 0.0, 1.0]], jnp.float32)
        mask = jnp.ones((1, L), jnp.float32)
        lr = 0.1

        def hs_loss(s0, s1):
            h = s0[centers]  # [1, D]
            f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, s1[points]))
            # BCE with target (1 - code)
            t = 1.0 - codes
            return -jnp.sum(t * jnp.log(f + 1e-12)
                            + (1 - t) * jnp.log(1 - f + 1e-12))

        g0, g1 = jax.grad(hs_loss, argnums=(0, 1))(syn0, syn1)
        new0, new1, _ = skipgram_step(
            syn0, syn1, jnp.zeros_like(syn1), centers, points, codes, mask,
            jnp.zeros((1, 1), jnp.int32), jnp.zeros((1, 1), jnp.float32),
            jnp.float32(lr), jnp.float32(16.0), use_hs=True, use_ns=False)
        assert np.allclose(np.asarray(new0), np.asarray(syn0 - lr * g0),
                           atol=1e-5)
        assert np.allclose(np.asarray(new1), np.asarray(syn1 - lr * g1),
                           atol=1e-5)

    def test_ns_update_matches_autodiff(self):
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nlp.learning import skipgram_step

        V, D, K = 6, 4, 3
        rs = np.random.RandomState(1)
        syn0 = jnp.asarray(rs.randn(V, D), jnp.float32) * 0.1
        syn1neg = jnp.asarray(rs.randn(V, D), jnp.float32) * 0.1
        centers = jnp.asarray([1, 4], jnp.int32)
        negt = jnp.asarray([[2, 0, 3, 5], [0, 2, 3, 1]], jnp.int32)
        negl = jnp.asarray([[1, 0, 0, 0], [1, 0, 0, 0]], jnp.float32)
        lr = 0.05

        def ns_loss(s0, sn):
            h = s0[centers]
            f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, sn[negt]))
            return -jnp.sum(negl * jnp.log(f + 1e-12)
                            + (1 - negl) * jnp.log(1 - f + 1e-12))

        g0, gn = jax.grad(ns_loss, argnums=(0, 1))(syn0, syn1neg)
        new0, _, newn = skipgram_step(
            syn0, jnp.zeros_like(syn0), syn1neg, centers,
            jnp.zeros((2, 1), jnp.int32), jnp.zeros((2, 1), jnp.float32),
            jnp.zeros((2, 1), jnp.float32), negt, negl,
            jnp.float32(lr), jnp.float32(16.0), use_hs=False, use_ns=True)
        assert np.allclose(np.asarray(new0), np.asarray(syn0 - lr * g0),
                           atol=1e-5)
        assert np.allclose(np.asarray(newn), np.asarray(syn1neg - lr * gn),
                           atol=1e-5)


class TestSegmentUpdates:
    """The sorted-segment row-update path must be numerically equivalent to
    the scatter-add path it replaces (same per-row dup_cap scaling, float
    summation order aside)."""

    def test_segment_row_add_matches_scatter(self):
        import jax.numpy as jnp

        from deeplearning4j_tpu.nlp.learning import (_row_mean_scale,
                                                     _segment_row_add)

        rs = np.random.RandomState(0)
        R, D, M, cap = 40, 8, 512, 4.0
        table = jnp.asarray(rs.randn(R, D), jnp.float32)
        idx = jnp.asarray(rs.randint(0, R, M), jnp.int32)
        w = jnp.asarray((rs.rand(M) > 0.2), jnp.float32)
        upd = jnp.asarray(rs.randn(M, D), jnp.float32) * w[:, None]
        s = _row_mean_scale(R, idx, w, cap)
        ref = table.at[idx].add(upd * s[:, None])
        out = _segment_row_add(idx, upd, w, jnp.float32(cap), table)
        assert np.allclose(np.asarray(ref), np.asarray(out), atol=1e-4)

    def test_epoch_parity_segment_vs_scatter(self):
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nlp.learning import skipgram_corpus_epoch

        rs = np.random.RandomState(2)
        V, D, W, K, L, B = 50, 16, 3, 4, 5, 64
        toks = rs.randint(0, V, 96).astype(np.int32)
        toks[::13] = -1
        n = 96
        while (n * 2 * W) % B:
            n *= 2
        toks = np.concatenate([toks, np.full(n - toks.size, -1, np.int32)])
        pts = rs.randint(0, V - 1, (V, L)).astype(np.int32)
        cds = (rs.rand(V, L) > 0.5).astype(np.float32)
        cmk = (rs.rand(V, L) > 0.3).astype(np.float32)
        neg = rs.randint(0, V, 256).astype(np.int32)
        kwargs = dict(window=W, batch=B, neg_k=K, use_hs=True, use_ns=True)
        args = (jnp.asarray(toks), jax.random.PRNGKey(5),
                jnp.float32(0.025), jnp.float32(0.01), jnp.float32(8.0),
                jnp.asarray(pts), jnp.asarray(cds), jnp.asarray(cmk),
                jnp.asarray(neg))

        def run(segment):
            syn0 = jnp.asarray(np.linspace(-1, 1, V * D).reshape(V, D),
                               jnp.float32)
            syn1 = jnp.zeros((V, D), jnp.float32) + 0.01
            syn1n = jnp.zeros((V, D), jnp.float32) + 0.02
            return skipgram_corpus_epoch(syn0, syn1, syn1n, *args,
                                         segment_updates=segment, **kwargs)

        a0, a1, an = run(True)
        b0, b1, bn = run(False)
        assert np.allclose(np.asarray(a0), np.asarray(b0), atol=2e-4)
        assert np.allclose(np.asarray(a1), np.asarray(b1), atol=2e-4)
        assert np.allclose(np.asarray(an), np.asarray(bn), atol=2e-4)

    @pytest.mark.parametrize("algo", ["cbow", "dm", "dbow"])
    def test_cbow_dbow_epoch_parity_segment_vs_scatter(self, algo):
        """The cbow/dbow epochs keep the scatter path as the A/B reference;
        the segment path (incl. per-slot label_cap plumbing) must match."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nlp.learning import (cbow_corpus_epoch,
                                                     dbow_corpus_epoch)

        rs = np.random.RandomState(4)
        V, D, W, K, L, B = 50, 16, 3, 4, 5, 64
        n = 128
        toks = rs.randint(0, V - 10, n).astype(np.int32)
        toks[::11] = -1
        labs = np.full(n, -1, np.int32)
        # per-"document" label rows in the top of the table
        doc = np.cumsum(toks < 0)
        labs = np.where(toks >= 0, V - 10 + (doc % 10), -1).astype(np.int32)
        pts = rs.randint(0, V - 1, (V, L)).astype(np.int32)
        cds = (rs.rand(V, L) > 0.5).astype(np.float32)
        cmk = (rs.rand(V, L) > 0.3).astype(np.float32)
        neg = rs.randint(0, V, 256).astype(np.int32)
        label_cap = np.inf if algo != "cbow" else 8.0
        common = (jnp.asarray(toks), jnp.asarray(labs),
                  jax.random.PRNGKey(9), jnp.float32(0.025),
                  jnp.float32(0.01), jnp.float32(8.0),
                  jnp.float32(label_cap), jnp.asarray(pts),
                  jnp.asarray(cds), jnp.asarray(cmk), jnp.asarray(neg))

        def run(segment):
            syn0 = jnp.asarray(np.linspace(-1, 1, V * D).reshape(V, D),
                               jnp.float32)
            syn1 = jnp.zeros((V, D), jnp.float32) + 0.01
            syn1n = jnp.zeros((V, D), jnp.float32) + 0.02
            if algo == "dbow":
                return dbow_corpus_epoch(syn0, syn1, syn1n, *common,
                                         batch=B, neg_k=K, use_hs=True,
                                         use_ns=True,
                                         segment_updates=segment)
            return cbow_corpus_epoch(syn0, syn1, syn1n, *common,
                                     window=W, batch=B, neg_k=K,
                                     use_hs=True, use_ns=True,
                                     with_labels=(algo == "dm"),
                                     segment_updates=segment)

        for a, b in zip(run(True), run(False)):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=2e-4)


class TestDistributedEmbeddings:
    """Vocab-row sharding over the mesh (the dl4j-spark-nlp Word2Vec
    equivalent — see nlp/distributed.py): the SAME epoch program runs
    GSPMD-partitioned, so sharded training must match single-device
    training, and queries must ignore mesh-padding rows."""

    def _corpus(self, n=300):
        rs = np.random.RandomState(6)
        day = ["day", "sun", "light", "bright", "warm"]
        night = ["night", "moon", "dark", "star", "cold"]
        out = []
        for _ in range(n):
            topic = day if rs.rand() < 0.5 else night
            out.append(" ".join(topic[rs.randint(5)] for _ in range(10)))
        return out

    def test_sharded_matches_single_device(self):
        from deeplearning4j_tpu.nlp.distributed import shard_embedding_tables
        from deeplearning4j_tpu.parallel.mesh import data_model_mesh

        sents = self._corpus()

        def train(sharded):
            # backend pinned: the parity under test is a DEVICE-path
            # property (sharding must not change the math); auto would
            # route the unsharded run to the native C loop instead
            w2v = Word2Vec(layer_size=16, window=3, min_word_frequency=2,
                           negative=5, use_hierarchic_softmax=False,
                           epochs=2, learning_rate=0.05, seed=11,
                           backend="device")
            w2v.build_vocab(sents)
            w2v.reset_weights()
            if sharded:
                mesh = data_model_mesh(1, 8)
                shard_embedding_tables(w2v, mesh)
            w2v.fit(CollectionSentenceIterator(sents))
            return w2v

        a = train(False)
        b = train(True)
        V = a.vocab.num_words()
        # padded rows beyond V; vocab rows must match the unsharded run
        assert np.asarray(b.syn0).shape[0] >= V
        assert np.allclose(np.asarray(a.syn0),
                           np.asarray(b.syn0)[:V], atol=1e-4)
        # query APIs unaffected by padding rows
        near = [w for w, _ in b.words_nearest("sun", 3)]
        assert near == [w for w, _ in a.words_nearest("sun", 3)]

    def test_sharded_model_serde_ignores_padding_rows(self):
        import tempfile, os
        from deeplearning4j_tpu.nlp.distributed import shard_embedding_tables
        from deeplearning4j_tpu.nlp.serde import (read_word2vec_binary,
                                                  write_word2vec_binary)
        from deeplearning4j_tpu.parallel.mesh import data_model_mesh

        sents = self._corpus(80)
        w2v = Word2Vec(layer_size=8, window=2, min_word_frequency=2,
                       negative=3, use_hierarchic_softmax=False, epochs=1,
                       seed=2)
        w2v.build_vocab(sents)
        w2v.reset_weights()
        shard_embedding_tables(w2v, data_model_mesh(1, 8))
        w2v.fit(CollectionSentenceIterator(sents))
        V = w2v.vocab.num_words()
        assert np.asarray(w2v.syn0).shape[0] > V  # padding present
        p = os.path.join(tempfile.mkdtemp(), "v.bin")
        write_word2vec_binary(w2v, p)
        words, vecs = read_word2vec_binary(p)
        assert len(words) == V and "None" not in words
        i = w2v.vocab.index_of("sun")
        assert np.allclose(vecs[words.index("sun")],
                           np.asarray(w2v.syn0)[i], atol=1e-6)

    def test_sharded_vocab_rows_padding(self):
        from deeplearning4j_tpu.nlp.distributed import sharded_vocab_rows
        from deeplearning4j_tpu.parallel.mesh import data_model_mesh
        mesh = data_model_mesh(1, 8)
        assert sharded_vocab_rows(16, mesh) == 16
        assert sharded_vocab_rows(17, mesh) == 24
        assert sharded_vocab_rows(1, mesh) == 8


class TestParagraphVectors:
    def _docs(self, n=120, seed=2):
        rs = np.random.RandomState(seed)
        day = ["day", "sun", "light", "bright", "warm"]
        night = ["night", "moon", "dark", "star", "cold"]
        docs = []
        for i in range(n):
            topic, label = (day, "DAY") if rs.rand() < 0.5 else \
                (night, "NIGHT")
            words = [topic[rs.randint(len(topic))]
                     for _ in range(rs.randint(6, 10))]
            docs.append(LabelledDocument(" ".join(words), label))
        return docs

    @pytest.mark.parametrize("algo", ["dbow", "dm"])
    def test_doc_classification(self, algo):
        docs = self._docs()
        pv = ParagraphVectors(layer_size=24, window=3, min_word_frequency=2,
                              epochs=6, negative=5,
                              use_hierarchic_softmax=False,
                              sequence_algorithm=algo, learning_rate=0.05,
                              seed=9)
        pv.fit(docs)
        assert set(pv.labels()) == {"DAY", "NIGHT"}
        assert pv.predict("sun light warm bright day sun") == "DAY"
        assert pv.predict("moon dark star cold night moon") == "NIGHT"

    def test_infer_vector_consistency(self):
        docs = self._docs()
        pv = ParagraphVectors(layer_size=24, window=3, min_word_frequency=2,
                              epochs=5, negative=5,
                              use_hierarchic_softmax=False, seed=4)
        pv.fit(docs)
        v1 = pv.infer_vector("sun light warm", iterations=10, seed=0)
        v2 = pv.infer_vector("sun light warm", iterations=10, seed=0)
        assert np.allclose(v1, v2)  # deterministic
        assert v1.shape == (24,)


class TestGlove:
    def test_glove_learns_topic_structure(self):
        corpus = _synthetic_corpus(250)
        g = Glove(layer_size=24, window=6, min_word_frequency=3, epochs=40,
                  learning_rate=0.1, seed=13)
        g.fit(corpus)
        assert g.similarity("day", "sun") > g.similarity("day", "moon")


class TestBagOfWords:
    def test_counts(self):
        bow = BagOfWordsVectorizer()
        X = bow.fit_transform(["a b a", "b c"])
        ia = bow.vocab.index_of("a")
        assert X[0, ia] == 2.0
        assert X[1, ia] == 0.0

    def test_tfidf_downweights_common_words(self):
        docs = ["the cat sat", "the dog ran", "the bird flew"]
        tv = TfidfVectorizer().fit(docs)
        v = tv.transform("the cat")
        i_the = tv.vocab.index_of("the")
        i_cat = tv.vocab.index_of("cat")
        assert v[i_the] == 0.0          # idf(the) = log(3/3) = 0
        assert v[i_cat] > 0.0


class TestNativeDoc2Vec:
    def test_native_dbow_learns_doc_structure(self):
        """The native pair kernel (DBOW.java analog) trains document
        vectors that separate two topics, mirroring the device-path
        classification test."""
        from deeplearning4j_tpu.native import skipgram_native_available
        from deeplearning4j_tpu.nlp import ParagraphVectors
        from deeplearning4j_tpu.nlp.tokenization import LabelledDocument

        if not skipgram_native_available():
            pytest.skip("no C toolchain")
        rs = np.random.RandomState(0)
        day = ["day", "sun", "light", "bright", "warm"]
        night = ["night", "moon", "dark", "star", "cold"]
        docs = []
        for i in range(60):
            topic, lab = (day, "d") if i % 2 == 0 else (night, "n")
            docs.append(LabelledDocument(
                " ".join(topic[rs.randint(5)] for _ in range(12)),
                f"{lab}{i}"))
        pv = ParagraphVectors(layer_size=24, window=3, min_word_frequency=1,
                              negative=5, use_hierarchic_softmax=False,
                              epochs=8, seed=3)
        assert pv.backend == "auto"
        pv.build_vocab_from_documents(docs)
        pv.reset_weights()
        assert pv._native_eligible_config()
        pv.fit(docs)
        # same-topic doc vectors must be closer than cross-topic
        import numpy as np_
        vecs = {d.labels[0]: np_.asarray(
            pv.syn0[pv._label_ids[d.labels[0]]]) for d in docs}

        def cos(a, b):
            return float(a @ b / (np_.linalg.norm(a) * np_.linalg.norm(b)
                                  + 1e-9))
        same = np_.mean([cos(vecs[f"d{i}"], vecs[f"d{i+2}"])
                         for i in range(0, 20, 2)])
        cross = np_.mean([cos(vecs[f"d{i}"], vecs[f"n{i+1}"])
                          for i in range(0, 20, 2)])
        assert same > cross, (same, cross)

    def test_native_dbow_routing_rules(self):
        from deeplearning4j_tpu.native import skipgram_native_available
        from deeplearning4j_tpu.nlp import ParagraphVectors

        if not skipgram_native_available():
            pytest.skip("no C toolchain")

        def pv(**kw):
            return ParagraphVectors(layer_size=8, min_word_frequency=1,
                                    **kw)

        assert pv(negative=5, use_hierarchic_softmax=False
                  )._native_eligible_config()
        assert not pv(negative=5, use_hierarchic_softmax=False,
                      backend="device")._native_eligible_config()
        # DM is native-eligible too since the CBOW/DM kernel landed
        assert pv(negative=5, use_hierarchic_softmax=False,
                  sequence_algorithm="dm")._native_eligible_config()
        assert not pv(negative=5, use_hierarchic_softmax=False,
                      train_words=True)._native_eligible_config()
        assert not pv(negative=0, use_hierarchic_softmax=True
                      )._native_eligible_config()


class TestNativeCbowDm:
    def test_native_cbow_learns_topic_structure(self):
        from deeplearning4j_tpu.native import skipgram_native_available

        if not skipgram_native_available():
            pytest.skip("no C toolchain")
        corpus = _synthetic_corpus()
        w2v = Word2Vec(layer_size=32, window=4, min_word_frequency=3,
                       epochs=6, negative=5, use_hierarchic_softmax=False,
                       elements_algorithm="cbow", learning_rate=0.05,
                       seed=3, backend="native")
        w2v.fit(CollectionSentenceIterator(corpus))
        assert w2v.similarity("day", "sun") > w2v.similarity("day", "moon")
        assert w2v.similarity("night", "moon") > \
            w2v.similarity("night", "sun")

    def test_native_dm_learns_doc_structure(self):
        from deeplearning4j_tpu.native import skipgram_native_available

        if not skipgram_native_available():
            pytest.skip("no C toolchain")
        rs = np.random.RandomState(1)
        day = ["day", "sun", "light", "bright", "warm"]
        night = ["night", "moon", "dark", "star", "cold"]
        docs = []
        for i in range(60):
            topic, lab = (day, "d") if i % 2 == 0 else (night, "n")
            docs.append(LabelledDocument(
                " ".join(topic[rs.randint(5)] for _ in range(12)),
                f"{lab}{i}"))
        pv = ParagraphVectors(layer_size=24, window=3, min_word_frequency=1,
                              negative=5, use_hierarchic_softmax=False,
                              epochs=10, seed=3, sequence_algorithm="dm",
                              backend="native")
        pv.build_vocab_from_documents(docs)
        pv.reset_weights()
        assert pv._native_eligible_config()
        pv.fit(docs)
        vecs = {d.labels[0]: np.asarray(
            pv.syn0[pv._label_ids[d.labels[0]]]) for d in docs}

        def cos(a, b):
            return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b)
                                  + 1e-9))
        same = np.mean([cos(vecs[f"d{i}"], vecs[f"d{i+2}"])
                        for i in range(0, 20, 2)])
        cross = np.mean([cos(vecs[f"d{i}"], vecs[f"n{i+1}"])
                         for i in range(0, 20, 2)])
        assert same > cross, (same, cross)


class TestCjkTokenizer:
    def test_cjk_bigrams_and_mixed_scripts(self):
        from deeplearning4j_tpu.nlp import CjkTokenizerFactory

        tf = CjkTokenizerFactory()
        # pure CJK run -> overlapping character bigrams
        assert tf.create("深度学习").tokens() == \
            ["深度", "度学", "学习"]
        # single CJK char stands alone
        assert tf.create("学").tokens() == ["学"]
        # mixed latin + CJK inside one whitespace chunk splits by script
        toks = tf.create("TPU深度 learning").tokens()
        assert toks == ["TPU", "深度", "learning"]
        # hangul + hiragana ranges covered
        assert tf.create("한국어").tokens() == \
            ["한국", "국어"]
        assert tf.create("ひらがな").tokens() == \
            ["ひら", "らが", "がな"]
        # iteration mark joins its run; halfwidth katakana and Ext-B
        # supplementary-plane ideographs are segmented too
        assert tf.create("人々の時々").tokens() == \
            ["人々", "々の", "の時", "時々"]
        assert tf.create("ｶﾀｶﾅ").tokens() == ["ｶﾀ", "ﾀｶ", "ｶﾅ"]
        assert "𠮷野" in tf.create("𠮷野家").tokens()
        # ideographic punctuation is a boundary, never a token
        assert tf.create("深度学习。音乐！").tokens() == \
            ["深度", "度学", "学习", "音乐"]

    def test_word2vec_trains_on_cjk_corpus(self):
        """The factory plugs into the SPI end-to-end: embeddings learn
        co-occurrence structure from an unspaced CJK corpus."""
        from deeplearning4j_tpu.nlp import CjkTokenizerFactory

        rs = np.random.RandomState(0)
        # two "topics" of CJK characters; sentences are unspaced runs
        a = "深度学习模型"   # topic A chars
        b = "音乐歌曲舞蹈"   # topic B chars
        sents = []
        for _ in range(300):
            src = a if rs.rand() < 0.5 else b
            sents.append("".join(src[rs.randint(len(src))]
                                 for _ in range(8)))
        w2v = Word2Vec(layer_size=24, window=3, min_word_frequency=2,
                       negative=5, use_hierarchic_softmax=False, epochs=4,
                       seed=5, tokenizer_factory=CjkTokenizerFactory())
        w2v.fit(CollectionSentenceIterator(sents))
        # bigrams from the same topic must be closer than cross-topic
        va, vb = a[:2], a[2:4]
        vc = b[:2]
        assert w2v.similarity(va, vb) > w2v.similarity(va, vc)
