"""graftcheck tests: the CI gate, the baseline contract, fixture-driven
positive/negative coverage per rule family, the seeded lock-order cycle,
and the runtime OrderedLock instrumentation."""
import json
import os

import pytest

import deeplearning4j_tpu
from deeplearning4j_tpu.analysis import Baseline, analyze, run_check
from deeplearning4j_tpu.analysis import instrument
from deeplearning4j_tpu.analysis.instrument import (LockOrderViolation,
                                                    OrderedCondition,
                                                    OrderedLock)

pytestmark = pytest.mark.analysis

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")
PKG = os.path.dirname(os.path.abspath(deeplearning4j_tpu.__file__))


def _scan(*names, baseline=None):
    files = [os.path.join(FIXTURES, n) for n in names]
    return analyze(root=FIXTURES, files=files, baseline=baseline)


# ---------------------------------------------------------------------------
# the gate: the shipped tree must analyze clean against the audited baseline
# ---------------------------------------------------------------------------

def test_gate_zero_unbaselined_findings():
    rep = run_check()
    assert rep.parse_errors == []
    assert [f.render() for f in rep.unbaselined] == []
    assert rep.stale_baseline == []
    assert rep.files_scanned > 100  # the whole package was actually walked


def test_server_stats_lock_discipline_is_clean():
    # satellite: after the _stats_lock fix, KerasBackendServer has ZERO
    # mixed-access attributes
    rep = analyze(root=PKG,
                  files=[os.path.join(PKG, "modelimport", "server.py")])
    mixed = [f for f in rep.findings
             if f.rule == "conc-mixed-lock" and f.scope == "KerasBackendServer"]
    assert mixed == []


# ---------------------------------------------------------------------------
# baseline contract
# ---------------------------------------------------------------------------

def test_baseline_entry_requires_justification(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps(
        {"entries": [{"key": "r::p::s::d", "justification": "   "}]}),
        encoding="utf-8")
    with pytest.raises(ValueError, match="justification"):
        Baseline.load(str(p))


def test_baseline_entry_requires_key(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"entries": [{"justification": "why"}]}),
                 encoding="utf-8")
    with pytest.raises(ValueError, match="key"):
        Baseline.load(str(p))


def test_stale_baseline_entries_are_reported():
    bl = Baseline(entries={"no-such-rule::x.py::S::d": "obsolete"})
    rep = _scan("conc_neg.py", baseline=bl)
    assert rep.stale_baseline == ["no-such-rule::x.py::S::d"]


def test_baseline_splits_findings():
    rep = _scan("conc_pos.py")
    key = next(f.key for f in rep.findings if f.rule == "conc-mixed-lock")
    rep2 = _scan("conc_pos.py", baseline=Baseline(entries={key: "audited"}))
    assert [f.key for f in rep2.baselined] == [key]
    assert key not in {f.key for f in rep2.unbaselined}
    assert len(rep2.unbaselined) == len(rep.findings) - 1


# ---------------------------------------------------------------------------
# JAX rule family: positives (must flag) and negatives (must not)
# ---------------------------------------------------------------------------

def test_jax_rules_positives():
    rep = _scan("jax_pos.py")
    got = {(f.rule, f.detail) for f in rep.findings}
    # retrace hazards: if / while / range on traced values
    assert ("jax-retrace-hazard", "retrace_if:if:threshold") in got
    assert ("jax-retrace-hazard", "retrace_while:while:n") in got
    assert ("jax-retrace-hazard", "retrace_range:range:n") in got
    # helper-seam hazard: accelerated-vs-stock backend chosen on a
    # traced value (the PagedAttentionHelper anti-pattern)
    assert ("jax-retrace-hazard",
            "helper_switch_on_traced:if:occupancy") in got
    # randomness baked in at trace time
    assert ("jax-untraced-randomness", "baked_noise:np.random.normal") in got
    assert ("jax-untraced-randomness", "baked_choice:random.random") in got
    # closure capture that varies per call
    assert ("jax-varying-capture", "step:scale") in got
    # donated buffer read after the donating dispatch
    assert ("jax-donation-misuse", "donation_read_after:buf") in got
    # per-iteration host syncs in a hot-loop function
    sync = {d for (r, d) in got if r == "jax-host-sync-in-hot-loop"}
    assert {"_decode_once:.item():1", "_decode_once:float():1",
            "_decode_once:np.asarray:1"} <= sync


def test_jax_rules_negatives():
    # includes the known-tricky negative: a Python `if` on a CLOSURE
    # CONFIG value inside a jitted fn (make_step) must NOT flag
    rep = _scan("jax_neg.py")
    assert [f.render() for f in rep.findings] == []


# ---------------------------------------------------------------------------
# concurrency rule family: positives and negatives
# ---------------------------------------------------------------------------

def test_concurrency_rules_positives():
    rep = _scan("conc_pos.py")
    mixed = {f.detail for f in rep.findings if f.rule == "conc-mixed-lock"}
    assert mixed == {"_count", "_state", "_items"}

    blocking = {f.detail for f in rep.findings
                if f.rule == "conc-lock-blocking-call"}
    assert blocking == {"wait_result:.result()",
                        "pull:.get() on queue `work_q`",
                        "cross_wait:.wait() on `other_cv`",
                        "nap:time.sleep()"}

    mono = {f.detail for f in rep.findings if f.rule == "monotonic-deadline"}
    assert mono == {"expired:time.time()", "wall_loop:time.time()",
                    "wall_assigned:t0"}


def test_concurrency_rules_negatives():
    # always-locked attrs, init-only reads, entry-lock propagation into a
    # private method, str.join / dict.get under lock, wait on the HELD
    # condition, plain wall-timestamp store: all clean
    rep = _scan("conc_neg.py")
    assert [f.render() for f in rep.findings] == []


def test_loop_ownership_positives():
    rep = _scan("loop_pos.py")
    owned = {f.detail for f in rep.findings
             if f.rule == "conc-loop-ownership"}
    assert owned == {"adopt:_slots", "reset:_round", "_bump:_round"}
    # the declaration exempts the attrs from conc-mixed-lock — the
    # ownership rule replaces it, never stacks on top of it
    assert not any(f.rule == "conc-mixed-lock" for f in rep.findings)


def test_loop_ownership_negatives():
    rep = _scan("loop_neg.py")
    assert [f.render() for f in rep.findings] == []


def test_baseline_only_shrinks():
    # ratchet: the audited debt ceiling is 3 entries (the deliberate
    # jax-host-sync fetches). New findings must be FIXED, not baselined;
    # lowering this number is the only allowed edit.
    from deeplearning4j_tpu.analysis.core import DEFAULT_BASELINE
    bl = Baseline.load(DEFAULT_BASELINE)
    assert len(bl.entries) <= 3
    assert all(k.startswith("jax-host-sync-in-hot-loop::")
               for k in bl.entries)


def test_seeded_lock_cycle_names_both_sites():
    # acceptance criterion: a deliberate broker<->generation lock-order
    # cycle fails loudly, naming BOTH acquisition sites
    rep = _scan("cycle_seed.py")
    cycles = [f for f in rep.findings if f.rule == "conc-lock-cycle"]
    assert len(cycles) == 1
    msg = cycles[0].message
    assert "StreamingBroker._lock" in msg
    assert "GenerationServer._cond" in msg
    import re
    sites = re.findall(r"acquired at (analysis/cycle_seed\.py:\d+)", msg)
    assert len(sites) == 2 and sites[0] != sites[1]


# ---------------------------------------------------------------------------
# runtime half: OrderedLock / OrderedCondition
# ---------------------------------------------------------------------------

def test_ordered_lock_ascending_order_ok():
    a, b = OrderedLock(10, "a"), OrderedLock(20, "b")
    with a:
        with b:
            assert b.locked()
    with b:  # stack fully unwound between uses
        pass


def test_ordered_lock_out_of_order_raises():
    a, b = OrderedLock(10, "a"), OrderedLock(20, "b")
    with b:
        with pytest.raises(LockOrderViolation, match="rank"):
            with a:
                pass
    with a:  # failed acquire left the rank stack clean
        pass


def test_ordered_condition_wait_releases_rank():
    cv, low = OrderedCondition(30, "cv"), OrderedLock(10, "low")
    ran = []

    def pred():
        # during wait_for the cv rank is popped, so a LOWER-ranked lock
        # is acquirable from the predicate without a violation
        with low:
            ran.append(1)
        return True

    with cv:
        assert cv.wait_for(pred, timeout=1.0)
        cv.notify_all()
    assert ran == [1]
    with cv:  # rank restored after the wait: low now violates again
        with pytest.raises(LockOrderViolation):
            with low:
                pass


def test_instrument_install_uninstall():
    from deeplearning4j_tpu.parallel.resilience import CircuitBreaker
    instrument.install()
    instrument.install()  # idempotent
    try:
        cb = CircuitBreaker()
        assert isinstance(cb._lock, OrderedLock)
        with cb._lock:
            pass
    finally:
        instrument.uninstall()
    cb2 = CircuitBreaker()
    assert not isinstance(cb2._lock, OrderedLock)
