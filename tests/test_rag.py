"""Retrieval-augmented serving tests (parallel/rag.py + the /rag route).

The contracts under test:

* ``assemble_passage_prefix`` is canonical — retrieval order, duplicate
  hits and IVF pad slots never change the assembled byte stream, and
  every passage lands chunk-aligned so page digests collide exactly
  when content does;
* the two-tier ``RagPipeline`` is BIT-exact vs the single-server
  non-RAG reference given the same assembled prompt (greedy AND
  sampled — the retrieval tier must add zero numerical surface);
* hot documents dedupe prefill through the prefix cache
  (``prefix_hits``/``prefix_tokens_reused`` climb, the document-cache
  headline) and the rag ledger balances with zero lost futures;
* query churn and occupancy churn add ZERO compiled programs on either
  tier after warmup (knn program cache + generation output cache);
* one deadline crosses the tier boundary: an exhausted budget fails
  typed ``DeadlineExceeded``, never a hang, and the pipeline serves on;
* caller errors raise typed ValueError synchronously; admission sheds
  ``ServerOverloaded``; close is idempotent and drains clean;
* the /rag HTTP route returns tokens + retrieval metadata and the
  one-scrape /metrics carries both tiers' registries under tier labels.

The fleet-building drills are ALSO marked slow (tier-1 runs within ~2%
of its own timeout cap — run them with ``-m rag``); the pure-function
assembly/validation tests stay in tier-1.
"""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import (TransformerLM, greedy_generate,
                                           sample_generate)
from deeplearning4j_tpu.nearestneighbors.index import EmbeddingIndex
from deeplearning4j_tpu.parallel.generation import (GenerationServer,
                                                    assemble_passage_prefix)
from deeplearning4j_tpu.parallel.rag import RagPipeline
from deeplearning4j_tpu.parallel.resilience import (DeadlineExceeded,
                                                    ServerOverloaded)

pytestmark = pytest.mark.rag

V = 17
D = 8
NDOCS = 64
PS = 4  # page size on BOTH tiers — the chunk-alignment contract


def _corpus(seed=0):
    """Well-separated doc vectors + variable-length passages (3..10
    tokens, so chunk padding actually pads)."""
    rs = np.random.RandomState(seed)
    vecs = rs.randn(NDOCS, D).astype(np.float32) * 4.0
    passages = [rs.randint(1, V, size=rs.randint(3, 11)).astype(np.int64)
                for _ in range(NDOCS)]
    return vecs, passages


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(num_labels=V, max_length=64, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


@pytest.fixture(scope="module")
def corpus():
    return _corpus()


@pytest.fixture(scope="module")
def rag(lm, corpus):
    """ONE shared two-tier pipeline (exact f32 knn tier — no training;
    paged generate tier) for the whole module: the fleet build and the
    prefill/decode compiles are paid once."""
    vecs, passages = corpus
    indexes = []

    def knn_factory(rid):
        idx = EmbeddingIndex(vecs)
        indexes.append(idx)
        return idx

    pipe = RagPipeline(
        knn_factory,
        lambda rid: GenerationServer(lm, V, slots=4, page_size=PS),
        passages, page_size=PS, k=2)
    pipe._test_indexes = indexes  # reach the knn replicas' program cache
    yield pipe
    pipe.close()


# ---------------------------------------------------------------------------
# canonical prefix assembly — pure function, tier-1
# ---------------------------------------------------------------------------

class TestAssemblePassagePrefix:
    def test_canonical_under_order_dups_and_padding(self):
        _vecs, passages = _corpus(1)
        q = np.array([1, 2, 3], np.int64)
        base, order, plen = assemble_passage_prefix(
            [7, 3, 11], passages, page_size=PS, query_ids=q)
        # retrieval-score order, duplicate hits, IVF -1 pad slots: the
        # assembled stream must not move a byte
        for ids in ([11, 7, 3], [3, 3, 7, 11, 11], [7, -1, 3, -1, 11]):
            prompt, o, n = assemble_passage_prefix(
                ids, passages, page_size=PS, query_ids=q)
            np.testing.assert_array_equal(prompt, base)
            assert o == order == [3, 7, 11] and n == plen
        # chunk alignment: every passage starts on a page boundary and
        # is padded to a page multiple; the query rides unpadded
        off = 0
        for d in order:
            p = passages[d]
            np.testing.assert_array_equal(base[off:off + p.size], p)
            off += p.size + (-p.size % PS)
        assert off == plen and plen % PS == 0
        np.testing.assert_array_equal(base[plen:], q)

    def test_empty_retrieval_and_validation(self):
        _vecs, passages = _corpus(1)
        q = np.array([4, 5], np.int64)
        prompt, order, plen = assemble_passage_prefix(
            [-1, -1], passages, page_size=PS, query_ids=q)
        np.testing.assert_array_equal(prompt, q)
        assert order == [] and plen == 0
        with pytest.raises(ValueError, match="page_size"):
            assemble_passage_prefix([0], passages, page_size=0)

    def test_pipeline_ctor_validation_precedes_fleet(self):
        def boom(_rid):
            raise AssertionError("factory ran before validation")

        for kw in ({"k": 0}, {"page_size": 0}, {"knn_replicas": 0},
                   {"generate_replicas": 0}):
            with pytest.raises(ValueError):
                RagPipeline(boom, boom, [], **kw)


# ---------------------------------------------------------------------------
# two-tier pipeline — fleet-building drills (slow; run with -m rag)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestRagPipeline:
    def test_greedy_bit_exact_vs_non_rag_reference(self, rag, lm, corpus):
        """The bit-exactness contract: given the same assembled prompt,
        the two-tier flow returns exactly what the single-server
        non-RAG path generates."""
        vecs, passages = corpus
        rs = np.random.RandomState(7)
        prompt = rs.randint(1, V, 5)
        fut = rag.submit(prompt, 6, query_vec=vecs[5] + 0.01)
        out = fut.result(timeout=120)
        assert 5 in fut._rag_docs
        assert fut._rag_docs == sorted(set(fut._rag_docs))
        assert fut._rag_prefix_len % PS == 0
        # the riding prompt is the canonical assembly of the docs
        ref_prompt, _o, plen = assemble_passage_prefix(
            fut._rag_docs, passages, page_size=PS, query_ids=prompt)
        np.testing.assert_array_equal(fut._rag_prompt, ref_prompt)
        assert plen == fut._rag_prefix_len
        ref = greedy_generate(lm, fut._rag_prompt[None], 6, V)[0]
        np.testing.assert_array_equal(out, ref)

    def test_sampled_bit_exact_vs_non_rag_reference(self, rag, lm, corpus):
        vecs, _passages = corpus
        rs = np.random.RandomState(8)
        prompt = rs.randint(1, V, 4)
        fut = rag.submit(prompt, 5, query_vec=vecs[9] - 0.01,
                         temperature=0.8, top_k=5, seed=11)
        out = fut.result(timeout=120)
        ref = sample_generate(lm, fut._rag_prompt[None], 5, V,
                              temperature=0.8, top_k=5, seed=11)[0]
        np.testing.assert_array_equal(out, ref)

    def test_hot_documents_dedupe_prefill(self, rag, corpus):
        """Concurrent requests retrieving the SAME documents share
        prefix pages: the document-cache counters climb and the rag
        ledger balances with zero lost futures."""
        vecs, _passages = corpus
        rs = np.random.RandomState(9)
        before = rag.stats()
        futs = [rag.submit(rs.randint(1, V, 5), 4, query_vec=vecs[21])
                for _ in range(6)]
        outs = [f.result(timeout=120) for f in futs]
        docs = futs[0]._rag_docs
        assert all(f._rag_docs == docs for f in futs)
        for o in outs:
            assert o.shape == (4,)
        st = rag.stats()
        assert st["prefix_hits"] > before["prefix_hits"]
        assert st["prefix_tokens_reused"] > before["prefix_tokens_reused"]
        assert st["inflight"] == 0
        assert st["submitted"] == (st["completed"] + st["failed"]
                                   + st["expired"] + st["rejected"])

    def test_zero_retrace_under_query_and_occupancy_churn(
            self, rag, lm, corpus):
        """After warming each document set once, query churn (different
        retrieved docs), occupancy churn (concurrent mixed admits) and
        sampling-parameter churn add ZERO compiled programs on EITHER
        tier — knn program cache and generation output cache both."""
        vecs, _passages = corpus
        rs = np.random.RandomState(10)
        hot = [31, 32, 33, 34]
        for d in hot:  # warm every bucket these doc sets produce
            rag.submit(rs.randint(1, V, 5), 3,
                       query_vec=vecs[d]).result(timeout=120)
        # one repeat so the prefix-share/COW page-copy path is compiled
        rag.submit(rs.randint(1, V, 5), 3,
                   query_vec=vecs[hot[0]]).result(timeout=120)
        knn_warm = sum(i.stats()["programs"] for i in rag._test_indexes)
        gen_warm = len(lm._output_cache)
        futs = [rag.submit(rs.randint(1, V, 5), 3,
                           query_vec=vecs[hot[i % len(hot)]] + 0.01,
                           temperature=0.5 * (i % 2), top_k=4 * (i % 2),
                           seed=i)
                for i in range(8)]
        for f in futs:
            assert f.result(timeout=120).shape == (3,)
        assert sum(i.stats()["programs"]
                   for i in rag._test_indexes) == knn_warm
        assert len(lm._output_cache) == gen_warm

    def test_deadline_propagates_across_tiers_typed(self, rag, corpus):
        """One budget armed at submit covers BOTH tiers: a 1 ms budget
        dies inside the pipeline (knn coalescing window alone is 2 ms)
        and fails typed DeadlineExceeded — then the pipeline serves the
        next request untouched."""
        vecs, _passages = corpus
        before = rag.stats()["expired"]
        prompt = np.array([1, 2, 3, 4, 5], np.int64)
        f = rag.submit(prompt, 3, query_vec=vecs[40], deadline_s=0.001)
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=120)
        assert rag.stats()["expired"] == before + 1
        ok = rag.submit(prompt, 3, query_vec=vecs[40])
        assert ok.result(timeout=120).shape == (3,)

    def test_caller_errors_typed_synchronously(self, rag, corpus):
        vecs, _passages = corpus
        good = np.array([1, 2, 3], np.int64)
        with pytest.raises(ValueError, match="non-empty"):
            rag.submit([], 3, query_vec=vecs[0])
        with pytest.raises(ValueError, match="max_tokens"):
            rag.submit(good, 0, query_vec=vecs[0])
        with pytest.raises(ValueError, match="k must be"):
            rag.submit(good, 3, query_vec=vecs[0], k=0)
        with pytest.raises(ValueError, match="deadline_s"):
            rag.submit(good, 3, query_vec=vecs[0], deadline_s=0.0)
        with pytest.raises(ValueError, match="encoder"):
            rag.submit(good, 3)  # no query_vec and no encoder attached

    def test_admission_watermark_sheds_typed(self, rag, corpus):
        """At the watermark the submit itself raises ServerOverloaded
        BEFORE entering the ledger — nothing to lose, nothing leaks."""
        vecs, _passages = corpus
        before = rag.stats()
        free = rag.admission.max_pending - rag.admission.pending
        for _ in range(free):
            rag.admission.acquire()
        try:
            with pytest.raises(ServerOverloaded):
                rag.submit(np.array([1, 2], np.int64), 3,
                           query_vec=vecs[0])
        finally:
            for _ in range(free):
                rag.admission.release()
        st = rag.stats()
        assert st["submitted"] == before["submitted"]
        assert st["rejected"] == before["rejected"]
        f = rag.submit(np.array([1, 2], np.int64), 3, query_vec=vecs[0])
        assert f.result(timeout=120).shape == (3,)

    def test_metrics_sources_carry_tier_labels(self, rag):
        labels = [lbl for lbl, _reg in rag.metrics_sources()]
        assert labels == [{}, {}, {"tier": "knn"}, {"tier": "generate"}]

    def test_tier_stats_and_slot_lever(self, rag):
        """Both tiers expose the autoscaler observation surface and the
        capacity lever through the pipeline."""
        for role in ("knn", "generate"):
            st = rag.tier_stats(role)
            assert st["replicas"] == 1 and st["slots"] > 0
        cap = rag.tier_stats("generate")["slots"]
        assert rag.set_tier_active_slots("generate", 1) == 1
        try:
            assert rag.tier_stats("generate")["active_slots"] <= 1
        finally:
            rag.set_tier_active_slots("generate", cap)

    def test_close_idempotent_and_submit_after_close(self, lm, corpus):
        vecs, passages = corpus
        pipe = RagPipeline(
            lambda rid: EmbeddingIndex(vecs),
            lambda rid: GenerationServer(lm, V, slots=2, page_size=PS),
            passages, page_size=PS, k=2)
        f = pipe.submit(np.array([1, 2, 3], np.int64), 3,
                        query_vec=vecs[3])
        pipe.close()
        pipe.close()  # idempotent
        assert f.done()  # drained, not abandoned
        with pytest.raises(RuntimeError, match="closed"):
            pipe.submit(np.array([1], np.int64), 2, query_vec=vecs[0])


# ---------------------------------------------------------------------------
# /rag HTTP route (slow; run with -m rag)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestRagRoute:
    def test_rag_route_end_to_end(self, lm, corpus):
        from deeplearning4j_tpu.modelimport.server import KerasBackendServer

        vecs, passages = corpus
        srv = KerasBackendServer()
        mid = srv.attach_rag(lm, vocab=V, passages=passages,
                             doc_vectors=vecs, k=2, slots=2,
                             page_size=PS, mid="rag0")
        port = srv.start()
        base = f"http://127.0.0.1:{port}"
        try:
            body = json.dumps({
                "model": mid, "prompt_ids": [1, 2, 3], "max_tokens": 4,
                "query_vec": [float(x) for x in vecs[12]],
            }).encode()
            req = urllib.request.Request(
                base + "/rag", body,
                {"Content-Type": "application/json"})
            out = json.loads(urllib.request.urlopen(req).read())
            assert len(out["tokens"]) == 4
            assert 12 in out["docs"]
            assert out["prefix_len"] % PS == 0

            text = urllib.request.urlopen(base + "/metrics").read().decode()
            # one exposition pass: the rag ledger, the knn tier and the
            # generate tier all present, tier-labeled
            assert 'rag_completed_total{model="rag0"} 1' in text
            assert 'rag_ttft_ms_count{model="rag0"} 1' in text
            assert 'rag_e2e_ms_count{model="rag0"} 1' in text
            assert f'knn_points{{model="rag0",tier="knn"}} {NDOCS}' in text
            assert 'knn_recall{model="rag0",tier="knn"}' in text
            assert 'generation_slots{model="rag0",tier="generate"} 2' \
                in text

            stats = json.loads(
                urllib.request.urlopen(base + "/stats").read())
            assert stats["rag"][mid]["completed"] == 1
        finally:
            srv.stop()
