"""Early stopping tests (ports the intent of
deeplearning4j-core/src/test/.../earlystopping/TestEarlyStopping.java)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.config import TerminationReason
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam, Sgd


def _net(lr=0.01, updater=None):
    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(updater or Adam(learning_rate=lr))
            .list(DenseLayer(n_out=16, activation="relu"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _iris_like_iterator(n=60, batch=20, seed=0):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 3, n)
    x = (rs.randn(n, 4) + labels[:, None] * 2.0).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[labels]
    ds = DataSet(x, y)
    return ListDataSetIterator(list(ds.batch_by(batch)), batch_size=batch)


class TestEarlyStopping:
    def test_max_epochs_termination(self):
        net = _net()
        it = _iris_like_iterator()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(5)],
            score_calculator=DataSetLossCalculator(_iris_like_iterator(seed=1)),
            model_saver=InMemoryModelSaver())
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.termination_reason == \
            TerminationReason.EPOCH_TERMINATION_CONDITION
        assert result.total_epochs == 5
        assert result.best_model is not None
        assert len(result.score_vs_epoch) == 5
        # best model's score matches the recorded best
        best = result.best_model
        calc = DataSetLossCalculator(_iris_like_iterator(seed=1))
        assert calc.calculate_score(best) == pytest.approx(
            result.best_model_score, rel=1e-5)

    def test_score_improvement_termination(self):
        """Training with LR=0 can't improve -> stops after patience runs out."""
        net = _net(updater=Sgd(learning_rate=0.0))
        it = _iris_like_iterator()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[
                ScoreImprovementEpochTerminationCondition(2),
                MaxEpochsTerminationCondition(50)],
            score_calculator=DataSetLossCalculator(_iris_like_iterator(seed=1)))
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.termination_reason == \
            TerminationReason.EPOCH_TERMINATION_CONDITION
        assert "ScoreImprovement" in result.termination_details
        assert result.total_epochs <= 5

    def test_max_score_iteration_termination(self):
        """Huge LR diverges -> MaxScoreIterationTerminationCondition fires."""
        net = _net(updater=Sgd(learning_rate=1e4))
        it = _iris_like_iterator()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(100)],
            iteration_termination_conditions=[
                MaxScoreIterationTerminationCondition(50.0),
                InvalidScoreIterationTerminationCondition()],
            score_calculator=DataSetLossCalculator(_iris_like_iterator(seed=1)))
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.termination_reason == \
            TerminationReason.ITERATION_TERMINATION_CONDITION

    def test_max_time_termination(self):
        net = _net()
        it = _iris_like_iterator()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(10000)],
            iteration_termination_conditions=[
                MaxTimeIterationTerminationCondition(0.0)],
            score_calculator=DataSetLossCalculator(_iris_like_iterator(seed=1)))
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.termination_reason == \
            TerminationReason.ITERATION_TERMINATION_CONDITION
        assert "MaxTime" in result.termination_details

    def test_max_time_ignores_wall_clock_jump(self, monkeypatch):
        """NTP step / VM migration regression: the time budget is
        measured on the monotonic clock, so a wall-clock jump must not
        fire termination early."""
        import time as _time

        cond = MaxTimeIterationTerminationCondition(3600.0)
        cond.initialize()
        real_time = _time.time
        # wall clock steps 2h forward — budget is 1h, but ~0 monotonic
        # seconds have elapsed
        monkeypatch.setattr(_time, "time", lambda: real_time() + 7200.0)
        assert not cond.terminate(0.0)
        # a genuinely exhausted budget still fires
        tiny = MaxTimeIterationTerminationCondition(0.0)
        tiny.initialize()
        assert tiny.terminate(0.0)

    def test_local_file_saver_roundtrip(self, tmp_path):
        net = _net()
        it = _iris_like_iterator()
        saver = LocalFileModelSaver(str(tmp_path))
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
            score_calculator=DataSetLossCalculator(_iris_like_iterator(seed=1)),
            model_saver=saver, save_last_model=True)
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert (tmp_path / "bestModel.bin").exists()
        assert (tmp_path / "latestModel.bin").exists()
        best = saver.get_best_model()
        x = np.random.RandomState(5).randn(4, 4).astype(np.float32)
        assert np.asarray(best.output(x)).shape == (4, 3)
        assert result.best_model_epoch >= 0
        assert result.best_model_epoch in result.score_vs_epoch

    def test_early_stopping_graph(self):
        """Same loop drives a ComputationGraph (reference:
        EarlyStoppingGraphTrainer)."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = (NeuralNetConfiguration.builder()
                .seed(1).updater(Adam(learning_rate=0.01))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_out=16, activation="relu"), "in")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "d")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        net = ComputationGraph(conf).init()
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
            score_calculator=DataSetLossCalculator(_iris_like_iterator(seed=1)))
        result = EarlyStoppingTrainer(cfg, net, _iris_like_iterator()).fit()
        assert result.total_epochs == 3
        assert result.best_model is not None
