"""Prefetch iterator tests: host-side async production
(AsyncDataSetIterator, reference datasets/iterator/AsyncDataSetIterator.java)
and device-transfer overlap (DevicePrefetchIterator, the flax
prefetch_to_device pattern over the DataSetIterator contract)."""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    AsyncDataSetIterator,
    DataSetIterator,
    DevicePrefetchIterator,
    ListDataSetIterator,
)


def _data(n=20, batch=8):
    rs = np.random.RandomState(0)
    return ListDataSetIterator(
        DataSet(rs.randn(n, 4).astype(np.float32),
                np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]),
        batch_size=batch)


class _Counting(DataSetIterator):
    """Wraps a base iterator, counting how many batches it has produced."""

    def __init__(self, base):
        self.base = base
        self.produced = 0

    def reset(self):
        self.base.reset()

    def _iterate(self):
        for ds in self.base._iterate():
            self.produced += 1
            yield ds


class TestAsyncDataSetIterator:
    def test_same_batches_as_base(self):
        base = list(_data())
        async_it = list(AsyncDataSetIterator(_data(), queue_size=2))
        assert len(async_it) == len(base)
        for a, b in zip(async_it, base):
            np.testing.assert_array_equal(np.asarray(a.features),
                                          np.asarray(b.features))

    def test_producer_exception_surfaces(self):
        class Boom(DataSetIterator):
            def _iterate(self):
                yield next(iter(_data()))
                raise RuntimeError("producer died")

        with pytest.raises(RuntimeError, match="producer died"):
            list(AsyncDataSetIterator(Boom()))


class TestDevicePrefetchIterator:
    def test_values_equal_and_on_device(self):
        base = list(_data())
        pre = list(DevicePrefetchIterator(_data(), depth=2))
        assert len(pre) == len(base)
        for a, b in zip(pre, base):
            assert isinstance(a.features, jax.Array)
            assert isinstance(a.labels, jax.Array)
            np.testing.assert_array_equal(np.asarray(a.features),
                                          np.asarray(b.features))
            np.testing.assert_array_equal(np.asarray(a.labels),
                                          np.asarray(b.labels))

    def test_transfers_run_ahead_of_consumption(self):
        counting = _Counting(_data(n=40, batch=8))  # 5 batches
        it = iter(DevicePrefetchIterator(counting, depth=3))
        next(it)
        # after ONE consumed batch, depth=3 lookahead has already pulled
        # (and device_put) batches 1..4 from the base stream
        assert counting.produced == 4

    def test_sharded_placement_on_mesh(self):
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("data",))
        sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data"))
        pre = DevicePrefetchIterator(_data(n=16, batch=8), depth=2,
                                     sharding=sh)
        for ds in pre:
            assert ds.features.sharding == sh
            assert len(ds.features.sharding.device_set) == 4

    def test_masks_and_none_labels_pass_through(self):
        rs = np.random.RandomState(1)
        ds = DataSet(rs.randn(4, 3, 2).astype(np.float32),
                     rs.randn(4, 3, 2).astype(np.float32),
                     features_mask=np.ones((4, 3), np.float32))
        out = list(DevicePrefetchIterator([ds], depth=1))[0]
        assert isinstance(out.features_mask, jax.Array)
        assert out.labels_mask is None

    def test_trains_a_network(self):
        from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers.core import (DenseLayer,
                                                            OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updater import Adam

        conf = (NeuralNetConfiguration.builder().seed(1)
                .updater(Adam(learning_rate=0.01))
                .list(DenseLayer(n_out=8, activation="relu"),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        s0 = net.score(next(iter(_data())))
        net.fit(DevicePrefetchIterator(_data(), depth=2), epochs=5)
        assert net.score(next(iter(_data()))) < s0

    def test_partial_batch_with_sharding_raises_clearly(self):
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("data",))
        sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data"))
        # 20 examples / batch 8 -> trailing batch of 4 < mesh size 4? no,
        # 4 divides; use 18 -> trailing 2, indivisible by 4
        it = DevicePrefetchIterator(_data(n=18, batch=8), depth=2,
                                    sharding=sh)
        with pytest.raises(ValueError, match="trailing partial batch"):
            list(it)
