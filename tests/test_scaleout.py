"""Scale-out facade tests: Spark-equivalent training master, parameter
server, early stopping on the mesh (ports the intent of
TestCompareParameterAveragingSparkVsSingleMachine, SparkDl4jMultiLayerTest,
ParameterServerParallelWrapperTest, TestParallelEarlyStopping)."""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    MaxEpochsTerminationCondition,
)
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Sgd
from deeplearning4j_tpu.parallel import (
    EarlyStoppingParallelTrainer,
    ParameterAveragingTrainingMaster,
    ParameterServer,
    ParameterServerClient,
    ParameterServerParallelWrapper,
    SparkDl4jMultiLayer,
)


def _net(seed=12345, lr=0.1, dtype="float64"):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(learning_rate=lr)).dtype(dtype)
            .list(DenseLayer(n_out=10, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(n_batches=16, batch=4, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        labels = rs.randint(0, 3, batch)
        x = (rs.randn(batch, 4) + labels[:, None]).astype(np.float64)
        out.append(DataSet(x, np.eye(3)[labels]))
    return out


class TestSparkFacade:
    def test_repartition_balances_ragged_batches(self):
        """repartitionBalanceIfRequired semantics: ragged input re-splits
        into uniform minibatches; uniform input is left alone."""
        from deeplearning4j_tpu.parallel.spark import (
            REPARTITION_NEVER, repartition_datasets)

        rs = np.random.RandomState(1)
        ragged = [DataSet(rs.randn(n, 4).astype(np.float32),
                          np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)])
                  for n in (7, 3, 9, 5)]
        out = repartition_datasets(ragged, batch_size=6)
        assert [d.features.shape[0] for d in out] == [6, 6, 6, 6]
        # examples preserved in order
        np.testing.assert_array_equal(
            np.concatenate([d.features for d in out]),
            np.concatenate([d.features for d in ragged]))
        # uniform input untouched (identity), never-strategy untouched
        uniform = _batches(4, 4)
        assert repartition_datasets(uniform, 6) is not uniform  # new list
        assert [d.features.shape[0]
                for d in repartition_datasets(uniform, 6)] == [4, 4, 4, 4]
        assert [d.features.shape[0]
                for d in repartition_datasets(ragged, 6,
                                              REPARTITION_NEVER)] == \
            [7, 3, 9, 5]

    def test_ragged_batches_train_without_drops(self):
        """End-to-end: ragged input through the facade trains every example
        (previously the wrapper dropped mid-stream size mismatches)."""
        rs = np.random.RandomState(2)
        ragged = [DataSet((rs.randn(n, 4) + 1).astype(np.float64),
                          np.eye(3)[rs.randint(0, 3, n)])
                  for n in (13, 7, 9, 3)]  # 32 examples
        net = _net()
        master = ParameterAveragingTrainingMaster(batch_size_per_worker=4,
                                                  workers=8)
        SparkDl4jMultiLayer(net, master).fit(ragged)
        assert net.iteration > 0

    def test_aggregation_depth_warns(self):
        import warnings as _warnings
        with _warnings.catch_warnings(record=True) as w:
            _warnings.simplefilter("always")
            ParameterAveragingTrainingMaster(aggregation_depth=4, workers=8)
        assert any("aggregation_depth" in str(x.message) for x in w)

    def test_param_averaging_equals_single_machine(self):
        """The ported TestCompareParameterAveragingSparkVsSingleMachine
        contract, through the Spark-style facade: averaging_frequency=1 SGD
        training over the mesh == single-device training on the concatenated
        worker batches."""
        batches = _batches(16, 4)

        spark_net = _net()
        master = ParameterAveragingTrainingMaster(averaging_frequency=1,
                                                  workers=8)
        SparkDl4jMultiLayer(spark_net, master).fit(batches)

        single = _net()
        # 8 workers x freq 1 -> rounds of 8 batches concatenated
        for r in range(2):
            group = batches[r * 8:(r + 1) * 8]
            merged = DataSet.merge(group)
            single.do_step(merged.features, merged.labels)

        np.testing.assert_allclose(spark_net.params_flat(),
                                   single.params_flat(), atol=1e-10)

    def test_facade_distributed_evaluate(self):
        net = _net(dtype="float32")
        batches = [DataSet(b.features.astype(np.float32),
                           b.labels.astype(np.float32))
                   for b in _batches(8, 8)]
        master = ParameterAveragingTrainingMaster(workers=8)
        facade = SparkDl4jMultiLayer(net, master)
        # 20 epochs: the trajectory crosses 0.5 around epoch 14 and reaches
        # ~0.6 by 20, so the bar has margin against compile-level rounding
        # shifts in the averaged step (10 epochs sat exactly at the bar).
        facade.fit(batches, epochs=20)
        ev = facade.evaluate(ListDataSetIterator(batches, batch_size=8))
        assert ev.accuracy() > 0.5


class TestParameterServer:
    def test_push_pull_averaging(self):
        ps = ParameterServer(np.zeros(4, np.float32), alpha=0.5)
        c = ParameterServerClient(server=ps)
        c.push(np.ones(4, np.float32))
        assert np.allclose(c.pull(), 0.5)
        c.push(np.ones(4, np.float32))
        assert np.allclose(c.pull(), 0.75)

    def test_http_transport_roundtrip(self):
        ps = ParameterServer(np.arange(6, dtype=np.float32))
        port = ps.serve()
        try:
            c = ParameterServerClient(address=f"http://127.0.0.1:{port}")
            assert np.allclose(c.pull(), np.arange(6))
            c.push(np.arange(6, dtype=np.float32) * 3)
            assert np.allclose(c.pull(), np.arange(6) * 2.0)  # alpha=0.5 avg
        finally:
            ps.stop()

    def test_async_wrapper_trains(self):
        net = _net(dtype="float32", lr=0.05)
        batches = [DataSet(b.features.astype(np.float32),
                           b.labels.astype(np.float32))
                   for b in _batches(12, 8, seed=3)]
        merged = DataSet.merge(batches)
        s0 = net.score(merged)
        wrapper = ParameterServerParallelWrapper(net, workers=3, alpha=0.5)
        wrapper.fit(batches, epochs=6)
        assert net.score(merged) < s0 * 0.8
        assert wrapper.server.pushes == 12 * 6

    def test_compressed_delta_wrapper_converges(self):
        # VERDICT r2 #5: threshold compression wired into a real training
        # path — workers push sparse ±threshold deltas w/ error feedback
        net = _net(dtype="float32", lr=0.05)
        batches = [DataSet(b.features.astype(np.float32),
                           b.labels.astype(np.float32))
                   for b in _batches(12, 8, seed=4)]
        merged = DataSet.merge(batches)
        s0 = net.score(merged)
        # threshold sized so a meaningful fraction of entries stays in the
        # residual each round (error feedback carries them forward)
        wrapper = ParameterServerParallelWrapper(
            net, workers=3, compress=True, threshold=2e-2)
        wrapper.fit(batches, epochs=6)
        assert net.score(merged) < s0 * 0.8, "compressed PS did not converge"
        dens = [d for t in wrapper.trainers for d in t.message_density]
        assert dens, "no compressed pushes recorded"
        assert all(0.0 <= d <= 1.0 for d in dens)
        # the wire message must actually be sparse on average
        assert np.mean(dens) < 0.5, f"messages not sparse: {np.mean(dens)}"

    def test_sparse_delta_http_roundtrip(self):
        ps = ParameterServer(np.zeros(8, np.float32))
        port = ps.serve()
        try:
            c = ParameterServerClient(address=f"http://127.0.0.1:{port}")
            c.push_sparse_delta(np.array([1, 5], np.int32),
                                np.array([True, False]), 0.25, 8)
            got = c.pull()
            expect = np.zeros(8, np.float32)
            expect[1], expect[5] = 0.25, -0.25
            assert np.allclose(got, expect)
        finally:
            ps.stop()

    def test_error_feedback_accumulates_small_deltas(self):
        # deltas below threshold are not lost: the residual carries them
        # until they cross threshold (EncodingHandler error feedback)
        from deeplearning4j_tpu.parallel.parameter_server import (
            ParameterServerTrainer,
        )

        ps = ParameterServer(np.zeros(4, np.float32), alpha=1.0)

        class TinyNet:
            """Deterministic fake: each fit moves params by +2e-4."""
            def __init__(self):
                self.flat = np.zeros(4, np.float32)

            def set_params_flat(self, f):
                self.flat = np.asarray(f, np.float32).copy()

            def params_flat(self):
                return self.flat

            def fit(self, ds):
                self.flat = self.flat + 3e-4

        t = ParameterServerTrainer(TinyNet(), ParameterServerClient(ps),
                                   compress=True, threshold=1e-3)
        for _ in range(3):
            t.fit(None)
        assert np.allclose(ps.pull(), 0.0)        # 9e-4: under threshold
        t.fit(None)                               # 1.2e-3 crosses
        assert np.allclose(ps.pull(), 1e-3)


class TestEarlyStoppingParallel:
    def test_early_stopping_on_mesh(self):
        net = _net(dtype="float32", lr=0.05)
        train = [DataSet(b.features.astype(np.float32),
                         b.labels.astype(np.float32))
                 for b in _batches(16, 4, seed=5)]
        val = ListDataSetIterator(
            [DataSet(b.features.astype(np.float32),
                     b.labels.astype(np.float32))
             for b in _batches(4, 8, seed=6)], batch_size=8)
        cfg = EarlyStoppingConfiguration(
            epoch_termination_conditions=[MaxEpochsTerminationCondition(3)],
            score_calculator=DataSetLossCalculator(val))
        trainer = EarlyStoppingParallelTrainer(
            cfg, net, ListDataSetIterator(train, batch_size=4), workers=8)
        result = trainer.fit()
        assert result.total_epochs == 3
        assert result.best_model is not None
        assert np.isfinite(result.best_model_score)
