"""Every example script must run end-to-end in smoke mode (the
dl4j-examples role: runnable documentation — broken examples are worse
than none)."""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(f for f in os.listdir(os.path.join(REPO, "examples"))
                  if f.endswith(".py"))

# slow: the three heaviest example smokes (~11-20s each); the subsystems
# they demonstrate have dedicated tier-1 modules (test_model_sharding.py/
# test_parallel.py, test_generation.py/test_zoo.py, test_modelimport.py)
# — see the tier-1 duration budget note in conftest.py
_SLOW_EXAMPLES = {"lenet_mesh_dataparallel.py",
                  "transformer_text_generation.py",
                  "keras_residual_import.py"}


@pytest.mark.parametrize(
    "script",
    [pytest.param(s, marks=pytest.mark.slow) if s in _SLOW_EXAMPLES else s
     for s in EXAMPLES])
def test_example_runs(script):
    env = dict(os.environ, EXAMPLES_SMOKE="1")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, (script, r.stderr[-800:])
    # every example prints a progress sentinel — exit code 0 alone cannot
    # catch an example that silently trains zero steps
    m = re.search(r"TRAINED iterations: (\d+)", r.stdout)
    assert m, (script, "missing TRAINED sentinel", r.stdout[-400:])
    assert int(m.group(1)) > 0, (script, "example trained zero steps")
