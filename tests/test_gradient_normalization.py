"""GradientNormalization: the 5 modes vs hand-computed values, plus
end-to-end application inside the jitted train step.

Reference: nn/conf/GradientNormalization.java, applied in
nn/updater/BaseMultiLayerUpdater.java preApply :310-352; reference tests:
gradientcheck + updater tests (TestGradientNormalization.java).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.gradient_normalization import (
    apply_gradient_normalization,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Sgd


def _grads():
    rs = np.random.RandomState(0)
    return {"W": jnp.asarray(rs.randn(4, 3) * 2, jnp.float64),
            "b": jnp.asarray(rs.randn(3) * 5, jnp.float64)}


def _layer(mode, threshold=1.0):
    lyr = DenseLayer(n_out=3, gradient_normalization=mode,
                     gradient_normalization_threshold=threshold)
    return {"0": lyr}


class TestModes:
    def test_renormalize_l2_per_layer(self):
        g = _grads()
        out = apply_gradient_normalization(
            _layer("renormalize_l2_per_layer"), {"0": g})["0"]
        l2 = np.sqrt(sum(np.sum(np.asarray(v) ** 2) for v in g.values()))
        for k in g:
            np.testing.assert_allclose(np.asarray(out[k]),
                                       np.asarray(g[k]) / l2, rtol=1e-12)
        # whole-layer norm is 1 afterwards
        total = np.sqrt(sum(np.sum(np.asarray(v) ** 2)
                            for v in out.values()))
        np.testing.assert_allclose(total, 1.0, rtol=1e-12)

    def test_renormalize_l2_per_param_type(self):
        g = _grads()
        out = apply_gradient_normalization(
            _layer("renormalize_l2_per_param_type"), {"0": g})["0"]
        for k in g:
            l2 = np.linalg.norm(np.asarray(g[k]).ravel())
            np.testing.assert_allclose(np.asarray(out[k]),
                                       np.asarray(g[k]) / l2, rtol=1e-12)
            np.testing.assert_allclose(
                np.linalg.norm(np.asarray(out[k]).ravel()), 1.0, rtol=1e-12)

    def test_clip_element_wise_absolute_value(self):
        g = _grads()
        out = apply_gradient_normalization(
            _layer("clip_element_wise_absolute_value", 0.5), {"0": g})["0"]
        for k in g:
            np.testing.assert_allclose(
                np.asarray(out[k]), np.clip(np.asarray(g[k]), -0.5, 0.5),
                rtol=1e-12)

    def test_clip_l2_per_layer_scales_only_above_threshold(self):
        g = _grads()
        l2 = np.sqrt(sum(np.sum(np.asarray(v) ** 2) for v in g.values()))
        # above threshold: scaled back to exactly threshold
        out = apply_gradient_normalization(
            _layer("clip_l2_per_layer", l2 / 2), {"0": g})["0"]
        for k in g:
            np.testing.assert_allclose(
                np.asarray(out[k]), np.asarray(g[k]) * 0.5, rtol=1e-12)
        # below threshold: untouched
        out2 = apply_gradient_normalization(
            _layer("clip_l2_per_layer", l2 * 2), {"0": g})["0"]
        for k in g:
            np.testing.assert_allclose(np.asarray(out2[k]),
                                       np.asarray(g[k]), rtol=1e-12)

    def test_clip_l2_per_param_type(self):
        g = _grads()
        t = float(np.linalg.norm(np.asarray(g["b"]))) / 2
        out = apply_gradient_normalization(
            _layer("clip_l2_per_param_type", t), {"0": g})["0"]
        for k in g:
            l2 = np.linalg.norm(np.asarray(g[k]).ravel())
            expect = (np.asarray(g[k]) * (t / l2) if l2 > t
                      else np.asarray(g[k]))
            np.testing.assert_allclose(np.asarray(out[k]), expect,
                                       rtol=1e-12)

    def test_none_and_missing_pass_through(self):
        g = _grads()
        out = apply_gradient_normalization(_layer("none"), {"0": g})
        assert out["0"] is g
        out = apply_gradient_normalization(_layer(None), {"0": g})
        assert out["0"] is g

    def test_unknown_mode_raises(self):
        with pytest.raises(ValueError, match="Unknown gradient_norm"):
            apply_gradient_normalization(_layer("bogus"), {"0": _grads()})

    def test_zero_gradient_stays_finite(self):
        z = {"W": jnp.zeros((2, 2), jnp.float64)}
        out = apply_gradient_normalization(
            _layer("renormalize_l2_per_layer"), {"0": z})["0"]
        assert np.isfinite(np.asarray(out["W"])).all()


class TestInTrainStep:
    def _net(self, **norm):
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Sgd(learning_rate=1.0))
                .dtype("float64")
                .list(DenseLayer(n_out=8, activation="tanh", **norm),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent", **norm))
                .set_input_type(InputType.feed_forward(6)).build())
        return MultiLayerNetwork(conf).init()

    def test_clip_bounds_the_sgd_step(self):
        """With Sgd(lr) and element-wise clipping at t, every parameter
        moves by at most lr*t — hand-computable from the update rule."""
        t = 1e-3
        net = self._net(gradient_normalization=(
            "clip_element_wise_absolute_value"),
            gradient_normalization_threshold=t)
        rs = np.random.RandomState(2)
        x = rs.randn(16, 6) * 10  # large inputs -> large raw gradients
        y = np.eye(3)[rs.randint(0, 3, 16)]
        before = np.asarray(net.params_flat())
        net.do_step(x, y)
        after = np.asarray(net.params_flat())
        assert np.max(np.abs(after - before)) <= t * 1.0 + 1e-12

    def test_global_conf_inherited_by_layers(self):
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Sgd(learning_rate=0.1))
                .gradient_normalization("clip_l2_per_layer")
                .gradient_normalization_threshold(2.5)
                .list(DenseLayer(n_out=4, activation="tanh"),
                      OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(3)).build())
        net = MultiLayerNetwork(conf).init()
        for lyr in net.layers:
            assert lyr.gradient_normalization == "clip_l2_per_layer"
            assert lyr.gradient_normalization_threshold == 2.5

    def test_renormalize_trains(self):
        """RenormalizeL2PerLayer still converges on a toy problem."""
        net = self._net(gradient_normalization="renormalize_l2_per_layer")
        rs = np.random.RandomState(3)
        x = rs.randn(32, 6)
        y = np.eye(3)[(x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)]
        losses = [float(net.do_step(x, y)[0]) for _ in range(30)]
        assert losses[-1] < losses[0]


def test_threshold_zero_is_respected():
    """threshold=0.0 must clip everything to zero, not fall back to 1.0."""
    g = {"0": _grads()}
    out = apply_gradient_normalization(
        _layer("clip_element_wise_absolute_value", 0.0), g)["0"]
    for k in out:
        np.testing.assert_array_equal(np.asarray(out[k]), 0.0)


def test_parallel_wrapper_applies_normalization():
    """ParallelWrapper SHARED_GRADIENTS with clipping == single device with
    clipping on the concatenated batch (the module's parity contract)."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.parallel import ParallelWrapper

    def build():
        conf = (NeuralNetConfiguration.builder().seed(11)
                .updater(Sgd(learning_rate=0.5))
                .gradient_normalization("clip_element_wise_absolute_value")
                .gradient_normalization_threshold(1e-3)
                .list(DenseLayer(n_out=8, activation="tanh"),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(6)).build())
        return MultiLayerNetwork(conf).init()

    rs = np.random.RandomState(7)
    x = (rs.randn(32, 6) * 10).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, 32)]

    single = build()
    single.do_step(x, y)

    dist = build()
    pw = ParallelWrapper(dist, workers=8, averaging_frequency=1,
                         mode="shared_gradients")
    pw.fit([DataSet(x[i * 4:(i + 1) * 4], y[i * 4:(i + 1) * 4])
            for i in range(8)], epochs=1)
    np.testing.assert_allclose(np.asarray(dist.params_flat()),
                               np.asarray(single.params_flat()), atol=1e-6)
