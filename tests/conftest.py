"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's backend-swap test strategy (Maven profile test-nd4j-native
vs test-nd4j-cuda, pom.xml:313-356): the same suite runs clusterless on CPU; the
driver separately validates the real-TPU path. Distributed tests see 8 XLA host
devices (the local[N] / BaseSparkTest equivalent).

Note: jax may already be imported at interpreter startup (site hooks registering a
TPU plugin), so the platform must be forced via jax.config, not env vars — config
updates take effect because no backend has been initialised yet when conftest runs.
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")
