"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's backend-swap test strategy (Maven profile test-nd4j-native
vs test-nd4j-cuda, pom.xml:313-356): the same suite runs clusterless on CPU; the
driver separately validates the real-TPU path. Distributed tests see 8 XLA host
devices (the local[N] / BaseSparkTest equivalent).

Note: jax may already be imported at interpreter startup (site hooks registering a
TPU plugin), so the platform must be forced via jax.config, not env vars — config
updates take effect because no backend has been initialised yet when conftest runs.
"""

import os

# must be set before the CPU backend initialises; harmless if the running
# jax already understands jax_num_cpu_devices (the flag below then wins)
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

# NOTE: do NOT enable jax's persistent compilation cache here. The suite
# is compile-dominated and the cache looks like a free 1.5x, but with
# this jaxlib the CPU executable DESERIALIZATION path is unsound: two
# full-suite runs with the cache enabled segfaulted at random points
# (one mid-trace "Garbage-collecting", one on a plain Python line — the
# signature of delayed heap corruption), while cache-less runs of the
# identical tree are stable.

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # pre-0.4.34 jax: the XLA_FLAGS fallback above applies
    pass
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

# A jitted train step compiled per minibatch (instead of per shape bucket)
# turns every fit loop into a compile loop. The fused/unfused step builders
# both route through Model._get_step, so counting cache misses per network
# instance catches any reintroduced per-batch recompile: a leak compiles
# once per iteration and blows well past this bound, while legitimate tests
# (a few shape buckets + mask/carry combos) stay under it.
MAX_STEP_COMPILES_PER_NET = 8


@pytest.fixture(autouse=True)
def _step_recompile_guard(request):
    if request.node.get_closest_marker("allow_step_recompiles"):
        yield
        return
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    counts: dict = {}
    patched = []

    def instrument(cls):
        orig = cls._get_step

        def counted(self, key, _orig=orig):
            if key not in self._step_cache:
                counts[id(self)] = counts.get(id(self), 0) + 1
            return _orig(self, key)

        cls._get_step = counted
        patched.append((cls, orig))

    instrument(MultiLayerNetwork)
    instrument(ComputationGraph)
    try:
        yield
    finally:
        for cls, orig in patched:
            cls._get_step = orig
    worst = max(counts.values(), default=0)
    assert worst <= MAX_STEP_COMPILES_PER_NET, (
        f"a single network compiled {worst} distinct train-step programs in "
        f"one test (cap {MAX_STEP_COMPILES_PER_NET}) — a jitted step is "
        "being allocated per iteration instead of per shape bucket; use the "
        "bucketed fused-fit path or mark the test @pytest.mark."
        "allow_step_recompiles if the shapes are genuinely diverse")


# Same idea for the inference side: output()/evaluate() route through
# Model._get_output with shape-bucketed keys (batch padded to a bucket), so
# a stream of arbitrary batch sizes compiles O(log max_batch) forward
# programs plus a fused-eval block and its K=1 tail variant. A per-batch
# leak compiles once per output() call and blows past this cap.
MAX_OUTPUT_COMPILES_PER_NET = 10


@pytest.fixture(autouse=True)
def _output_recompile_guard(request):
    if request.node.get_closest_marker("allow_output_recompiles"):
        yield
        return
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    counts: dict = {}
    patched = []

    def instrument(cls):
        orig = cls._get_output

        def counted(self, key, build, _orig=orig):
            if key not in self._output_cache:
                counts[id(self)] = counts.get(id(self), 0) + 1
            return _orig(self, key, build)

        cls._get_output = counted
        patched.append((cls, orig))

    instrument(MultiLayerNetwork)
    instrument(ComputationGraph)
    try:
        yield
    finally:
        for cls, orig in patched:
            cls._get_output = orig
    worst = max(counts.values(), default=0)
    assert worst <= MAX_OUTPUT_COMPILES_PER_NET, (
        f"a single network compiled {worst} distinct inference programs in "
        f"one test (cap {MAX_OUTPUT_COMPILES_PER_NET}) — output()/evaluate() "
        "is compiling per batch instead of per shape bucket; route through "
        "the bucketed cache or mark the test @pytest.mark."
        "allow_output_recompiles if the shapes are genuinely diverse")


# Tier-1 duration budget (pinned 2026-08-07, PR 18): the `-m 'not slow'`
# suite measured 938s against its own 870s timeout cap on the single-core
# CI box (845 passed, `--durations=25`). To restore >=5% headroom
# (<=826s), the heaviest compile-bound entries moved to `slow`, chosen so
# every code path keeps a cheaper tier-1 sibling:
#   test_zoo big-model params InceptionResNetV1 (23.5s), GoogLeNet
#     (20.6s), ResNet50 (15.2s) — AlexNet/VGG16/VGG19/FaceNet still run;
#   test_zoo small-model param SimpleCNN (17.7s) — LeNet + LSTM still run;
#   test_examples lenet_mesh_dataparallel.py (19.9s),
#     transformer_text_generation.py (12.8s), keras_residual_import.py
#     (11.4s) — each subsystem has a dedicated tier-1 module.
# ~121s moved -> ~818s estimated. Every NEW test that builds a fleet or
# trains an index must be marked slow (see the federation/rag marker
# descriptions below); re-run with --durations=25 before adding anything
# >5s to tier-1.
def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")
    config.addinivalue_line(
        "markers",
        "health: numerical-health guard / NaN-injection tests (CPU-fast; "
        "runs in tier-1, deliberately NOT in the slow set)")
    config.addinivalue_line(
        "markers",
        "serving: serving-path resilience tests (deadlines, admission "
        "control, breaker, chaos — CPU-fast; runs in tier-1, deliberately "
        "NOT in the slow set)")
    config.addinivalue_line(
        "markers",
        "generation: continuous-batching generation serving tests "
        "(slot-pooled KV cache, prefill buckets, decode-step recompile "
        "guard — CPU-fast; runs in tier-1, deliberately NOT in the slow "
        "set)")
    config.addinivalue_line(
        "markers",
        "fleet: replica-fleet serving tests (health routing, failover "
        "redispatch, supervised restart, hedging, chaos soak — CPU-fast; "
        "runs in tier-1, deliberately NOT in the slow set)")
    config.addinivalue_line(
        "markers",
        "metrics: observability tests (metrics registry, Prometheus "
        "exposition, autoscaler, load harness — CPU-fast; runs in "
        "tier-1, deliberately NOT in the slow set)")
    config.addinivalue_line(
        "markers",
        "allow_step_recompiles: opt out of the per-test train-step "
        "recompile-count guard")
    config.addinivalue_line(
        "markers",
        "allow_output_recompiles: opt out of the per-test inference "
        "recompile-count guard")
    config.addinivalue_line(
        "markers",
        "analysis: graftcheck static-analyzer tests (AST rules, baseline "
        "gate, lock-order instrumentation — CPU-fast; the zero-unbaselined"
        "-findings gate runs in tier-1, deliberately NOT in the slow set)")
    config.addinivalue_line(
        "markers",
        "quant: int8 quantization tests (per-channel weight quant "
        "round-trip and eval parity, int8 paged/streaming KV-cache greedy "
        "agreement, quantization-off bit-exactness — CPU-fast; runs in "
        "tier-1, deliberately NOT in the slow set)")
    config.addinivalue_line(
        "markers",
        "handoff: KV-snapshot/migration serving tests (snapshot "
        "round-trip bit-exactness, corrupted-checksum fallback, "
        "mid-stream failover resume, drain-migrate — CPU-fast; runs in "
        "tier-1, deliberately NOT in the slow set)")
    config.addinivalue_line(
        "markers",
        "disagg: disaggregated prefill/decode tier tests (prefill-export "
        "-> decode-adopt bit-exactness, mid-handoff kills on each side, "
        "corrupt/drop/truncate transfer fallback, decode-tier-dark "
        "degraded mode + recovery — CPU-fast; runs in tier-1, "
        "deliberately NOT in the slow set)")
    config.addinivalue_line(
        "markers",
        "runtime: serving-runtime lifecycle tests (ServingLoop state "
        "machine, LoopSupervisor crash recovery, shutdown-phase chaos, "
        "idempotent drain/close across all servers — CPU-fast; runs in "
        "tier-1, deliberately NOT in the slow set)")
    config.addinivalue_line(
        "markers",
        "knn: retrieval serving tests (EmbeddingIndex exact/int8/IVF "
        "stores, query coalescer parity, recall gates, hardened /knn "
        "HTTP tier — CPU-fast; runs in tier-1, deliberately NOT in the "
        "slow set)")
    config.addinivalue_line(
        "markers",
        "mesh: tensor-parallel mesh-sharded decode tests (head-sharded "
        "page pool over a model mesh, tp>1 greedy/sampled parity, "
        "cross-TP snapshot handoff, replica-group fleets — CPU-fast on "
        "8 forced virtual devices; runs in tier-1, deliberately NOT in "
        "the slow set)")
    config.addinivalue_line(
        "markers",
        "pallas: Pallas-kernel parity tests (paged-attention helper seam "
        "XLA-vs-kernel bit-exactness in interpret mode, backend "
        "selection, backend-tagged program caches — CPU-fast; runs in "
        "tier-1, deliberately NOT in the slow set; skips cleanly when "
        "the installed jax cannot interpret Pallas TPU kernels on CPU)")
    config.addinivalue_line(
        "markers",
        "federation: cross-host fleet federation tests (framed host RPC, "
        "heartbeat gossip suspect detection, whole-process SIGKILL with "
        "bit-exact cross-host snapshot adoption, partition heal, "
        "degraded mode). The wire/chaos/shed tests are CPU-fast and run "
        "in tier-1; the drills that build real fleets or spawn host "
        "processes are ALSO marked slow — tier-1 already runs within "
        "~2% of its own timeout cap, so per-drill fleet builds cannot "
        "ride in it (run them with -m federation)")
    config.addinivalue_line(
        "markers",
        "rag: retrieval-augmented serving tests (two-tier knn->generate "
        "RagPipeline, canonical passage-prefix assembly, prefix-cache "
        "dedupe across hot documents, deadline propagation across the "
        "tier boundary, /rag HTTP route). The unit/parity tests are "
        "CPU-fast and run in tier-1; the drills that build fleets or "
        "train sharded k-means are ALSO marked slow — tier-1 runs "
        "within ~2% of its own timeout cap (run them with -m rag)")


@pytest.fixture(autouse=True)
def _lock_order_debug(request):
    """Opt-in runtime lock-order assertion: with DL4J_TPU_LOCK_DEBUG=1,
    tests under the serving/generation markers run with the serving
    locks wrapped in rank-checked OrderedLocks (analysis/instrument.py),
    so any out-of-order acquisition fails the test instead of deadlocking
    in production."""
    if os.environ.get("DL4J_TPU_LOCK_DEBUG") != "1" or not (
            request.node.get_closest_marker("serving")
            or request.node.get_closest_marker("generation")
            or request.node.get_closest_marker("fleet")
            or request.node.get_closest_marker("metrics")
            or request.node.get_closest_marker("quant")
            or request.node.get_closest_marker("handoff")
            or request.node.get_closest_marker("disagg")
            or request.node.get_closest_marker("runtime")
            or request.node.get_closest_marker("knn")
            or request.node.get_closest_marker("pallas")
            or request.node.get_closest_marker("mesh")
            or request.node.get_closest_marker("federation")
            or request.node.get_closest_marker("rag")):
        yield
        return
    from deeplearning4j_tpu.analysis import instrument
    instrument.install()
    try:
        yield
    finally:
        instrument.uninstall()
