"""InputPreProcessors: all 12 of the reference's nn/conf/preprocessor/ set —
shape round-trips for the adapters, value checks for the normalizers,
straight-through sampling, and composition.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.preprocessors import (
    BinomialSamplingPreProcessor,
    CnnToFeedForwardPreProcessor,
    CnnToRnnPreProcessor,
    ComposableInputPreProcessor,
    FeedForwardToCnnPreProcessor,
    FeedForwardToRnnPreProcessor,
    RnnToCnnPreProcessor,
    RnnToFeedForwardPreProcessor,
    UnitVarianceProcessor,
    ZeroMeanAndUnitVariancePreProcessor,
    ZeroMeanPrePreProcessor,
)
from deeplearning4j_tpu.utils.serde import from_json, to_json


def _x(*shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape),
                       jnp.float64)


class TestShapeAdapters:
    def test_cnn_ff_round_trip(self):
        x = _x(2, 4, 5, 3)
        flat = CnnToFeedForwardPreProcessor(4, 5, 3).forward(x)
        assert flat.shape == (2, 60)
        back = FeedForwardToCnnPreProcessor(4, 5, 3).forward(flat)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))

    def test_rnn_cnn_round_trip(self):
        x = _x(2, 20, 3)  # T = H*W = 20
        img = RnnToCnnPreProcessor(4, 5, 3).forward(x)
        assert img.shape == (2, 4, 5, 3)
        back = CnnToRnnPreProcessor(4, 5, 3).forward(img)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(x))

    def test_rnn_ff_shapes(self):
        x = _x(2, 7, 5)
        assert RnnToFeedForwardPreProcessor().forward(x).shape == (14, 5)
        y = _x(3, 6)
        assert FeedForwardToRnnPreProcessor().forward(y).shape == (3, 1, 6)

    def test_output_types(self):
        t = CnnToFeedForwardPreProcessor(4, 5, 3).output_type(
            InputType.convolutional(4, 5, 3))
        assert t.kind == "feed_forward" and t.flat_size() == 60
        t = RnnToCnnPreProcessor(4, 5, 3).output_type(
            InputType.recurrent(3, 20))
        assert t.kind == "convolutional"


class TestNormalizers:
    def test_zero_mean(self):
        x = _x(8, 5)
        out = ZeroMeanPrePreProcessor().forward(x)
        np.testing.assert_allclose(np.asarray(jnp.mean(out, axis=0)), 0,
                                   atol=1e-12)

    def test_unit_variance(self):
        x = _x(8, 5, seed=1) * 7
        out = UnitVarianceProcessor().forward(x)
        np.testing.assert_allclose(np.asarray(jnp.std(out, axis=0, ddof=1)),
                                   1.0, atol=1e-3)

    def test_zero_mean_unit_variance(self):
        x = _x(16, 4, seed=2) * 3 + 10
        out = ZeroMeanAndUnitVariancePreProcessor().forward(x)
        np.testing.assert_allclose(np.asarray(jnp.mean(out, axis=0)), 0,
                                   atol=1e-10)
        np.testing.assert_allclose(np.asarray(jnp.std(out, axis=0, ddof=1)),
                                   1.0, atol=1e-3)

    def test_backprop_is_pass_through(self):
        """Reference backprop returns epsilon unchanged: the batch
        statistics must be gradient-constants."""
        x = _x(6, 3, seed=3)
        for proc in (ZeroMeanPrePreProcessor(), UnitVarianceProcessor(),
                     ZeroMeanAndUnitVariancePreProcessor()):
            g = jax.grad(lambda v: jnp.sum(proc.forward(v) * 2.0))(x)
            if isinstance(proc, ZeroMeanPrePreProcessor):
                expect = np.full_like(np.asarray(x), 2.0)
            else:
                std = np.std(np.asarray(x), axis=0, ddof=1) + 1e-5
                expect = 2.0 / std * np.ones_like(np.asarray(x))
            np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-10)


class TestBinomialSampling:
    def test_samples_are_binary_and_straight_through(self):
        p = jnp.asarray(np.random.RandomState(4).rand(32, 8), jnp.float64)
        proc = BinomialSamplingPreProcessor(seed=9)
        out = proc.forward(p)
        vals = np.unique(np.asarray(out))
        assert set(vals).issubset({0.0, 1.0})
        # straight-through: gradient flows as if identity
        g = jax.grad(lambda v: jnp.sum(proc.forward(v) * 3.0))(p)
        np.testing.assert_allclose(np.asarray(g), 3.0)

    def test_sampling_tracks_probabilities(self):
        p = jnp.full((2000,), 0.75, jnp.float64)
        out = BinomialSamplingPreProcessor(seed=1).forward(p)
        assert abs(float(jnp.mean(out)) - 0.75) < 0.05


class TestComposable:
    def test_chain_applies_in_order(self):
        x = _x(4, 4, 5, 3, seed=5)
        comp = ComposableInputPreProcessor(processors=[
            CnnToFeedForwardPreProcessor(4, 5, 3),
            ZeroMeanPrePreProcessor(),
        ])
        out = comp.forward(x)
        assert out.shape == (4, 60)
        np.testing.assert_allclose(np.asarray(jnp.mean(out, axis=0)), 0,
                                   atol=1e-12)
        t = comp.output_type(InputType.convolutional(4, 5, 3))
        assert t.kind == "feed_forward" and t.flat_size() == 60

    def test_serde_round_trip(self):
        comp = ComposableInputPreProcessor(processors=[
            CnnToFeedForwardPreProcessor(4, 5, 3),
            BinomialSamplingPreProcessor(seed=3),
        ])
        back = from_json(to_json(comp))
        assert back == comp

    def test_fresh_rng_gives_fresh_samples(self):
        """Training threads the per-step rng: different keys must give
        different samples (the reference redraws each call), while the
        straight-through gradient stays identity."""
        p = jnp.asarray(np.random.RandomState(6).rand(16, 8), jnp.float64)
        proc = BinomialSamplingPreProcessor(seed=0)
        a = proc.forward(p, rng=jax.random.PRNGKey(1))
        b = proc.forward(p, rng=jax.random.PRNGKey(2))
        assert not np.array_equal(np.asarray(a), np.asarray(b))
        g = jax.grad(lambda v: jnp.sum(proc.forward(
            v, rng=jax.random.PRNGKey(1)) * 2.0))(p)
        np.testing.assert_allclose(np.asarray(g), 2.0)
