"""End-to-end smoke: build, train, evaluate, serialise (the stage-2 de-risking slice)."""

import os
import tempfile

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.datasets.mnist import IrisDataSetIterator
from deeplearning4j_tpu.utils.model_serializer import load_model, save_model


def _iris_net(seed=12345):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Adam(learning_rate=0.02))
            .weight_init("xavier")
            .activation("relu")
            .list(
                DenseLayer(n_out=16),
                DenseLayer(n_out=16),
                OutputLayer(n_out=3, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def test_iris_trains_to_high_accuracy():
    net = _iris_net()
    it = IrisDataSetIterator(batch_size=32)
    net.fit(it, epochs=60)
    ev = net.evaluate(it)
    assert ev.accuracy() > 0.93, ev.stats()


def test_score_decreases():
    net = _iris_net()
    it = IrisDataSetIterator(batch_size=150)
    ds = next(iter(it))
    s0 = net.score(ds)
    net.fit(it, epochs=30)
    s1 = net.score(ds)
    assert s1 < s0 / 2


def test_output_shape_and_softmax():
    net = _iris_net()
    it = IrisDataSetIterator(batch_size=10)
    ds = next(iter(it))
    out = np.asarray(net.output(ds.features))
    assert out.shape == (10, 3)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-5)


def test_serialization_roundtrip():
    net = _iris_net()
    it = IrisDataSetIterator(batch_size=50)
    net.fit(it, epochs=3)
    ds = next(iter(it))
    out_before = np.asarray(net.output(ds.features))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.zip")
        save_model(net, path)
        net2 = load_model(path)
    out_after = np.asarray(net2.output(ds.features))
    np.testing.assert_allclose(out_before, out_after, atol=1e-6)
    assert net2.iteration == net.iteration
    # training continues seamlessly after restore (updater state preserved)
    net2.fit(it, epochs=1)


def test_json_roundtrip():
    net = _iris_net()
    js = net.conf.to_json()
    from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
    conf2 = MultiLayerConfiguration.from_json(js)
    assert conf2.to_json() == js
    net2 = MultiLayerNetwork(conf2).init()
    assert net2.num_params() == net.num_params()


def test_flat_param_view_roundtrip():
    net = _iris_net()
    flat = net.params_flat()
    assert flat.size == net.num_params()
    net2 = _iris_net()
    net2.set_params_flat(flat)
    np.testing.assert_allclose(net2.params_flat(), flat)
