"""Failure detection + elastic recovery (parallel/elastic.py).

The reference has no fault handling to port (SURVEY §5); these tests pin
the beyond-parity contract: crash-consistent checkpoints, corrupt-file
quarantine, exact resume (resumed run == uninterrupted run), process-kill
recovery in a subprocess, and heartbeat-based stall detection.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.parallel.elastic import (
    CheckpointListener,
    CheckpointStore,
    FailureDetector,
    FaultInjectionListener,
    FaultTolerantTrainer,
    Heartbeat,
)


def _net(seed=12345):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Adam(learning_rate=0.01))
            .list(DenseLayer(n_out=16, activation="relu"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(n=12, batch=16, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        x = rs.randn(batch, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, batch)]
        out.append(DataSet(x, y))
    return out


class TestCheckpointStore:
    def test_roundtrip_and_prune(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep=2)
        net = _net()
        data = _batches(1)[0]
        for _ in range(4):
            net.fit(data)
            store.save(net, {"epoch": net.epoch, "batch_in_epoch": 0})
        ckpts = store.checkpoints()
        assert len(ckpts) == 2  # pruned to keep=2
        restored, meta = store.restore()
        assert restored.iteration == net.iteration
        np.testing.assert_allclose(restored.params_flat(),
                                   np.asarray(net.params_flat(),
                                              dtype=np.float32))

    def test_corrupt_checkpoint_quarantined(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep=5)
        net = _net()
        data = _batches(1)[0]
        net.fit(data)
        good = store.save(net)
        net.fit(data)
        bad = store.save(net)
        # truncate the newest checkpoint (simulates a crash mid-write that
        # somehow survived the atomic rename, or disk corruption)
        raw = open(bad, "rb").read()
        with open(bad, "wb") as fh:
            fh.write(raw[:len(raw) // 2])
        with pytest.warns(UserWarning, match="quarantining"):
            assert store.latest() == good
        assert os.path.exists(bad + ".corrupt")
        restored, _ = store.restore()
        assert restored.iteration == 1

    def test_transient_oserror_does_not_quarantine(self, tmp_path,
                                                   monkeypatch):
        """An OSError while validating (concurrent prune/replace from a
        sharing process) must skip the file this pass, not rename a
        possibly-good checkpoint to .corrupt."""
        import zipfile as _zf

        store = CheckpointStore(str(tmp_path), keep=5)
        net = _net()
        net.fit(_batches(1)[0])
        path = store.save(net)
        real_zip = _zf.ZipFile

        def flaky_zip(p, *a, **kw):
            raise OSError("transient read failure")

        monkeypatch.setattr(_zf, "ZipFile", flaky_zip)
        with pytest.warns(UserWarning, match="transient"):
            assert store.latest() is None  # skipped this pass
        monkeypatch.setattr(_zf, "ZipFile", real_zip)
        assert not os.path.exists(path + ".corrupt")
        assert store.latest() == path  # still valid next pass

    def test_restore_falls_back_when_newest_vanishes_midread(
            self, tmp_path, monkeypatch):
        """A sharing process can prune a checkpoint between validation and
        the reopen inside restore(): fall back to next-older, and do NOT
        blacklist the filename for the store's lifetime (save() legally
        reuses it after resuming)."""
        import deeplearning4j_tpu.parallel.elastic as el

        store = CheckpointStore(str(tmp_path), keep=5)
        net = _net()
        ds = _batches(1)[0]
        net.fit(ds)
        p1 = store.save(net)
        net.fit(ds)
        p2 = store.save(net)
        real = el.load_model

        def racy(path):
            if path == p2:
                os.unlink(p2)  # the concurrent pruner strikes mid-read
                raise OSError("gone")
            return real(path)

        monkeypatch.setattr(el, "load_model", racy)
        with pytest.warns(UserWarning, match="trying next-older"):
            restored, _ = store.restore()
        monkeypatch.setattr(el, "load_model", real)
        assert restored.iteration == 1  # fell back to p1
        restored.fit(ds)
        assert store.save(restored) == p2  # same filename re-saved...
        r2, _ = store.restore()
        assert r2.iteration == 2           # ...and restorable again

    def test_restore_raises_when_all_checkpoints_unloadable(
            self, tmp_path, monkeypatch):
        """If EVERY validated checkpoint fails to load (persistent format
        problem, not the transient race), restore must raise rather than
        silently restart the run from scratch."""
        import deeplearning4j_tpu.parallel.elastic as el

        store = CheckpointStore(str(tmp_path))
        net = _net()
        net.fit(_batches(1)[0])
        store.save(net)

        def broken(path):
            raise KeyError("metadata.json")

        monkeypatch.setattr(el, "load_model", broken)
        with pytest.warns(UserWarning), \
                pytest.raises(RuntimeError, match="refusing to silently"):
            store.restore()

    def test_atomic_save_never_leaves_partial(self, tmp_path):
        store = CheckpointStore(str(tmp_path))
        net = _net()
        net.fit(_batches(1)[0])
        store.save(net)
        names = os.listdir(tmp_path)
        assert all(n.startswith("ckpt-") and n.endswith(".zip")
                   for n in names), names


class TestCheckpointListener:
    def test_saves_on_frequency(self, tmp_path):
        store = CheckpointStore(str(tmp_path), keep=10)
        net = _net()
        listener = CheckpointListener(store, frequency=3)
        net.set_listeners(listener)
        it = ListDataSetIterator(_batches(7), batch_size=16)
        net.fit(it)
        assert listener.saved == 2  # iterations 3 and 6
        assert len(store.checkpoints()) == 2


class TestFaultTolerantTrainer:
    def test_resume_equals_uninterrupted(self, tmp_path):
        batches = _batches(6)
        factory = lambda: ListDataSetIterator(list(batches), batch_size=16)

        # uninterrupted baseline
        base = _net()
        for _ in range(2):
            for ds in batches:
                base._fit_batch(ds)

        # crashed-and-resumed run: fault at iteration 7 (epoch 1, batch 1)
        net = _net()
        net.set_listeners(FaultInjectionListener(at_iteration=7))
        store = CheckpointStore(str(tmp_path), keep=5)
        trainer = FaultTolerantTrainer(net, store, frequency=2)
        with pytest.raises(FaultInjectionListener.InjectedFault):
            trainer.run(factory, epochs=2)
        assert store.latest() is not None

        # "restarted process": fresh trainer around a throwaway net; run()
        # must restore from the checkpoint, fast-forward, and finish
        net2 = _net(seed=999)  # wrong seed on purpose: must be replaced
        net2.set_listeners()
        trainer2 = FaultTolerantTrainer(net2, store, frequency=2)
        final = trainer2.run(factory, epochs=2)
        assert final.iteration == base.iteration
        np.testing.assert_allclose(
            np.asarray(final.params_flat(), np.float32),
            np.asarray(base.params_flat(), np.float32), rtol=0, atol=0)

    def test_skip_spill_into_next_epoch_warns(self, tmp_path):
        """A resumed stream shorter than at checkpoint time (violated
        iterator_factory determinism) must warn and drop leftover skips
        instead of silently swallowing head batches of later epochs."""
        store = CheckpointStore(str(tmp_path))
        trainer = FaultTolerantTrainer(_net(), store, frequency=100)
        batches = _batches(2)
        factory = lambda: ListDataSetIterator(list(batches), batch_size=16)
        with pytest.warns(UserWarning, match="iterator_factory"):
            # skip_batches=3 > 2 batches/epoch: spills into epoch 2
            trainer.fit(factory, epochs=2, skip_batches=3)
        # epoch 2 trained ALL its batches (skips dropped, not spilled)
        assert trainer.net.iteration == 2

    def test_completed_run_not_retrained(self, tmp_path):
        batches = _batches(3)
        factory = lambda: ListDataSetIterator(list(batches), batch_size=16)
        store = CheckpointStore(str(tmp_path))
        trainer = FaultTolerantTrainer(_net(), store, frequency=2)
        done = trainer.run(factory, epochs=1)
        it_before = done.iteration
        again = FaultTolerantTrainer(_net(seed=7), store, frequency=2)
        final = again.run(factory, epochs=1)
        assert final.iteration == it_before  # restored, not retrained


_SUBPROCESS_SCRIPT = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.optimize.listeners import TrainingListener
from deeplearning4j_tpu.parallel.elastic import (CheckpointStore,
                                                 FaultTolerantTrainer)

ckpt_dir, crash_at = sys.argv[1], int(sys.argv[2])

conf = (NeuralNetConfiguration.builder().seed(12345)
        .updater(Adam(learning_rate=0.01))
        .list(DenseLayer(n_out=16, activation="relu"),
              OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(4)).build())
net = MultiLayerNetwork(conf).init()

rs = np.random.RandomState(0)
batches = [DataSet(rs.randn(16, 4).astype(np.float32),
                   np.eye(3, dtype=np.float32)[rs.randint(0, 3, 16)])
           for _ in range(6)]
factory = lambda: ListDataSetIterator(list(batches), batch_size=16)


class HardKill(TrainingListener):
    def iteration_done(self, model, iteration):
        if iteration == crash_at:
            os._exit(137)  # simulated SIGKILL: no cleanup, no atexit


if crash_at > 0:
    net.set_listeners(HardKill())
trainer = FaultTolerantTrainer(net, CheckpointStore(ckpt_dir, keep=3),
                               frequency=2)
final = trainer.run(factory, epochs=2)
print("FINAL", final.iteration,
      float(np.abs(np.asarray(final.params_flat())).sum()))
"""


@pytest.mark.slow
class TestProcessKillRecovery:
    def test_kill_and_resume_subprocess(self, tmp_path):
        script = tmp_path / "train.py"
        script.write_text(_SUBPROCESS_SCRIPT)
        ckpt = str(tmp_path / "ckpts")
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": repo_root + os.pathsep
               + os.environ.get("PYTHONPATH", "")}

        # run 1: dies hard (os._exit) at iteration 7 of 12
        p1 = subprocess.run([sys.executable, str(script), ckpt, "7"],
                            capture_output=True, text=True, env=env,
                            timeout=300)
        assert p1.returncode == 137, p1.stderr

        # run 2: same command with crash disabled = the restarted job
        p2 = subprocess.run([sys.executable, str(script), ckpt, "0"],
                            capture_output=True, text=True, env=env,
                            timeout=300)
        assert p2.returncode == 0, p2.stderr
        line = [ln for ln in p2.stdout.splitlines()
                if ln.startswith("FINAL")][0]
        assert line.split()[1] == "12"  # 2 epochs x 6 batches, no repeats

        # uninterrupted reference: fresh dir, no crash
        p3 = subprocess.run([sys.executable, str(script),
                             str(tmp_path / "ckpts2"), "0"],
                            capture_output=True, text=True, env=env,
                            timeout=300)
        assert p3.returncode == 0, p3.stderr
        ref = [ln for ln in p3.stdout.splitlines()
               if ln.startswith("FINAL")][0]
        # identical iteration count and identical param-sum fingerprint
        assert line.split()[1] == ref.split()[1]
        assert abs(float(line.split()[2]) - float(ref.split()[2])) < 1e-4


class TestEmergencyCheckpoint:
    def test_crash_writes_emergency_checkpoint(self, tmp_path):
        """A raise anywhere in the fit loop leaves a best-effort checkpoint
        at the crash point, so restart resumes from HERE rather than the
        last periodic save (frequency here is too large to ever fire)."""
        batches = _batches(6)
        factory = lambda: ListDataSetIterator(list(batches), batch_size=16)

        base = _net()
        for ds in batches:
            base._fit_batch(ds)

        store = CheckpointStore(str(tmp_path), keep=5)
        net = _net()
        net.set_listeners(FaultInjectionListener(at_iteration=3))
        trainer = FaultTolerantTrainer(net, store, frequency=10_000)
        with pytest.raises(FaultInjectionListener.InjectedFault):
            trainer.fit(factory, epochs=1)
        restored, meta = store.restore()
        assert meta["emergency"] is True
        assert "InjectedFault" in meta["error"]
        # the listener raised AFTER iteration 3's update was applied: the
        # in-flight batch counts as trained, so resume starts at batch 3
        assert meta["epoch"] == 0 and meta["batch_in_epoch"] == 3
        assert restored.iteration == 3

        # restarted process: resumes from the emergency point and ends
        # identical to the uninterrupted run
        trainer2 = FaultTolerantTrainer(_net(seed=9), store, frequency=10_000)
        final = trainer2.run(factory, epochs=1)
        assert final.iteration == base.iteration == 6
        np.testing.assert_allclose(
            np.asarray(final.params_flat(), np.float32),
            np.asarray(base.params_flat(), np.float32), rtol=0, atol=0)

    def test_emergency_save_failure_never_masks_original(self, tmp_path,
                                                         monkeypatch):
        """A second failure inside the emergency save (disk full) must warn
        and re-raise the ORIGINAL exception, not its own."""
        store = CheckpointStore(str(tmp_path))
        net = _net()
        net.set_listeners(FaultInjectionListener(at_iteration=2))
        trainer = FaultTolerantTrainer(net, store, frequency=10_000)

        def broken_save(*a, **kw):
            raise OSError("disk full")

        monkeypatch.setattr(store, "save", broken_save)
        factory = lambda: ListDataSetIterator(_batches(4), batch_size=16)
        with pytest.warns(UserWarning, match="emergency checkpoint failed"), \
                pytest.raises(FaultInjectionListener.InjectedFault):
            trainer.fit(factory, epochs=1)


class TestFailureDetection:
    def test_heartbeat_and_stall_detection(self, tmp_path):
        hb_dir = tmp_path
        alive = Heartbeat(str(hb_dir / "w0.heartbeat"), interval=0.2)
        alive.start()
        # a worker that died 100s ago
        stale = {"pid": 99999, "ts": time.time() - 100}
        (hb_dir / "w1.heartbeat").write_text(json.dumps(stale))
        # a worker whose file is garbage (half-written at crash)
        (hb_dir / "w2.heartbeat").write_text("{\"pid\": 3")
        try:
            det = FailureDetector(str(hb_dir), timeout=1.0)
            assert set(det.workers()) == {"w0", "w1", "w2"}
            # unreadable file is dead immediately; stale-but-readable ts
            # needs a change-detection window (two scans) before it ages
            # out on the observer's monotonic clock
            assert det.dead_workers() == ["w2"]
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                dead = det.dead_workers()
                if dead == ["w1", "w2"]:
                    break
                time.sleep(0.05)
            # w1's ts never advances -> ages out; w0 keeps beating every
            # 0.2s so its ts keeps changing and it stays alive
            assert dead == ["w1", "w2"]
        finally:
            alive.stop()

    def test_heartbeat_survives_transient_oserror(self, tmp_path):
        """beat() failures (disk full, NFS blip) must not kill the loop — a
        dead heartbeat thread reads as a dead WORKER to every observer. The
        loop warns after WARN_AFTER_FAILURES consecutive misses and clears
        the streak on the next success."""
        import threading

        hb = Heartbeat(str(tmp_path / "w.heartbeat"), interval=0.005)
        real_beat = hb.beat
        failing = threading.Event()

        def flaky_beat():
            if failing.is_set():
                raise OSError("disk full")
            real_beat()

        hb.beat = flaky_beat
        hb.start()  # initial beat succeeds (fail-fast contract unchanged)
        try:
            failing.set()
            deadline = time.time() + 10
            while time.time() < deadline and not hb._warned:
                time.sleep(0.01)
            assert hb._warned
            assert hb.consecutive_failures >= Heartbeat.WARN_AFTER_FAILURES
            assert hb._thread.is_alive()  # still beating, not dead
            failing.clear()
            deadline = time.time() + 10
            while time.time() < deadline and hb.consecutive_failures:
                time.sleep(0.01)
            assert hb.consecutive_failures == 0  # success clears the streak
            assert not hb._warned
            assert hb._thread.is_alive()
        finally:
            hb.stop()

    def test_heartbeat_initial_beat_still_fails_fast(self, tmp_path):
        """start() keeps raising on an unwritable path: a worker that can
        NEVER heartbeat should fail at startup, not spin silently."""
        hb = Heartbeat(str(tmp_path / "no" / "such" / "dir" / "w.heartbeat"),
                       interval=0.01)
        with pytest.raises(OSError):
            hb.start()

    def test_wedged_worker_ages_out(self, tmp_path):
        hb = Heartbeat(str(tmp_path / "w.heartbeat"), interval=60)
        hb.beat()  # one beat, then the "worker" wedges (no thread running)
        det = FailureDetector(str(tmp_path), timeout=5.0)
        assert det.dead_workers() == []
        # the persisted ts never advances; 30 observer-monotonic seconds
        # later the worker has aged out
        assert det.dead_workers(now=time.monotonic() + 30) == ["w"]

    def test_wall_clock_jump_does_not_expire_fresh_lease(self, tmp_path,
                                                         monkeypatch):
        """NTP step / VM migration regression: the persisted heartbeat ts
        is a VERSION NUMBER, so a wall-clock jump on either side must not
        kill a freshly-beating worker. (The old scheme compared writer
        wall clock to observer wall clock and declared every worker dead
        the moment either clock stepped.)"""
        hb = Heartbeat(str(tmp_path / "w.heartbeat"), interval=60)
        hb.beat()
        det = FailureDetector(str(tmp_path), timeout=5.0)
        assert det.dead_workers() == []

        # observer's wall clock jumps 2h forward: ts now looks 2h stale
        # by wall math, but no observer-monotonic time has passed
        real_time = time.time
        monkeypatch.setattr(time, "time", lambda: real_time() + 7200.0)
        assert det.dead_workers() == []

        # writer's wall clock jumps too: the rewritten ts CHANGES, which
        # only proves liveness — still not dead
        hb.beat()
        assert det.dead_workers() == []

        # backward step on the writer (ts goes 2h into the past) is still
        # just a new version — alive
        monkeypatch.setattr(time, "time", lambda: real_time() - 7200.0)
        hb.beat()
        assert det.dead_workers() == []


class TestDerivedResume:
    def test_bare_listener_checkpoint_resumes_without_double_training(
            self, tmp_path):
        """A checkpoint written by a bare CheckpointListener (no meta_fn)
        has no position metadata; run() must derive the resume point from
        the iteration counter instead of silently re-training."""
        batches = _batches(6)
        factory = lambda: ListDataSetIterator(list(batches), batch_size=16)

        base = _net()
        for _ in range(2):
            for ds in batches:
                base._fit_batch(ds)

        # crash after iteration 8 (epoch 1, batch 2); checkpoints at 4, 8
        # come from a plain listener attached to an ordinary fit loop
        store = CheckpointStore(str(tmp_path), keep=5)
        net = _net()
        net.set_listeners(CheckpointListener(store, frequency=4),
                          FaultInjectionListener(at_iteration=8))
        with pytest.raises(FaultInjectionListener.InjectedFault):
            net.fit(factory(), epochs=2)

        resumed = FaultTolerantTrainer(_net(seed=3), store, frequency=4)
        with pytest.warns(UserWarning, match="derived resume point"):
            final = resumed.run(factory, epochs=2)
        assert final.iteration == base.iteration
        # The crashing net trains through the (default) fused fit, whose
        # compiled program matches the per-batch reference only to
        # compile-level rounding (~1e-7); double-training a batch would diff
        # at ~1e-3, so a tight-but-nonzero tolerance still discriminates the
        # silent-retrain bug this test guards against.
        np.testing.assert_allclose(
            np.asarray(final.params_flat(), np.float32),
            np.asarray(base.params_flat(), np.float32), rtol=0, atol=5e-6)
