"""Orbax sharded checkpointing: save/restore with shardings preserved and
training resumable (the ModelSerializer role for mesh-sharded state)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.utils.orbax_checkpoint import (load_checkpoint,
                                                       save_checkpoint)

pytest.importorskip("orbax.checkpoint")


def _net():
    conf = (NeuralNetConfiguration.builder()
            .seed(5).updater(Adam(learning_rate=0.01))
            .list(DenseLayer(n_out=16, activation="relu"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _ds(seed=0):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 3, 32)
    return DataSet((rs.randn(32, 4) + labels[:, None]).astype(np.float32),
                   np.eye(3, dtype=np.float32)[labels])


class TestOrbaxCheckpoint:
    def test_save_restore_resume(self, tmp_path):
        net = _net()
        ds = _ds()
        for _ in range(5):
            net.fit(ds)
        save_checkpoint(net, str(tmp_path / "ckpt"))

        # restore WITHOUT the original object (config rebuilt from JSON)
        restored = load_checkpoint(str(tmp_path / "ckpt"))
        np.testing.assert_allclose(restored.params_flat(),
                                   net.params_flat(), atol=0)
        assert restored.iteration == net.iteration == 5

        # resume: one more step on each must match exactly
        net.fit(ds)
        restored.fit(ds)
        np.testing.assert_allclose(restored.params_flat(),
                                   net.params_flat(), atol=1e-7)

    def test_sharded_round_trip_preserves_sharding(self, tmp_path):
        from deeplearning4j_tpu.parallel import data_model_mesh
        from deeplearning4j_tpu.parallel.model_sharding import shard_network

        net = _net()
        mesh = data_model_mesh(2, 4)
        shard_network(net, mesh)
        ds = _ds(1)
        net.fit(ds)
        save_checkpoint(net, str(tmp_path / "sharded"))

        # restore INTO a sharded target: arrays come back sharded
        net2 = _net()
        shard_network(net2, mesh)
        load_checkpoint(str(tmp_path / "sharded"), net=net2)
        np.testing.assert_allclose(net2.params_flat(), net.params_flat(),
                                   atol=0)
        s_orig = net.params["0"]["W"].sharding
        s_back = net2.params["0"]["W"].sharding
        assert s_back == s_orig
        assert net2.iteration == net.iteration