"""Fused multi-step fit driver tests (optimize/fused_fit.py).

Covers the ISSUE-1 acceptance surface: fused-vs-unfused loss-trajectory and
parameter equivalence (same seeds, K in {1, 4}), trailing-partial-batch
correctness under shape bucketing, the one-program-per-ragged-epoch
guarantee, the score_value contract, and the block-level listener semantics.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam
from deeplearning4j_tpu.optimize.fused_fit import (
    DEFAULT_FUSED_STEPS_CPU,
    FusedFitDriver,
    device_put_ahead,
    resolve_fused_steps,
)
from deeplearning4j_tpu.optimize.listeners import (
    CollectScoresIterationListener,
    TrainingListener,
)

TOL = 1e-5


def _mln(seed=12345):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.02))
            .weight_init("xavier").activation("relu")
            .list(DenseLayer(n_out=16), DenseLayer(n_out=16),
                  OutputLayer(n_out=3, loss="mcxent", activation="softmax"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _graph(seed=12345):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.02))
            .weight_init("xavier").activation("relu")
            .graph_builder().add_inputs("in")
            .add_layer("d1", DenseLayer(n_out=16), "in")
            .add_layer("out",
                       OutputLayer(n_out=3, loss="mcxent", activation="softmax"),
                       "d1")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4)).build())
    return ComputationGraph(conf).init()


def _iris_like(n, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]
    return DataSet(x, y)


def _max_param_diff(a, b):
    return max(float(jnp.max(jnp.abs(x - y))) for x, y in
               zip(jax.tree_util.tree_leaves(a.params),
                   jax.tree_util.tree_leaves(b.params)))


# ------------------------------------------------------------- equivalence
class TestFusedEquivalence:
    @pytest.mark.parametrize("k", [1, 4])
    def test_fused_matches_unfused_mln(self, k):
        """Same seeds: K-fused training equals the per-minibatch path, both
        in the per-iteration score trajectory and the final parameters."""
        it = ListDataSetIterator(_iris_like(128), batch_size=32)
        ref, fus = _mln(), _mln()
        ref_scores = CollectScoresIterationListener()
        fus_scores = CollectScoresIterationListener()
        ref.set_listeners(ref_scores)
        fus.set_listeners(fus_scores)
        ref.fit(it, epochs=2, fused_steps=1)
        fus.fit(it, epochs=2, fused_steps=k)
        assert fus.iteration == ref.iteration == 8
        assert _max_param_diff(ref, fus) <= TOL
        ref_traj = [float(s) for _, s in ref_scores.scores]
        fus_traj = [float(s) for _, s in fus_scores.scores]
        assert [i for i, _ in ref_scores.scores] == [i for i, _ in fus_scores.scores]
        np.testing.assert_allclose(fus_traj, ref_traj, atol=TOL)

    def test_fused_matches_unfused_graph(self):
        it = ListDataSetIterator(_iris_like(128), batch_size=32)
        ref, fus = _graph(), _graph()
        ref.fit(it, epochs=2, fused_steps=1)
        fus.fit(it, epochs=2, fused_steps=4)
        assert fus.iteration == ref.iteration == 8
        assert _max_param_diff(ref, fus) <= TOL
        assert abs(ref.score() - fus.score()) <= TOL

    def test_tail_group_runs_unfused(self):
        """A stream whose length is not a multiple of K: the trailing group
        of fewer than K microbatches takes the per-minibatch path, and the
        result still matches the unfused reference exactly."""
        it = ListDataSetIterator(_iris_like(192), batch_size=32)  # 6 batches
        ref, fus = _mln(), _mln()
        ref.fit(it, epochs=1, fused_steps=1)
        fus.fit(it, epochs=1, fused_steps=4)  # 1 block + 2-batch tail
        assert fus.iteration == ref.iteration == 6
        assert _max_param_diff(ref, fus) <= TOL
        fused_keys = [kk for kk in fus._step_cache if kk[0] == "fused"]
        unfused_keys = [kk for kk in fus._step_cache if kk[0] != "fused"]
        assert len(fused_keys) == 1 and len(unfused_keys) == 1


# --------------------------------------------------- bucketing / recompiles
class TestShapeBucketing:
    def test_trailing_partial_batch_correctness(self):
        """118 examples at batch 32 -> 32,32,32,22: the undersized batch is
        padded to the bucket with zeroed label-mask rows, and training
        matches the unfused path (which sees the raw 22-row batch)."""
        it = ListDataSetIterator(_iris_like(118), batch_size=32)
        ref, fus = _mln(), _mln()
        ref.fit(it, epochs=3, fused_steps=1)
        fus.fit(it, epochs=3, fused_steps=4)
        assert fus.iteration == ref.iteration == 12
        assert _max_param_diff(ref, fus) <= TOL

    def test_ragged_epoch_single_program(self):
        """The recompile-count guarantee: a ragged-batch epoch compiles ONE
        fused program — the padded tail batch reuses the full-block key."""
        it = ListDataSetIterator(_iris_like(118), batch_size=32)
        net = _mln()
        net.fit(it, epochs=3, fused_steps=4)
        assert len(net._step_cache) == 1
        (key,) = net._step_cache
        assert key[0] == "fused" and key[1] == 4

    def test_masked_stream_buckets(self):
        """Streams that already carry a labels_mask bucket too (the pad rows
        extend the existing mask with zeros)."""
        rs = np.random.RandomState(3)
        n = 80  # batch 32 -> 32,32,16
        x = rs.randn(n, 4).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]
        lm = np.ones(n, np.float32)
        lm[::7] = 0.0
        ds = DataSet(x, y, None, lm)
        ref, fus = _mln(), _mln()
        ref.fit(ListDataSetIterator(ds, batch_size=32), epochs=3, fused_steps=1)
        fus.fit(ListDataSetIterator(ds, batch_size=32), epochs=3, fused_steps=3)
        assert fus.iteration == ref.iteration == 9
        assert _max_param_diff(ref, fus) <= TOL
        assert len([k for k in fus._step_cache if k[0] == "fused"]) == 1


# ------------------------------------------------------- score_value contract
class TestScoreValueContract:
    def test_score_value_stays_device_side(self):
        """score_value holds the device scalar after training (no per-step
        host sync); score() with no arguments coerces it to a float."""
        net = _mln()
        assert isinstance(net.score(), float) and np.isnan(net.score())
        net.fit(ListDataSetIterator(_iris_like(64), batch_size=32),
                epochs=1, fused_steps=2)
        assert isinstance(net.score_value, jax.Array)
        s = net.score()
        assert isinstance(s, float) and np.isfinite(s)

    def test_score_no_arg_graph(self):
        net = _graph()
        net.fit(ListDataSetIterator(_iris_like(64), batch_size=32),
                epochs=1, fused_steps=2)
        s = net.score()
        assert isinstance(s, float) and np.isfinite(s)

    def test_listener_path_scores_are_host_values(self):
        """With listeners attached the block's stacked losses come back in
        ONE device fetch; iteration_done then observes host-side scores."""
        net = _mln()
        seen = []

        class Probe(TrainingListener):
            def iteration_done(self, model, iteration):
                seen.append((iteration, model.score_value))

        net.set_listeners(Probe())
        net.fit(ListDataSetIterator(_iris_like(128), batch_size=32),
                epochs=1, fused_steps=4)
        assert [i for i, _ in seen] == [1, 2, 3, 4]
        assert all(isinstance(s, np.floating) for _, s in seen)


# ----------------------------------------------------------- block listeners
class TestBlockListeners:
    def test_on_block_done_fires_once_per_block(self):
        net = _mln()
        blocks = []
        iters = []

        class Probe(TrainingListener):
            def on_block_done(self, model, iterations, scores):
                blocks.append((list(iterations), np.asarray(scores)))

            def iteration_done(self, model, iteration):
                iters.append(iteration)

        net.set_listeners(Probe())
        net.fit(ListDataSetIterator(_iris_like(256), batch_size=32),
                epochs=1, fused_steps=4)  # 8 batches -> 2 full blocks
        assert len(blocks) == 2
        assert blocks[0][0] == [1, 2, 3, 4] and blocks[1][0] == [5, 6, 7, 8]
        assert all(s.shape == (4,) for _, s in blocks)
        # per-iteration hooks still fire once per iteration, after the block
        assert iters == list(range(1, 9))


# ------------------------------------------------------------- driver bits
class TestDriverPlumbing:
    def test_fused_steps_validation(self):
        net = _mln()
        with pytest.raises(ValueError):
            net.fit(_iris_like(32), fused_steps=0)
        with pytest.raises(ValueError):
            FusedFitDriver(net, 0)

    def test_cpu_default_fused_steps(self):
        assert jax.default_backend() == "cpu"
        assert resolve_fused_steps(_mln(), None) == DEFAULT_FUSED_STEPS_CPU

    def test_device_put_ahead_order_and_depth(self):
        placed = []
        out = list(device_put_ahead(range(7), 3, lambda v: placed.append(v) or v))
        assert out == list(range(7)) and placed == out
        with pytest.raises(ValueError):
            list(device_put_ahead(range(3), 0, lambda v: v))


# ------------------------------------------------------------------ e2e perf
@pytest.mark.slow
def test_fit_e2e_fused_not_slower():
    """End-to-end fit() wall clock (dispatch + transfer + listener round-trip
    included): the fused path must not regress the per-minibatch path. The
    headline ratio lives in bench.py's fit_e2e sub-metric; this guard uses a
    loose floor because single-core CI boxes time with +/-15% noise."""
    data = _iris_like(512)

    def run(k):
        it = ListDataSetIterator(data, batch_size=8)
        net = _mln()
        net.fit(it, epochs=1, fused_steps=k)  # warm both programs
        t0 = time.perf_counter()
        net.fit(it, epochs=4, fused_steps=k)
        float(net.score())
        return time.perf_counter() - t0

    unfused, fused = run(1), run(2)
    assert fused <= unfused * 1.25, (
        f"fused e2e {fused:.3f}s vs unfused {unfused:.3f}s")
