"""Tensor-parallel (GSPMD dp x tp) training of real networks.

The core invariant mirrors the reference's distributed-equals-local contract
(TestCompareParameterAveragingSparkVsSingleMachine.java, adapted to TP):
the SAME train step compiled against a (data, model) mesh with tensor-sharded
parameters must produce the single-device result to float tolerance — GSPMD
partitions the program, it does not change the math.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.convolution import (
    ConvolutionLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.layers.core import (
    DenseLayer,
    EmbeddingLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam, Sgd
from deeplearning4j_tpu.parallel import ShardedTrainer, data_model_mesh
from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS
from deeplearning4j_tpu.parallel.model_sharding import network_param_specs


def _cnn(seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(learning_rate=1e-3))
            .list(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                   activation="relu"),
                  SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
                  DenseLayer(n_out=16, activation="relu"),
                  OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(12, 12, 1))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(n=3, b=4, seed=0):
    rs = np.random.RandomState(seed)
    return [DataSet(rs.randn(b, 12, 12, 1).astype(np.float32),
                    np.eye(4, dtype=np.float32)[rs.randint(0, 4, b)])
            for _ in range(n)]


class TestShardedTrainer:
    def test_specs_shard_kernels_and_biases(self):
        net = _cnn()
        specs = network_param_specs(net, model_size=2)
        assert specs["0"]["W"] == P(None, None, None, MODEL_AXIS)
        assert specs["0"]["b"] == P(MODEL_AXIS)
        assert specs["2"]["W"] == P(None, MODEL_AXIS)
        assert specs["3"]["W"] == P(None, MODEL_AXIS)  # 4 % 2 == 0

    def test_indivisible_dims_stay_replicated(self):
        net = _cnn()
        specs = network_param_specs(net, model_size=3)
        # 8 % 3 != 0 -> replicated
        assert specs["0"]["W"] == P()
        assert specs["0"]["b"] == P()

    def test_dp_tp_matches_single_device(self):
        ref = _cnn()
        tp = _cnn()
        batches = _batches()
        for ds in batches:
            ref.do_step(ds.features, ds.labels)

        mesh = data_model_mesh(2, 2)
        trainer = ShardedTrainer(tp, mesh)
        # placed params really are tensor-sharded over the model axis
        assert tp.params["0"]["W"].sharding.spec == P(
            None, None, None, MODEL_AXIS)
        trainer.fit(batches)

        for k in ref.params:
            for name in ref.params[k]:
                np.testing.assert_allclose(
                    np.asarray(ref.params[k][name]),
                    np.asarray(tp.params[k][name]),
                    rtol=2e-4, atol=2e-5,
                    err_msg=f"param {k}/{name} diverged under dp x tp")
        out_ref = np.asarray(ref.output(batches[0].features))
        out_tp = np.asarray(trainer.output(batches[0].features))
        np.testing.assert_allclose(out_ref, out_tp, rtol=2e-4, atol=2e-5)

    def test_embedding_vocab_rows_sharded(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(3).updater(Sgd(learning_rate=0.1))
                .list(EmbeddingLayer(n_in=32, n_out=8,
                                     activation="identity"),
                      OutputLayer(n_in=8, n_out=4, activation="softmax",
                                  loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        specs = network_param_specs(net, model_size=2)
        assert specs["0"]["W"] == P(MODEL_AXIS, None)  # vocab rows

        mesh = data_model_mesh(2, 2)
        trainer = ShardedTrainer(net, mesh)
        rs = np.random.RandomState(1)
        x = rs.randint(0, 32, (8, 1)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rs.randint(0, 4, 8)]
        before = np.asarray(net.params["0"]["W"]).copy()
        trainer.fit(DataSet(x, y))
        assert np.isfinite(float(net.score_value))
        assert not np.allclose(before, np.asarray(net.params["0"]["W"]))

    def test_batch_not_divisible_raises(self):
        net = _cnn()
        trainer = ShardedTrainer(net, data_model_mesh(2, 2))
        bad = DataSet(np.zeros((3, 12, 12, 1), np.float32),
                      np.eye(4, dtype=np.float32)[np.zeros(3, int)])
        with pytest.raises(ValueError, match="not divisible"):
            trainer.fit(bad)

    def test_updater_state_sharded_like_params(self):
        net = _cnn()
        ShardedTrainer(net, data_model_mesh(2, 2))
        assert net.updater_state["m"]["0"]["W"].sharding.spec == P(
            None, None, None, MODEL_AXIS)


@pytest.mark.slow
class TestZooTensorParallel:
    def test_vgg16_trains_dp_tp(self):
        from deeplearning4j_tpu.models import VGG16

        net = VGG16(num_labels=8, input_shape=(32, 32, 3)).init()
        mesh = data_model_mesh(2, 4)
        trainer = ShardedTrainer(net, mesh)
        # all VGG conv stacks (64..512 channels) divide by 4: every kernel
        # is genuinely tensor-sharded
        assert net.params["0"]["W"].sharding.spec == P(
            None, None, None, MODEL_AXIS)
        rs = np.random.RandomState(0)
        x = rs.randn(4, 32, 32, 3).astype(np.float32)
        y = np.eye(8, dtype=np.float32)[rs.randint(0, 8, 4)]
        trainer.fit(DataSet(x, y))
        assert np.isfinite(float(net.score_value))

    def test_transformer_lm_trains_dp_tp(self):
        """The transformer's attention/FFN weight matrices tensor-shard
        over the model axis; a GSPMD train step stays finite and matches
        the unsharded step numerically."""
        from deeplearning4j_tpu.models import TransformerLM

        V, T = 8, 8
        rs = np.random.RandomState(2)
        idx = rs.randint(0, V, (4, T + 1))
        x = np.eye(V, dtype=np.float32)[idx[:, :-1]]
        y = np.eye(V, dtype=np.float32)[idx[:, 1:]]

        def train(sharded):
            net = TransformerLM(num_labels=V, max_length=T, d_model=16,
                                n_heads=2, n_blocks=1, seed=4).init()
            if sharded:
                trainer = ShardedTrainer(net, data_model_mesh(2, 4))
                # FFN expansion [16, 64] shards on the model axis
                assert net.params["ff0a"]["W"].sharding.spec == P(
                    None, MODEL_AXIS)
                trainer.fit(DataSet(x, y))
            else:
                net.fit(DataSet(x, y))
            return net

        a, b = train(False), train(True)
        assert np.isfinite(float(b.score_value))
        for k in a.params:
            for name in a.params[k]:
                np.testing.assert_allclose(
                    np.asarray(b.params[k][name]),
                    np.asarray(a.params[k][name]), rtol=5e-4, atol=1e-5,
                    err_msg=f"{k}/{name}")
