"""Streaming ingestion: producer-thread push training, backpressure,
collation (ports the intent of dl4j-streaming's Kafka route tests,
clusterlessly — the boundary is tested, the broker client is out of
scope)."""

import queue
import threading
import time

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.streaming import (
    ExampleCollator,
    QueueDataSetIterator,
    StreamingDataSetIterator,
)
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam


def _net():
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Adam(learning_rate=0.01))
            .list(DenseLayer(n_out=8, activation="relu"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _batch(rs, n=16):
    labels = rs.randint(0, 2, n)
    return DataSet((rs.randn(n, 4) + labels[:, None]).astype(np.float32),
                   np.eye(2, dtype=np.float32)[labels])


class TestQueueIterator:
    def test_train_from_producer_thread(self):
        it = QueueDataSetIterator(maxsize=4)
        rs = np.random.RandomState(0)

        def produce():
            for _ in range(12):
                it.put(_batch(rs))
                time.sleep(0.002)  # trickle like a real stream
            it.end()

        t = threading.Thread(target=produce)
        t.start()
        net = _net()
        net.fit(it)          # drains the stream as one pass
        t.join()
        assert net.iteration == 12

    def test_backpressure_blocks_producer(self):
        it = QueueDataSetIterator(maxsize=2)
        rs = np.random.RandomState(1)
        it.put(_batch(rs))
        it.put(_batch(rs))
        try:
            it.put(_batch(rs), timeout=0.1)
        except queue.Full:
            return
        raise AssertionError("expected queue.Full under backpressure")

    def test_put_after_end_rejected(self):
        it = QueueDataSetIterator()
        it.end()
        rs = np.random.RandomState(2)
        try:
            it.put(_batch(rs))
        except RuntimeError:
            return
        raise AssertionError("expected RuntimeError")

    def test_second_pass_after_end_terminates(self):
        it = QueueDataSetIterator()
        rs = np.random.RandomState(5)
        it.put(_batch(rs))
        it.end()
        assert len(list(it)) == 1
        assert list(it) == []  # drained stream: ends, does not deadlock

    def test_end_with_full_buffer_does_not_block(self):
        it = QueueDataSetIterator(maxsize=1)
        rs = np.random.RandomState(6)
        it.put(_batch(rs))
        t0 = time.time()
        it.end()               # buffer full: must return immediately
        assert time.time() - t0 < 1.0
        assert len(list(it)) == 1


class TestStreamingIterator:
    def test_bounded_pass_over_endless_source(self):
        rs = np.random.RandomState(3)

        def endless():
            while True:
                yield _batch(rs)

        it = StreamingDataSetIterator(endless(), max_batches=5)
        net = _net()
        net.fit(it)
        assert net.iteration == 5
        # a second pass continues the same stream (no reset-to-start)
        net.fit(it)
        assert net.iteration == 10


class TestCollator:
    def test_collates_records_into_batches(self):
        sink = QueueDataSetIterator()
        col = ExampleCollator(batch_size=4, sink=sink)
        rs = np.random.RandomState(4)
        for i in range(10):
            col.add(rs.randn(3).astype(np.float32),
                    np.eye(2, dtype=np.float32)[i % 2])
        col.flush()
        sink.end()
        sizes = [ds.features.shape[0] for ds in sink]
        assert sizes == [4, 4, 2]

    def test_thread_safe_collation(self):
        col = ExampleCollator(batch_size=8)
        out = []
        rs_lock = threading.Lock()

        def worker(seed):
            rs = np.random.RandomState(seed)
            for _ in range(40):
                ds = col.add(rs.randn(3).astype(np.float32),
                             np.eye(2, dtype=np.float32)[0])
                if ds is not None:
                    with rs_lock:
                        out.append(ds)

        ts = [threading.Thread(target=worker, args=(s,)) for s in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        tail = col.flush()
        total = sum(d.features.shape[0] for d in out) + \
            (tail.features.shape[0] if tail is not None else 0)
        assert total == 160
        assert all(d.features.shape[0] == 8 for d in out)
