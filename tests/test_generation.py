"""Continuous-batching generation serving tests (parallel/generation.py).

Covers the GenerationServer contract end to end on the CPU mesh:
correctness (greedy bit-parity with greedy_generate, sampled parity with
sample_generate under the shared fold_in key schedule), scheduling
(EOS/max-tokens slot retirement, occupancy churn with ZERO decode-step
recompiles), and the PR-4 resilience posture carried over wholesale
(deadlines queued and mid-generation, admission watermark, chaos with
retries, typed hard-fault recovery, drain/close never leaving a hung
future). Streaming-mask unit tests for the attention layer ride along —
they are the layer-level property the prefill path depends on.
"""

import time
from contextlib import contextmanager

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import (TransformerLM, greedy_generate,
                                           lm_stream_forward,
                                           sample_generate)
from deeplearning4j_tpu.parallel.generation import GenerationServer
from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                    CircuitBreaker,
                                                    DeadlineExceeded,
                                                    ResilienceError,
                                                    RetryPolicy,
                                                    ServerOverloaded)

V = 17


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(num_labels=V, max_length=16, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


@pytest.fixture(scope="module")
def greedy_refs(lm):
    """Mixed-length request set + serial greedy references (computed while
    no server is live, so the reference scan programs compile without a
    concurrent cache writer)."""
    rs = np.random.RandomState(4)
    shapes = [(3, 6), (5, 4), (9, 5), (3, 5), (5, 6), (9, 4)]
    reqs = [(rs.randint(0, V, p), s) for p, s in shapes]
    refs = [greedy_generate(lm, p[None], s, V)[0] for p, s in reqs]
    return reqs, refs


@contextmanager
def serving(*args, **kwargs):
    srv = GenerationServer(*args, **kwargs)
    try:
        yield srv
    finally:
        srv.close()


@pytest.mark.generation
class TestGenerationCorrectness:
    def test_greedy_parity_mixed_length_concurrent(self, lm, greedy_refs):
        """Six concurrent requests of three prompt lengths through three
        slots (occupancy churns as short requests retire) decode
        BIT-identically to per-request greedy_generate."""
        reqs, refs = greedy_refs
        with serving(lm, V, slots=3) as srv:
            futs = [srv.submit(p, s) for p, s in reqs]
            outs = [f.result(timeout=120) for f in futs]
            st = srv.stats()
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(got, ref)
        assert st["completed"] == len(reqs)
        assert st["failed"] == 0 and st["expired"] == 0
        assert st["prefills"] == len(reqs)
        assert st["tokens_generated"] == sum(s for _, s in reqs)

    def test_sampled_parity_and_determinism(self, lm):
        """Sampled requests share sample_generate's per-token key schedule
        (fold_in(PRNGKey(seed), token_index)), so the pooled batch-S path
        reproduces the serial batch-1 path exactly; same seed twice in
        DIFFERENT slots of one batch is also identical."""
        rs = np.random.RandomState(5)
        prompt = rs.randint(0, V, 4)
        ref = sample_generate(lm, prompt[None], 6, V, temperature=0.9,
                              top_k=5, seed=7)[0]
        with serving(lm, V, slots=3) as srv:
            f1 = srv.submit(prompt, 6, temperature=0.9, top_k=5, seed=7)
            f2 = srv.submit(prompt, 6, temperature=0.9, top_k=5, seed=7)
            a, b = f1.result(timeout=120), f2.result(timeout=120)
        np.testing.assert_array_equal(a, ref)
        np.testing.assert_array_equal(a, b)

    def test_mixed_sampling_params_one_batch(self, lm, greedy_refs):
        """Greedy and sampled requests coexist in one pooled batch (the
        params are traced per-slot values): the greedy row still matches
        its serial reference exactly."""
        reqs, refs = greedy_refs
        (gp, gs), gref = reqs[0], refs[0]
        rs = np.random.RandomState(8)
        sp = rs.randint(0, V, 5)
        sref = sample_generate(lm, sp[None], 4, V, temperature=1.3,
                               top_k=0, seed=11)[0]
        with serving(lm, V, slots=3) as srv:
            fg = srv.submit(gp, gs)
            fs = srv.submit(sp, 4, temperature=1.3, top_k=0, seed=11)
            np.testing.assert_array_equal(fg.result(timeout=120), gref)
            np.testing.assert_array_equal(fs.result(timeout=120), sref)

    def test_eos_retires_slot_early(self, lm, greedy_refs):
        """A per-request eos_id truncates the output at (and including)
        the EOS token and frees the slot; a sibling request without EOS
        runs to max_tokens untouched."""
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        (p1, s1), ref1 = reqs[1], refs[1]
        eos = int(ref0[3])
        k = int(np.where(ref0 == eos)[0][0])        # first occurrence
        with serving(lm, V, slots=3) as srv:
            fe = srv.submit(p0, s0, eos_id=eos)
            fn = srv.submit(p1, s1)
            got = fe.result(timeout=120)
            np.testing.assert_array_equal(fn.result(timeout=120), ref1)
            st = srv.stats()
        np.testing.assert_array_equal(got, ref0[:k + 1])
        assert len(got) == k + 1 < s0               # actually truncated
        assert st["completed"] == 2

    def test_submit_validation(self, lm):
        with serving(lm, V, slots=3) as srv:
            with pytest.raises(ValueError, match="prompt_ids"):
                srv.submit(np.zeros((0,), np.int64), 4)
            with pytest.raises(ValueError, match="prompt_ids"):
                srv.submit(np.zeros((2, 3), np.int64), 4)
            with pytest.raises(ValueError, match="max_tokens"):
                srv.submit(np.array([1, 2]), 0)
            with pytest.raises(ValueError, match="temperature"):
                srv.submit(np.array([1, 2]), 4, temperature=-1.0)
            with pytest.raises(ValueError, match="top_k"):
                srv.submit(np.array([1, 2]), 4, top_k=V + 1)
            # infeasible size is a typed, shed-able overload — admission
            # rejects up front, never mid-prefill after a slot is burned
            with pytest.raises(ServerOverloaded, match="capacity"):
                srv.submit(np.array([1, 2]), 100000)

    def test_rejects_model_without_kv_carry(self):
        """GenerationServer serves explicit-KV-carry streamers; a model
        whose streaming carry is not seedable up front fails at
        construction, not mid-serve."""
        from deeplearning4j_tpu.nn.conf.builders import \
            NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                       OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(0)
                .weight_init("xavier").activation("relu")
                .list(DenseLayer(n_out=8),
                      OutputLayer(n_out=3, loss="mcxent",
                                  activation="softmax"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        with pytest.raises(ValueError, match="KV carry"):
            GenerationServer(net, 3, slots=2)


@pytest.mark.generation
class TestGenerationScheduling:
    def test_no_recompile_on_occupancy_churn(self):
        """The whole point of page pooling: after warmup (ONE decode
        program, one prefill program per PAGE bucket, one page-copy
        program), arbitrary occupancy churn — admits, retirements, mixed
        lengths, idle slots, page sharing and COW — adds ZERO compiled
        programs. Block tables, positions and refcounts are all data."""
        net = TransformerLM(num_labels=V, max_length=16, d_model=8,
                            n_heads=2, n_blocks=1, seed=9).init()
        rs = np.random.RandomState(0)
        with serving(net, V, slots=3, min_prefill_bucket=4) as srv:
            base = len(net._output_cache)
            warm = [srv.submit(rs.randint(0, V, 3), 5),
                    srv.submit(rs.randint(0, V, 7), 2)]
            for f in warm:
                f.result(timeout=120)
            warmed = len(net._output_cache)
            # the decode step, the 1-page prefill bucket (every prompt
            # here covers one page, so they ALL share one program), and
            # the COW page-copy — nothing else
            assert warmed - base == 3

            churn = [(4, 3), (2, 7), (6, 1), (8, 4), (3, 2), (5, 6)]
            futs = []
            for plen, mt in churn:
                futs.append(srv.submit(rs.randint(0, V, plen), mt))
                time.sleep(0.02)  # stagger: arrive at varied occupancy
            for f, (_plen, mt) in zip(futs, churn):
                assert f.result(timeout=120).shape == (mt,)
            assert len(net._output_cache) == warmed
            st = srv.stats()
        assert st["completed"] == 8
        assert st["decode_steps"] > 0

    def test_deadline_expired_while_queued(self, lm, greedy_refs):
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        with serving(lm, V, slots=3) as srv:
            f = srv.submit(p0, s0, deadline_s=0.0)
            with pytest.raises(DeadlineExceeded, match="queued"):
                f.result(timeout=30)
            # the server is unharmed: the next request serves normally
            np.testing.assert_array_equal(
                srv.submit(p0, s0).result(timeout=120), ref0)
            st = srv.stats()
        assert st["expired"] == 1 and st["completed"] == 1

    def test_deadline_expired_mid_generation(self, lm, greedy_refs):
        """A request whose budget runs out mid-decode fails typed AND
        frees its slot — with every dispatch slowed by injected latency
        the 200-token ask cannot finish inside 180 ms."""
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        chaos = ChaosPolicy(latency_rate=1.0, latency_s=0.05)
        with serving(lm, V, slots=3, chaos=chaos) as srv:
            f = srv.submit(p0, 200, deadline_s=0.18)
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=30)
            st = srv.stats()
            assert st["expired"] == 1
            assert st["active_slots"] == 0              # slot freed
            chaos.latency_rate = 0.0
            np.testing.assert_array_equal(
                srv.submit(p0, s0).result(timeout=120), ref0)

    def test_admission_watermark_sheds_load(self, lm, greedy_refs):
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        chaos = ChaosPolicy(latency_rate=1.0, latency_s=0.2)
        with serving(lm, V, slots=3, max_pending=1, chaos=chaos) as srv:
            f1 = srv.submit(p0, s0)
            with pytest.raises(ServerOverloaded):
                srv.submit(p0, s0)
            np.testing.assert_array_equal(f1.result(timeout=120), ref0)
            # admission released on resolution: capacity is back
            chaos.latency_rate = 0.0
            np.testing.assert_array_equal(
                srv.submit(p0, s0).result(timeout=120), ref0)
            st = srv.stats()
        assert st["rejected"] == 1 and st["completed"] == 2


@pytest.mark.generation
class TestGenerationResilience:
    def test_chaos_transients_retry_zero_lost_futures(self, lm,
                                                      greedy_refs):
        """Under a 35% transient-fault rate every future still resolves —
        almost always to the exact greedy reference (retries), in the
        worst case to a typed ResilienceError — and never hangs."""
        reqs, refs = greedy_refs
        chaos = ChaosPolicy(seed=2, transient_rate=0.35)
        retry = RetryPolicy(max_attempts=6, base_s=0.001, cap_s=0.01,
                            seed=0, sleep=lambda _s: None)
        breaker = CircuitBreaker(failure_threshold=1.1)  # never trips
        with serving(lm, V, slots=3, retry=retry, breaker=breaker,
                     chaos=chaos) as srv:
            futs = [srv.submit(p, s) for p, s in reqs]
            ok = 0
            for f, ref in zip(futs, refs):
                try:
                    got = f.result(timeout=120)
                except ResilienceError:
                    continue  # typed, not lost — acceptable under chaos
                np.testing.assert_array_equal(got, ref)
                ok += 1
            st = srv.stats()
        assert all(f.done() for f in futs)              # zero lost
        assert ok >= 1                                  # retries do work
        assert chaos.injected_transient > 0
        assert st["retried"] > 0

    def test_hard_decode_fault_fails_typed_and_recovers(self, lm,
                                                        greedy_refs):
        """A hard (non-retryable) decode fault fails the in-flight batch
        typed, the pooled carry is rebuilt from zeros, and the next
        request decodes correctly — the server never wedges."""
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        chaos = ChaosPolicy(latency_rate=1.0, latency_s=0.05)
        breaker = CircuitBreaker(failure_threshold=1.1)
        with serving(lm, V, slots=3, breaker=breaker, chaos=chaos) as srv:
            f = srv.submit(p0, 200)
            for _ in range(600):                  # wait until mid-decode
                if srv.stats()["prefills"] >= 1:
                    break
                time.sleep(0.01)
            chaos.hard_rate = 1.0                 # next dispatch dies hard
            with pytest.raises(RuntimeError, match="hard fault"):
                f.result(timeout=30)
            chaos.hard_rate = 0.0
            chaos.latency_rate = 0.0
            np.testing.assert_array_equal(
                srv.submit(p0, s0).result(timeout=120), ref0)
            st = srv.stats()
        assert st["failed"] >= 1 and st["completed"] == 1

    def test_drain_resolves_everything(self, lm, greedy_refs):
        reqs, refs = greedy_refs
        with serving(lm, V, slots=2) as srv:
            futs = [srv.submit(p, s) for p, s in reqs]
            assert srv.drain(timeout=120)
            assert all(f.done() for f in futs)
            for f, ref in zip(futs, refs):
                np.testing.assert_array_equal(f.result(timeout=1), ref)

    def test_close_fails_stragglers_typed(self, lm):
        """close() with work still in flight past its timeout resolves
        the stragglers with a typed error instead of leaving hung
        futures; submitting after close is refused."""
        rs = np.random.RandomState(12)
        chaos = ChaosPolicy(latency_rate=1.0, latency_s=0.25)
        srv = GenerationServer(lm, V, slots=3, chaos=chaos)
        f = srv.submit(rs.randint(0, V, 3), 400)
        srv.close(timeout=0.3)
        assert f.done()
        with pytest.raises(RuntimeError, match="closed"):
            f.result(timeout=1)
        with pytest.raises(RuntimeError, match="closed"):
            srv.submit(rs.randint(0, V, 3), 2)


@pytest.mark.generation
class TestStreamingMask:
    """Layer-level property the prefill bucket path depends on: a right-
    padded prompt with a [B, T] validity mask streams identically to the
    unpadded prompt, and inapplicable mask shapes fail loudly."""

    def _carry(self, lm, batch=1):
        lm.rnn_clear_previous_state()
        seed = lm._seed_streaming_carry(batch)
        lm.rnn_clear_previous_state()
        return seed

    def test_masked_right_pad_matches_unpadded(self, lm):
        rs = np.random.RandomState(13)
        plen, bucket = 5, 8
        ids = rs.randint(0, V, plen)
        eye = np.eye(V, dtype=np.float32)
        fwd = lm_stream_forward(lm)

        x_pad = np.zeros((1, bucket, V), np.float32)
        x_pad[0, :plen] = eye[ids]
        mask = np.zeros((1, bucket), np.float32)
        mask[0, :plen] = 1
        out_pad, _ = fwd(lm.params, lm.state, x_pad, self._carry(lm), mask)
        out_raw, _ = fwd(lm.params, lm.state, eye[ids][None],
                         self._carry(lm), None)
        # true positions identical; the padded tail is garbage the caller
        # never reads (prefill samples from position plen-1 only)
        np.testing.assert_allclose(np.asarray(out_pad)[:, :plen],
                                   np.asarray(out_raw), atol=1e-6)

    def test_bad_mask_shape_raises(self, lm):
        rs = np.random.RandomState(14)
        x = np.eye(V, dtype=np.float32)[rs.randint(0, V, 4)][None]
        fwd = lm_stream_forward(lm)
        with pytest.raises(ValueError, match="streaming attention mask"):
            fwd(lm.params, lm.state, x, self._carry(lm),
                np.ones((1, 4, 1), np.float32))
        with pytest.raises(ValueError, match="streaming attention mask"):
            fwd(lm.params, lm.state, x, self._carry(lm),
                np.ones((2, 4), np.float32))  # batch mismatch


@pytest.mark.generation
class TestGenerationLockDiscipline:
    """Targeted regressions for the graftcheck generation-lock fixes:
    the closing flag is checked under self._cond in submit(), and the
    decode counters are batched into one condition acquisition per step."""

    def test_submit_close_race_never_hangs(self, lm):
        import threading

        srv = GenerationServer(lm, V, slots=2)
        futs, refused = [], []
        go = threading.Event()

        def submitter(i):
            go.wait(10)
            try:
                futs.append(srv.submit(np.array([1 + i % 5]), 3))
            except (RuntimeError, ResilienceError) as e:
                refused.append(e)  # typed refusal is a valid outcome

        ts = [threading.Thread(target=submitter, args=(i,))
              for i in range(8)]
        for t in ts:
            t.start()
        go.set()
        srv.close()
        for t in ts:
            t.join(30)
        assert len(futs) + len(refused) == 8
        for f in futs:
            try:
                f.result(timeout=30)
            except Exception:
                pass  # resolved with an error: fine — just never hung
            assert f.done()

    def test_counters_batched_per_decode_step(self, lm):
        with serving(lm, V, slots=2) as srv:
            futs = [srv.submit(np.array([1, 2, 3]), 4) for _ in range(3)]
            outs = [f.result(timeout=120) for f in futs]
            st = srv.stats()
        assert st["prefills"] == 3
        assert st["completed"] == 3
        # every generated token is counted exactly once, via ONE condition
        # acquisition per decode step (not one per token)
        assert st["tokens_generated"] == sum(len(o) for o in outs)
        assert 1 <= st["decode_steps"] <= 4 * 3


@pytest.mark.generation
class TestPagedSharing:
    """Paged-pool properties layered on the serving contract: prefix
    sharing with copy-on-write parity, page-budget admission, preemption
    under pool pressure, and refcounts draining to zero when the server
    empties."""

    def test_prefix_sharing_cow_parity(self, lm):
        """Two prompts sharing a 32-token (two-page) prefix: the second
        adopts the first's registered pages read-only and prefills only
        its suffix — outputs stay BIT-identical to the serial references
        because every divergent write copies the page off first."""
        rs = np.random.RandomState(21)
        pre = rs.randint(0, V, 32)
        p1 = np.concatenate([pre, rs.randint(0, V, 5)])
        p2 = np.concatenate([pre, rs.randint(0, V, 7)])
        r1 = greedy_generate(lm, p1[None], 4, V)[0]
        r2 = greedy_generate(lm, p2[None], 4, V)[0]
        with serving(lm, V, slots=2) as srv:
            np.testing.assert_array_equal(
                srv.submit(p1, 4).result(timeout=120), r1)
            np.testing.assert_array_equal(
                srv.submit(p2, 4).result(timeout=120), r2)
            pg = srv.stats()["pages"]
        assert pg["prefix_hits"] >= 1
        assert pg["prefix_tokens_reused"] >= 32     # both prefix pages
        assert pg["cow_copies"] >= 1                # divergence copied off

    def test_identical_prompt_tail_page_shared(self, lm):
        """A byte-identical re-submission (same seed) reuses everything
        up to the LAST prompt token — the partial tail page is shared via
        the whole-prompt digest — and still matches exactly."""
        rs = np.random.RandomState(22)
        p = rs.randint(0, V, 11)                    # sub-page prompt
        ref = greedy_generate(lm, p[None], 5, V)[0]
        with serving(lm, V, slots=2) as srv:
            np.testing.assert_array_equal(
                srv.submit(p, 5).result(timeout=120), ref)
            np.testing.assert_array_equal(
                srv.submit(p, 5).result(timeout=120), ref)
            pg = srv.stats()["pages"]
        assert pg["prefix_hits"] == 1
        assert pg["prefix_tokens_reused"] == 10     # plen - 1

    def test_refcounts_drain_when_idle(self, lm):
        """After every request resolves, no page is refcounted: the pool
        is free pages + reclaimable prefix-cache pages, nothing leaked."""
        rs = np.random.RandomState(23)
        with serving(lm, V, slots=3) as srv:
            futs = [srv.submit(rs.randint(0, V, 4 + i), 3)
                    for i in range(5)]
            for f in futs:
                f.result(timeout=120)
            assert srv.drain(timeout=60)
            pg = srv.stats()["pages"]
        assert pg["pages_refcounted"] == 0
        assert pg["pages_free"] + pg["pages_cached"] \
            == pg["pages_total"] - 1                # all but garbage page

    def test_page_budget_admission(self, lm):
        """submit() validates the whole-lifetime page need against the
        pool budget up front: an infeasible request is a typed
        ServerOverloaded before any slot or page is consumed, and a
        feasible one on the same server still serves exactly."""
        rs = np.random.RandomState(24)
        p = rs.randint(0, V, 3)
        ref = greedy_generate(lm, p[None], 4, V)[0]
        with serving(lm, V, slots=2, pages=4) as srv:  # 3 usable pages
            with pytest.raises(ServerOverloaded, match="page"):
                srv.submit(p, 60)                   # needs 4 pages
            np.testing.assert_array_equal(
                srv.submit(p, 4).result(timeout=120), ref)
            st = srv.stats()
        assert st["completed"] == 1 and st["failed"] == 0

    def test_preemption_under_pool_pressure(self, lm):
        """Two long requests whose combined page need exceeds the pool:
        the newest slot is preempted (pages freed, request requeued at
        the FRONT) — and because decode is deterministic under the
        fold_in key schedule, BOTH still complete bit-exactly."""
        rs = np.random.RandomState(25)
        pa = rs.randint(0, V, 40)                   # 3 pages of prompt
        pb = rs.randint(0, V, 40)
        ra = greedy_generate(lm, pa[None], 30, V)[0]
        rb = greedy_generate(lm, pb[None], 30, V)[0]
        # each request needs 5 pages end to end; 9 usable < 10 combined
        with serving(lm, V, slots=2, pages=10, prefix_cache=False) as srv:
            fa = srv.submit(pa, 30)
            fb = srv.submit(pb, 30)
            np.testing.assert_array_equal(fa.result(timeout=180), ra)
            np.testing.assert_array_equal(fb.result(timeout=180), rb)
            st = srv.stats()
        assert st["pages"]["preempted"] >= 1
        assert st["completed"] == 2 and st["failed"] == 0

    def test_lru_eviction_reclaims_cached_pages(self, lm):
        """Prefix-cache pages are reclaimable, not leaked: when the free
        list runs dry the oldest unreferenced cached page is evicted to
        serve new allocations, and serving continues exactly."""
        rs = np.random.RandomState(26)
        prompts = [rs.randint(0, V, 16) for _ in range(6)]
        refs = [greedy_generate(lm, p[None], 3, V)[0] for p in prompts]
        # 6 distinct one-page prompts through a 4-usable-page pool: the
        # prefix cache must evict to keep admitting
        with serving(lm, V, slots=1, pages=5) as srv:
            for p, ref in zip(prompts, refs):
                np.testing.assert_array_equal(
                    srv.submit(p, 3).result(timeout=120), ref)
            pg = srv.stats()["pages"]
        assert pg["evictions"] >= 1
        assert pg["pages_refcounted"] == 0


@pytest.mark.generation
class TestSpeculative:
    """Speculative decoding: the draft proposes, the target verifies all
    K positions in one chunked dispatch, and every emitted token is the
    TARGET's selection under the serial fold_in schedule — so outputs are
    bit-exact regardless of draft quality."""

    def test_perfect_draft_all_accept(self, lm, greedy_refs):
        """Draft == target: every proposal verifies, the accept rate is
        ~1, and all completions match the serial references exactly."""
        reqs, refs = greedy_refs
        with serving(lm, V, slots=3, draft_net=lm, spec_k=3) as srv:
            futs = [srv.submit(p, s) for p, s in reqs]
            outs = [f.result(timeout=180) for f in futs]
            pg = srv.stats()["pages"]
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(got, ref)
        assert pg["spec_rounds"] > 0
        assert pg["spec_accept_rate"] > 0.9

    def test_mismatched_draft_still_bit_exact(self, lm, greedy_refs):
        """A draft with unrelated weights proposes mostly-rejected tokens:
        throughput degrades, correctness does not — greedy AND sampled
        completions still match the serial paths token-for-token."""
        reqs, refs = greedy_refs
        draft = TransformerLM(num_labels=V, max_length=16, d_model=8,
                              n_heads=2, n_blocks=1, seed=99).init()
        rs = np.random.RandomState(31)
        sp = rs.randint(0, V, 4)
        sref = sample_generate(lm, sp[None], 6, V, temperature=0.9,
                               top_k=5, seed=7)[0]
        with serving(lm, V, slots=3, draft_net=draft, spec_k=4) as srv:
            futs = [srv.submit(p, s) for p, s in reqs]
            fs = srv.submit(sp, 6, temperature=0.9, top_k=5, seed=7)
            outs = [f.result(timeout=180) for f in futs]
            sout = fs.result(timeout=180)
            pg = srv.stats()["pages"]
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(got, ref)
        np.testing.assert_array_equal(sout, sref)
        assert pg["spec_accept_rate"] < 1.0

    def test_eos_mid_speculative_round(self, lm, greedy_refs):
        """EOS produced inside a verified chunk truncates the emission at
        (and including) the EOS token, exactly as the serial path."""
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        eos = int(ref0[3])
        k = int(np.where(ref0 == eos)[0][0])
        with serving(lm, V, slots=2, draft_net=lm, spec_k=4) as srv:
            got = srv.submit(p0, s0, eos_id=eos).result(timeout=180)
        np.testing.assert_array_equal(got, ref0[:k + 1])

    def test_spec_zero_recompiles_on_churn(self):
        """Speculative serving compiles one spec round + one draft
        prefill per token bucket (on the DRAFT's cache) and one target
        prefill per page bucket + the page copy (on the target's) — then
        occupancy churn and accept/reject variation add ZERO programs."""
        net = TransformerLM(num_labels=V, max_length=16, d_model=8,
                            n_heads=2, n_blocks=1, seed=9).init()
        draft = TransformerLM(num_labels=V, max_length=16, d_model=8,
                              n_heads=2, n_blocks=1, seed=10).init()
        rs = np.random.RandomState(32)
        with serving(net, V, slots=3, draft_net=draft, spec_k=3) as srv:
            nb, db = len(net._output_cache), len(draft._output_cache)
            warm = [srv.submit(rs.randint(0, V, 3), 5),
                    srv.submit(rs.randint(0, V, 7), 2)]
            for f in warm:
                f.result(timeout=180)
            nw, dw = len(net._output_cache), len(draft._output_cache)
            assert nw - nb == 2     # page-bucket prefill + page copy
            assert dw - db == 2     # spec round + draft prefill bucket
            churn = [(4, 3), (2, 7), (6, 1), (8, 4), (3, 2), (5, 6)]
            futs = [srv.submit(rs.randint(0, V, plen), mt)
                    for plen, mt in churn]
            for f, (_plen, mt) in zip(futs, churn):
                assert f.result(timeout=180).shape == (mt,)
            assert len(net._output_cache) == nw
            assert len(draft._output_cache) == dw

    def test_draft_validation(self, lm):
        """Constructor contract: spec_k < 2 and a draft that cannot reach
        the target's positions are loud construction-time errors."""
        with pytest.raises(ValueError, match="spec_k"):
            GenerationServer(lm, V, slots=2, draft_net=lm, spec_k=1)


@pytest.mark.generation
class TestBucketPages:
    """bucket_pages: the page-granular sibling of bucket_length that the
    paged prefill keys its program cache on."""

    def test_pow2_page_counts(self):
        from deeplearning4j_tpu.optimize.bucketing import bucket_pages
        assert bucket_pages(1, 16) == 1
        assert bucket_pages(16, 16) == 1
        assert bucket_pages(17, 16) == 2
        assert bucket_pages(40, 16) == 4            # ceil 3 -> pow2 4
        # distant token counts collapse onto one page bucket
        assert bucket_pages(810, 16) == bucket_pages(900, 16) == 64

    def test_maximum_caps_and_rejects(self):
        from deeplearning4j_tpu.optimize.bucketing import bucket_pages
        assert bucket_pages(70, 16, maximum=5) == 5  # pow2 8 capped at 5
        with pytest.raises(ValueError, match="page budget"):
            bucket_pages(81, 16, maximum=5)          # 81 > 5*16
        with pytest.raises(ValueError, match="page_size"):
            bucket_pages(8, 0)
        with pytest.raises(ValueError, match="token"):
            bucket_pages(0, 16)
