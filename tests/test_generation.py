"""Continuous-batching generation serving tests (parallel/generation.py).

Covers the GenerationServer contract end to end on the CPU mesh:
correctness (greedy bit-parity with greedy_generate, sampled parity with
sample_generate under the shared fold_in key schedule), scheduling
(EOS/max-tokens slot retirement, occupancy churn with ZERO decode-step
recompiles), and the PR-4 resilience posture carried over wholesale
(deadlines queued and mid-generation, admission watermark, chaos with
retries, typed hard-fault recovery, drain/close never leaving a hung
future). Streaming-mask unit tests for the attention layer ride along —
they are the layer-level property the prefill path depends on.
"""

import time
from contextlib import contextmanager

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import (TransformerLM, greedy_generate,
                                           lm_stream_forward,
                                           sample_generate)
from deeplearning4j_tpu.parallel.generation import GenerationServer
from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                    CircuitBreaker,
                                                    DeadlineExceeded,
                                                    ResilienceError,
                                                    RetryPolicy,
                                                    ServerOverloaded)

V = 17


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(num_labels=V, max_length=16, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


@pytest.fixture(scope="module")
def greedy_refs(lm):
    """Mixed-length request set + serial greedy references (computed while
    no server is live, so the reference scan programs compile without a
    concurrent cache writer)."""
    rs = np.random.RandomState(4)
    shapes = [(3, 6), (5, 4), (9, 5), (3, 5), (5, 6), (9, 4)]
    reqs = [(rs.randint(0, V, p), s) for p, s in shapes]
    refs = [greedy_generate(lm, p[None], s, V)[0] for p, s in reqs]
    return reqs, refs


@contextmanager
def serving(*args, **kwargs):
    srv = GenerationServer(*args, **kwargs)
    try:
        yield srv
    finally:
        srv.close()


@pytest.mark.generation
class TestGenerationCorrectness:
    def test_greedy_parity_mixed_length_concurrent(self, lm, greedy_refs):
        """Six concurrent requests of three prompt lengths through three
        slots (occupancy churns as short requests retire) decode
        BIT-identically to per-request greedy_generate."""
        reqs, refs = greedy_refs
        with serving(lm, V, slots=3) as srv:
            futs = [srv.submit(p, s) for p, s in reqs]
            outs = [f.result(timeout=120) for f in futs]
            st = srv.stats()
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(got, ref)
        assert st["completed"] == len(reqs)
        assert st["failed"] == 0 and st["expired"] == 0
        assert st["prefills"] == len(reqs)
        assert st["tokens_generated"] == sum(s for _, s in reqs)

    def test_sampled_parity_and_determinism(self, lm):
        """Sampled requests share sample_generate's per-token key schedule
        (fold_in(PRNGKey(seed), token_index)), so the pooled batch-S path
        reproduces the serial batch-1 path exactly; same seed twice in
        DIFFERENT slots of one batch is also identical."""
        rs = np.random.RandomState(5)
        prompt = rs.randint(0, V, 4)
        ref = sample_generate(lm, prompt[None], 6, V, temperature=0.9,
                              top_k=5, seed=7)[0]
        with serving(lm, V, slots=3) as srv:
            f1 = srv.submit(prompt, 6, temperature=0.9, top_k=5, seed=7)
            f2 = srv.submit(prompt, 6, temperature=0.9, top_k=5, seed=7)
            a, b = f1.result(timeout=120), f2.result(timeout=120)
        np.testing.assert_array_equal(a, ref)
        np.testing.assert_array_equal(a, b)

    def test_mixed_sampling_params_one_batch(self, lm, greedy_refs):
        """Greedy and sampled requests coexist in one pooled batch (the
        params are traced per-slot values): the greedy row still matches
        its serial reference exactly."""
        reqs, refs = greedy_refs
        (gp, gs), gref = reqs[0], refs[0]
        rs = np.random.RandomState(8)
        sp = rs.randint(0, V, 5)
        sref = sample_generate(lm, sp[None], 4, V, temperature=1.3,
                               top_k=0, seed=11)[0]
        with serving(lm, V, slots=3) as srv:
            fg = srv.submit(gp, gs)
            fs = srv.submit(sp, 4, temperature=1.3, top_k=0, seed=11)
            np.testing.assert_array_equal(fg.result(timeout=120), gref)
            np.testing.assert_array_equal(fs.result(timeout=120), sref)

    def test_eos_retires_slot_early(self, lm, greedy_refs):
        """A per-request eos_id truncates the output at (and including)
        the EOS token and frees the slot; a sibling request without EOS
        runs to max_tokens untouched."""
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        (p1, s1), ref1 = reqs[1], refs[1]
        eos = int(ref0[3])
        k = int(np.where(ref0 == eos)[0][0])        # first occurrence
        with serving(lm, V, slots=3) as srv:
            fe = srv.submit(p0, s0, eos_id=eos)
            fn = srv.submit(p1, s1)
            got = fe.result(timeout=120)
            np.testing.assert_array_equal(fn.result(timeout=120), ref1)
            st = srv.stats()
        np.testing.assert_array_equal(got, ref0[:k + 1])
        assert len(got) == k + 1 < s0               # actually truncated
        assert st["completed"] == 2

    def test_submit_validation(self, lm):
        with serving(lm, V, slots=3) as srv:
            with pytest.raises(ValueError, match="prompt_ids"):
                srv.submit(np.zeros((0,), np.int64), 4)
            with pytest.raises(ValueError, match="prompt_ids"):
                srv.submit(np.zeros((2, 3), np.int64), 4)
            with pytest.raises(ValueError, match="max_tokens"):
                srv.submit(np.array([1, 2]), 0)
            with pytest.raises(ValueError, match="temperature"):
                srv.submit(np.array([1, 2]), 4, temperature=-1.0)
            with pytest.raises(ValueError, match="top_k"):
                srv.submit(np.array([1, 2]), 4, top_k=V + 1)
            with pytest.raises(ValueError, match="capacity"):
                srv.submit(np.array([1, 2]), 100000)

    def test_rejects_model_without_kv_carry(self):
        """GenerationServer serves explicit-KV-carry streamers; a model
        whose streaming carry is not seedable up front fails at
        construction, not mid-serve."""
        from deeplearning4j_tpu.nn.conf.builders import \
            NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                       OutputLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(0)
                .weight_init("xavier").activation("relu")
                .list(DenseLayer(n_out=8),
                      OutputLayer(n_out=3, loss="mcxent",
                                  activation="softmax"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        with pytest.raises(ValueError, match="KV carry"):
            GenerationServer(net, 3, slots=2)


@pytest.mark.generation
class TestGenerationScheduling:
    def test_no_recompile_on_occupancy_churn(self):
        """The whole point of slot pooling: after warmup (ONE decode
        program + one prefill program per pow2 prompt bucket), arbitrary
        occupancy churn — admits, retirements, mixed lengths, idle slots
        — adds ZERO compiled programs."""
        net = TransformerLM(num_labels=V, max_length=16, d_model=8,
                            n_heads=2, n_blocks=1, seed=9).init()
        rs = np.random.RandomState(0)
        with serving(net, V, slots=3, min_prefill_bucket=4) as srv:
            base = len(net._output_cache)
            warm = [srv.submit(rs.randint(0, V, 3), 5),   # bucket 4
                    srv.submit(rs.randint(0, V, 7), 2)]   # bucket 8
            for f in warm:
                f.result(timeout=120)
            warmed = len(net._output_cache)
            # decode step + the two prefill buckets, nothing else
            assert warmed - base == 1 + 2

            churn = [(4, 3), (2, 7), (6, 1), (8, 4), (3, 2), (5, 6)]
            futs = []
            for plen, mt in churn:
                futs.append(srv.submit(rs.randint(0, V, plen), mt))
                time.sleep(0.02)  # stagger: arrive at varied occupancy
            for f, (_plen, mt) in zip(futs, churn):
                assert f.result(timeout=120).shape == (mt,)
            assert len(net._output_cache) == warmed
            st = srv.stats()
        assert st["completed"] == 8
        assert st["decode_steps"] > 0

    def test_deadline_expired_while_queued(self, lm, greedy_refs):
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        with serving(lm, V, slots=3) as srv:
            f = srv.submit(p0, s0, deadline_s=0.0)
            with pytest.raises(DeadlineExceeded, match="queued"):
                f.result(timeout=30)
            # the server is unharmed: the next request serves normally
            np.testing.assert_array_equal(
                srv.submit(p0, s0).result(timeout=120), ref0)
            st = srv.stats()
        assert st["expired"] == 1 and st["completed"] == 1

    def test_deadline_expired_mid_generation(self, lm, greedy_refs):
        """A request whose budget runs out mid-decode fails typed AND
        frees its slot — with every dispatch slowed by injected latency
        the 200-token ask cannot finish inside 180 ms."""
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        chaos = ChaosPolicy(latency_rate=1.0, latency_s=0.05)
        with serving(lm, V, slots=3, chaos=chaos) as srv:
            f = srv.submit(p0, 200, deadline_s=0.18)
            with pytest.raises(DeadlineExceeded):
                f.result(timeout=30)
            st = srv.stats()
            assert st["expired"] == 1
            assert st["active_slots"] == 0              # slot freed
            chaos.latency_rate = 0.0
            np.testing.assert_array_equal(
                srv.submit(p0, s0).result(timeout=120), ref0)

    def test_admission_watermark_sheds_load(self, lm, greedy_refs):
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        chaos = ChaosPolicy(latency_rate=1.0, latency_s=0.2)
        with serving(lm, V, slots=3, max_pending=1, chaos=chaos) as srv:
            f1 = srv.submit(p0, s0)
            with pytest.raises(ServerOverloaded):
                srv.submit(p0, s0)
            np.testing.assert_array_equal(f1.result(timeout=120), ref0)
            # admission released on resolution: capacity is back
            chaos.latency_rate = 0.0
            np.testing.assert_array_equal(
                srv.submit(p0, s0).result(timeout=120), ref0)
            st = srv.stats()
        assert st["rejected"] == 1 and st["completed"] == 2


@pytest.mark.generation
class TestGenerationResilience:
    def test_chaos_transients_retry_zero_lost_futures(self, lm,
                                                      greedy_refs):
        """Under a 35% transient-fault rate every future still resolves —
        almost always to the exact greedy reference (retries), in the
        worst case to a typed ResilienceError — and never hangs."""
        reqs, refs = greedy_refs
        chaos = ChaosPolicy(seed=2, transient_rate=0.35)
        retry = RetryPolicy(max_attempts=6, base_s=0.001, cap_s=0.01,
                            seed=0, sleep=lambda _s: None)
        breaker = CircuitBreaker(failure_threshold=1.1)  # never trips
        with serving(lm, V, slots=3, retry=retry, breaker=breaker,
                     chaos=chaos) as srv:
            futs = [srv.submit(p, s) for p, s in reqs]
            ok = 0
            for f, ref in zip(futs, refs):
                try:
                    got = f.result(timeout=120)
                except ResilienceError:
                    continue  # typed, not lost — acceptable under chaos
                np.testing.assert_array_equal(got, ref)
                ok += 1
            st = srv.stats()
        assert all(f.done() for f in futs)              # zero lost
        assert ok >= 1                                  # retries do work
        assert chaos.injected_transient > 0
        assert st["retried"] > 0

    def test_hard_decode_fault_fails_typed_and_recovers(self, lm,
                                                        greedy_refs):
        """A hard (non-retryable) decode fault fails the in-flight batch
        typed, the pooled carry is rebuilt from zeros, and the next
        request decodes correctly — the server never wedges."""
        reqs, refs = greedy_refs
        (p0, s0), ref0 = reqs[0], refs[0]
        chaos = ChaosPolicy(latency_rate=1.0, latency_s=0.05)
        breaker = CircuitBreaker(failure_threshold=1.1)
        with serving(lm, V, slots=3, breaker=breaker, chaos=chaos) as srv:
            f = srv.submit(p0, 200)
            for _ in range(600):                  # wait until mid-decode
                if srv.stats()["prefills"] >= 1:
                    break
                time.sleep(0.01)
            chaos.hard_rate = 1.0                 # next dispatch dies hard
            with pytest.raises(RuntimeError, match="hard fault"):
                f.result(timeout=30)
            chaos.hard_rate = 0.0
            chaos.latency_rate = 0.0
            np.testing.assert_array_equal(
                srv.submit(p0, s0).result(timeout=120), ref0)
            st = srv.stats()
        assert st["failed"] >= 1 and st["completed"] == 1

    def test_drain_resolves_everything(self, lm, greedy_refs):
        reqs, refs = greedy_refs
        with serving(lm, V, slots=2) as srv:
            futs = [srv.submit(p, s) for p, s in reqs]
            assert srv.drain(timeout=120)
            assert all(f.done() for f in futs)
            for f, ref in zip(futs, refs):
                np.testing.assert_array_equal(f.result(timeout=1), ref)

    def test_close_fails_stragglers_typed(self, lm):
        """close() with work still in flight past its timeout resolves
        the stragglers with a typed error instead of leaving hung
        futures; submitting after close is refused."""
        rs = np.random.RandomState(12)
        chaos = ChaosPolicy(latency_rate=1.0, latency_s=0.25)
        srv = GenerationServer(lm, V, slots=3, chaos=chaos)
        f = srv.submit(rs.randint(0, V, 3), 400)
        srv.close(timeout=0.3)
        assert f.done()
        with pytest.raises(RuntimeError, match="closed"):
            f.result(timeout=1)
        with pytest.raises(RuntimeError, match="closed"):
            srv.submit(rs.randint(0, V, 3), 2)


@pytest.mark.generation
class TestStreamingMask:
    """Layer-level property the prefill bucket path depends on: a right-
    padded prompt with a [B, T] validity mask streams identically to the
    unpadded prompt, and inapplicable mask shapes fail loudly."""

    def _carry(self, lm, batch=1):
        lm.rnn_clear_previous_state()
        seed = lm._seed_streaming_carry(batch)
        lm.rnn_clear_previous_state()
        return seed

    def test_masked_right_pad_matches_unpadded(self, lm):
        rs = np.random.RandomState(13)
        plen, bucket = 5, 8
        ids = rs.randint(0, V, plen)
        eye = np.eye(V, dtype=np.float32)
        fwd = lm_stream_forward(lm)

        x_pad = np.zeros((1, bucket, V), np.float32)
        x_pad[0, :plen] = eye[ids]
        mask = np.zeros((1, bucket), np.float32)
        mask[0, :plen] = 1
        out_pad, _ = fwd(lm.params, lm.state, x_pad, self._carry(lm), mask)
        out_raw, _ = fwd(lm.params, lm.state, eye[ids][None],
                         self._carry(lm), None)
        # true positions identical; the padded tail is garbage the caller
        # never reads (prefill samples from position plen-1 only)
        np.testing.assert_allclose(np.asarray(out_pad)[:, :plen],
                                   np.asarray(out_raw), atol=1e-6)

    def test_bad_mask_shape_raises(self, lm):
        rs = np.random.RandomState(14)
        x = np.eye(V, dtype=np.float32)[rs.randint(0, V, 4)][None]
        fwd = lm_stream_forward(lm)
        with pytest.raises(ValueError, match="streaming attention mask"):
            fwd(lm.params, lm.state, x, self._carry(lm),
                np.ones((1, 4, 1), np.float32))
        with pytest.raises(ValueError, match="streaming attention mask"):
            fwd(lm.params, lm.state, x, self._carry(lm),
                np.ones((2, 4), np.float32))  # batch mismatch


@pytest.mark.generation
class TestGenerationLockDiscipline:
    """Targeted regressions for the graftcheck generation-lock fixes:
    the closing flag is checked under self._cond in submit(), and the
    decode counters are batched into one condition acquisition per step."""

    def test_submit_close_race_never_hangs(self, lm):
        import threading

        srv = GenerationServer(lm, V, slots=2)
        futs, refused = [], []
        go = threading.Event()

        def submitter(i):
            go.wait(10)
            try:
                futs.append(srv.submit(np.array([1 + i % 5]), 3))
            except (RuntimeError, ResilienceError) as e:
                refused.append(e)  # typed refusal is a valid outcome

        ts = [threading.Thread(target=submitter, args=(i,))
              for i in range(8)]
        for t in ts:
            t.start()
        go.set()
        srv.close()
        for t in ts:
            t.join(30)
        assert len(futs) + len(refused) == 8
        for f in futs:
            try:
                f.result(timeout=30)
            except Exception:
                pass  # resolved with an error: fine — just never hung
            assert f.done()

    def test_counters_batched_per_decode_step(self, lm):
        with serving(lm, V, slots=2) as srv:
            futs = [srv.submit(np.array([1, 2, 3]), 4) for _ in range(3)]
            outs = [f.result(timeout=120) for f in futs]
            st = srv.stats()
        assert st["prefills"] == 3
        assert st["completed"] == 3
        # every generated token is counted exactly once, via ONE condition
        # acquisition per decode step (not one per token)
        assert st["tokens_generated"] == sum(len(o) for o in outs)
        assert 1 <= st["decode_steps"] <= 4 * 3
