"""Shared lifecycle regression: drain()/close() are idempotent and
re-entrant on EVERY runtime-hosted server (satellite of the unified
serving runtime). One parametrized suite — ParallelInference,
GenerationServer, StreamingBroker, ReplicaFleet — proves the contract
uniformly: drain twice, close twice, close from four threads at once,
drain after close, submit after close fails typed. Before the runtime
each server hand-rolled these paths; a fix in one historically missed
the other three.
"""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import TransformerLM
from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
from deeplearning4j_tpu.parallel.generation import GenerationServer
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.streaming.broker import StreamingBroker

from tests.test_fused_fit import _iris_like, _mln

pytestmark = pytest.mark.runtime

V = 17

_lm_cache = {}


def _lm():
    if "lm" not in _lm_cache:
        _lm_cache["lm"] = TransformerLM(num_labels=V, max_length=16,
                                        d_model=16, n_heads=2, n_blocks=1,
                                        seed=3).init()
    return _lm_cache["lm"]


class _Spec:
    """Uniform lifecycle surface over one server kind."""

    name = ""

    def make(self):
        raise NotImplementedError

    def submit(self, srv):
        """Issue one request; return a Future-like or None."""
        return None

    def drain(self, srv, timeout=5.0):
        return srv.drain(timeout)

    def close(self, srv, timeout=10.0):
        srv.close(timeout)


class _PISpec(_Spec):
    name = "parallel-inference"

    def make(self):
        return ParallelInference(_mln(), workers=4, max_wait_ms=5)

    def submit(self, srv):
        x = np.asarray(_iris_like(1, seed=0).features)
        return srv.submit(x)


class _GenSpec(_Spec):
    name = "generation-server"

    def make(self):
        return GenerationServer(_lm(), V, slots=2)

    def submit(self, srv):
        return srv.submit(np.array([3, 1, 4]), 3)


class _BrokerSpec(_Spec):
    name = "streaming-broker"

    def make(self):
        return StreamingBroker(port=0).start()


class _FleetSpec(_Spec):
    name = "replica-fleet"

    def make(self):
        return ReplicaFleet(lambda rid: GenerationServer(_lm(), V, slots=2),
                            replicas=1)

    def submit(self, srv):
        return srv.submit(np.array([3, 1, 4]), 3)


SPECS = [_PISpec(), _GenSpec(), _BrokerSpec(), _FleetSpec()]


@pytest.fixture(params=SPECS, ids=[s.name for s in SPECS])
def spec(request):
    return request.param


class TestLifecycleIdempotence:
    def test_drain_twice_then_close_twice(self, spec):
        srv = spec.make()
        f = spec.submit(srv)
        assert spec.drain(srv) is True
        assert spec.drain(srv) is True  # drain is idempotent
        if f is not None:
            # nothing left in flight: the future resolves promptly (the
            # result is set just outside the counter lock, so done() can
            # lag drain() by a scheduler beat)
            f.result(timeout=5)
        spec.close(srv)
        spec.close(srv)  # close is idempotent

    def test_concurrent_close_from_four_threads(self, spec):
        srv = spec.make()
        spec.submit(srv)
        errs = []

        def closer():
            try:
                spec.close(srv)
            except Exception as e:  # noqa: BLE001 - the assertion target
                errs.append(e)

        ts = [threading.Thread(target=closer) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert not any(t.is_alive() for t in ts)  # no closer hung
        assert errs == []  # every concurrent close returned cleanly

    def test_drain_after_close_is_trivially_true(self, spec):
        srv = spec.make()
        spec.close(srv)
        # nothing in flight on a closed server: drain reports success
        # immediately instead of raising or hanging
        assert spec.drain(srv, timeout=1.0) is True
        spec.close(srv)  # and close stays callable afterwards

    def test_submit_after_close_fails_typed(self, spec):
        srv = spec.make()
        spec.close(srv)
        f = None
        try:
            f = spec.submit(srv)
        except RuntimeError as e:
            assert "closed" in str(e).lower()
        if f is not None:
            with pytest.raises(Exception, match="(?i)closed"):
                f.result(timeout=5)
