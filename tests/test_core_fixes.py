"""Regression tests for round-1 advisor/verdict findings.

Covers: bias-vs-weight regularization classification (bidirectional LSTM, VAE),
LastTimeStep with non-contiguous masks, per-layer/bias learning-rate plumbing,
mask-aware output()/evaluate(), and tbptt back!=fwd rejection.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.layers.recurrent import (
    GravesBidirectionalLSTM,
    LastTimeStep,
    LSTM,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.layers.variational import VariationalAutoencoder
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Sgd


def test_bidirectional_lstm_l2_covers_backward_weights():
    """All weight params (f_* and b_* directions) must get l2; only f_b/b_b are biases."""
    layer = GravesBidirectionalLSTM(n_in=3, n_out=4, l2=0.1, l1=0.0,
                                    l2_bias=0.0, l1_bias=0.0)
    layer.finalize(None)
    params = {k: jnp.ones((2, 2)) if "W" in k else jnp.ones((4,))
              for k in layer.param_order()}
    reg = float(layer.regularization(params))
    expected = 0.0
    for k, v in params.items():
        if k not in ("f_b", "b_b"):
            expected += 0.5 * 0.1 * float(jnp.sum(v * v))
    assert np.isclose(reg, expected), (reg, expected)


def test_vae_bias_params_excluded_from_weight_decay():
    vae = VariationalAutoencoder(n_in=4, n_out=2, encoder_layer_sizes=(3,),
                                 decoder_layer_sizes=(3,), l2=0.5)
    vae.finalize(None)
    biases = vae.bias_param_names()
    assert {"eb0", "db0", "mb", "lb", "rb"} <= set(biases)
    params = vae.init_params(__import__("jax").random.PRNGKey(0))
    reg = float(vae.regularization(params))
    expected = sum(0.5 * 0.5 * float(jnp.sum(v * v))
                   for k, v in params.items() if k not in biases)
    assert np.isclose(reg, expected, rtol=1e-6)


def test_last_time_step_non_contiguous_mask():
    lts = LastTimeStep(n_in=2, n_out=2)
    x = jnp.arange(2 * 5 * 2, dtype=jnp.float32).reshape(2, 5, 2)
    # row 0: last active step is index 3 (interior zero at index 2)
    # row 1: last active step is index 1
    mask = jnp.array([[1, 1, 0, 1, 0], [1, 1, 0, 0, 0]], jnp.float32)
    out, _ = lts.forward({}, {}, x, mask=mask)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(x[0, 3]))
    np.testing.assert_allclose(np.asarray(out[1]), np.asarray(x[1, 1]))


def test_per_layer_and_bias_learning_rate():
    """Layer 0 trains at 10x lr, its bias at 0x; layer 1 at base lr."""
    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater(Sgd(learning_rate=0.1))
            .list(DenseLayer(n_in=3, n_out=4, activation="identity",
                             learning_rate=1.0, bias_learning_rate=0.0),
                  OutputLayer(n_in=4, n_out=2, loss="mse",
                              activation="identity"))
            .build())
    net = MultiLayerNetwork(conf).init()
    p0 = {k: np.asarray(v).copy() for k, v in net.params["0"].items()}
    p1 = {k: np.asarray(v).copy() for k, v in net.params["1"].items()}
    x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
    net.do_step(x, y)
    # bias of layer 0 frozen by bias_learning_rate=0
    np.testing.assert_allclose(np.asarray(net.params["0"]["b"]), p0["b"])
    # weights of layer 0 moved 10x more than they would at base lr: just check moved
    assert not np.allclose(np.asarray(net.params["0"]["W"]), p0["W"])
    assert not np.allclose(np.asarray(net.params["1"]["W"]), p1["W"])
    # ratio check: re-run with a copy at base lr and compare step magnitude
    conf2 = (NeuralNetConfiguration.builder()
             .seed(7).updater(Sgd(learning_rate=0.1))
             .list(DenseLayer(n_in=3, n_out=4, activation="identity"),
                   OutputLayer(n_in=4, n_out=2, loss="mse",
                               activation="identity"))
             .build())
    net2 = MultiLayerNetwork(conf2).init()
    net2.do_step(x, y)
    step_fast = np.abs(np.asarray(net.params["0"]["W"]) - p0["W"])
    step_base = np.abs(np.asarray(net2.params["0"]["W"]) - p0["W"])
    np.testing.assert_allclose(step_fast, 10.0 * step_base, rtol=1e-4)


def test_masked_output_and_evaluate():
    """output(mask=...) must make LastTimeStep pick the right step for padded rows."""
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Sgd(learning_rate=0.05))
            .list(LSTM(n_in=3, n_out=5),
                  LastTimeStep(),
                  OutputLayer(n_in=5, n_out=2, loss="mcxent",
                              activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(0)
    x_full = rs.randn(4, 6, 3).astype(np.float32)
    # rows padded after step 2: mask them out
    mask = np.ones((4, 6), np.float32)
    mask[2:, 3:] = 0.0
    x_masked = x_full.copy()
    x_masked[2:, 3:] = 999.0  # garbage in padded region
    out_short = net.output(x_full[2:, :3])  # truth: only the 3 valid steps
    out_masked = net.output(x_masked, mask=mask)
    np.testing.assert_allclose(np.asarray(out_masked[2:]), np.asarray(out_short),
                               rtol=1e-5, atol=1e-6)


def test_tbptt_back_neq_fwd_rejected():
    with pytest.raises(ValueError, match="tbptt_back_length"):
        (NeuralNetConfiguration.builder()
         .list(LSTM(n_in=2, n_out=3),
               RnnOutputLayer(n_in=3, n_out=2, loss="mcxent"))
         .backprop_type("tbptt", fwd_length=10, back_length=5)
         .build())
