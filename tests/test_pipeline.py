"""Pipeline parallelism tests: stage balancing, and the GPipe parity
contract — microbatched pipeline training over multiple devices equals
single-device full-batch training."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam, Sgd
from deeplearning4j_tpu.parallel.pipeline import (PipelineTrainer,
                                                  balanced_stages)


def _mlp(updater):
    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(updater)
            .list(DenseLayer(n_out=32, activation="tanh"),
                  DenseLayer(n_out=24, activation="relu"),
                  DenseLayer(n_out=16, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def _data(seed=0, n=32):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 3, n)
    return ((rs.randn(n, 6) + labels[:, None]).astype(np.float64),
            np.eye(3)[labels])


class TestStageBalance:
    def test_contiguous_cover_all_layers(self):
        net = _mlp(Sgd(learning_rate=0.1))
        for n_stages in (2, 3, 4):
            stages = balanced_stages(net, n_stages)
            assert len(stages) == n_stages
            flat = [i for st in stages for i in st]
            assert flat == list(range(len(net.layers)))


class TestPipelineParity:
    @pytest.mark.parametrize("updater,stages,micro,atol", [
        # SGD is linear in the gradient: microbatch sum/M reorders float
        # additions only -> exact. Adam's m/sqrt(v)+eps amplifies the
        # reordering noise to ~1e-7 (stable, non-accumulating).
        (Sgd(learning_rate=0.1), 2, 4, 1e-8),
        (Sgd(learning_rate=0.1), 4, 2, 1e-8),
        (Adam(learning_rate=0.01), 2, 4, 1e-6),
    ])
    def test_matches_single_device(self, updater, stages, micro, atol):
        x, y = _data()
        single = _mlp(updater)
        pipe_net = _mlp(updater)
        pt = PipelineTrainer(pipe_net, n_stages=stages, n_micro=micro)
        for _ in range(4):
            single.do_step(x, y)
            pt.do_step(x, y)
        pt._sync_back()
        np.testing.assert_allclose(pipe_net.params_flat(),
                                   single.params_flat(), atol=atol)
        assert pt.iteration == 4

    def test_fit_and_predict_through_wrapped_net(self):
        x, y = _data(1, 64)
        net = _mlp(Adam(learning_rate=0.05))
        pt = PipelineTrainer(net, n_stages=2, n_micro=4)
        s0 = None
        for _ in range(30):
            s = pt.do_step(x, y)
            s0 = s0 or s
        pt._sync_back()
        assert pt.score_value < s0  # learning
        pred = np.argmax(np.asarray(net.output(x.astype(np.float32))), 1)
        assert (pred == np.argmax(y, 1)).mean() > 0.8

    def test_conv_stack_pipeline(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(5).updater(Sgd(learning_rate=0.05))
                .list(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                       activation="relu"),
                      SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
                      DenseLayer(n_out=16, activation="relu"),
                      OutputLayer(n_out=2, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.convolutional(8, 8, 1)).build())
        rs = np.random.RandomState(0)
        x = rs.randn(16, 8, 8, 1).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 16)]
        single = MultiLayerNetwork(conf).init()
        pnet = MultiLayerNetwork(conf).init()
        pt = PipelineTrainer(pnet, n_stages=2, n_micro=4)
        for _ in range(3):
            single.do_step(x, y)
            pt.do_step(x, y)
        pt._sync_back()
        np.testing.assert_allclose(pnet.params_flat(),
                                   single.params_flat(), atol=1e-8)

    def test_regularization_clipping_and_layer_lr_parity(self):
        """The silent-parity-gap traps: l2 weight decay, gradient
        clipping, and per-layer LR overrides must all flow through the
        pipeline exactly as on a single device."""
        def build():
            conf = (NeuralNetConfiguration.builder()
                    .seed(9).updater(Sgd(learning_rate=0.1))
                    .l2(1e-3)
                    .gradient_normalization("clip_l2_per_layer")
                    .gradient_normalization_threshold(0.5)
                    .list(DenseLayer(n_out=24, activation="tanh"),
                          DenseLayer(n_out=16, activation="relu",
                                     learning_rate=0.02),
                          OutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
                    .set_input_type(InputType.feed_forward(6)).build())
            return MultiLayerNetwork(conf).init()

        x, y = _data(7)
        single = build()
        pnet = build()
        pt = PipelineTrainer(pnet, n_stages=2, n_micro=4)
        for _ in range(4):
            single.do_step(x, y)
            pt.do_step(x, y)
        pt._sync_back()
        np.testing.assert_allclose(pnet.params_flat(),
                                   single.params_flat(), atol=1e-8)

    def test_dropout_is_active_under_pipeline(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(4).updater(Sgd(learning_rate=0.0))
                .list(DenseLayer(n_out=64, activation="identity",
                                 dropout=0.5),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(6)).build())
        net = MultiLayerNetwork(conf).init()
        pt = PipelineTrainer(net, n_stages=2, n_micro=2)
        x, y = _data(8, 16)
        # lr=0: params frozen; the LOSS still varies across steps iff the
        # dropout masks are actually being drawn
        losses = {round(pt.do_step(x, y), 10) for _ in range(4)}
        assert len(losses) > 1, "dropout inactive: identical losses"

    def test_bn_running_stats_update_in_last_stage(self):
        from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
        conf = (NeuralNetConfiguration.builder()
                .seed(6).updater(Sgd(learning_rate=0.01))
                .list(DenseLayer(n_out=8, activation="relu"),
                      BatchNormalization(),
                      OutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
                .set_input_type(InputType.feed_forward(6)).build())
        net = MultiLayerNetwork(conf).init()
        pt = PipelineTrainer(net, n_stages=2, n_micro=2)
        bn_stage = next(s for s, idxs in enumerate(pt.stages) if 1 in idxs)
        assert bn_stage == len(pt.stages) - 1  # BN sits in the LAST stage
        x, y = _data(9, 16)
        for _ in range(3):
            pt.do_step(x, y)
        pt._sync_back()
        mean = np.asarray(net.state["1"]["mean"])
        assert not np.allclose(mean, 0.0), "BN running stats never updated"

    def test_indivisible_batch_rejected(self):
        net = _mlp(Sgd(learning_rate=0.1))
        pt = PipelineTrainer(net, n_stages=2, n_micro=4)
        x, y = _data(2, 30)
        with pytest.raises(ValueError):
            pt.do_step(x, y)
