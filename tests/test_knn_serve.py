"""Retrieval serving (nearestneighbors/index.py + the rebuilt server).

The contracts under test:

* the pure f32 ``EmbeddingIndex`` is BYTE-identical to
  ``DeviceBruteForceIndex`` (same upload arithmetic, same pad/bucket
  code, same ``_knn`` jit cache);
* N one-row ``submit()`` calls coalesce into ONE fused matmul+top_k
  dispatch and slice back bit-exactly;
* the int8 store clears the recall gate at >=1.8x capacity and rebuilds
  bit-identically after drain/close (deterministic host quantization);
* IVF clears recall >= 0.95 vs exact on a clustered corpus;
* the serving posture fails typed (DeadlineExceeded / ServerOverloaded /
  CircuitOpen), never hangs, and drain/close loses ZERO futures;
* batch-size churn never retraces past the pow2 program budget;
* the hardened HTTP tier answers structured 400/404/413/429/503/504.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nearestneighbors.brute import DeviceBruteForceIndex
from deeplearning4j_tpu.nearestneighbors.index import EmbeddingIndex
from deeplearning4j_tpu.nearestneighbors.server import NearestNeighborsServer
from deeplearning4j_tpu.parallel.resilience import (
    ChaosPolicy,
    CircuitBreaker,
    CircuitOpen,
    DeadlineExceeded,
    RetryPolicy,
    ServerOverloaded,
)

pytestmark = pytest.mark.knn


def _corpus(n, d, seed=0):
    return np.random.RandomState(seed).randn(n, d).astype(np.float32)


def _clustered(n, d, centers=128, seed=0):
    """Mixture of gaussians — the corpus shape IVF is built for (pure
    noise spreads each query's neighbors over many cells and is the
    pathological case for any partitioned index)."""
    rs = np.random.RandomState(seed)
    mu = rs.randn(centers, d).astype(np.float32) * 4.0
    pts = mu[rs.randint(0, centers, n)] + rs.randn(n, d).astype(
        np.float32) * 0.6
    return pts.astype(np.float32)


def _post(base, path, obj, raw=None):
    """POST helper returning (status, parsed json) — error statuses
    included instead of raised."""
    data = raw if raw is not None else json.dumps(obj).encode()
    req = urllib.request.Request(
        base + path, data=data,
        headers={"Content-Type": "application/json"})
    try:
        res = urllib.request.urlopen(req)
        return res.status, json.loads(res.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------------------
# exact parity with DeviceBruteForceIndex
# ---------------------------------------------------------------------------

class TestExactParity:
    @pytest.mark.parametrize("metric", ["euclidean", "cosine"])
    def test_f32_byte_identical_to_brute(self, metric):
        pts = _corpus(257, 12, seed=1)
        qs = _corpus(19, 12, seed=2)
        brute = DeviceBruteForceIndex(pts, metric=metric)
        index = EmbeddingIndex(pts, metric)
        for k in (1, 3, 7):
            db, ib = brute.search_batch_arrays(qs, k)
            de, ie = index.search_batch_arrays(qs, k)
            assert np.array_equal(db, de), "distances diverged from brute"
            assert np.array_equal(ib, ie), "indices diverged from brute"
        # single-query VPTree-shaped entry agrees too
        assert index.search(qs[0], 3) == brute.search(qs[0], 3)
        index.close()

    def test_k_above_n_clamps_on_both_backends(self):
        pts = _corpus(10, 4)
        brute = DeviceBruteForceIndex(pts)
        index = EmbeddingIndex(pts)
        db, ib = brute.search_batch_arrays(pts[:3], 999)
        de, ie = index.search_batch_arrays(pts[:3], 999)
        assert db.shape == de.shape == (3, 10)
        assert np.array_equal(ib, ie)
        index.close()

    @pytest.mark.parametrize("bad_k", [0, -2, 2.5, "x", True])
    def test_bad_k_typed_on_both_backends(self, bad_k):
        pts = _corpus(10, 4)
        brute = DeviceBruteForceIndex(pts)
        index = EmbeddingIndex(pts)
        with pytest.raises(ValueError):
            brute.search_batch_arrays(pts[:2], bad_k)
        with pytest.raises(ValueError):
            index.search_batch_arrays(pts[:2], bad_k)
        index.close()

    def test_dims_mismatch_and_empty_typed(self):
        index = EmbeddingIndex(_corpus(10, 4))
        with pytest.raises(ValueError, match="dims mismatch"):
            index.search_batch_arrays(np.zeros((2, 5), np.float32), 3)
        index.close()
        empty = EmbeddingIndex()
        with pytest.raises(ValueError, match="empty"):
            empty.search_batch_arrays(np.zeros((1, 4), np.float32), 1)
        empty.close()


# ---------------------------------------------------------------------------
# the coalescer
# ---------------------------------------------------------------------------

class TestCoalescer:
    def test_32_one_row_submits_are_one_dispatch_bit_exact(self):
        """The headline: 32 concurrent one-row submits == ONE batched
        device program, each caller's slice bit-identical to the
        synchronous batched answer."""
        pts = _corpus(300, 8, seed=3)
        qs = _corpus(32, 8, seed=4)
        index = EmbeddingIndex(pts, max_batch=32, max_wait_ms=100.0)
        d_sync, i_sync = index.search_batch_arrays(qs, 5)  # also warms jit
        before = index.stats()["dispatches"]
        futs = [index.submit(qs[i:i + 1], 5) for i in range(32)]
        outs = [f.result(timeout=60) for f in futs]
        assert index.stats()["dispatches"] == before + 1, \
            "one-row submits did not coalesce into a single dispatch"
        for i, (d, ix) in enumerate(outs):
            assert np.array_equal(d, d_sync[i:i + 1])
            assert np.array_equal(ix, i_sync[i:i + 1])
        st = index.stats()
        assert st["completed"] == 32 and st["failed"] == 0
        assert st["pending"] == 0
        index.close()

    def test_no_recompile_under_batch_churn(self):
        """Arbitrary query-batch sizes stay inside the pow2 program
        budget: O(log max_batch) programs, not one per size."""
        pts = _corpus(200, 6, seed=5)
        index = EmbeddingIndex(pts)
        rs = np.random.RandomState(0)
        for _ in range(40):
            q = _corpus(int(rs.randint(1, 64)), 6, seed=int(rs.randint(99)))
            index.search_batch_arrays(q, 8)
        # sizes 1..64 bucket to {1,2,4,8,16,32,64}: at most 7 programs
        assert index.stats()["programs"] <= 7, \
            f"batch churn retraced: {index.stats()['programs']} programs"
        index.close()

    def test_mixed_k_submits_resolve_with_right_widths(self):
        pts = _corpus(100, 5, seed=6)
        index = EmbeddingIndex(pts, max_wait_ms=5.0)
        futs = [index.submit(_corpus(2, 5, seed=i), k) for i, k in
                enumerate([1, 3, 4, 7, 8])]
        for f, k in zip(futs, [1, 3, 4, 7, 8]):
            d, idx = f.result(timeout=60)
            assert d.shape == (2, k) and idx.shape == (2, k)
        index.close()


# ---------------------------------------------------------------------------
# int8 store
# ---------------------------------------------------------------------------

class TestInt8Store:
    def test_recall_gate_and_capacity_ratio(self):
        pts = _corpus(2048, 16, seed=7)
        qs = _corpus(64, 16, seed=8)
        f32 = EmbeddingIndex(pts, mesh=None)
        q8 = EmbeddingIndex(pts, store="int8")
        recall = q8.measure_recall(qs, k=10)
        assert recall >= 0.9, f"int8 recall {recall} below gate"
        assert q8.stats()["recall"] == pytest.approx(recall)
        ratio = f32.resident_bytes / q8.resident_bytes
        assert ratio >= 1.8, f"int8 capacity ratio {ratio:.2f} < 1.8"
        f32.close()
        q8.close()

    def test_bit_identical_rebuild_after_drain_close(self):
        """Deterministic host quantization: an index rebuilt from the
        same points after a full drain/close answers bit-identically —
        the durability story for a restarted replica."""
        pts = _corpus(500, 16, seed=3)
        qs = _corpus(16, 16, seed=9)
        first = EmbeddingIndex(pts, store="int8")
        first.submit(qs[:4], 5).result(timeout=60)
        d1, i1 = first.search_batch_arrays(qs, 10)
        assert first.drain(timeout=30)
        # drain is a serving pause, not a store teardown: sync still works
        d_mid, i_mid = first.search_batch_arrays(qs, 10)
        assert np.array_equal(d1, d_mid) and np.array_equal(i1, i_mid)
        first.close()
        second = EmbeddingIndex(pts, store="int8")
        d2, i2 = second.search_batch_arrays(qs, 10)
        assert np.array_equal(d1, d2), "int8 rebuild not bit-identical"
        assert np.array_equal(i1, i2)
        second.close()


# ---------------------------------------------------------------------------
# IVF
# ---------------------------------------------------------------------------

class TestIVF:
    def test_recall_gate_on_clustered_corpus(self):
        pts = _clustered(4096, 16, seed=0)
        # queries live near the indexed clusters (perturbed corpus rows)
        rs = np.random.RandomState(1)
        qs = pts[rs.choice(4096, 64, replace=False)] \
            + rs.randn(64, 16).astype(np.float32) * 0.2
        index = EmbeddingIndex(pts, partitions=64, nprobe=8,
                               kmeans_iters=10, seed=0)
        st = index.stats()
        assert st["variant"] == "ivf" and st["partitions"] == 64
        recall = index.measure_recall(qs, k=10)
        assert recall >= 0.95, f"IVF recall {recall} below the 0.95 gate"
        index.close()

    def test_int8_ivf_composes(self):
        pts = _clustered(2048, 16, seed=2)
        rs = np.random.RandomState(3)
        qs = pts[rs.choice(2048, 32, replace=False)] \
            + rs.randn(32, 16).astype(np.float32) * 0.2
        index = EmbeddingIndex(pts, store="int8", partitions=32, nprobe=8,
                               kmeans_iters=10, seed=0)
        recall = index.measure_recall(qs, k=10)
        assert recall >= 0.9, f"int8 IVF recall {recall} below gate"
        d, idx = index.search_batch_arrays(qs, 5)
        assert d.shape == (32, 5)
        assert (idx >= 0).all() and (idx < 2048).all()
        index.close()


# ---------------------------------------------------------------------------
# mesh sharding (8 virtual CPU devices, conftest)
# ---------------------------------------------------------------------------

class TestMeshSharded:
    def test_sharded_flat_agrees_with_unsharded(self):
        from deeplearning4j_tpu.parallel.mesh import data_mesh
        pts = _corpus(300, 8, seed=10)   # 300 pads to 304 on 8 devices
        qs = _corpus(9, 8, seed=11)
        plain = EmbeddingIndex(pts)
        shard = EmbeddingIndex(pts, mesh=data_mesh(8))
        dp, ip = plain.search_batch_arrays(qs, 7)
        ds, is_ = shard.search_batch_arrays(qs, 7)
        assert np.array_equal(ip, is_)
        np.testing.assert_allclose(dp, ds, rtol=1e-5, atol=1e-5)
        plain.close()
        shard.close()

    def test_sharded_int8_recall(self):
        from deeplearning4j_tpu.parallel.mesh import data_mesh
        pts = _corpus(1024, 16, seed=12)
        qs = _corpus(32, 16, seed=13)
        index = EmbeddingIndex(pts, store="int8", mesh=data_mesh(8))
        assert index.measure_recall(qs, k=10) >= 0.9
        index.close()


# ---------------------------------------------------------------------------
# nprobe contract: typed under-probing, clamped over-probing
# ---------------------------------------------------------------------------

class TestNprobeContract:
    def test_nprobe_below_one_typed(self):
        """Silent fallback was the old behavior; under-probing is now a
        caller error (typed before any store builds)."""
        for bad in (0, -3):
            with pytest.raises(ValueError, match="nprobe must be >= 1"):
                EmbeddingIndex(_corpus(64, 8), partitions=8, nprobe=bad)

    @pytest.mark.slow
    def test_nprobe_above_partitions_clamps_to_full_probe_parity(self):
        """Over-probing clamps to the partition count — and a full probe
        IS an exact search (every cell's candidates re-ranked), so the
        clamp boundary must agree with the exact index: identical
        neighbor ids, matching distances."""
        pts = _clustered(512, 16, seed=20)
        rs = np.random.RandomState(21)
        qs = pts[rs.choice(512, 16, replace=False)] \
            + rs.randn(16, 16).astype(np.float32) * 0.2
        exact = EmbeddingIndex(pts)
        ivf = EmbeddingIndex(pts, partitions=16, nprobe=99,
                             kmeans_iters=10, seed=0)
        assert ivf.stats()["nprobe"] == 16  # clamped at build
        de, ie = exact.search_batch_arrays(qs, 10)
        dv, iv = ivf.search_batch_arrays(qs, 10)
        np.testing.assert_array_equal(ie, iv)
        np.testing.assert_allclose(de, dv, rtol=1e-4, atol=1e-4)
        exact.close()
        ivf.close()


# ---------------------------------------------------------------------------
# HNSW graph store (host-side greedy-descent beam search)
# ---------------------------------------------------------------------------

class TestHNSW:
    def test_ctor_validation(self):
        pts = _corpus(64, 8)
        with pytest.raises(ValueError, match="store"):
            EmbeddingIndex(pts, store="float16")
        with pytest.raises(ValueError, match="hnsw"):
            EmbeddingIndex(pts, store="hnsw", partitions=8)
        with pytest.raises(ValueError, match="kmeans"):
            EmbeddingIndex(pts, kmeans="spherical")
        with pytest.raises(ValueError, match="sharded"):
            EmbeddingIndex(pts, partitions=8, kmeans="sharded")

    @pytest.mark.slow
    def test_recall_gate_and_stats(self):
        pts = _clustered(2048, 16, seed=22)
        rs = np.random.RandomState(23)
        qs = pts[rs.choice(2048, 32, replace=False)] \
            + rs.randn(32, 16).astype(np.float32) * 0.2
        # clustered corpora fragment the graph: wider links + deeper
        # construction beam than the defaults buy the recall margin
        index = EmbeddingIndex(pts, store="hnsw", hnsw_m=32,
                               ef_construction=128, ef_search=128)
        st = index.stats()
        assert st["variant"] == "hnsw" and st["hnsw_m"] == 32
        assert st["levels"] >= 1
        recall = index.measure_recall(qs, k=10)
        assert recall >= 0.95, f"HNSW recall {recall} below the 0.95 gate"
        d, idx = index.search_batch_arrays(qs, 5)
        assert d.shape == (32, 5)
        assert (idx >= 0).all() and (idx < 2048).all()
        index.close()


# ---------------------------------------------------------------------------
# sharded k-means training + probe-local IVF residency (8 virtual devices)
# ---------------------------------------------------------------------------

class TestShardedIVF:
    @pytest.mark.slow
    def test_sharded_kmeans_recall_gate(self):
        """Per-device assign sweeps + all-reduced centroid updates train
        to the same recall gate as the host loop."""
        from deeplearning4j_tpu.parallel.mesh import data_mesh
        pts = _clustered(2048, 16, seed=24)
        rs = np.random.RandomState(25)
        qs = pts[rs.choice(2048, 32, replace=False)] \
            + rs.randn(32, 16).astype(np.float32) * 0.2
        index = EmbeddingIndex(pts, mesh=data_mesh(8), kmeans="sharded",
                               partitions=32, nprobe=8, kmeans_iters=10,
                               seed=0)
        st = index.stats()
        assert st["variant"] == "ivf" and st["probe_local"] is True
        recall = index.measure_recall(qs, k=10)
        assert recall >= 0.95, f"sharded-kmeans recall {recall} below gate"
        index.close()

    @pytest.mark.slow
    def test_probe_local_recall_never_below_global_probe(self):
        """Per-device residency probes nprobe LOCAL cells per device —
        the union candidate pool is a superset of the global-probe
        pool, so recall can only go up (the acceptance property of the
        probe-local gather)."""
        from deeplearning4j_tpu.parallel.mesh import data_mesh
        pts = _clustered(4096, 16, seed=26)
        rs = np.random.RandomState(27)
        qs = pts[rs.choice(4096, 32, replace=False)] \
            + rs.randn(32, 16).astype(np.float32) * 0.2
        kw = dict(store="int8", partitions=64, nprobe=4,
                  kmeans_iters=10, seed=0)
        local = EmbeddingIndex(pts, mesh=data_mesh(8), **kw)
        globl = EmbeddingIndex(pts, **kw)
        assert local.stats()["probe_local"] is True
        assert globl.stats()["probe_local"] is False
        r_local = local.measure_recall(qs, k=10)
        r_global = globl.measure_recall(qs, k=10)
        assert r_local >= r_global, (
            f"probe-local recall {r_local} fell below global-probe "
            f"{r_global} — the superset guarantee broke")
        assert r_local >= 0.9
        local.close()
        globl.close()


# ---------------------------------------------------------------------------
# typed failures — never a hang, never a silent loss
# ---------------------------------------------------------------------------

class TestTypedFailures:
    def test_expired_deadline_is_deadline_exceeded(self):
        pts = _corpus(100, 4)
        index = EmbeddingIndex(pts, max_wait_ms=1.0)
        fut = index.submit(pts[:1], 3, deadline_s=1e-6)
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=30)
        assert index.stats()["expired"] >= 1
        index.close()

    def test_burst_beyond_watermark_sheds_typed(self):
        pts = _corpus(100, 4)
        chaos = ChaosPolicy(seed=0, latency_rate=1.0, latency_s=0.05)
        index = EmbeddingIndex(pts, max_batch=4, max_wait_ms=1.0,
                               inflight=1, max_pending=8, chaos=chaos)
        index.search_batch_arrays(pts[:1], 3)  # warm the programs
        admitted, shed = [], 0
        for i in range(40):
            try:
                admitted.append(index.submit(_corpus(1, 4, seed=i), 3))
            except ServerOverloaded:
                shed += 1
        assert shed > 0, "burst never hit the watermark"
        for f in admitted:
            d, idx = f.result(timeout=60)
            assert d.shape == (1, 3)
        st = index.stats()
        assert st["rejected"] == shed
        assert st["pending"] == 0
        index.close()

    def test_open_breaker_fast_fails_submits(self):
        pts = _corpus(100, 4)
        chaos = ChaosPolicy(seed=0, hard_rate=1.0)  # every dispatch dies
        breaker = CircuitBreaker(failure_threshold=0.5, window=8,
                                 min_calls=2, reset_timeout_s=60.0)
        index = EmbeddingIndex(pts, max_wait_ms=1.0, chaos=chaos,
                               breaker=breaker,
                               retry=RetryPolicy(max_attempts=1))
        saw_open = False
        for i in range(12):
            try:
                fut = index.submit(pts[:1], 3)
            except CircuitOpen:
                saw_open = True
                break
            with pytest.raises(RuntimeError):
                fut.result(timeout=30)
        assert saw_open or breaker.state == CircuitBreaker.OPEN
        with pytest.raises(CircuitOpen):
            index.submit(pts[:1], 3)
        st = index.stats()
        assert st["breaker_state"] == "open"
        assert st["rejected_circuit"] >= 1
        index.close()


# ---------------------------------------------------------------------------
# lifecycle — drain/close loses nothing
# ---------------------------------------------------------------------------

class TestLifecycle:
    def test_close_with_inflight_loses_zero_futures(self):
        pts = _corpus(200, 6)
        chaos = ChaosPolicy(seed=0, latency_rate=1.0, latency_s=0.02)
        index = EmbeddingIndex(pts, max_batch=4, max_wait_ms=1.0,
                               inflight=1, chaos=chaos)
        futs = [index.submit(_corpus(1, 6, seed=i), 3) for i in range(16)]
        index.close()
        resolved = failed = 0
        for f in futs:
            assert f.done(), "close() left a future unresolved"
            if f.exception() is None:
                d, _ = f.result()
                assert d.shape == (1, 3)
                resolved += 1
            else:
                failed += 1
        assert resolved + failed == 16
        st = index.stats()
        assert st["pending"] == 0
        assert st["completed"] + st["failed"] == st["accepted"]

    def test_submit_after_close_and_idempotent_close(self):
        index = EmbeddingIndex(_corpus(50, 4))
        index.submit(_corpus(1, 4), 3).result(timeout=60)
        index.close()
        index.close()  # idempotent
        with pytest.raises(RuntimeError, match="closed"):
            index.submit(_corpus(1, 4), 3)

    def test_add_grows_store_and_serves_new_rows(self):
        pts = _corpus(50, 4, seed=20)
        index = EmbeddingIndex(pts)
        assert index.n_points == 50
        extra = _corpus(10, 4, seed=21)
        assert index.add(extra) == 60
        d, idx = index.search_batch_arrays(extra[:1], 1)
        assert idx[0, 0] == 50  # its own row, freshly appended
        assert d[0, 0] == pytest.approx(0.0, abs=1e-5)
        index.close()


# ---------------------------------------------------------------------------
# fleet compatibility
# ---------------------------------------------------------------------------

class TestFleetCompat:
    def test_index_replicas_ride_the_fleet(self):
        from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
        pts = _corpus(100, 4, seed=22)
        fleet = ReplicaFleet(
            lambda rid: EmbeddingIndex(pts, max_wait_ms=1.0), replicas=2)
        try:
            futs = [fleet.submit(pts[i:i + 1], 3) for i in range(8)]
            for i, f in enumerate(futs):
                d, idx = f.result(timeout=60)
                assert idx[0, 0] == i  # each query finds its own row
        finally:
            fleet.close()


# ---------------------------------------------------------------------------
# the hardened HTTP tier
# ---------------------------------------------------------------------------

class TestServerHardening:
    def test_malformed_payloads_answer_structured_400(self):
        pts = _corpus(20, 3, seed=30)
        with NearestNeighborsServer(pts, port=0) as server:
            base = f"http://127.0.0.1:{server.port}"
            cases = [
                {"k": 2},                                   # missing point
                {"k": 2, "point": "zzz"},                   # non-numeric
                {"k": 2, "points": [[1, 2, 3], [1, 2]]},    # ragged
                {"k": "x", "point": [1, 2, 3]},             # bad k
                {"k": 0, "point": [1, 2, 3]},               # k < 1
                {"k": 2.5, "point": [1, 2, 3]},             # fractional k
                {"k": 2, "point": [1, 2]},                  # dims mismatch
                {"k": 2, "points": [1, 2, 3]},              # wrong ndim
            ]
            for body in cases:
                status, res = _post(base, "/knn", body)
                assert status == 400, f"{body} answered {status}"
                assert res["error"] == "BadRequest"
                assert res["detail"]
            status, res = _post(base, "/knn", None,
                                raw=b"this is not json")
            assert status == 400
            status, res = _post(base, "/knn", [1, 2, 3])  # not an object
            assert status == 400
            status, res = _post(base, "/nope", {"k": 1})
            assert status == 404 and res["error"] == "NotFound"

    def test_oversized_body_answers_413(self):
        pts = _corpus(20, 3)
        with NearestNeighborsServer(pts, port=0,
                                    max_body_bytes=1024) as server:
            base = f"http://127.0.0.1:{server.port}"
            big = {"k": 1, "points": [[1.0, 2.0, 3.0]] * 5000}
            status, res = _post(base, "/knn", big)
            assert status == 413
            assert res["error"] == "BodyTooLarge"

    def test_stats_and_metrics_endpoints(self):
        pts = _corpus(20, 3)
        with NearestNeighborsServer(pts, port=0,
                                    backend="index") as server:
            base = f"http://127.0.0.1:{server.port}"
            _post(base, "/knn", {"k": 1, "point": pts[0].tolist()})
            st = json.loads(urllib.request.urlopen(base + "/stats").read())
            assert st["backend"] == "index"
            assert st["points"] == 20 and st["dims"] == 3
            assert st["index"]["completed"] >= 1
            text = urllib.request.urlopen(base + "/metrics").read().decode()
            for name in ("knn_http_requests_total", "knn_latency_ms",
                         "knn_resident_bytes", "knn_recall"):
                assert name in text, f"{name} missing from /metrics"

    def test_index_backend_end_to_end(self):
        pts = _corpus(50, 3, seed=31)
        with NearestNeighborsServer(pts, port=0, backend="index",
                                    store="int8") as server:
            base = f"http://127.0.0.1:{server.port}"
            st = json.loads(urllib.request.urlopen(base + "/status").read())
            assert st == {"points": 50, "dims": 3}
            q = pts[7] + 0.001
            status, res = _post(base, "/knn",
                                {"k": 2, "point": q.tolist()})
            assert status == 200
            assert res["results"][0]["index"] == 7
            status, res = _post(base, "/knnVector",
                                {"k": 1, "points": [pts[3].tolist(),
                                                    pts[9].tolist()]})
            assert status == 200
            assert [r[0]["index"] for r in res["results"]] == [3, 9]
            # /encode with add=true grows the store
            status, res = _post(base, "/encode",
                                {"docs": [[9.0, 9.0, 9.0]], "add": True})
            assert status == 200 and res["added"] == 1
            st = json.loads(urllib.request.urlopen(base + "/status").read())
            assert st["points"] == 51
            status, res = _post(base, "/knn",
                                {"k": 1, "point": [9.0, 9.0, 9.0]})
            assert res["results"][0]["index"] == 50

    def test_expired_deadline_maps_to_504(self):
        pts = _corpus(50, 3)
        with NearestNeighborsServer(pts, port=0, backend="index",
                                    max_wait_ms=1.0) as server:
            base = f"http://127.0.0.1:{server.port}"
            status, res = _post(
                base, "/knn",
                {"k": 1, "point": pts[0].tolist(), "deadline_s": 1e-6})
            assert status == 504
            assert res["error"] == "DeadlineExceeded"

    def test_encode_requires_index_backend(self):
        pts = _corpus(20, 3)
        with NearestNeighborsServer(pts, port=0) as server:
            base = f"http://127.0.0.1:{server.port}"
            status, res = _post(base, "/encode", {"docs": [[1, 2, 3]]})
            assert status == 400
            assert "backend" in res["detail"]
