"""Coalescing inference server tests (parallel/inference.py submit()).

The BatchedInferenceObservable contract: concurrent small requests merge
into one padded device batch (N=32 single-row submits -> <= 2 dispatches),
every caller gets exactly its own rows back (identical to a sequential
output() call), the deadline flushes partial batches, and request order is
preserved within a coalesced batch.
"""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.inference import ParallelInference

from tests.test_fused_fit import _graph, _iris_like, _mln


def _features(n, seed=0):
    return np.asarray(_iris_like(n, seed=seed).features)


class TestCoalescing:
    def test_32_submits_coalesce_to_two_dispatches(self):
        """The acceptance criterion: 32 concurrent 1-row submits complete in
        at most 2 device dispatches, results identical to output()."""
        net = _mln()
        x = _features(32)
        with ParallelInference(net, workers=8, max_wait_ms=50) as inf:
            ref = inf.output(x)
            base = inf.dispatch_count
            futs = [inf.submit(x[i:i + 1]) for i in range(32)]
            res = [f.result(timeout=30) for f in futs]
            assert inf.dispatch_count - base <= 2
        got = np.concatenate(res)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_order_preserved_within_batch(self):
        """Each future resolves to exactly its own rows: distinct inputs map
        to their own outputs, in submission row order."""
        net = _mln()
        x = _features(16, seed=3)
        with ParallelInference(net, workers=8, max_wait_ms=50) as inf:
            seq = inf.output(x)
            futs = [inf.submit(x[i:i + 2]) for i in range(0, 16, 2)]
            for i, f in enumerate(futs):
                np.testing.assert_allclose(f.result(timeout=30),
                                           seq[2 * i:2 * i + 2],
                                           rtol=1e-5, atol=1e-6)

    def test_deadline_flushes_partial_batch(self):
        """Fewer than max_batch rows still complete: the max_wait deadline
        dispatches whatever has arrived."""
        net = _mln()
        x = _features(3, seed=1)
        with ParallelInference(net, workers=8, max_batch=64,
                               max_wait_ms=5) as inf:
            ref = inf.output(x)
            futs = [inf.submit(x[i:i + 1]) for i in range(3)]
            got = np.concatenate([f.result(timeout=30) for f in futs])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_max_batch_triggers_immediate_dispatch(self):
        """Reaching max_batch rows dispatches without waiting out the
        deadline (a long max_wait must not serialize a full batch)."""
        net = _mln()
        x = _features(8, seed=2)
        with ParallelInference(net, workers=8, max_batch=8,
                               max_wait_ms=10_000) as inf:
            futs = [inf.submit(x[i:i + 1]) for i in range(8)]
            got = np.concatenate([f.result(timeout=30) for f in futs])
            ref = inf.output(x)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_multithreaded_submitters(self):
        """Submissions racing from many threads all resolve correctly."""
        net = _mln()
        x = _features(24, seed=4)
        results = {}
        with ParallelInference(net, workers=8, max_wait_ms=20) as inf:
            ref = inf.output(x)

            def worker(i):
                results[i] = inf.submit(x[i:i + 1]).result(timeout=30)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(24)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for i in range(24):
            np.testing.assert_allclose(results[i], ref[i:i + 1],
                                       rtol=1e-5, atol=1e-6)

    def test_graph_net_submit(self):
        """The server works on ComputationGraph too (single-output)."""
        net = _graph()
        x = _features(8, seed=5)
        with ParallelInference(net, workers=8, max_wait_ms=20) as inf:
            ref = inf.output(x)
            futs = [inf.submit(x[i:i + 1]) for i in range(8)]
            got = np.concatenate([f.result(timeout=30) for f in futs])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


class TestLifecycle:
    def test_submit_after_close_raises(self):
        net = _mln()
        inf = ParallelInference(net, workers=8)
        inf.submit(_features(1)).result(timeout=30)
        inf.close()
        with pytest.raises(RuntimeError):
            inf.submit(_features(1))

    def test_close_fails_requests_queued_behind_sentinel(self):
        """Requests a racing submit() slipped into the inbox behind the
        shutdown sentinel must be FAILED by close(), never left as futures
        nobody will ever resolve. Staged deterministically: the coalescer
        loop is closed first (its pool has exited at the sentinel), then
        requests land in its inbox the way a racing put would."""
        from deeplearning4j_tpu.parallel import inference as inf_mod

        inf = ParallelInference(_mln(), workers=8)
        with inf._lock:
            co = inf._ensure_workers()
        co.close(timeout=5)  # the pool retires at the sentinel
        reqs = [inf_mod._Request(_features(1, seed=i), None)
                for i in range(3)]
        for r in reqs:
            co._inbox.put(r)
        inf.close()
        for r in reqs:
            with pytest.raises(RuntimeError, match="closed"):
                r.future.result(timeout=5)
        assert co._inbox.empty()

    def test_submit_racing_close_resolves_future(self):
        """A submit that passes the closed check just before close() lands
        still gets a resolved (failed) future instead of hanging forever.
        Staged deterministically: every runtime worker is retired first
        (so nothing can serve the request), then close() is injected
        between the submit's enqueue and its post-enqueue re-check."""
        import time as _time

        from deeplearning4j_tpu.parallel import runtime as rt

        inf = ParallelInference(_mln(), workers=8)
        with inf._lock:
            co = inf._ensure_workers()
            cm = inf._completer
        for loop in (co, cm):
            for _ in range(loop.alive_workers):
                loop._inbox.put(rt._RESIGN)
        deadline = _time.monotonic() + 5
        while (co.alive_workers or cm.alive_workers) \
                and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert co.alive_workers == 0 and cm.alive_workers == 0
        orig_put = co.put

        def put_then_close(item, timeout=None):
            orig_put(item, timeout=timeout)
            # close() lands exactly between this submit's enqueue and
            # its post-enqueue closed re-check
            if not inf._closed:
                inf.close()

        co.put = put_then_close
        fut = inf.submit(_features(1))
        with pytest.raises(RuntimeError, match="closed"):
            fut.result(timeout=5)
        with pytest.raises(RuntimeError):
            inf.submit(_features(1))  # and the server stays closed

    def test_single_example_promoted_to_batch(self):
        """A 1-D feature vector is treated as a 1-row batch."""
        net = _mln()
        x = _features(1, seed=6)
        with ParallelInference(net, workers=8, max_wait_ms=5) as inf:
            out = inf.submit(x[0]).result(timeout=30)
            ref = inf.output(x)
        assert out.shape == ref.shape
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


class TestBucketedCache:
    def test_output_request_sizes_share_buckets(self):
        """Request sizes 1..9 pad to power-of-two worker-multiple buckets:
        at most 2 distinct programs (8 and 16 rows with 8 workers)."""
        net = _mln()
        x = _features(16, seed=7)
        inf = ParallelInference(net, workers=8)
        full = inf.output(x)
        for n in range(1, 10):
            np.testing.assert_allclose(inf.output(x[:n]), full[:n],
                                       rtol=1e-5, atol=1e-6)
        programs = [k for k in net._output_cache if k[0] == "pi_fwd"]
        assert len(programs) <= 2

    def test_fwd_programs_shared_across_instances(self):
        """A rebuilt server over the same net (the fleet's supervised
        restart) reuses the net-level compiled programs: no new cache
        entries on the second instance's dispatches."""
        net = _mln()
        x = _features(8, seed=8)
        inf1 = ParallelInference(net, workers=8)
        ref = inf1.output(x)
        n_programs = len(net._output_cache)
        inf2 = ParallelInference(net, workers=8)
        np.testing.assert_allclose(inf2.output(x), ref, rtol=1e-5,
                                   atol=1e-6)
        assert len(net._output_cache) == n_programs


@pytest.mark.serving
class TestLockDiscipline:
    """Targeted regressions for the graftcheck serving-lock fixes: the
    draining flag is checked under self._lock in submit(), and the
    dispatch counter is published under self._stats_lock."""

    def test_submit_rejected_while_draining(self):
        inf = ParallelInference(_mln(), workers=8)
        inf.submit(_features(1)).result(timeout=30)
        assert inf.drain(timeout=30)  # nothing pending -> completes
        with pytest.raises(RuntimeError, match="draining"):
            inf.submit(_features(1))
        inf.close()
        with pytest.raises(RuntimeError, match="closed"):
            inf.submit(_features(1))

    def test_stats_dispatches_consistent_under_concurrent_readers(self):
        snapshots = []
        stop = threading.Event()
        with ParallelInference(_mln(), workers=8, max_wait_ms=5) as inf:

            def reader():
                while not stop.is_set():
                    snapshots.append(inf.stats()["dispatches"])

            r = threading.Thread(target=reader, daemon=True)
            r.start()
            futs = [inf.submit(_features(1, seed=i)) for i in range(24)]
            for f in futs:
                f.result(timeout=60)
            stop.set()
            r.join(10)
            final = inf.stats()
        assert final["completed"] == 24
        assert final["dispatches"] >= 1
        # the counter only increments; a torn/unlocked read would show up
        # as a non-monotone snapshot sequence
        assert snapshots == sorted(snapshots)
