"""Declarative UI components: serde round-trips and standalone page
rendering (ports the intent of TestComponentSerialization and
TestStandAlone from deeplearning4j-ui-components)."""

import json

import numpy as np

from deeplearning4j_tpu.ui.components import (
    ChartHistogram,
    ChartHorizontalBar,
    ChartLine,
    ChartScatter,
    ChartStackedArea,
    Component,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    StyleChart,
    StyleText,
    render_html,
    render_html_file,
)


def _all_components():
    line = ChartLine(title="loss").add_series("train", [0, 1, 2],
                                              [1.0, 0.5, 0.3])
    line.add_series("val", [0, 1, 2], [1.2, 0.7, 0.5])
    scatter = ChartScatter(title="emb").add_series("pts", [0.1, 0.5],
                                                   [0.2, 0.9])
    hist = (ChartHistogram(title="weights")
            .add_bin(-1, 0, 5).add_bin(0, 1, 12))
    bars = ChartHorizontalBar(title="acc", labels=["a", "b"],
                              values=[0.9, 0.7])
    area = ChartStackedArea(title="mem", x=[0, 1, 2],
                            y=[[1, 2, 3], [2, 2, 2]], labels=["heap", "dev"])
    table = ComponentTable(header=["k", "v"],
                           content=[["lr", "0.01"], ["bs", "128"]])
    text = ComponentText(text="training report",
                         style=StyleText(font_size=18))
    return [line, scatter, hist, bars, area, table, text]


class TestComponentSerde:
    def test_round_trip_all_types(self):
        for c in _all_components():
            back = Component.from_json(c.to_json())
            assert type(back) is type(c)
            assert back == c, type(c).__name__

    def test_component_type_tag(self):
        d = json.loads(ChartLine(title="t").to_json())
        assert d["componentType"] == "ChartLine"

    def test_unknown_type_rejected(self):
        try:
            Component.from_json('{"componentType": "Nope"}')
        except ValueError:
            return
        raise AssertionError("expected ValueError")

    def test_div_nests_children(self):
        div = ComponentDiv().add(ComponentText(text="a"),
                                 ChartLine(title="b"))
        back = Component.from_json(div.to_json())
        assert len(back.children) == 2
        assert back.children[0]["componentType"] == "ComponentText"
        # children round-trip individually
        child = Component.from_dict(back.children[1])
        assert isinstance(child, ChartLine) and child.title == "b"

    def test_style_round_trip(self):
        c = ChartLine(style=StyleChart(width=200, stroke_width=4.0))
        back = Component.from_json(c.to_json())
        assert back.style.width == 200 and back.style.stroke_width == 4.0


class TestRenderHtml:
    def test_standalone_page_embeds_data_and_renderer(self):
        page = render_html(_all_components(), title="report 1")
        assert "<title>report 1</title>" in page
        assert "renderComponent" in page
        assert "ChartStackedArea" in page and "ComponentTable" in page
        # data embedded verbatim (training report text + a series value)
        assert "training report" in page
        # page is self-contained: no external scripts or stylesheets
        assert "http" not in page.split("</title>")[1]

    def test_script_breakout_escaped(self):
        page = render_html([ComponentText(text="x</script><b>oops")],
                           title="<t>&1")
        assert "</script><b>oops" not in page
        assert "<\\/script>" in page       # inert to the HTML parser
        assert "<title>&lt;t&gt;&amp;1</title>" in page

    def test_render_file(self, tmp_path):
        p = tmp_path / "report.html"
        render_html_file(_all_components(), str(p))
        assert p.read_text().startswith("<!doctype html>")

    def test_from_stats_histogram_renders(self):
        # end-to-end with the stats pipeline schema
        counts, edges = np.histogram(np.random.RandomState(0).randn(500),
                                     bins=10)
        h = ChartHistogram(title="0/W")
        for i, c in enumerate(counts):
            h.add_bin(edges[i], edges[i + 1], float(c))
        page = render_html([h])
        assert "0/W" in page and str(int(counts.max())) in page
