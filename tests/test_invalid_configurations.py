"""Invalid configs fail at build() with named-layer messages, not raw XLA
shape errors at fit time (ports the intent of
deeplearning4j-core/src/test/.../exceptions/TestInvalidConfigurations.java)."""

import pytest

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.convolution import (
    ConvolutionLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM
from deeplearning4j_tpu.nn.updater import Sgd


def _mln(*layers, input_type=None):
    b = (NeuralNetConfiguration.builder().seed(1)
         .updater(Sgd(learning_rate=0.1)).list(*layers))
    if input_type is not None:
        b = b.set_input_type(input_type)
    return b.build()


class TestZeroSizes:
    def test_dense_nout_0(self):
        with pytest.raises(ValueError, match="n_out must be > 0"):
            _mln(DenseLayer(n_out=0),
                 OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                 input_type=InputType.feed_forward(4))

    def test_dense_nin_unset_without_input_type(self):
        with pytest.raises(ValueError, match="n_in must be > 0"):
            _mln(DenseLayer(n_out=8),
                 OutputLayer(n_in=8, n_out=3, activation="softmax",
                             loss="mcxent"))

    def test_output_nout_0(self):
        with pytest.raises(ValueError, match="n_out must be > 0"):
            _mln(DenseLayer(n_out=8),
                 OutputLayer(n_out=0, activation="softmax", loss="mcxent"),
                 input_type=InputType.feed_forward(4))

    def test_lstm_nout_0(self):
        with pytest.raises(ValueError, match="n_out must be > 0"):
            _mln(LSTM(n_out=0),
                 OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                 input_type=InputType.recurrent(5))

    def test_conv_nout_0(self):
        with pytest.raises(ValueError, match="n_out must be > 0"):
            _mln(ConvolutionLayer(n_out=0, kernel_size=(3, 3)),
                 OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                 input_type=InputType.convolutional(8, 8, 1))

    def test_error_names_the_layer(self):
        with pytest.raises(ValueError, match="hidden2"):
            _mln(DenseLayer(n_out=8),
                 DenseLayer(n_out=0, name="hidden2"),
                 OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                 input_type=InputType.feed_forward(4))


class TestConvGeometry:
    def test_invalid_kernel(self):
        with pytest.raises(ValueError, match="kernel.*positive"):
            _mln(ConvolutionLayer(n_out=4, kernel_size=(0, 3)),
                 OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                 input_type=InputType.convolutional(8, 8, 1))

    def test_invalid_stride(self):
        with pytest.raises(ValueError, match="stride.*positive"):
            _mln(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                  stride=(0, 1)),
                 OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                 input_type=InputType.convolutional(8, 8, 1))

    def test_negative_padding(self):
        with pytest.raises(ValueError, match="padding.*non-negative"):
            _mln(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                  padding=(-1, 0)),
                 OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                 input_type=InputType.convolutional(8, 8, 1))

    def test_subsampling_invalid_kernel(self):
        with pytest.raises(ValueError, match="kernel.*positive"):
            _mln(ConvolutionLayer(n_out=4, kernel_size=(3, 3)),
                 SubsamplingLayer(kernel_size=(0, 2)),
                 OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                 input_type=InputType.convolutional(8, 8, 1))

    def test_input_smaller_than_kernel(self):
        # 8x8 input, 5x5 kernel, then a second 5x5 on the resulting 4x4
        with pytest.raises(ValueError, match="smaller than the .padded. "
                                             "kernel"):
            _mln(ConvolutionLayer(n_out=4, kernel_size=(5, 5)),
                 ConvolutionLayer(n_out=4, kernel_size=(5, 5)),
                 OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                 input_type=InputType.convolutional(8, 8, 1))

    def test_strict_mode_indivisible_stride(self):
        with pytest.raises(ValueError, match="Strict"):
            _mln(ConvolutionLayer(n_out=4, kernel_size=(2, 2),
                                  stride=(2, 2),
                                  convolution_mode="strict"),
                 OutputLayer(n_out=3, activation="softmax", loss="mcxent"),
                 input_type=InputType.convolutional(9, 9, 1))


class TestValidStillBuilds:
    def test_good_cnn_builds(self):
        conf = _mln(ConvolutionLayer(n_out=4, kernel_size=(3, 3)),
                    SubsamplingLayer(kernel_size=(2, 2)),
                    OutputLayer(n_out=3, activation="softmax",
                                loss="mcxent"),
                    input_type=InputType.convolutional(8, 8, 1))
        assert conf is not None


class TestValidationBypassesClosed:
    """Regressions for paths that skipped the base check: validate()
    overrides, wrapper layers, and graphs without declared input types."""

    def test_attention_without_input_type(self):
        from deeplearning4j_tpu.nn.conf.layers.attention import (
            SelfAttentionLayer,
        )
        with pytest.raises(ValueError, match="n_in must be > 0"):
            _mln(SelfAttentionLayer(n_out=16, n_heads=4),
                 OutputLayer(n_in=16, n_out=3, activation="softmax",
                             loss="mcxent"))

    def test_frozen_wrapper_validates_inner(self):
        from deeplearning4j_tpu.nn.conf.layers.misc import FrozenLayer
        with pytest.raises(ValueError, match="n_out must be > 0"):
            _mln(FrozenLayer(inner=DenseLayer(n_in=4, n_out=0)),
                 OutputLayer(n_in=8, n_out=3, activation="softmax",
                             loss="mcxent"))

    def test_graph_without_input_types_still_validates(self):
        b = (NeuralNetConfiguration.builder().seed(1)
             .updater(Sgd(learning_rate=0.1)).graph_builder()
             .add_inputs("in"))
        b.add_layer("bad", DenseLayer(n_in=4, n_out=0), "in")
        b.add_layer("out", OutputLayer(n_in=8, n_out=3,
                                       activation="softmax",
                                       loss="mcxent"), "bad")
        b.set_outputs("out")
        with pytest.raises(ValueError, match="n_out must be > 0"):
            b.build()


def test_attention_heads_must_divide_width():
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (RnnOutputLayer,
                                                   SelfAttentionLayer)

    with pytest.raises(ValueError, match="divisible"):
        (NeuralNetConfiguration.builder().seed(1)
         .list(SelfAttentionLayer(n_out=10, n_heads=3),
               RnnOutputLayer(n_out=2, activation="softmax", loss="mcxent"))
         .set_input_type(InputType.recurrent(10, 8)).build())
