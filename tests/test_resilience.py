"""Serving resilience (parallel/resilience.py + its wiring through
ParallelInference and KerasBackendServer).

The contract under test is the SRE one: an admitted request either
resolves or fails promptly with a typed error (DeadlineExceeded /
ServerOverloaded / CircuitOpen / the original error once the retry budget
is spent) — never hangs, never silently disappears. The headline is the
chaos end-to-end: a saturating burst of submits with 10% injected
transient faults loses ZERO futures.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.resilience import (
    AdmissionController,
    ChaosPolicy,
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
    ResilienceError,
    RetryPolicy,
    ServerOverloaded,
    TransientDispatchError,
)

from tests.test_fused_fit import _iris_like, _mln

pytestmark = pytest.mark.serving

TYPED = (DeadlineExceeded, ServerOverloaded, CircuitOpen,
         TransientDispatchError)


def _features(n, seed=0):
    return np.asarray(_iris_like(n, seed=seed).features)


# --------------------------------------------------------------- primitives
class TestDeadline:
    def test_remaining_counts_down_and_expires(self):
        t = [0.0]
        d = Deadline(1.0, clock=lambda: t[0])
        assert d.remaining() == pytest.approx(1.0)
        assert not d.expired()
        t[0] = 0.75
        assert d.remaining() == pytest.approx(0.25)
        t[0] = 1.25
        assert d.expired() and d.remaining() < 0

    def test_zero_budget_is_born_expired(self):
        assert Deadline(0.0).expired()


class TestRetryPolicy:
    def test_gives_up_after_budget_with_original_error(self):
        calls = []
        policy = RetryPolicy(max_attempts=3, seed=0, sleep=lambda s: None)

        def always_transient():
            calls.append(1)
            raise TransientDispatchError("flaky")

        with pytest.raises(TransientDispatchError, match="flaky"):
            policy.call(always_transient)
        assert len(calls) == 3

    def test_succeeds_mid_budget(self):
        calls = []
        policy = RetryPolicy(max_attempts=4, seed=0, sleep=lambda s: None)

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise TransientDispatchError("flaky")
            return "ok"

        assert policy.call(flaky) == "ok"
        assert len(calls) == 3

    def test_non_transient_errors_are_not_retried(self):
        calls = []
        policy = RetryPolicy(max_attempts=5, seed=0, sleep=lambda s: None)

        def hard():
            calls.append(1)
            raise ValueError("hard")

        with pytest.raises(ValueError):
            policy.call(hard)
        assert len(calls) == 1

    def test_backoff_is_capped_and_jittered_deterministically(self):
        a = RetryPolicy(base_s=0.01, cap_s=0.05, seed=7)
        b = RetryPolicy(base_s=0.01, cap_s=0.05, seed=7)
        seq_a = [a.backoff_s(0.01) for _ in range(20)]
        seq_b = [b.backoff_s(0.01) for _ in range(20)]
        assert seq_a == seq_b  # seeded: reproducible
        assert all(0.01 <= s <= 0.05 for s in seq_a)

    def test_deadline_too_tight_for_backoff_gives_up(self):
        sleeps = []
        policy = RetryPolicy(max_attempts=5, base_s=0.05, cap_s=0.05,
                             seed=0, sleep=sleeps.append)
        deadline = Deadline(0.01)  # cannot cover even one 50 ms backoff

        def always_transient():
            raise TransientDispatchError("flaky")

        with pytest.raises(TransientDispatchError):
            policy.call(always_transient, deadline=deadline)
        assert sleeps == []  # gave up instead of sleeping past the budget


class TestCircuitBreaker:
    def _breaker(self, t):
        return CircuitBreaker(failure_threshold=0.5, window=8, min_calls=4,
                              reset_timeout_s=10.0, clock=lambda: t[0])

    def test_closed_to_open_on_failure_rate(self):
        t = [0.0]
        br = self._breaker(t)
        assert br.state == CircuitBreaker.CLOSED
        for _ in range(3):
            br.record_failure()
        assert br.state == CircuitBreaker.CLOSED  # under min_calls
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        assert not br.allow()
        assert br.open_count == 1

    def test_successes_keep_failure_rate_under_threshold(self):
        t = [0.0]
        br = self._breaker(t)
        for _ in range(8):
            br.record_success()
        for _ in range(3):
            br.record_failure()  # 3/8 failures in window < 0.5
        assert br.state == CircuitBreaker.CLOSED

    def test_half_open_probe_then_close_on_success(self):
        t = [0.0]
        br = self._breaker(t)
        for _ in range(4):
            br.record_failure()
        assert not br.allow()
        t[0] = 10.0  # reset timeout elapses
        assert br.state == CircuitBreaker.HALF_OPEN
        assert br.allow()       # the single probe
        assert not br.allow()   # probe budget spent
        br.record_success()
        assert br.state == CircuitBreaker.CLOSED
        assert br.allow()

    def test_half_open_probe_failure_reopens(self):
        t = [0.0]
        br = self._breaker(t)
        for _ in range(4):
            br.record_failure()
        t[0] = 10.0
        assert br.allow()
        br.record_failure()
        assert br.state == CircuitBreaker.OPEN
        assert not br.allow()
        assert br.open_count == 2

    def test_lost_probe_does_not_wedge_half_open(self):
        """A probe that never reports (e.g. its request expired before
        dispatch) must not leave the breaker rejecting forever."""
        t = [0.0]
        br = self._breaker(t)
        for _ in range(4):
            br.record_failure()
        t[0] = 10.0
        assert br.allow()       # probe vanishes without an outcome
        assert not br.allow()
        t[0] = 20.0             # another reset window passes
        assert br.allow()       # probe budget replenished


class TestAdmissionController:
    def test_rejects_typed_at_watermark_and_releases(self):
        adm = AdmissionController(max_pending=2)
        adm.acquire()
        adm.acquire()
        with pytest.raises(ServerOverloaded):
            adm.acquire()
        assert (adm.accepted, adm.rejected, adm.pending) == (2, 1, 2)
        adm.release()
        adm.acquire()  # capacity freed
        assert adm.accepted == 3


class TestChaosPolicy:
    def test_deterministic_under_seed(self):
        def run(seed):
            chaos = ChaosPolicy(seed=seed, transient_rate=0.3,
                                hard_rate=0.1)
            fn = chaos.wrap(lambda: "ok")
            out = []
            for _ in range(50):
                try:
                    out.append(fn())
                except TransientDispatchError:
                    out.append("transient")
                except RuntimeError:
                    out.append("hard")
            return out

        assert run(3) == run(3)
        assert run(3) != run(4)

    def test_shutdown_modes_at_zero_rate_keep_legacy_sequence(self):
        # the PR-14 shutdown-phase draws are gated on their own rates:
        # calling them at rate 0 consumes NO rng draws, so every chaos
        # sequence recorded before they existed replays byte-identically
        def run(call_new_hooks):
            chaos = ChaosPolicy(seed=7, transient_rate=0.3, hard_rate=0.1)
            fn = chaos.wrap(lambda: "ok")
            out = []
            for _ in range(30):
                if call_new_hooks:
                    chaos.drain_fault()      # rate 0: no draw, no fault
                    chaos.sentinel_fault()
                try:
                    out.append(fn())
                except TransientDispatchError:
                    out.append("transient")
                except RuntimeError:
                    out.append("hard")
            return out

        assert run(True) == run(False)

    def test_shutdown_mode_draws_are_seeded(self):
        def seq(seed):
            chaos = ChaosPolicy(seed=seed, kill_during_drain_rate=0.5,
                                stall_sentinel_rate=0.5)
            hits = []
            for _ in range(40):
                try:
                    chaos.drain_fault()
                    hits.append(False)
                except BaseException:  # noqa: B036 — LoopKilled by design
                    hits.append(True)
            assert chaos.injected_drain_kill == sum(hits)
            return hits

        assert seq(5) == seq(5)
        assert seq(5) != seq(6)
        assert 0 < sum(seq(5)) < 40  # an actual mix at rate 0.5

    def test_rates_and_counters(self):
        chaos = ChaosPolicy(seed=0, transient_rate=0.5)
        fn = chaos.wrap(lambda: "ok")
        outcomes = []
        for _ in range(200):
            try:
                outcomes.append(fn())
            except TransientDispatchError:
                outcomes.append(None)
        n_faults = outcomes.count(None)
        assert n_faults == chaos.injected_transient
        assert 60 <= n_faults <= 140  # ~50% of 200
        assert chaos.injected_hard == 0

    def test_latency_injection(self):
        slept = []
        chaos = ChaosPolicy(seed=0, latency_rate=1.0, latency_s=0.05,
                            sleep=slept.append)
        assert chaos.wrap(lambda: "ok")() == "ok"
        assert slept == [0.05]
        assert chaos.injected_latency == 1


# ----------------------------------------------------- ParallelInference
class TestDeadlinesInServer:
    def test_born_expired_request_fails_typed_pre_dispatch(self):
        """Deadline expiry PRE-queue: a zero-budget submit fails with
        DeadlineExceeded and never costs a dispatch."""
        net = _mln()
        with ParallelInference(net, workers=8, max_wait_ms=5) as inf:
            inf.submit(_features(1)).result(timeout=30)  # warm
            base = inf.dispatch_count
            fut = inf.submit(_features(1), deadline_s=0.0)
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=10)
            assert inf.dispatch_count == base
            assert inf.stats()["expired"] == 1

    def test_request_expiring_mid_queue_fails_typed(self):
        """Deadline expiry MID-queue: requests stuck behind a slow
        dispatch expire in the coalescer, not on the device."""
        net = _mln()
        chaos = ChaosPolicy(seed=0, latency_rate=1.0, latency_s=0.4)
        with ParallelInference(net, workers=8, max_wait_ms=1,
                               chaos=chaos) as inf:
            ok = inf.submit(_features(1))
            time.sleep(0.1)  # ok's batch is now mid-dispatch (chaos sleep)
            dead = inf.submit(_features(1, seed=1), deadline_s=0.05)
            assert ok.result(timeout=30).shape == (1, 3)
            with pytest.raises(DeadlineExceeded):
                dead.result(timeout=30)

    def test_generous_deadline_resolves_normally(self):
        net = _mln()
        with ParallelInference(net, workers=8, max_wait_ms=5) as inf:
            ref = inf.output(_features(2))
            got = inf.submit(_features(2), deadline_s=60.0).result(timeout=30)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_short_deadline_flushes_batch_early(self):
        """Remaining-time propagation: a member with less budget than the
        coalesce window dispatches before expiry instead of after."""
        net = _mln()
        with ParallelInference(net, workers=8, max_batch=64,
                               max_wait_ms=10_000) as inf:
            inf.output(_features(1))  # warm the 1-row bucket
            fut = inf.submit(_features(1), deadline_s=1.0)
            # without the early flush this would wait out the 10 s window
            assert fut.result(timeout=5).shape == (1, 3)


class TestAdmissionInServer:
    def test_burst_beyond_watermark_sheds_typed(self):
        """Overload shedding: a burst past max_pending rejects immediately
        with ServerOverloaded; every ADMITTED request still resolves."""
        net = _mln()
        chaos = ChaosPolicy(seed=0, latency_rate=1.0, latency_s=0.05)
        with ParallelInference(net, workers=8, max_batch=4, max_wait_ms=1,
                               inflight=1, max_pending=8,
                               chaos=chaos) as inf:
            inf.output(_features(4))
            admitted, shed = [], 0
            for i in range(40):
                try:
                    admitted.append(inf.submit(_features(1, seed=i)))
                except ServerOverloaded:
                    shed += 1
            assert shed > 0, "burst never hit the watermark"
            for f in admitted:
                assert f.result(timeout=60).shape == (1, 3)
            st = inf.stats()
            assert st["rejected"] == shed
            assert st["accepted"] == len(admitted)
            assert st["pending"] == 0

    def test_rejected_submit_does_not_leak_pending(self):
        net = _mln()
        with ParallelInference(net, workers=8, max_pending=1,
                               max_wait_ms=5) as inf:
            inf.submit(_features(1)).result(timeout=30)
            assert inf.stats()["pending"] == 0


class TestBreakerInServer:
    def test_open_breaker_fast_fails_submits(self):
        """Sustained dispatch failure trips the breaker; subsequent
        submits fail with CircuitOpen without touching the queue."""
        net = _mln()
        chaos = ChaosPolicy(seed=0, hard_rate=1.0)  # every dispatch dies
        breaker = CircuitBreaker(failure_threshold=0.5, window=8,
                                 min_calls=2, reset_timeout_s=60.0)
        retry = RetryPolicy(max_attempts=1)
        with ParallelInference(net, workers=8, max_wait_ms=1, chaos=chaos,
                               breaker=breaker, retry=retry) as inf:
            failures = [inf.submit(_features(1, seed=i)) for i in range(4)]
            for f in failures:
                with pytest.raises(RuntimeError):
                    f.result(timeout=30)
            deadline = time.monotonic() + 10
            while (breaker.state != CircuitBreaker.OPEN
                   and time.monotonic() < deadline):
                try:
                    f = inf.submit(_features(1))
                except CircuitOpen:
                    break
                with pytest.raises(RuntimeError):
                    f.result(timeout=30)
            with pytest.raises(CircuitOpen):
                inf.submit(_features(1))
            assert inf.stats()["breaker_state"] == "open"
            assert inf.stats()["rejected_circuit"] >= 1

    def test_breaker_recovers_after_faults_stop(self):
        """Half-open probe succeeds once the fault source is gone and the
        server serves again."""
        net = _mln()
        chaos = ChaosPolicy(seed=0, hard_rate=1.0)
        breaker = CircuitBreaker(failure_threshold=0.5, window=8,
                                 min_calls=2, reset_timeout_s=0.2)
        retry = RetryPolicy(max_attempts=1)
        inf = ParallelInference(net, workers=8, max_wait_ms=1, chaos=chaos,
                                breaker=breaker, retry=retry)
        try:
            for i in range(3):
                with pytest.raises(RuntimeError):
                    inf.submit(_features(1, seed=i)).result(timeout=30)
            # stop the chaos: dispatches are healthy again
            chaos.hard_rate = 0.0
            deadline = time.monotonic() + 15
            out = None
            while out is None and time.monotonic() < deadline:
                try:
                    out = inf.submit(_features(1)).result(timeout=30)
                except (CircuitOpen, RuntimeError):
                    time.sleep(0.05)  # waits out reset_timeout_s
            assert out is not None and out.shape == (1, 3)
            assert breaker.state == CircuitBreaker.CLOSED
        finally:
            inf.close()


class TestRetryInServer:
    def test_transient_faults_are_retried_to_success(self):
        """A fault rate well under the retry budget: every request
        resolves, and the retry counter shows the policy worked."""
        net = _mln()
        chaos = ChaosPolicy(seed=1, transient_rate=0.3)
        retry = RetryPolicy(max_attempts=6, base_s=1e-4, cap_s=1e-3, seed=0)
        with ParallelInference(net, workers=8, max_wait_ms=1, chaos=chaos,
                               breaker=False, retry=retry) as inf:
            ref = inf.output(_features(1))
            futs = [inf.submit(_features(1)) for _ in range(30)]
            for f in futs:
                np.testing.assert_allclose(f.result(timeout=60), ref,
                                           rtol=1e-5, atol=1e-6)
            assert inf.stats()["retried"] >= 1
            assert chaos.injected_transient >= 1

    def test_retry_budget_exhaustion_surfaces_original_error(self):
        net = _mln()
        chaos = ChaosPolicy(seed=0, transient_rate=1.0)  # never heals
        retry = RetryPolicy(max_attempts=3, base_s=1e-4, cap_s=1e-3, seed=0)
        with ParallelInference(net, workers=8, max_wait_ms=1, chaos=chaos,
                               breaker=False, retry=retry) as inf:
            fut = inf.submit(_features(1))
            with pytest.raises(TransientDispatchError):
                fut.result(timeout=30)


class TestDrainAndClose:
    def test_drain_completes_inflight_and_rejects_new(self):
        net = _mln()
        chaos = ChaosPolicy(seed=0, latency_rate=1.0, latency_s=0.05)
        inf = ParallelInference(net, workers=8, max_batch=2, max_wait_ms=1,
                                chaos=chaos)
        try:
            inf.output(_features(2))
            futs = [inf.submit(_features(1, seed=i)) for i in range(6)]
            drainer = {}

            def drain():
                drainer["ok"] = inf.drain(timeout=60)

            t = threading.Thread(target=drain)
            t.start()
            time.sleep(0.01)  # let drain flip the draining flag
            with pytest.raises(RuntimeError, match="draining"):
                inf.submit(_features(1))
            t.join(70)
            assert drainer["ok"] is True
            for f in futs:
                assert f.result(timeout=1).shape == (1, 3)  # already done
            assert inf.stats()["pending"] == 0
        finally:
            inf.close()

    def test_drain_idle_server_returns_immediately(self):
        net = _mln()
        inf = ParallelInference(net, workers=8)
        assert inf.drain(timeout=1) is True
        inf.close()

    def test_close_still_resolves_everything(self):
        """close() (drain + shutdown) leaves no unresolved future."""
        net = _mln()
        inf = ParallelInference(net, workers=8, max_wait_ms=1)
        futs = [inf.submit(_features(1, seed=i)) for i in range(8)]
        inf.close()
        for f in futs:
            assert f.done()
            # each either resolved with rows or failed typed by shutdown
            if f.exception() is None:
                assert f.result().shape == (1, 3)

    def test_submit_after_close_still_raises_closed(self):
        net = _mln()
        inf = ParallelInference(net, workers=8)
        inf.close()
        with pytest.raises(RuntimeError, match="closed"):
            inf.submit(_features(1))


class TestChaosEndToEnd:
    def test_200_submits_10pct_faults_zero_lost_futures(self):
        """THE acceptance criterion: a saturating burst of 200 submits
        with 10% injected transient faults — every future resolves or
        fails with a typed error; none is lost or left pending."""
        net = _mln()
        chaos = ChaosPolicy(seed=42, transient_rate=0.10)
        retry = RetryPolicy(max_attempts=4, base_s=1e-4, cap_s=2e-3, seed=0)
        with ParallelInference(net, workers=8, max_batch=16, max_wait_ms=1,
                               max_pending=512, retry=retry,
                               chaos=chaos) as inf:
            ref = inf.output(_features(1))
            futs, shed = [], 0
            for i in range(200):
                try:
                    futs.append(inf.submit(_features(1)))
                except (ServerOverloaded, CircuitOpen):
                    shed += 1  # typed at submit: also not lost
            resolved = failed_typed = 0
            for f in futs:
                try:
                    out = f.result(timeout=120)
                    np.testing.assert_allclose(out, ref, rtol=1e-5,
                                               atol=1e-6)
                    resolved += 1
                except TYPED:
                    failed_typed += 1
            assert resolved + failed_typed == len(futs)
            assert resolved + failed_typed + shed == 200
            for f in futs:
                assert f.done(), "a future was left pending"
            st = inf.stats()
            assert st["pending"] == 0
            assert st["completed"] == resolved
            assert chaos.injected_transient > 0, "chaos never fired"
            # at 10% faults with a 4-attempt budget, retries recover the
            # overwhelming majority of requests
            assert resolved >= 0.95 * len(futs)


# ------------------------------------------------------ KerasBackendServer
class _FakeNet:
    """Stands in for an imported Keras model: deterministic output, no
    keras dependency, optional injected latency."""

    def __init__(self, latency_s=0.0):
        self.latency_s = latency_s

    def output(self, x):
        if self.latency_s:
            time.sleep(self.latency_s)
        x = np.asarray(x, np.float32)
        return x * 2.0


class _Http:
    def __init__(self, port):
        self.base = f"http://127.0.0.1:{port}"

    def post(self, path, payload, raw=None):
        body = raw if raw is not None else json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base + path, body, {"Content-Type": "application/json"})
        try:
            resp = urllib.request.urlopen(req)
            return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def get(self, path):
        resp = urllib.request.urlopen(self.base + path)
        return resp.status, json.loads(resp.read())


@pytest.fixture
def http_server():
    from deeplearning4j_tpu.modelimport.server import KerasBackendServer

    def make(**kwargs):
        srv = KerasBackendServer(**kwargs)
        srv._models["m0"] = _FakeNet()
        servers.append(srv)
        return srv, _Http(srv.start())

    servers = []
    try:
        yield make
    finally:
        for s in servers:
            s.stop()


class TestHttpErrorContract:
    def test_malformed_json_is_structured_400(self, http_server):
        srv, http = http_server()
        status, body = http.post("/predict", None, raw=b"{not json]")
        assert status == 400
        assert body["type"] == "BadRequest" and "error" in body

    def test_non_object_json_is_400(self, http_server):
        srv, http = http_server()
        status, body = http.post("/predict", None, raw=b"[1, 2, 3]")
        assert status == 400 and body["type"] == "BadRequest"

    def test_unknown_model_is_404(self, http_server):
        srv, http = http_server()
        status, body = http.post("/predict", {"model": "nope",
                                              "features": [[1.0]]})
        assert status == 404
        assert body["type"] == "UnknownModelError"
        assert "nope" in body["error"]

    def test_missing_field_is_400_not_404(self, http_server):
        srv, http = http_server()
        status, body = http.post("/predict", {"model": "m0"})
        assert status == 400 and body["type"] == "BadRequest"

    def test_oversized_body_is_413_without_buffering(self, http_server):
        srv, http = http_server(max_body_bytes=128)
        big = {"model": "m0", "features": [[0.0] * 1000]}
        status, body = http.post("/predict", big)
        assert status == 413 and body["type"] == "BodyTooLarge"

    def test_multi_megabyte_oversized_body_still_gets_its_413(
            self, http_server):
        """The client must RECEIVE the 413 even when its send is still in
        flight — the server drains (discards) the oversized body instead
        of slamming the socket into the client's sendall."""
        srv, http = http_server(max_body_bytes=1 << 20)
        big = {"model": "m0", "features": [[0.0] * 784] * 400}  # > 1 MB
        status, body = http.post("/predict", big)
        assert status == 413 and body["type"] == "BodyTooLarge"

    def test_unknown_route_is_404(self, http_server):
        srv, http = http_server()
        status, body = http.post("/nope", {})
        assert status == 404

    def test_happy_path_predict_and_stats(self, http_server):
        srv, http = http_server()
        status, body = http.post("/predict", {"model": "m0",
                                              "features": [[1.0, 2.0]]})
        assert status == 200
        assert body["output"] == [[2.0, 4.0]]
        status, st = http.get("/stats")
        assert status == 200
        assert st["completed"] == 1 and st["accepted"] == 1
        assert st["breaker_state"] == "closed"


class TestHttpResilienceMapping:
    def test_deadline_maps_to_504(self, http_server):
        srv, http = http_server()
        status, body = http.post(
            "/predict",
            {"model": "m0", "features": [[1.0]], "deadline_s": 0.0})
        assert status == 504 and body["type"] == "DeadlineExceeded"
        assert srv.stats()["expired"] == 1

    def test_overload_maps_to_429(self, http_server):
        srv, http = http_server(max_pending=1)
        srv._models["m0"].latency_s = 0.5
        results = []

        def hit():
            results.append(http.post("/predict", {"model": "m0",
                                                  "features": [[1.0]]}))

        threads = [threading.Thread(target=hit) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        codes = sorted(status for status, _ in results)
        assert 429 in codes, codes
        assert 200 in codes, codes  # the admitted request still served
        rejected = [b for s, b in results if s == 429]
        assert all(b["type"] == "ServerOverloaded" for b in rejected)
        assert srv.stats()["rejected"] == codes.count(429)

    def test_open_breaker_maps_to_503(self, http_server):
        chaos = ChaosPolicy(seed=0, hard_rate=1.0)
        breaker = CircuitBreaker(failure_threshold=0.5, window=4,
                                 min_calls=2, reset_timeout_s=60.0)
        srv, http = http_server(
            chaos=chaos, breaker=breaker,
            retry=RetryPolicy(max_attempts=1))
        for _ in range(3):
            status, _ = http.post("/predict", {"model": "m0",
                                               "features": [[1.0]]})
            assert status in (500, 503)
        status, body = http.post("/predict", {"model": "m0",
                                              "features": [[1.0]]})
        assert status == 503 and body["type"] == "CircuitOpen"
        assert srv.stats()["breaker_state"] == "open"

    def test_transient_faults_retried_transparently(self, http_server):
        chaos = ChaosPolicy(seed=1, transient_rate=0.4)
        srv, http = http_server(
            chaos=chaos,
            retry=RetryPolicy(max_attempts=6, base_s=1e-4, cap_s=1e-3,
                              seed=0))
        for _ in range(10):
            status, body = http.post("/predict", {"model": "m0",
                                                  "features": [[3.0]]})
            assert status == 200 and body["output"] == [[6.0]]
        assert srv.stats()["retried"] >= 1


# ------------------------------------------------- stats-lock discipline
class TestServerStatsLockDiscipline:
    def test_concurrent_predicts_count_exactly(self):
        """Every stats counter moves under self._stats_lock (graftcheck
        conc-mixed-lock gate): hammer predict() from many threads while a
        reader spins on stats(); the final completed count must be exact
        and no intermediate snapshot may exceed it."""
        from deeplearning4j_tpu.modelimport.server import KerasBackendServer

        class _Net:
            def output(self, x):
                return np.asarray(x) * 2.0

        srv = KerasBackendServer(max_pending=64)
        srv._models["m0"] = _Net()

        threads, per, errs = 8, 25, []
        snapshots = []
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                snapshots.append(srv.stats()["completed"])

        def hammer():
            try:
                for _ in range(per):
                    out = srv.predict("m0", [[1.0, 2.0]])
                    assert out == [[2.0, 4.0]]
            except Exception as e:  # pragma: no cover - failure detail
                errs.append(e)

        r = threading.Thread(target=reader, daemon=True)
        r.start()
        ts = [threading.Thread(target=hammer) for _ in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        stop.set()
        r.join(10)
        assert errs == []
        st = srv.stats()
        assert st["completed"] == threads * per
        assert st["failed"] == 0
        assert all(0 <= s <= threads * per for s in snapshots)
