"""Transfer learning tests (ports the intent of
nn/transferlearning/TransferLearningMLNTest.java / CompGraphTest.java /
TransferLearningHelperTest.java)."""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.layers.misc import FrozenLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration,
    TransferLearning,
    TransferLearningHelper,
)
from deeplearning4j_tpu.nn.updater import Adam, Sgd


def _mln(n_in=4, n_out=3, seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(learning_rate=0.01))
            .list(DenseLayer(n_out=8, activation="tanh"),
                  DenseLayer(n_out=6, activation="tanh"),
                  OutputLayer(n_out=n_out, activation="softmax",
                              loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=24, n_in=4, n_classes=3, seed=0):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, n_classes, n)
    x = (rs.randn(n, n_in) + labels[:, None]).astype(np.float32)
    return DataSet(x, np.eye(n_classes, dtype=np.float32)[labels])


class TestTransferLearningMLN:
    def test_feature_extractor_freezes_layers(self):
        net = _mln()
        ds = _data()
        net.fit(ds, epochs=3)
        new_net = (TransferLearning.Builder(net)
                   .set_feature_extractor(1)
                   .build())
        assert isinstance(new_net.conf.layers[0], FrozenLayer)
        assert isinstance(new_net.conf.layers[1], FrozenLayer)
        assert not isinstance(new_net.conf.layers[2], FrozenLayer)
        p0_before = np.asarray(new_net.params["0"]["W"]).copy()
        p2_before = np.asarray(new_net.params["2"]["W"]).copy()
        new_net.fit(ds, epochs=5)
        assert np.allclose(np.asarray(new_net.params["0"]["W"]), p0_before)
        assert not np.allclose(np.asarray(new_net.params["2"]["W"]),
                               p2_before)

    def test_frozen_params_copied_from_original(self):
        net = _mln()
        ds = _data()
        net.fit(ds, epochs=2)
        new_net = (TransferLearning.Builder(net)
                   .set_feature_extractor(0).build())
        for i in ("0", "1", "2"):
            for k in net.params[i]:
                assert np.allclose(np.asarray(net.params[i][k]),
                                   np.asarray(new_net.params[i][k]))

    def test_nout_replace_reinits_this_and_next(self):
        net = _mln()
        new_net = (TransferLearning.Builder(net)
                   .nout_replace(1, 12, weight_init="xavier")
                   .build())
        assert new_net.params["1"]["W"].shape == (8, 12)
        assert new_net.params["2"]["W"].shape == (12, 3)
        # layer 0 copied
        assert np.allclose(np.asarray(net.params["0"]["W"]),
                           np.asarray(new_net.params["0"]["W"]))

    def test_remove_and_add_output_layer(self):
        net = _mln()
        new_net = (TransferLearning.Builder(net)
                   .set_feature_extractor(1)
                   .remove_output_layer()
                   .add_layer(DenseLayer(n_out=5, activation="relu"))
                   .add_layer(OutputLayer(n_out=7, activation="softmax",
                                          loss="mcxent"))
                   .build())
        assert len(new_net.conf.layers) == 4
        x = _data().features
        out = np.asarray(new_net.output(x))
        assert out.shape == (24, 7)
        new_net.fit(_data(n_classes=7), epochs=2)

    def test_fine_tune_configuration_overrides(self):
        net = _mln()
        ftc = FineTuneConfiguration(updater=Sgd(learning_rate=0.5),
                                    l2=0.01, seed=99)
        new_net = (TransferLearning.Builder(net)
                   .fine_tune_configuration(ftc)
                   .build())
        assert type(new_net.conf.updater).__name__ == "Sgd"
        assert new_net.conf.updater.learning_rate == 0.5
        assert new_net.conf.seed == 99
        assert new_net.conf.layers[1].l2 == 0.01

    def test_transfer_net_trains(self):
        net = _mln()
        ds = _data()
        net.fit(ds, epochs=3)
        new_net = (TransferLearning.Builder(net)
                   .set_feature_extractor(0)
                   .nout_replace(2, 3, weight_init="xavier")
                   .build())
        s0 = new_net.score(ds)
        new_net.fit(ds, epochs=10)
        assert new_net.score(ds) < s0


class TestTransferLearningHelper:
    def test_featurize_and_fit(self):
        net = _mln()
        ds = _data()
        net.fit(ds, epochs=2)
        frozen = (TransferLearning.Builder(net)
                  .set_feature_extractor(1).build())
        helper = TransferLearningHelper(frozen)
        fds = helper.featurize(ds)
        assert fds.features.shape == (24, 6)  # boundary activations
        s0 = helper.unfrozen_mln().score(fds)
        helper.fit_featurized(fds, epochs=10)
        assert helper.unfrozen_mln().score(fds) < s0
        # featurized training == full-net equivalent output
        out_full = np.asarray(frozen.output(ds.features))
        out_sub = np.asarray(helper.output_featurized(fds.features))
        assert np.allclose(out_full, out_sub, atol=1e-5)


class TestTransferLearningGraph:
    def _graph(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(3).updater(Adam(learning_rate=0.01))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=8, activation="tanh"), "in")
                .add_layer("d2", DenseLayer(n_out=6, activation="tanh"), "d1")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "d2")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        return ComputationGraph(conf).init()

    def test_freeze_ancestors(self):
        g = self._graph()
        ds = _data()
        g.fit(ds, epochs=2)
        new_g = (TransferLearning.GraphBuilder(g)
                 .set_feature_extractor("d2")
                 .build())
        assert isinstance(new_g.conf.vertices["d1"].layer, FrozenLayer)
        assert isinstance(new_g.conf.vertices["d2"].layer, FrozenLayer)
        assert not isinstance(new_g.conf.vertices["out"].layer, FrozenLayer)
        d1 = np.asarray(new_g.params["d1"]["W"]).copy()
        new_g.fit(ds, epochs=4)
        assert np.allclose(np.asarray(new_g.params["d1"]["W"]), d1)

    def test_replace_head(self):
        g = self._graph()
        new_g = (TransferLearning.GraphBuilder(g)
                 .set_feature_extractor("d2")
                 .remove_vertex_and_connections("out")
                 .add_layer("newout", OutputLayer(n_out=5,
                                                  activation="softmax",
                                                  loss="mcxent"), "d2")
                 .set_outputs("newout")
                 .build())
        out = np.asarray(new_g.output(_data().features))
        assert out.shape == (24, 5)
        new_g.fit(_data(n_classes=5), epochs=2)

    def test_nout_replace_graph(self):
        g = self._graph()
        new_g = (TransferLearning.GraphBuilder(g)
                 .nout_replace("d2", 10, weight_init="xavier")
                 .build())
        assert new_g.params["d2"]["W"].shape == (8, 10)
        assert new_g.params["out"]["W"].shape == (10, 3)

    def test_nout_replace_propagates_through_parameterless_vertices(self):
        """Width change must flow through ElementWise/Activation vertices to
        the next parameterised layer (the DAG analogue of the MLN builder's
        scan-to-next-parameterised-layer)."""
        from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertex
        from deeplearning4j_tpu.nn.conf.layers.core import ActivationLayer

        conf = (NeuralNetConfiguration.builder()
                .seed(5).updater(Adam(learning_rate=0.01))
                .graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_out=6, activation="tanh"), "in")
                .add_layer("d2", DenseLayer(n_out=6, activation="identity"),
                           "d1")
                .add_vertex("res", ElementWiseVertex(op="add"), "d1", "d2")
                .add_layer("act", ActivationLayer(activation="relu"), "res")
                .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                              loss="mcxent"), "act")
                .set_outputs("out")
                .set_input_types(InputType.feed_forward(4))
                .build())
        g = ComputationGraph(conf).init()
        new_g = (TransferLearning.GraphBuilder(g)
                 .nout_replace("d1", 9, weight_init="xavier")
                 .nout_replace("d2", 9, weight_init="xavier")
                 .build())
        assert new_g.params["out"]["W"].shape == (9, 3)
        out = np.asarray(new_g.output(_data().features))
        assert out.shape == (24, 3)

    def test_remove_frozen_vertex_then_build(self):
        g = self._graph()
        new_g = (TransferLearning.GraphBuilder(g)
                 .set_feature_extractor("d2")
                 .remove_vertex_and_connections("out")
                 .add_layer("newout", OutputLayer(n_out=2,
                                                  activation="softmax",
                                                  loss="mcxent"), "d2")
                 .set_outputs("newout")
                 .build())
        assert "newout" in new_g.conf.vertices
