"""Mesh data-parallel tests on the virtual 8-device CPU mesh.

The core invariant is ported from the reference's
TestCompareParameterAveragingSparkVsSingleMachine.java: distributed training
with averaging_frequency=1 must equal single-machine training on the
concatenated batch, to float tolerance. Plus: SHARED_GRADIENTS step parity,
averaging_frequency>1 local-SGD rounds, sharded inference parity, and
map-reduce Evaluation.merge.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.layers.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam, Sgd
from deeplearning4j_tpu.parallel import (
    ParallelInference,
    ParallelWrapper,
    data_mesh,
    evaluate_on_mesh,
)


def _mlp_conf(updater, seed=42):
    return (NeuralNetConfiguration.builder()
            .seed(seed).updater(updater)
            .list(DenseLayer(n_in=6, n_out=16, activation="tanh"),
                  OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss="mcxent"))
            .build())


def _make_data(n, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 6).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, n)]
    return x, y


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_averaging_freq1_equals_single_device():
    """8-device DP with per-device batch 4 == single device with batch 32."""
    W, B, steps = 8, 4, 5
    x, y = _make_data(W * B * steps)

    single = MultiLayerNetwork(_mlp_conf(Sgd(learning_rate=0.1))).init()
    for s in range(steps):
        sl = slice(s * W * B, (s + 1) * W * B)
        single.do_step(x[sl], y[sl])

    dist = MultiLayerNetwork(_mlp_conf(Sgd(learning_rate=0.1))).init()
    batches = [DataSet(x[i * B:(i + 1) * B], y[i * B:(i + 1) * B])
               for i in range(W * steps)]
    pw = ParallelWrapper(dist, workers=8, averaging_frequency=1)
    pw.fit(ListDataSetIterator(batches, batch_size=B))

    for k in single.params:
        for name in single.params[k]:
            np.testing.assert_allclose(
                np.asarray(dist.params[k][name]),
                np.asarray(single.params[k][name]), rtol=1e-5, atol=1e-6,
                err_msg=f"param {k}/{name}")


def test_shared_gradients_equals_single_device_adam():
    """SHARED_GRADIENTS keeps replicas exactly in sync even with Adam state."""
    W, B, steps = 8, 4, 4
    x, y = _make_data(W * B * steps, seed=3)

    single = MultiLayerNetwork(_mlp_conf(Adam(learning_rate=1e-2))).init()
    for s in range(steps):
        sl = slice(s * W * B, (s + 1) * W * B)
        single.do_step(x[sl], y[sl])

    dist = MultiLayerNetwork(_mlp_conf(Adam(learning_rate=1e-2))).init()
    batches = [DataSet(x[i * B:(i + 1) * B], y[i * B:(i + 1) * B])
               for i in range(W * steps)]
    pw = ParallelWrapper(dist, workers=8, averaging_frequency=1,
                         mode="shared_gradients")
    pw.fit(ListDataSetIterator(batches, batch_size=B))

    for k in single.params:
        for name in single.params[k]:
            np.testing.assert_allclose(
                np.asarray(dist.params[k][name]),
                np.asarray(single.params[k][name]), rtol=1e-4, atol=1e-5,
                err_msg=f"param {k}/{name}")


def test_averaging_frequency_local_sgd():
    """freq=3: 8 workers each take 3 local steps then average; loss decreases
    and the final params are finite and shared."""
    W, B, F, rounds = 8, 4, 3, 4
    x, y = _make_data(W * B * F * rounds, seed=5)
    net = MultiLayerNetwork(_mlp_conf(Sgd(learning_rate=0.1))).init()
    batches = [DataSet(x[i * B:(i + 1) * B], y[i * B:(i + 1) * B])
               for i in range(W * F * rounds)]
    pw = ParallelWrapper(net, workers=8, averaging_frequency=F)
    s0 = net.score(x=x, y=y)
    pw.fit(ListDataSetIterator(batches, batch_size=B), epochs=3)
    s1 = net.score(x=x, y=y)
    assert np.isfinite(s1) and s1 < s0
    assert net.iteration == 3 * rounds * F


def test_averaging_with_updater_state():
    """freq>1 with a momentum updater: updater state averaged without error."""
    W, B, F = 4, 4, 2
    x, y = _make_data(W * B * F * 3, seed=7)
    net = MultiLayerNetwork(_mlp_conf(Adam(learning_rate=1e-2))).init()
    batches = [DataSet(x[i * B:(i + 1) * B], y[i * B:(i + 1) * B])
               for i in range(W * F * 3)]
    mesh = data_mesh(4)
    pw = ParallelWrapper(net, mesh=mesh, averaging_frequency=F,
                         average_updaters=True)
    pw.fit(ListDataSetIterator(batches, batch_size=B))
    flat = net.params_flat()
    assert np.all(np.isfinite(flat))
    # Adam slots must mirror param structure after averaging
    assert set(net.updater_state.keys()) == {"m", "v"}


def test_parallel_inference_matches_output():
    net = MultiLayerNetwork(_mlp_conf(Sgd(learning_rate=0.1))).init()
    x, y = _make_data(21, seed=11)  # deliberately not divisible by 8
    inf = ParallelInference(net, workers=8)
    out_par = inf.output(x)
    out_seq = np.asarray(net.output(x))
    np.testing.assert_allclose(out_par, out_seq, rtol=1e-5, atol=1e-6)


def test_parallel_inference_rnn_with_mask():
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Sgd(learning_rate=0.1))
            .list(LSTM(n_in=4, n_out=6),
                  RnnOutputLayer(n_in=6, n_out=2, activation="softmax",
                                 loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rs = np.random.RandomState(2)
    x = rs.randn(10, 5, 4).astype(np.float32)
    mask = (rs.rand(10, 5) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0
    inf = ParallelInference(net, workers=8)
    np.testing.assert_allclose(inf.output(x, mask=mask),
                               np.asarray(net.output(x, mask=mask)),
                               rtol=1e-5, atol=1e-6)


def test_distributed_evaluation_merge():
    """Mesh evaluation (per-shard evals + merge) == sequential evaluation."""
    net = MultiLayerNetwork(_mlp_conf(Sgd(learning_rate=0.1))).init()
    x, y = _make_data(64, seed=13)
    batches = [DataSet(x[i * 16:(i + 1) * 16], y[i * 16:(i + 1) * 16])
               for i in range(4)]
    net.fit(ListDataSetIterator(batches, batch_size=16), epochs=2)
    ev_seq = net.evaluate(ListDataSetIterator(batches, batch_size=16))
    ev_par = evaluate_on_mesh(net, ListDataSetIterator(batches, batch_size=16))
    assert ev_par.accuracy() == pytest.approx(ev_seq.accuracy())
    assert ev_par.f1() == pytest.approx(ev_seq.f1())


def test_mid_stream_batch_mismatch_warns_and_counts():
    """A mid-stream minibatch of odd size is dropped WITH a warning and
    counted; a genuine trailing partial is skipped silently (reference
    semantics: ParallelWrapper.java:409-487 drops only trailing partial
    worker groups)."""
    import warnings as _w

    x, y = _make_data(8 * 4)
    batches = [DataSet(x[i * 4:(i + 1) * 4], y[i * 4:(i + 1) * 4])
               for i in range(8)]
    odd = DataSet(x[:2], y[:2])

    # mid-stream odd batch -> warning + counter
    net = MultiLayerNetwork(_mlp_conf(Sgd(learning_rate=0.1))).init()
    pw = ParallelWrapper(net, workers=8, averaging_frequency=1)
    stream = batches[:4] + [odd] + batches[4:]
    with _w.catch_warnings(record=True) as caught:
        _w.simplefilter("always")
        pw.fit(stream, epochs=1)
    assert pw.dropped_batches == 1
    assert any("mid-stream" in str(w.message) for w in caught)

    # trailing partial -> silent, not counted
    net2 = MultiLayerNetwork(_mlp_conf(Sgd(learning_rate=0.1))).init()
    pw2 = ParallelWrapper(net2, workers=8, averaging_frequency=1)
    with _w.catch_warnings(record=True) as caught2:
        _w.simplefilter("always")
        pw2.fit(batches + [odd], epochs=1)
    assert pw2.dropped_batches == 0
    assert not any("mid-stream" in str(w.message) for w in caught2)
