"""Format-stability regression tests (reference:
regressiontest/RegressionTest050/060/071/080.java — model files produced
by OLD versions must keep loading and producing identical outputs; the
serialization format is a tested contract, not an implementation detail).

The fixtures under tests/fixtures/ are COMMITTED artifacts of the round
that produced them (``*_r4`` by round-4 code, ``*_r5`` by round-5 code) —
never regenerate them to make a failing test pass; a failure here means
the format or numerics changed incompatibly.
"""

import os

import numpy as np

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


class TestModelZipFormat:
    def test_round4_convnet_zip_loads_and_reproduces(self):
        from deeplearning4j_tpu.utils.model_serializer import load_model

        net = load_model(os.path.join(FIXTURES,
                                      "regression_convnet_r4.zip"))
        exp = np.load(os.path.join(FIXTURES,
                                   "regression_convnet_r4_expected.npz"))
        assert abs(float(np.asarray(net.params_flat()).sum())
                   - float(exp["params_sum"])) < 1e-4
        out = np.asarray(net.output(exp["probe"]))
        np.testing.assert_allclose(out, exp["output"], atol=1e-5)
        # a loaded model must remain trainable (updater state intact)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rs = np.random.RandomState(0)
        net.fit(DataSet(rs.randn(8, 8, 8, 1).astype(np.float32),
                        np.eye(3, dtype=np.float32)[rs.randint(0, 3, 8)]))

    def test_round4_zip_via_model_guesser(self):
        from deeplearning4j_tpu.utils.model_guesser import (guess_format,
                                                            load_model_guess)
        p = os.path.join(FIXTURES, "regression_convnet_r4.zip")
        assert guess_format(p) == "dl4j-zip"
        assert load_model_guess(p) is not None


class TestWordVectorFormat:
    def test_round4_binary_vectors_load(self):
        from deeplearning4j_tpu.nlp.serde import read_word2vec_binary

        words, vecs = read_word2vec_binary(
            os.path.join(FIXTURES, "regression_vectors_r4.bin"))
        exp = np.load(os.path.join(FIXTURES,
                                   "regression_vectors_r4_expected.npz"))
        i = words.index("w1")
        np.testing.assert_allclose(vecs[i], exp["w1"], atol=1e-6)
        assert vecs.shape[1] == 12

    def test_round5_transformer_zip_loads_and_reproduces(self):
        """Round-5 fixture: a trained TransformerLM ComputationGraph —
        pins the wire format of the graph config plus the new layer
        types (SelfAttentionLayer, LayerNormalization,
        PositionalEncodingLayer) and their params."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.utils.model_serializer import load_model

        net = load_model(os.path.join(
            FIXTURES, "regression_transformer_r5.zip"))
        exp = np.load(os.path.join(
            FIXTURES, "regression_transformer_r5_expected.npz"))
        assert abs(float(np.asarray(net.params_flat()).sum())
                   - float(exp["params_sum"])) < 1e-4
        out = np.asarray(net.output(exp["probe"]))
        np.testing.assert_allclose(out, exp["output"], atol=1e-5)
        # loaded graph remains trainable AND streamable
        V = exp["probe"].shape[-1]
        rs = np.random.RandomState(1)
        idx = rs.randint(0, V, (2, exp["probe"].shape[1]))
        oh = np.eye(V, dtype=np.float32)[idx]
        net.fit(DataSet(oh, oh))
        net.rnn_clear_previous_state()
        stream = np.asarray(net.rnn_time_step(exp["probe"][:, :3]))
        assert stream.shape == (2, 3, V)
