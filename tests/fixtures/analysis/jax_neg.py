"""Must-NOT-flag cases for the JAX rules, including the known-tricky
negatives (graftcheck fixture — never imported, only parsed)."""
from functools import partial

import jax
import numpy as np


def make_step(steps):
    # TRICKY NEGATIVE jax-retrace-hazard: `steps` is closure CONFIG —
    # fixed at trace time, the if is resolved once (models/zoo.py
    # generate() does exactly this)
    @jax.jit
    def step(x):
        if steps == 1:
            return x
        return x * steps

    return step


@partial(jax.jit, static_argnames=("mode",))
def static_name_branch(x, mode):
    # NEGATIVE jax-retrace-hazard: `mode` is declared static
    if mode == "fast":
        return x
    return x * 2


@jax.jit
def none_check(x, mask):
    # TRICKY NEGATIVE jax-retrace-hazard: `is None` is concrete at
    # trace time (the pytree structure, not the traced value)
    if mask is None:
        return x
    return x * mask


@jax.jit
def shape_branch(x):
    # NEGATIVE jax-retrace-hazard: .shape/.ndim are trace-time statics
    if x.shape[0] > 4 and x.ndim == 2:
        return x.sum(axis=0)
    for i in range(x.shape[0]):  # static bound: unrolled ONCE per shape
        x = x + i
    return x


def trace_time_noise(key):
    # NEGATIVE jax-untraced-randomness: np.random OUTSIDE jitted code
    init = np.random.normal(size=3)

    @jax.jit
    def step(x):
        return x + jax.random.normal(key, (3,))  # sanctioned path

    return step(init)


def donation_rebound(buf, x):
    step = jax.jit(lambda b, v: b + v, donate_argnums=(0,))
    buf = step(buf, x)  # NEGATIVE jax-donation-misuse: rebound first
    return buf.sum()


def summarize(state, xs):
    # NEGATIVE jax-host-sync-in-hot-loop: not a hot-loop function name —
    # a one-off fetch at epoch end is fine
    return float(state.loss) + np.asarray(xs).sum()


def make_paged_step(backend):
    # NEGATIVE jax-retrace-hazard: the helper-seam backend is HOST
    # config captured by the closure — resolved once at build time, one
    # program per backend family, never a branch on traced data
    @jax.jit
    def step(x):
        if backend == "pallas":
            return x * 2.0  # pretend: the accelerated kernel
        return x + 1.0      # pretend: the stock fallback

    return step
