"""Must-flag cases for every JAX rule (graftcheck fixture — never
imported, only parsed)."""
import random

import jax
import numpy as np


def retrace_if(x, threshold):
    # POSITIVE jax-retrace-hazard: Python `if` on a traced scalar
    if threshold > 0:
        return x * threshold
    return x


retrace_if_j = jax.jit(retrace_if)


@jax.jit
def retrace_while(x, n):
    # POSITIVE jax-retrace-hazard: `while` on a traced value
    while n > 0:
        x = x + 1
        n = n - 1
    return x


@jax.jit
def retrace_range(x, n):
    # POSITIVE jax-retrace-hazard: range() over a traced bound unrolls
    # per value
    acc = x
    for _ in range(n):
        acc = acc + 1
    return acc


@jax.jit
def baked_noise(x):
    # POSITIVE jax-untraced-randomness: runs ONCE at trace time
    return x + np.random.normal(size=3)


@jax.jit
def baked_choice(x):
    # POSITIVE jax-untraced-randomness: stdlib random inside a trace
    return x * random.random()


def varying_capture(xs):
    total = 0.0
    for scale in xs:

        def step(v):
            return v * scale  # POSITIVE jax-varying-capture

        total += jax.jit(step)(1.0)
    return total


def donation_read_after(buf, x):
    step = jax.jit(lambda b, v: b + v, donate_argnums=(0,))
    out = step(buf, x)
    # POSITIVE jax-donation-misuse: buf's buffer may already be reused
    return out, buf.sum()


def _decode_once(state, xs):
    # hot-loop function name: every one of these is a per-iteration
    # device->host sync
    a = state.val.item()          # POSITIVE jax-host-sync-in-hot-loop
    b = float(state.loss)         # POSITIVE jax-host-sync-in-hot-loop
    c = np.asarray(xs)            # POSITIVE jax-host-sync-in-hot-loop
    return a + b + c.sum()


@jax.jit
def helper_switch_on_traced(x, occupancy):
    # POSITIVE jax-retrace-hazard: a helper-seam backend chosen on a
    # TRACED value — every occupancy retraces a fresh program, and the
    # two "backends" silently share one program-cache key
    if occupancy > 4:
        return x * 2.0  # pretend: the accelerated kernel
    return x + 1.0      # pretend: the stock fallback
