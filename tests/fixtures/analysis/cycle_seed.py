"""Deliberately seeded lock-order cycle between a broker and a
generation server (graftcheck fixture — never imported, only parsed).

The cycle detector must fail loudly on this file, naming BOTH
acquisition sites: broker holds ``_lock`` while entering the generator's
``_cond``, and the generator holds ``_cond`` while entering the broker's
``_lock``."""
import threading


class StreamingBroker:
    def __init__(self):
        self._lock = threading.Lock()
        self.gen = GenerationServer()

    def publish(self, item):
        with self._lock:
            # edge: StreamingBroker._lock -> GenerationServer._cond
            self.gen.step(item)

    def accept(self, item):
        with self._lock:
            return item


class GenerationServer:
    def __init__(self):
        self._cond = threading.Condition()
        self.broker = StreamingBroker()

    def step(self, item):
        with self._cond:
            return item

    def flush(self):
        with self._cond:
            # edge: GenerationServer._cond -> StreamingBroker._lock
            self.broker.publish(None)
