"""Must-NOT-flag cases for conc-loop-ownership (graftcheck fixture —
never imported, only parsed)."""
import threading


class CleanTickServer:
    """Every write is either loop-exclusive or holds the declared loop
    lock; reads of loop-owned attrs are exempt from conc-mixed-lock."""

    _LOOP_OWNED = ("_slots", "_round")
    _LOOP_LOCK = "_cond"

    def __init__(self):
        self._cond = threading.Condition()
        self._slots = {}
        self._round = 0
        self._loop = ServingLoop("clean", tick=self._tick)

    def _tick(self):
        # on the owning loop thread: lock-free writes are legal
        self._round += 1
        self._advance()
        return True

    def _advance(self):
        # reachable ONLY from the loop root: still loop-exclusive
        self._slots.clear()

    def adopt(self, rid, page):
        # off-thread write UNDER the declared loop lock: legal
        with self._cond:
            self._slots[rid] = page

    def _reset_locked(self):
        # private helper whose every call site holds the loop lock:
        # entry-lock propagation keeps it clean
        self._round = 0

    def restart(self):
        with self._cond:
            self._reset_locked()

    def snapshot(self):
        with self._cond:
            return dict(self._slots), self._round


class Undeclared:
    """No _LOOP_OWNED declaration: the rule stays silent even with a
    thread target writing state (mixed-lock governs such classes)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._lock:
            self._n += 1

    def count(self):
        with self._lock:
            return self._n
