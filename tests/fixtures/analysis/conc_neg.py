"""Must-NOT-flag cases for the concurrency rules (graftcheck fixture —
never imported, only parsed)."""
import threading
import time


class DisciplinedServer:
    """Clean lock discipline: no conc-mixed-lock, no blocking findings."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0      # always accessed under the lock
        self._config = {}    # written only in __init__, read-only after
        self._done = []      # mutated only via _retire (callers hold lock)

    def incr(self):
        with self._lock:
            self._count += 1

    def snapshot(self):
        with self._lock:
            # NEGATIVE conc-mixed-lock: every access is locked
            return self._count

    def lookup(self, k):
        with self._lock:
            # NEGATIVE conc-lock-blocking-call: dict.get, not queue.get
            return self._config.get(k)

    def describe(self):
        # NEGATIVE conc-mixed-lock: init-only write + read-only use
        return ", ".join(sorted(self._config))

    def _retire(self, x):
        # NEGATIVE conc-mixed-lock: private method — entry-lock
        # propagation sees every call site holds self._lock
        self._done.append(x)

    def finish(self, x):
        with self._lock:
            self._retire(x)

    def render(self, names):
        with self._lock:
            # NEGATIVE conc-lock-blocking-call: str.join is not
            # thread.join
            return ", ".join(names)


class CondOwner:
    def __init__(self):
        self._cv = threading.Condition()
        self._ready = False

    def wait_ready(self):
        with self._cv:
            while not self._ready:
                # NEGATIVE conc-lock-blocking-call: waiting on the
                # condition you HOLD releases it — that is the point
                self._cv.wait(timeout=0.1)
            return True

    def set_ready(self):
        with self._cv:
            self._ready = True
            self._cv.notify_all()


def record_heartbeat(path):
    # NEGATIVE monotonic-deadline: storing a wall timestamp (no
    # arithmetic) is legitimate — it is data, not a duration
    stamp = time.time()
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(str(stamp))
