"""Must-flag cases for the concurrency rules (graftcheck fixture —
never imported, only parsed)."""
import threading
import time


class MixedCounter:
    """Three conc-mixed-lock positives: `_count`, `_state`, `_items`."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._state = "idle"
        self._items = []

    def incr(self):
        with self._lock:
            self._count += 1

    def read_fast(self):
        # POSITIVE conc-mixed-lock: unlocked read racing the locked writer
        return self._count

    def set_state(self, s):
        # POSITIVE conc-mixed-lock: unlocked write, locked reader below
        self._state = s

    def get_state(self):
        with self._lock:
            return self._state

    def push(self, x):
        with self._lock:
            self._items.append(x)

    def drain(self):
        # POSITIVE conc-mixed-lock: unlocked container read + mutation
        out = list(self._items)
        self._items.clear()
        return out


class BlockingHolder:
    """Four conc-lock-blocking-call positives."""

    def __init__(self):
        self._lock = threading.Lock()
        self._results = {}

    def wait_result(self, fut):
        with self._lock:
            # POSITIVE conc-lock-blocking-call: Future.result under lock
            return fut.result()

    def pull(self, work_q):
        with self._lock:
            # POSITIVE conc-lock-blocking-call: queue.get under lock
            return work_q.get(timeout=1.0)

    def cross_wait(self, other_cv):
        with self._lock:
            # POSITIVE conc-lock-blocking-call: waiting on a DIFFERENT
            # condition than the lock held
            other_cv.wait(timeout=0.1)

    def nap(self):
        with self._lock:
            # POSITIVE conc-lock-blocking-call: sleep under lock
            time.sleep(0.05)


class WallDeadline:
    def __init__(self, budget):
        self.budget = budget
        self._start = time.time()

    def expired(self):
        # POSITIVE monotonic-deadline: duration math on wall clock
        return (time.time() - self._start) > self.budget


def wall_loop(tasks, budget):
    start = time.time()
    for t in tasks:
        if time.time() - start > budget:  # POSITIVE monotonic-deadline
            break
        t()


def wall_assigned(budget):
    t0 = time.time()
    # POSITIVE monotonic-deadline: arithmetic on a name assigned from
    # time.time() in the same function
    deadline = t0 + budget
    return deadline
