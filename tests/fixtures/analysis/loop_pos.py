"""Must-flag cases for conc-loop-ownership (graftcheck fixture —
never imported, only parsed)."""
import threading


class TickServer:
    """Three conc-loop-ownership positives: loop-owned state written
    off the owning loop thread without the declared loop lock."""

    _LOOP_OWNED = ("_slots", "_round")
    _LOOP_LOCK = "_cond"

    def __init__(self):
        self._cond = threading.Condition()
        self._slots = {}
        self._round = 0
        self._thread = threading.Thread(target=self._tick, daemon=True)

    def _tick(self):
        # loop-exclusive: lock-free writes on the owning thread are the
        # whole point of the declaration — never flagged
        self._round += 1
        self._slots[self._round] = "run"
        self._bump()
        return True

    def adopt(self, rid, page):
        # POSITIVE conc-loop-ownership: a public caller thread mutates a
        # loop-owned container without the loop lock
        self._slots[rid] = page

    def reset(self):
        # POSITIVE conc-loop-ownership: off-thread write, no lock
        self._round = 0

    def kick(self):
        # a public entry into the shared helper makes it NON-exclusive
        self._bump()

    def _bump(self):
        # POSITIVE conc-loop-ownership: reachable from BOTH the loop
        # root and a public method, so the write needs the loop lock
        self._round += 1
