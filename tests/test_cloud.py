"""Cloud provisioning command layer (cloud/provision.py) — the
deeplearning4j-aws analog. Tests run entirely in dry-run mode: they assert
the exact gcloud/gsutil argv the module would execute."""

import pytest

from deeplearning4j_tpu.cloud import ClusterSetup, GcsTransfer, TpuVmProvisioner
from deeplearning4j_tpu.cloud.provision import CommandRunner


class TestTpuVmProvisioner:
    def test_create_describe_delete_argv(self):
        r = CommandRunner(dry_run=True)
        tpus = TpuVmProvisioner("my-proj", "us-central1-a", r)
        tpus.create("pod1", accelerator_type="v5litepod-8", preemptible=True)
        tpus.describe("pod1")
        tpus.delete("pod1")
        create, describe, delete = r.history
        assert create[:5] == ["gcloud", "compute", "tpus", "tpu-vm",
                              "create"]
        assert "pod1" in create
        assert "--accelerator-type=v5litepod-8" in create
        assert "--preemptible" in create
        assert "--project=my-proj" in create and \
               "--zone=us-central1-a" in create
        assert "describe" in describe and "delete" in delete

    def test_wait_until_ready_polls_state(self):
        r = CommandRunner(dry_run=True)
        r.canned[("gcloud", "compute", "tpus", "tpu-vm", "describe")] = \
            "READY\n"
        tpus = TpuVmProvisioner("p", "z", r)
        tpus.wait_until_ready("pod1")
        assert any("describe" in argv for argv in r.history)

    def test_ssh_and_scp_target_all_workers(self):
        r = CommandRunner(dry_run=True)
        tpus = TpuVmProvisioner("p", "z", r)
        tpus.ssh("pod1", "hostname")
        tpus.scp("pod1", "wheel.whl", "~/wheel.whl")
        ssh, scp = r.history
        assert "--worker=all" in ssh and "--command=hostname" in ssh
        assert "pod1:~/wheel.whl" in scp
        assert "--recurse" not in scp  # plain file: no recursive copy

    def test_scp_directory_adds_recurse(self, tmp_path):
        # a directory package (ClusterSetup pushes "the training package")
        # needs gcloud's --recurse or the copy fails at runtime
        r = CommandRunner(dry_run=True)
        tpus = TpuVmProvisioner("p", "z", r)
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        tpus.scp("pod1", str(pkg), "~/pkg")
        (scp,) = r.history
        assert "--recurse" in scp
        assert scp.index("--recurse") < scp.index(str(pkg))


class TestGcsTransfer:
    def test_upload_download_argv_and_uri_validation(self):
        r = CommandRunner(dry_run=True)
        gcs = GcsTransfer(r)
        gcs.upload("model.zip", "gs://bucket/model.zip")
        gcs.download("gs://bucket/data", "data/")
        up, down = r.history
        assert up == ["gsutil", "-m", "cp", "-r", "model.zip",
                      "gs://bucket/model.zip"]
        assert down[-2:] == ["gs://bucket/data", "data/"]
        with pytest.raises(ValueError):
            gcs.upload("x", "s3://nope")


class TestClusterSetup:
    def test_full_flow_records_a_runnable_script(self):
        cs = ClusterSetup("my-proj", "us-central1-a", dry_run=True)
        cs.provision("train-pod", package_path="dist/pkg.whl")
        cs.launch("train-pod", "python -m train --epochs 10")
        cs.teardown("train-pod")
        script = cs.runner.script()
        # ordered: create -> describe(wait) -> scp -> pip -> launch -> delete
        order = [script.index(tok) for tok in
                 ("create", "describe", "scp", "pip install",
                  "python -m train", "delete")]
        assert order == sorted(order), script
        # every line is a real gcloud/gsutil invocation
        assert all(line.startswith(("gcloud ", "gsutil "))
                   for line in script.splitlines())

    def test_pip_spec_install_when_no_package(self):
        cs = ClusterSetup("p", "z", dry_run=True)
        cs.provision("pod", pip_spec="deeplearning4j_tpu==1.0")
        assert any("pip install deeplearning4j_tpu==1.0" in " ".join(argv)
                   for argv in cs.runner.history)
