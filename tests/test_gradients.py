"""Finite-difference gradient checks (parity with the reference's
gradientcheck/ test suite: GradientCheckTests, CNNGradientCheckTest,
LSTMGradientCheckTests, BNGradientCheckTest, GlobalPoolingGradientCheckTests,
VaeGradientCheckTests, GradientCheckTestsMasking). Tiny nets, float64, smooth
activations (tanh/softplus) per the reference's activation whitelist
(GradientCheckUtil.java:50-59)."""

import numpy as np
import pytest

from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    GravesLSTM,
    GravesBidirectionalLSTM,
    LocalResponseNormalization,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
    SimpleRnn,
    SubsamplingLayer,
    ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Sgd


def _build(layers, input_type, seed=42, l1=0.0, l2=0.0):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(Sgd(learning_rate=0.1))
            .weight_init("xavier")
            .dtype("float64")
            .l1(l1).l2(l2)
            .list(*layers)
            .set_input_type(input_type)
            .build())
    return MultiLayerNetwork(conf).init()


def _onehot(rng, n, c):
    return np.eye(c)[rng.integers(0, c, n)]


def test_mlp_gradients():
    rng = np.random.default_rng(0)
    net = _build([DenseLayer(n_out=6, activation="tanh"),
                  DenseLayer(n_out=5, activation="softplus"),
                  OutputLayer(n_out=3, loss="mcxent", activation="softmax")],
                 InputType.feed_forward(4))
    x = rng.normal(0, 1, (5, 4))
    y = _onehot(rng, 5, 3)
    assert check_gradients(net, x, y, verbose=True)


def test_mlp_gradients_with_l1_l2():
    rng = np.random.default_rng(1)
    net = _build([DenseLayer(n_out=6, activation="tanh"),
                  OutputLayer(n_out=3, loss="mcxent", activation="softmax")],
                 InputType.feed_forward(4), l1=0.01, l2=0.02)
    x = rng.normal(0, 1, (5, 4))
    y = _onehot(rng, 5, 3)
    assert check_gradients(net, x, y, verbose=True)


@pytest.mark.parametrize("loss,act", [("mse", "identity"), ("xent", "sigmoid"),
                                      ("mean_absolute_error", "tanh"),
                                      ("negativeloglikelihood", "softmax")])
def test_loss_function_gradients(loss, act):
    rng = np.random.default_rng(2)
    net = _build([DenseLayer(n_out=5, activation="tanh"),
                  OutputLayer(n_out=3, loss=loss, activation=act)],
                 InputType.feed_forward(4))
    x = rng.normal(0, 1, (4, 4))
    if loss == "xent":
        y = (rng.random((4, 3)) > 0.5).astype(float)
    elif act == "softmax":
        y = _onehot(rng, 4, 3)
    else:
        y = rng.normal(0, 1, (4, 3))
    assert check_gradients(net, x, y, verbose=True)


def test_cnn_gradients():
    rng = np.random.default_rng(3)
    net = _build([ConvolutionLayer(n_out=3, kernel_size=(2, 2), stride=(1, 1),
                                   activation="tanh"),
                  SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                   stride=(2, 2)),
                  OutputLayer(n_out=2, loss="mcxent", activation="softmax")],
                 InputType.convolutional(6, 6, 2))
    x = rng.normal(0, 1, (3, 6, 6, 2))
    y = _onehot(rng, 3, 2)
    assert check_gradients(net, x, y, verbose=True)


def test_cnn_avg_pool_same_mode_gradients():
    rng = np.random.default_rng(4)
    net = _build([ConvolutionLayer(n_out=2, kernel_size=(3, 3), stride=(1, 1),
                                   convolution_mode="same", activation="softplus"),
                  SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2),
                                   stride=(2, 2), convolution_mode="same"),
                  ZeroPaddingLayer(pad_top=1, pad_bottom=1, pad_left=1, pad_right=1),
                  OutputLayer(n_out=2, loss="mcxent", activation="softmax")],
                 InputType.convolutional(5, 5, 1))
    x = rng.normal(0, 1, (3, 5, 5, 1))
    y = _onehot(rng, 3, 2)
    assert check_gradients(net, x, y, verbose=True)


def test_batchnorm_gradients():
    rng = np.random.default_rng(5)
    net = _build([DenseLayer(n_out=5, activation="tanh"),
                  BatchNormalization(),
                  OutputLayer(n_out=3, loss="mcxent", activation="softmax")],
                 InputType.feed_forward(4))
    x = rng.normal(0, 1, (6, 4))
    y = _onehot(rng, 6, 3)
    assert check_gradients(net, x, y, verbose=True)


def test_layernorm_gradients():
    from deeplearning4j_tpu.nn.conf.layers import LayerNormalization

    rng = np.random.default_rng(15)
    net = _build([DenseLayer(n_out=5, activation="tanh"),
                  LayerNormalization(),
                  OutputLayer(n_out=3, loss="mcxent", activation="softmax")],
                 InputType.feed_forward(4))
    x = rng.normal(0, 1, (6, 4))
    y = _onehot(rng, 6, 3)
    assert check_gradients(net, x, y, verbose=True)


def test_layernorm_sequence_gradients():
    from deeplearning4j_tpu.nn.conf.layers import (LayerNormalization,
                                                   PositionalEncodingLayer)

    rng = np.random.default_rng(16)
    net = _build([PositionalEncodingLayer(),
                  SimpleRnn(n_out=5, activation="tanh"),
                  LayerNormalization(),
                  RnnOutputLayer(n_out=2, loss="mcxent",
                                 activation="softmax")],
                 InputType.recurrent(3, 4))
    x = rng.normal(0, 1, (2, 4, 3))
    y = np.zeros((2, 4, 2))
    y[..., 0] = 1
    assert check_gradients(net, x, y, verbose=True)


def test_lrn_gradients():
    rng = np.random.default_rng(6)
    net = _build([ConvolutionLayer(n_out=4, kernel_size=(2, 2), activation="tanh"),
                  LocalResponseNormalization(),
                  OutputLayer(n_out=2, loss="mcxent", activation="softmax")],
                 InputType.convolutional(4, 4, 1))
    x = rng.normal(0, 1, (2, 4, 4, 1))
    y = _onehot(rng, 2, 2)
    assert check_gradients(net, x, y, verbose=True)


@pytest.mark.parametrize("layer_cls", [LSTM, GravesLSTM, SimpleRnn])
def test_rnn_gradients(layer_cls):
    rng = np.random.default_rng(7)
    net = _build([layer_cls(n_out=4, activation="tanh"),
                  RnnOutputLayer(n_out=3, loss="mcxent", activation="softmax")],
                 InputType.recurrent(3))
    x = rng.normal(0, 1, (2, 5, 3))
    y = np.eye(3)[rng.integers(0, 3, (2, 5))]
    assert check_gradients(net, x, y, verbose=True)


def test_bidirectional_lstm_gradients():
    rng = np.random.default_rng(8)
    net = _build([GravesBidirectionalLSTM(n_out=3, activation="tanh"),
                  RnnOutputLayer(n_out=2, loss="mcxent", activation="softmax")],
                 InputType.recurrent(3))
    x = rng.normal(0, 1, (2, 4, 3))
    y = np.eye(2)[rng.integers(0, 2, (2, 4))]
    assert check_gradients(net, x, y, verbose=True)


def test_lstm_masking_gradients():
    """Masked timesteps must contribute zero gradient (GradientCheckTestsMasking)."""
    rng = np.random.default_rng(9)
    net = _build([GravesLSTM(n_out=4, activation="tanh"),
                  RnnOutputLayer(n_out=2, loss="mcxent", activation="softmax")],
                 InputType.recurrent(3))
    x = rng.normal(0, 1, (3, 5, 3))
    y = np.eye(2)[rng.integers(0, 2, (3, 5))]
    mask = np.array([[1, 1, 1, 1, 1], [1, 1, 1, 0, 0], [1, 0, 0, 0, 0]], float)
    assert check_gradients(net, x, y, input_mask=mask, label_mask=mask, verbose=True)


def test_global_pooling_gradients():
    rng = np.random.default_rng(10)
    net = _build([GravesLSTM(n_out=4, activation="tanh"),
                  GlobalPoolingLayer(pooling_type="avg"),
                  OutputLayer(n_out=2, loss="mcxent", activation="softmax")],
                 InputType.recurrent(3))
    x = rng.normal(0, 1, (2, 4, 3))
    y = _onehot(rng, 2, 2)
    assert check_gradients(net, x, y, verbose=True)


def test_cnn_global_pooling_gradients():
    rng = np.random.default_rng(11)
    net = _build([ConvolutionLayer(n_out=3, kernel_size=(2, 2), activation="tanh"),
                  GlobalPoolingLayer(pooling_type="max"),
                  OutputLayer(n_out=2, loss="mcxent", activation="softmax")],
                 InputType.convolutional(5, 5, 1))
    x = rng.normal(0, 1, (2, 5, 5, 1))
    y = _onehot(rng, 2, 2)
    assert check_gradients(net, x, y, verbose=True)


def test_embedding_gradients():
    rng = np.random.default_rng(12)
    net = _build([EmbeddingLayer(n_in=7, n_out=4, activation="tanh"),
                  OutputLayer(n_in=4, n_out=3, loss="mcxent", activation="softmax")],
                 None)
    # embedding takes int indices; no input_type, so nIn is set explicitly
    x = rng.integers(0, 7, (5, 1)).astype(float)
    y = _onehot(rng, 5, 3)
    assert check_gradients(net, x, y, verbose=True)


def test_bn_with_global_l2_gradients():
    """BatchNormalization gamma/beta are exempt from l1/l2 (reference:
    BatchNormalization.calcL1/calcL2 -> 0): the closed-form reg-grad path
    must not decay them even when a global l2 fills the layer's fields."""
    rng = np.random.default_rng(4)
    net = _build([DenseLayer(n_out=6, activation="tanh"),
                  BatchNormalization(),
                  OutputLayer(n_out=3, loss="mcxent", activation="softmax")],
                 InputType.feed_forward(4), l1=0.01, l2=0.02)
    x = rng.normal(0, 1, (6, 4))
    y = _onehot(rng, 6, 3)
    assert check_gradients(net, x, y, train=False)


def test_moe_load_balance_term_trains():
    """The MoE load-balance auxiliary must still produce a gradient on the
    gate weights after the closed-form reg split (it is stop_gradient-ed
    in the loss value and re-added analytically)."""
    from deeplearning4j_tpu.nn.conf.layers.moe import MixtureOfExpertsLayer

    layer = MixtureOfExpertsLayer(n_in=4, n_out=4, n_experts=2,
                                  expert_hidden=8, load_balance_coef=0.1)
    import jax
    params = layer.init_params(jax.random.PRNGKey(0))
    g = layer.regularization_grad(params)
    np.testing.assert_allclose(np.asarray(g["Wg"]),
                               2 * 0.1 * np.asarray(params["Wg"]))
    # and finite differences agree end-to-end through a network
    net = _build([MixtureOfExpertsLayer(n_out=4, n_experts=2, expert_hidden=8,
                                        load_balance_coef=0.05),
                  OutputLayer(n_out=3, loss="mcxent", activation="softmax")],
                 InputType.feed_forward(4))
    rng = np.random.default_rng(5)
    x = rng.normal(0, 1, (6, 4))
    y = _onehot(rng, 6, 3)
    assert check_gradients(net, x, y, train=False)


def test_layernorm_semantics_and_serde():
    """LayerNormalization: per-example last-axis normalization (mean 0,
    var 1 pre-affine), train == eval, JSON round trip."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.layers import LayerNormalization
    from deeplearning4j_tpu.utils.serde import from_json, to_json

    lyr = LayerNormalization(n_out=8)
    params = lyr.init_params(jax.random.PRNGKey(0), dtype=jnp.float64)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(3.0, 5.0, (4, 6, 8)))
    out_train, _ = lyr.forward(params, {}, x, train=True)
    out_eval, _ = lyr.forward(params, {}, x, train=False)
    np.testing.assert_array_equal(np.asarray(out_train),
                                  np.asarray(out_eval))  # no running stats
    np.testing.assert_allclose(np.asarray(out_train).mean(-1), 0.0,
                               atol=1e-10)
    np.testing.assert_allclose(np.asarray(out_train).std(-1), 1.0,
                               atol=1e-4)
    back = from_json(to_json(LayerNormalization(n_out=8, eps=1e-3)))
    assert back == LayerNormalization(n_out=8, eps=1e-3)


def test_positional_encoding_semantics():
    """Sinusoidal table: deterministic, position-distinguishing, additive
    (zero input returns the table itself), serde round trip."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.layers import PositionalEncodingLayer
    from deeplearning4j_tpu.utils.serde import from_json, to_json

    lyr = PositionalEncodingLayer()
    z = jnp.zeros((1, 12, 16))
    pe, _ = lyr.forward({}, {}, z)
    pe = np.asarray(pe)[0]
    # rows are pairwise distinct (positions distinguishable)
    for i in range(12):
        for j in range(i + 1, 12):
            assert np.abs(pe[i] - pe[j]).max() > 1e-3
    # additive: forward(x) == x + forward(0)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(0, 1, (2, 12, 16)), jnp.float32)
    out, _ = lyr.forward({}, {}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) + pe,
                               atol=1e-6)
    assert from_json(to_json(lyr)) == lyr
