"""Observability stack tests (deeplearning4j_tpu/metrics/).

Covers the four layers the tentpole added, each at its contract:

- registry — thread-safe counters/gauges/histograms, reservoir
  quantiles against numpy's nearest-rank, label sets, the NullRegistry
  twin;
- exposition — a golden Prometheus 0.0.4 text render, multi-source
  merge with injected labels;
- autoscaler — the hysteresis state machine driven by a fake clock and
  a fake target: scale-up, cooldown, no-flap under oscillation,
  scale-down on idle, floor/ceiling clamps;
- load harness — deterministic seeded arrival schedules, the
  zero-lost-futures ledger, typed synchronous rejections;

plus the serving integration: the legacy ``stats()`` dict shapes of
all five surfaces (generation, inference, fleet, broker, HTTP server)
pinned key-for-key in order, and one end-to-end ``GET /metrics``
scrape over a live KerasBackendServer with inference + generation
models attached, a broker registered, and a health guard publishing.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.metrics.autoscale import Autoscaler
from deeplearning4j_tpu.metrics.exposition import CONTENT_TYPE, render_text
from deeplearning4j_tpu.metrics.loadgen import (LoadGenerator,
                                                poisson_arrivals,
                                                ramp_profile, spike_profile)
from deeplearning4j_tpu.metrics.registry import (Histogram, MetricsRegistry,
                                                 NullRegistry, nearest_rank)

pytestmark = pytest.mark.metrics


@pytest.fixture(scope="module")
def lm():
    """Tiny TransformerLM shared by the generation-surface tests."""
    from deeplearning4j_tpu.models.zoo import TransformerLM

    return TransformerLM(num_labels=17, max_length=16, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_threaded_counter_correctness(self):
        """8 racing incrementers lose no updates (the leaf lock is the
        whole thread-safety story — no serving lock involved)."""
        reg = MetricsRegistry()
        c = reg.counter("hits_total", "hits")
        lab = reg.counter("typed_total", "typed", labels=("kind",))

        def hammer():
            for _ in range(10_000):
                c.inc()
                lab.labels(kind="a").inc(2)

        threads = [threading.Thread(target=hammer) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert int(c.value) == 80_000
        assert int(lab.labels(kind="a").value) == 160_000

    def test_counter_rejects_negative(self):
        c = MetricsRegistry().counter("c_total", "c")
        with pytest.raises(ValueError):
            c.inc(-1)
        c.inc(0)          # zero and float increments are legal
        c.inc(2.5)
        assert c.value == 2.5

    def test_gauge_set_and_callback(self):
        reg = MetricsRegistry()
        g = reg.gauge("level", "setter-style")
        g.set(7)
        assert g.value == 7.0
        state = {"n": 3}
        reg.gauge("depth", "callback-style", fn=lambda: state["n"])
        assert reg.snapshot()["depth"] == 3.0
        state["n"] = 9
        assert reg.snapshot()["depth"] == 9.0

    def test_reservoir_quantiles_match_numpy(self):
        """With the reservoir holding every observation, quantile() must
        equal numpy's nearest-rank over the same sample."""
        rng = np.random.default_rng(7)
        xs = rng.lognormal(mean=2.0, sigma=0.8, size=1000)
        h = Histogram(reservoir=len(xs))
        for v in xs:
            h.observe(float(v))
        s = sorted(float(v) for v in xs)
        for q in (0.5, 0.9, 0.99, 0.999):
            expect = s[max(0, int(np.ceil(q * len(s))) - 1)]
            assert h.quantile(q) == pytest.approx(expect)
            assert nearest_rank(s, q) == pytest.approx(expect)

    def test_nearest_rank_is_not_off_by_one(self):
        """The bench's old inline math indexed int(n * 0.99) — rank 100
        of 100 (and past the end at exact multiples). Nearest-rank p99
        of 100 samples is rank 99 (index 98)."""
        s = list(range(100))
        assert nearest_rank(s, 0.99) == 98
        assert nearest_rank(s, 0.5) == 49
        assert nearest_rank(s, 1.0) == 99
        assert nearest_rank([5.0], 0.99) == 5.0

    def test_subsampling_reservoir_stays_plausible(self):
        """Past the reservoir bound the quantiles are estimates — they
        must still land inside the observed range, deterministically
        for a fixed seed."""
        h1 = Histogram(reservoir=128)
        h2 = Histogram(reservoir=128)
        rng = np.random.default_rng(3)
        xs = [float(v) for v in rng.uniform(10.0, 20.0, size=5000)]
        for v in xs:
            h1.observe(v)
            h2.observe(v)
        assert 10.0 <= h1.quantile(0.99) <= 20.0
        assert h1.quantile(0.99) == h2.quantile(0.99)  # seeded, no wall clock

    def test_null_registry_is_inert(self):
        reg = NullRegistry()
        c = reg.counter("x_total", "x")
        c.inc(5)
        assert c.value == 0.0
        assert reg.snapshot() == {}
        assert render_text([({}, reg)]) == ""


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------


class TestExposition:
    def test_content_type(self):
        assert CONTENT_TYPE == "text/plain; version=0.0.4; charset=utf-8"

    def test_golden_render(self):
        """Byte-exact 0.0.4 exposition: merged same-named families
        across sources, injected labels prepended, histogram as the
        bucket/sum/count triple, integral floats bare."""
        reg = MetricsRegistry()
        c = reg.counter("demo_requests_total", "requests served",
                        labels=("route",))
        c.labels(route="/predict").inc(3)
        c.labels(route="/generate").inc()
        reg.gauge("demo_temperature", "a gauge").set(36.6)
        h = reg.histogram("demo_latency_ms", "latency", buckets=(1.0, 5.0))
        for v in (0.5, 3.0, 7.0):
            h.observe(v)
        other = MetricsRegistry()
        other.counter("demo_requests_total", "requests served",
                      labels=("route",)).labels(route="/predict").inc(2)
        golden = (
            '# HELP demo_requests_total requests served\n'
            '# TYPE demo_requests_total counter\n'
            'demo_requests_total{route="/predict"} 3\n'
            'demo_requests_total{route="/generate"} 1\n'
            'demo_requests_total{model="m0",route="/predict"} 2\n'
            '# HELP demo_temperature a gauge\n'
            '# TYPE demo_temperature gauge\n'
            'demo_temperature 36.6\n'
            '# HELP demo_latency_ms latency\n'
            '# TYPE demo_latency_ms histogram\n'
            'demo_latency_ms_bucket{le="1"} 1\n'
            'demo_latency_ms_bucket{le="5"} 2\n'
            'demo_latency_ms_bucket{le="+Inf"} 3\n'
            'demo_latency_ms_sum 10.5\n'
            'demo_latency_ms_count 3\n'
        )
        assert render_text([({}, reg), ({"model": "m0"}, other)]) == golden

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.counter("esc_total", "esc", labels=("path",)).labels(
            path='a"b\\c\nd').inc()
        text = render_text([({}, reg)])
        assert 'esc_total{path="a\\"b\\\\c\\nd"} 1' in text


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------


class _FakeTarget:
    """Scriptable target: the test sets depth/miss before each tick."""

    name = "fake"
    min_level = 1
    max_level = 4

    def __init__(self, level=2):
        self.level = level
        self.depth = 0
        self.miss = 0.0
        self.set_calls = []

    def observe(self):
        return self.depth, self.miss

    def get(self):
        return self.level

    def set(self, n):
        self.level = n
        self.set_calls.append(n)


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestAutoscaler:
    def _scaler(self, target, **kw):
        clock = _FakeClock()
        kw.setdefault("high_depth", 8)
        kw.setdefault("low_depth", 1)
        kw.setdefault("up_ticks", 2)
        kw.setdefault("down_ticks", 3)
        kw.setdefault("cooldown_s", 5.0)
        return Autoscaler([target], clock=clock, **kw), clock

    def test_scale_up_needs_sustained_breach(self):
        tgt = _FakeTarget(level=2)
        sc, clock = self._scaler(tgt)
        tgt.depth = 20
        assert sc.tick() == []          # 1 hot tick: not yet
        clock.t += 1
        made = sc.tick()                # 2nd consecutive: scale up
        assert [d.action for d in made] == ["scale_up"]
        assert tgt.level == 3
        assert made[0].level_from == 2 and made[0].level_to == 3

    def test_cooldown_quarantines_after_change(self):
        tgt = _FakeTarget(level=1)
        sc, clock = self._scaler(tgt)
        tgt.depth = 20
        sc.tick()
        clock.t += 1
        sc.tick()
        assert tgt.level == 2
        # still breaching, but inside the 5 s cooldown: no second step
        for _ in range(4):
            clock.t += 1
            sc.tick()
        assert tgt.level == 2
        clock.t += 5                    # cooldown expires
        sc.tick()                       # hi streak rebuilt during cooldown
        assert tgt.level == 3

    def test_oscillation_produces_zero_decisions(self):
        """Queue flapping above/below the threshold every tick must
        never flap capacity — the consecutive-tick streak resets."""
        tgt = _FakeTarget(level=2)
        sc, clock = self._scaler(tgt)
        for i in range(40):
            tgt.depth = 20 if i % 2 == 0 else 4
            clock.t += 1
            sc.tick()
        assert tgt.set_calls == []
        assert list(sc.decisions) == []

    def test_scale_down_on_idle_and_floor(self):
        tgt = _FakeTarget(level=2)
        sc, clock = self._scaler(tgt)
        tgt.depth = 0
        for _ in range(3):
            clock.t += 1
            sc.tick()
        assert tgt.level == 1           # one step down after down_ticks
        clock.t += 10
        for _ in range(6):
            clock.t += 1
            sc.tick()
        assert tgt.level == 1           # clamped at min_level

    def test_ceiling_clamp(self):
        tgt = _FakeTarget(level=4)
        sc, clock = self._scaler(tgt)
        tgt.depth = 100
        for _ in range(6):
            clock.t += 1
            sc.tick()
        assert tgt.level == 4 and tgt.set_calls == []

    def test_miss_rate_alone_scales_up(self):
        """Deadline-miss rate is an OR trigger with queue depth."""
        tgt = _FakeTarget(level=1)
        sc, clock = self._scaler(tgt, high_miss_rate=0.05)
        tgt.depth = 0
        tgt.miss = 0.5
        sc.tick()
        clock.t += 1
        sc.tick()
        assert tgt.level == 2

    def test_decisions_land_in_registry(self):
        reg = MetricsRegistry()
        tgt = _FakeTarget(level=1)
        clock = _FakeClock()
        sc = Autoscaler([tgt], up_ticks=1, cooldown_s=0.0, registry=reg,
                        clock=clock)
        tgt.depth = 100
        sc.tick()
        text = render_text([({}, reg)])
        assert ('autoscale_decisions_total{target="fake",'
                'action="scale_up"} 1') in text
        assert 'autoscale_level{target="fake"} 2' in text


# ---------------------------------------------------------------------------
# load harness
# ---------------------------------------------------------------------------


class _InstantFuture:
    def add_done_callback(self, cb):
        cb(self)

    def exception(self):
        return None


class TestLoadGen:
    def test_poisson_schedule_deterministic(self):
        rate = ramp_profile(50.0, 200.0, 1.0)
        a = poisson_arrivals(rate, 2.0, 200.0, seed=11)
        b = poisson_arrivals(rate, 2.0, 200.0, seed=11)
        c = poisson_arrivals(rate, 2.0, 200.0, seed=12)
        assert a == b
        assert a != c
        assert all(0.0 <= t < 2.0 for t in a)
        assert a == sorted(a)

    def test_profiles(self):
        r = ramp_profile(100.0, 300.0, 2.0)
        assert r(0.0) == 100.0 and r(1.0) == 200.0
        assert r(2.0) == 300.0 and r(99.0) == 300.0
        s = spike_profile(100.0, 900.0, at_s=1.0, dur_s=0.5)
        assert s(0.9) == 100.0 and s(1.0) == 900.0
        assert s(1.49) == 900.0 and s(1.5) == 100.0

    def test_open_loop_ledger_and_determinism(self):
        """Same seed -> same schedule, same request indices; every
        future accounted for (lost == 0)."""
        def run():
            issued = []

            def submit(i):
                issued.append(i)
                return _InstantFuture()

            lg = LoadGenerator(submit, seed=5)
            res = lg.run_open(lambda t: 400.0, 0.4, 400.0, timeout_s=30)
            return issued, res

        issued_a, res_a = run()
        issued_b, res_b = run()
        assert issued_a == issued_b
        assert res_a.submitted == res_b.submitted == len(issued_a) > 0
        assert res_a.lost == 0 and res_a.failed == 0
        assert res_a.completed == res_a.submitted

    def test_synchronous_rejection_counts_as_typed_failure(self):
        def submit(i):
            if i % 5 == 0:
                raise ValueError("shed")
            return _InstantFuture()

        lg = LoadGenerator(submit, seed=1)
        res = lg.run_open(lambda t: 300.0, 0.3, 300.0, timeout_s=30)
        assert res.lost == 0
        assert res.failed == res.errors.get("ValueError")
        assert res.completed + res.failed == res.submitted
        assert res.failed > 0

    def test_closed_loop(self):
        lg = LoadGenerator(lambda i: _InstantFuture(), seed=2)
        res = lg.run_closed(workers=3, requests_per_worker=5,
                            timeout_s=30)
        assert res.submitted == 15
        assert res.lost == 0 and res.completed == 15

    def test_latency_publishes_into_registry(self):
        reg = MetricsRegistry()
        lg = LoadGenerator(lambda i: _InstantFuture(), seed=0,
                           registry=reg)
        lg.run_open(lambda t: 200.0, 0.2, 200.0, timeout_s=30)
        snap = reg.snapshot()
        assert snap["soak_submitted_total"] > 0
        assert snap["soak_completed_total"] == snap["soak_submitted_total"]


# ---------------------------------------------------------------------------
# legacy stats() shapes — the five re-homed surfaces
# ---------------------------------------------------------------------------


GEN_KEYS = ["slots", "active_slots", "queued", "admitted", "expired",
            "retired", "completed", "failed", "retried", "pool_rebuilds",
            "prefills", "decode_steps", "tokens_generated", "tokens_per_s",
            "accepted", "rejected", "pending", "breaker_state", "pages",
            "handoff", "role"]
GEN_HANDOFF_KEYS = ["snapshot_every", "snapshots", "bytes", "resumes",
                    "tokens_saved", "fallbacks", "preempt_resumes",
                    "migrated", "prefill_exports"]
GEN_PAGE_KEYS = ["page_size", "pages_total", "pages_free", "pages_cached",
                 "pages_shared", "pages_refcounted", "resident_kv_bytes",
                 "peak_resident_kv_bytes", "cow_copies", "prefix_hits",
                 "prefix_tokens_reused", "evictions", "preempted", "spec_k",
                 "spec_rounds", "spec_proposed", "spec_accepted",
                 "spec_accept_rate", "kv_cache_dtype", "bytes_per_token"]
INF_KEYS = ["retried", "expired", "rejected_circuit", "completed", "failed",
            "dispatches", "accepted", "rejected", "pending", "breaker_state"]
FLEET_KEYS = ["replica_count", "submitted", "rejected_submits", "completed",
              "failed", "expired", "redispatched", "hedged",
              "losers_cancelled", "deaths", "restarts", "parked", "inflight",
              "handoff_resumes", "handoff_fallbacks",
              "admission", "replicas", "tier_handoffs", "degraded_submits",
              "degraded_mode"]
FLEET_REPLICA_KEYS = ["rid", "state", "role", "generation", "health_score",
                      "ewma_latency_ms", "failure_ewma", "inflight",
                      "restarts", "spawn_failures", "dispatched", "completed",
                      "failed", "rejected", "breaker", "breaker_trips",
                      "admission", "server"]
BROKER_KEYS = ["subscribers", "frames_dropped", "subscribers_disconnected",
               "dropped_by_topic"]
SERVER_KEYS = ["retried", "expired", "rejected_circuit", "completed",
               "failed", "accepted", "rejected", "pending", "breaker_state",
               "models"]
RAG_KEYS = ["submitted", "completed", "failed", "expired", "rejected",
            "inflight", "k", "page_size", "prefix_hits",
            "prefix_tokens_reused", "tiers"]
RAG_TIER_KEYS = ["replicas", "queued", "expired", "completed",
                 "active_slots", "slots"]


class TestLegacyStatsShapes:
    """The re-home moved every serving counter into the registry; the
    public dicts — key set AND order, which is the JSON serialization
    order clients see — must not have moved an inch."""

    def test_generation_server(self, lm):
        from deeplearning4j_tpu.parallel.generation import GenerationServer

        srv = GenerationServer(lm, 17, slots=2)
        try:
            st = srv.stats()
        finally:
            srv.close()
        assert list(st.keys()) == GEN_KEYS
        assert list(st["pages"].keys()) == GEN_PAGE_KEYS
        assert list(st["handoff"].keys()) == GEN_HANDOFF_KEYS
        assert isinstance(st["completed"], int)
        assert isinstance(st["tokens_per_s"], float)

    def test_parallel_inference(self):
        from deeplearning4j_tpu.parallel.inference import ParallelInference

        from tests.test_fused_fit import _mln

        with ParallelInference(_mln(), workers=8) as inf:
            st = inf.stats()
        assert list(st.keys()) == INF_KEYS
        assert all(isinstance(st[k], int) for k in INF_KEYS[:-1])

    def test_replica_fleet(self, lm):
        from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
        from deeplearning4j_tpu.parallel.generation import GenerationServer

        fl = ReplicaFleet(lambda rid: GenerationServer(lm, 17, slots=2),
                          replicas=1)
        try:
            st = fl.stats()
        finally:
            fl.close()
        assert list(st.keys()) == FLEET_KEYS
        assert list(st["replicas"][0].keys()) == FLEET_REPLICA_KEYS

    def test_streaming_broker(self):
        from deeplearning4j_tpu.streaming.broker import StreamingBroker

        b = StreamingBroker().start()
        try:
            st = b.stats()
        finally:
            b.stop()
        assert list(st.keys()) == BROKER_KEYS

    def test_keras_backend_server(self):
        from deeplearning4j_tpu.modelimport.server import KerasBackendServer

        st = KerasBackendServer().stats()
        assert list(st.keys()) == SERVER_KEYS

    @pytest.mark.slow  # builds a two-tier fleet: tier-1 timing headroom
    def test_rag_pipeline(self, lm):
        from deeplearning4j_tpu.nearestneighbors.index import EmbeddingIndex
        from deeplearning4j_tpu.parallel.generation import GenerationServer
        from deeplearning4j_tpu.parallel.rag import RagPipeline

        vecs = np.random.RandomState(0).randn(16, 4).astype(np.float32)
        pipe = RagPipeline(
            lambda rid: EmbeddingIndex(vecs),
            lambda rid: GenerationServer(lm, 17, slots=2, page_size=4),
            [np.arange(1, 5, dtype=np.int64)] * 16, page_size=4, k=2)
        try:
            st = pipe.stats()
        finally:
            pipe.close()
        assert list(st.keys()) == RAG_KEYS
        assert list(st["tiers"].keys()) == ["knn", "generate"]
        for role in ("knn", "generate"):
            assert list(st["tiers"][role].keys()) == RAG_TIER_KEYS


# ---------------------------------------------------------------------------
# GET /metrics end to end
# ---------------------------------------------------------------------------


class TestMetricsEndpoint:
    def test_one_scrape_covers_every_surface(self, lm):
        """A single /metrics page carries the server's own counters, the
        attached inference AND generation registries (labeled by model
        id), a registered broker registry, and a health guard — while
        /stats keeps serving the legacy JSON from the same counters."""
        from deeplearning4j_tpu.modelimport.server import KerasBackendServer
        from deeplearning4j_tpu.optimize.health import HealthPolicy
        from deeplearning4j_tpu.streaming.broker import StreamingBroker

        from tests.test_fused_fit import _mln

        srv = KerasBackendServer()
        broker = StreamingBroker().start()
        guard_reg = MetricsRegistry()
        HealthPolicy(registry=guard_reg)
        srv.attach_inference(_mln(), mid="inf0", max_wait_ms=5.0)
        srv.attach_generation(lm, vocab=17, mid="gen0", slots=2)
        srv.register_metrics({"component": "broker"}, broker.metrics)
        srv.register_metrics({"component": "health"}, guard_reg)
        port = srv.start()
        base = f"http://127.0.0.1:{port}"
        try:
            req = urllib.request.Request(
                base + "/predict",
                json.dumps({"model": "inf0",
                            "features": [[0.0, 0.0, 0.0, 0.0]]}).encode(),
                {"Content-Type": "application/json"})
            out = json.loads(urllib.request.urlopen(req).read())
            assert "output" in out

            resp = urllib.request.urlopen(base + "/metrics")
            assert resp.headers["Content-Type"] == CONTENT_TYPE
            text = resp.read().decode("utf-8")
            # server's own serving ledger
            assert "server_completed_total 1" in text
            # attached inference registry, injected model label
            assert ('inference_completed_total{model="inf0"} 1'
                    in text)
            assert 'inference_batch_rows_bucket{model="inf0",le="1"}' in text
            # attached generation registry (gauges registered at ctor)
            assert 'generation_slots{model="gen0"} 2' in text
            assert 'generation_active_slot_cap{model="gen0"} 2' in text
            # registered extras keep their injected labels
            assert 'broker_subscribers{component="broker"} 0' in text
            assert ('health_consecutive_skips{component="health"} 0'
                    in text)

            # the legacy JSON view survives, fed from the same registry
            stats = json.loads(
                urllib.request.urlopen(base + "/stats").read())
            assert list(stats.keys())[:10] == SERVER_KEYS
            assert stats["completed"] == 1
            assert stats["inference"]["inf0"]["completed"] == 1
            assert list(stats["inference"]["inf0"].keys()) == INF_KEYS
            assert list(stats["generation"]["gen0"].keys()) == GEN_KEYS
        finally:
            srv.stop()
            broker.stop()
