"""Mixture-of-Experts layer: routing semantics, gradients, serde, training
quality, and expert-parallel sharding parity."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    MixtureOfExpertsLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam, Sgd


def _net(top_k=2, n_experts=4, lb=0.0, dtype=None):
    b = (NeuralNetConfiguration.builder()
         .seed(2).updater(Adam(learning_rate=0.01)))
    if dtype:
        b = b.dtype(dtype)
    conf = (b.list(MixtureOfExpertsLayer(n_out=16, n_experts=n_experts,
                                         top_k=top_k, expert_hidden=24,
                                         load_balance_coef=lb),
                   OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


class TestRouting:
    def test_topk_gates_sparse_and_normalized(self):
        import jax.numpy as jnp
        net = _net(top_k=2, n_experts=5)
        layer = net.layers[0]
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(8, 6), jnp.float32)
        gates = np.asarray(layer._gate(net.params["0"], x))
        assert gates.shape == (8, 5)
        assert ((gates > 0).sum(axis=1) <= 2).all()      # top-2 sparsity
        np.testing.assert_allclose(gates.sum(axis=1), 1.0, atol=1e-6)
        # exact top-k even under ties: a zero row gives uniform logits
        zgates = np.asarray(layer._gate(net.params["0"],
                                        jnp.zeros((1, 6), jnp.float32)))
        assert (zgates > 0).sum() == 2

    def test_top1_equals_argmax_expert(self):
        import jax.numpy as jnp
        net = _net(top_k=1, n_experts=3)
        layer = net.layers[0]
        p = net.params["0"]
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(4, 6), jnp.float32)
        out, _ = layer.forward(p, {}, x)
        logits = np.asarray(x @ p["Wg"])
        pick = np.argmax(logits, axis=1)
        # manual single-expert FFN for each example
        import jax
        h = np.maximum(np.einsum("bd,edh->beh", np.asarray(x),
                                 np.asarray(p["W1"]))
                       + np.asarray(p["b1"]), 0)
        y = np.einsum("beh,eho->beo", h, np.asarray(p["W2"])) \
            + np.asarray(p["b2"])
        expected = y[np.arange(4), pick]
        np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)

    def test_full_softmax_when_topk_equals_experts(self):
        net = _net(top_k=4, n_experts=4)
        rs = np.random.RandomState(2)
        out = net.output(rs.randn(5, 6).astype(np.float32))
        assert np.asarray(out).shape == (5, 3)


class TestTraining:
    def test_gradcheck_through_moe(self):
        # top_k == n_experts: the gate is a plain softmax and the whole
        # layer is smooth, so central differences validate every einsum /
        # FFN / gate gradient. (With top_k < E the hard selection is
        # piecewise-constant BY DESIGN — finite differences straddling a
        # routing boundary measure the jump, not the gradient; autodiff
        # within a region is exercised by the training test.)
        from deeplearning4j_tpu.gradientcheck import check_gradients
        net = _net(top_k=4, n_experts=4, dtype="float64")
        rs = np.random.RandomState(3)
        x = rs.randn(4, 6)
        y = np.eye(3)[rs.randint(0, 3, 4)]
        assert check_gradients(net, x, y)

    def test_learns_partitioned_function(self):
        # two input regimes with different linear maps: an MoE should
        # specialize experts and beat chance easily
        rs = np.random.RandomState(4)
        n = 256
        regime = rs.randint(0, 2, n)
        x = rs.randn(n, 6).astype(np.float32)
        x[:, 0] = regime * 4 - 2           # routing signal
        labels = np.where(regime == 0,
                          (x[:, 1] > 0).astype(int),
                          2 * (x[:, 2] > 0).astype(int))
        y = np.eye(3, dtype=np.float32)[labels]
        net = _net(top_k=1)
        ds = DataSet(x, y)
        for _ in range(150):
            net.fit(ds)
        pred = np.argmax(np.asarray(net.output(x)), 1)
        assert (pred == labels).mean() > 0.9

    def test_serde_round_trip(self, tmp_path):
        from deeplearning4j_tpu.utils.model_serializer import (load_model,
                                                               save_model)
        net = _net()
        p = str(tmp_path / "moe.zip")
        save_model(net, p)
        back = load_model(p)
        rs = np.random.RandomState(5)
        x = rs.randn(3, 6).astype(np.float32)
        np.testing.assert_allclose(np.asarray(back.output(x)),
                                   np.asarray(net.output(x)), atol=1e-6)
        assert back.layers[0].n_experts == 4

    def test_validation(self):
        with pytest.raises(ValueError):
            _net(top_k=9, n_experts=4)


class TestExpertParallel:
    def test_sharded_matches_single_device(self):
        from deeplearning4j_tpu.parallel import data_model_mesh
        from deeplearning4j_tpu.parallel.model_sharding import (
            network_param_specs, shard_network)
        from jax.sharding import PartitionSpec as P

        rs = np.random.RandomState(6)
        labels = rs.randint(0, 3, 32)
        x = (rs.randn(32, 6) + labels[:, None]).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[labels]
        ds = DataSet(x, y)

        def build():
            conf = (NeuralNetConfiguration.builder()
                    .seed(7).updater(Sgd(learning_rate=0.05))
                    .list(MixtureOfExpertsLayer(n_out=16, n_experts=4,
                                                top_k=2, expert_hidden=24),
                          OutputLayer(n_out=3, activation="softmax",
                                      loss="mcxent"))
                    .set_input_type(InputType.feed_forward(6)).build())
            return MultiLayerNetwork(conf).init()

        single = build()
        sharded = build()
        mesh = data_model_mesh(2, 4)
        specs = network_param_specs(sharded, 4)
        # expert tensors shard on the EXPERT axis
        assert specs["0"]["W1"] == P("model", None, None)
        assert specs["0"]["b1"] == P("model", None)
        shard_network(sharded, mesh)
        for _ in range(4):
            single.do_step(x, y)
            sharded.do_step(x, y)
        np.testing.assert_allclose(np.asarray(sharded.params_flat()),
                                   np.asarray(single.params_flat()),
                                   atol=1e-5)
