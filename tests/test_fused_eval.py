"""Device-side fused evaluation tests (evaluation/fused_eval.py).

Covers the ISSUE-2 acceptance surface: fused evaluate() matches the
per-batch host path to EXACT integer counts (confusion matrix, top-N) on
both network classes, masked time series, ragged tail batches, the
program-count guarantees of the bucketed inference cache, and the
mesh-sharded on-device merge.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.evaluation.fused_eval import FusedEvalDriver
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer, OutputLayer,
                                               RnnOutputLayer, SimpleRnn)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Adam

from tests.test_fused_fit import _graph, _iris_like, _mln


def _batches(n, batch_size, seed=0):
    ds = _iris_like(n, seed=seed)
    x, y = np.asarray(ds.features), np.asarray(ds.labels)
    return [DataSet(x[i:i + batch_size], y[i:i + batch_size])
            for i in range(0, n, batch_size)]


def _rnn_net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.02))
            .weight_init("xavier")
            .list(SimpleRnn(n_out=8, activation="tanh"),
                  RnnOutputLayer(n_out=3, loss="mcxent",
                                 activation="softmax"))
            .set_input_type(InputType.recurrent(4)).build())
    return MultiLayerNetwork(conf).init()


def _assert_same_counts(ev_a, ev_b):
    assert ev_a.confusion is not None and ev_b.confusion is not None
    np.testing.assert_array_equal(ev_a.confusion, ev_b.confusion)
    assert ev_a.top_n_correct == ev_b.top_n_correct
    assert ev_a.top_n_total == ev_b.top_n_total


# ------------------------------------------------------------------ parity
class TestFusedEvalParity:
    @pytest.mark.parametrize("make_net", [_mln, _graph],
                             ids=["mln", "graph"])
    def test_matches_per_batch_exactly(self, make_net):
        """Fused confusion counts equal the host per-batch path's, as exact
        integers (the acceptance criterion, not allclose)."""
        net = make_net()
        it = ListDataSetIterator(_batches(96, 16), batch_size=16)
        ev_fused = net.evaluate(it)
        it.reset()
        ev_ref = net.evaluate(it, fused=False)
        _assert_same_counts(ev_fused, ev_ref)
        assert ev_fused.accuracy() == ev_ref.accuracy()

    @pytest.mark.parametrize("make_net", [_mln, _graph],
                             ids=["mln", "graph"])
    def test_top_n_matches(self, make_net):
        net = make_net()
        it = ListDataSetIterator(_batches(80, 16, seed=3), batch_size=16)
        ev_fused = net.evaluate(it, top_n=2)
        it.reset()
        ev_ref = net.evaluate(it, top_n=2, fused=False)
        _assert_same_counts(ev_fused, ev_ref)
        assert ev_fused.top_n_accuracy() == ev_ref.top_n_accuracy()

    def test_ragged_tail_batches(self):
        """Undersized trailing batches are padded with zero-weight rows:
        counts are exactly the unpadded stream's."""
        net = _mln()
        ds = _iris_like(86, seed=5)  # 32, 32, 22
        x, y = np.asarray(ds.features), np.asarray(ds.labels)
        batches = [DataSet(x[0:32], y[0:32]), DataSet(x[32:64], y[32:64]),
                   DataSet(x[64:86], y[64:86])]
        ev_fused = net.evaluate(ListDataSetIterator(batches, batch_size=32))
        ev_ref = net.evaluate(ListDataSetIterator(batches, batch_size=32),
                              fused=False)
        _assert_same_counts(ev_fused, ev_ref)
        assert int(ev_fused.confusion.sum()) == 86

    def test_masked_time_series(self):
        """3-D labels: the labels_mask selects timesteps, exactly as the
        host path's flatten-and-select."""
        net = _rnn_net()
        rs = np.random.RandomState(11)
        batches = []
        for _ in range(4):
            x = rs.randn(6, 5, 4).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[rs.randint(0, 3, (6, 5))]
            lm = (rs.rand(6, 5) > 0.3).astype(np.float32)
            im = np.ones((6, 5), np.float32)
            batches.append(DataSet(x, y, features_mask=im, labels_mask=lm))
        it = ListDataSetIterator(batches, batch_size=6)
        ev_fused = net.evaluate(it)
        it.reset()
        ev_ref = net.evaluate(it, fused=False)
        _assert_same_counts(ev_fused, ev_ref)
        # only unmasked timesteps counted
        total = sum(int(b.labels_mask.sum()) for b in batches)
        assert int(ev_fused.confusion.sum()) == total

    def test_eval_loss_attached(self):
        """The device accumulator tracks the masked mean loss for free; it
        matches score() on the concatenated stream."""
        net = _mln()
        ds = _iris_like(64, seed=2)
        ev = net.evaluate(ListDataSetIterator(_batches(64, 16, seed=2),
                                              batch_size=16))
        assert abs(ev.eval_loss - net.score(ds)) < 1e-5


# --------------------------------------------------------- program economy
class TestProgramCounts:
    def test_fused_eval_two_programs_per_ragged_stream(self):
        """A uniform stream with one ragged tail compiles exactly two eval
        programs: the K-block and its K=1 tail instance."""
        net = _mln()
        before = len(net._output_cache)
        net.evaluate(ListDataSetIterator(_batches(86, 16), batch_size=16))
        eval_keys = [k for k in net._output_cache
                     if isinstance(k, tuple) and k and k[0] == "fused_eval"]
        assert 1 <= len(eval_keys) <= 2
        assert len(net._output_cache) - before <= 2

    def test_output_bucketing_collapses_programs(self):
        """output() with batch sizes 1..9 pads to power-of-two buckets:
        at most 5 programs (1, 2, 4, 8, 16), not 9."""
        net = _mln()
        rs = np.random.RandomState(0)
        full = rs.randn(16, 4).astype(np.float32)
        for n in range(1, 10):
            out = net.output(full[:n])
            assert out.shape[0] == n
        fwd_keys = [k for k in net._output_cache
                    if not (isinstance(k, tuple) and k
                            and k[0] == "fused_eval")]
        assert len(fwd_keys) <= 5

    def test_output_bucket_padding_is_invisible(self):
        """Padded rows never leak: bucketed output equals the full-batch
        slice."""
        net = _mln()
        rs = np.random.RandomState(1)
        x = rs.randn(8, 4).astype(np.float32)
        full = np.asarray(net.output(x))
        for n in (1, 3, 5, 7):
            np.testing.assert_allclose(np.asarray(net.output(x[:n])),
                                       full[:n], rtol=1e-6, atol=1e-7)


# ------------------------------------------------------------------- mesh
class TestMeshFusedEval:
    def test_mesh_matches_host_counts(self):
        """Mesh-sharded fused eval (on-device merge) produces the same
        integer counts as the single-device host path."""
        from deeplearning4j_tpu.parallel import evaluate_on_mesh

        net = _mln()
        it = ListDataSetIterator(_batches(96, 16, seed=9), batch_size=16)
        ev_mesh = evaluate_on_mesh(net, it)
        it.reset()
        ev_ref = net.evaluate(it, fused=False)
        _assert_same_counts(ev_mesh, ev_ref)

    def test_mesh_unfused_path_still_works(self):
        from deeplearning4j_tpu.parallel import evaluate_on_mesh

        net = _mln()
        it = ListDataSetIterator(_batches(64, 16, seed=4), batch_size=16)
        ev_old = evaluate_on_mesh(net, it, fused=False)
        it.reset()
        ev_ref = net.evaluate(it, fused=False)
        _assert_same_counts(ev_old, ev_ref)

    def test_mesh_ragged_tail(self):
        """Ragged tails under sharding: padded to a worker multiple, zero
        weights keep the counts exact."""
        from deeplearning4j_tpu.parallel import evaluate_on_mesh

        net = _mln()
        ds = _iris_like(53, seed=6)
        x, y = np.asarray(ds.features), np.asarray(ds.labels)
        batches = [DataSet(x[0:16], y[0:16]), DataSet(x[16:32], y[16:32]),
                   DataSet(x[32:48], y[32:48]), DataSet(x[48:53], y[48:53])]
        it = ListDataSetIterator(batches, batch_size=16)
        ev_mesh = evaluate_on_mesh(net, it)
        it.reset()
        ev_ref = net.evaluate(it, fused=False)
        _assert_same_counts(ev_mesh, ev_ref)
        assert int(ev_mesh.confusion.sum()) == 53


# --------------------------------------------------------------- driver API
class TestDriverEdges:
    def test_explicit_k(self):
        net = _mln()
        it = ListDataSetIterator(_batches(96, 16), batch_size=16)
        drv = FusedEvalDriver(net, eval_batches=3)
        from deeplearning4j_tpu.evaluation.classification import Evaluation
        ev = drv.evaluate(it, Evaluation())
        it.reset()
        ev_ref = net.evaluate(it, fused=False)
        _assert_same_counts(ev, ev_ref)

    def test_bad_k_rejected(self):
        from deeplearning4j_tpu.evaluation.fused_eval import \
            resolve_eval_batches
        with pytest.raises(ValueError):
            resolve_eval_batches(0)

    def test_evaluate_with_arrays(self):
        """evaluate(x, y) convenience form routes through the fused path."""
        net = _mln()
        ds = _iris_like(32, seed=8)
        ev = net.evaluate(np.asarray(ds.features), np.asarray(ds.labels))
        ev_ref = net.evaluate(np.asarray(ds.features),
                              np.asarray(ds.labels), fused=False)
        _assert_same_counts(ev, ev_ref)
