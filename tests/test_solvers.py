"""Solver + gradient-accumulation tests (ports the intent of
optimize/solver tests — BackTrackLineSearchTest, TestOptimizers — and the
EncodingHandler threshold-compression contract)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers.core import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import Sgd
from deeplearning4j_tpu.optimize.accumulation import (
    BasicGradientsAccumulator,
    EncodingHandler,
    sparsify,
    threshold_encode,
    unsparsify,
)
from deeplearning4j_tpu.optimize.solvers import (
    ConjugateGradient,
    LBFGS,
    LineGradientDescent,
    Solver,
)


def _net(seed=3):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(learning_rate=0.1)).dtype("float64")
            .list(DenseLayer(n_out=8, activation="tanh"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=40, seed=0):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 3, n)
    x = rs.randn(n, 4) + 1.5 * labels[:, None]
    return x, np.eye(3)[labels]


class TestSolvers:
    @pytest.mark.parametrize("cls", [LineGradientDescent, ConjugateGradient,
                                     LBFGS])
    def test_optimizer_reduces_loss(self, cls):
        net = _net()
        x, y = _data()
        s0 = net.score(x=x, y=y)
        opt = cls(max_iterations=20)
        final = opt.optimize(net, x, y)
        assert final < s0 * 0.6, (s0, final)
        assert abs(net.score(x=x, y=y) - final) < 1e-8

    def test_lbfgs_converges_faster_than_line_gd(self):
        """On a smooth problem L-BFGS should beat steepest descent for the
        same iteration budget."""
        x, y = _data()
        n1, n2 = _net(), _net()
        l_gd = LineGradientDescent(max_iterations=15).optimize(n1, x, y)
        l_bfgs = LBFGS(max_iterations=15).optimize(n2, x, y)
        assert l_bfgs <= l_gd + 1e-9

    def test_solver_facade_dispatch(self):
        net = _net()
        x, y = _data()
        s = Solver(net, algorithm="lbfgs", max_iterations=10)
        final = s.optimize(x, y)
        assert final < 1.2
        with pytest.raises(ValueError, match="Unknown optimization"):
            Solver(net, algorithm="newton_raphson")

    def test_sgd_algorithm_uses_jitted_step(self):
        net = _net()
        x, y = _data()
        s = Solver(net, algorithm="stochastic_gradient_descent")
        before = net.iteration
        s.optimize(x.astype(np.float64), y.astype(np.float64))
        assert net.iteration == before + 1


class TestThresholdCompression:
    def test_encode_quantises_and_keeps_residual(self):
        import jax.numpy as jnp

        g = jnp.asarray([0.5, -0.3, 0.001, -0.0005, 0.0])
        res = jnp.zeros(5)
        msg, new_res = threshold_encode(g, res, jnp.float32(0.01))
        assert np.allclose(msg, [0.01, -0.01, 0.0, 0.0, 0.0])
        # residual holds exactly what was not transmitted
        assert np.allclose(np.asarray(msg) + np.asarray(new_res),
                           np.asarray(g), atol=1e-7)

    def test_residual_error_feedback_transmits_eventually(self):
        """Small gradients accumulate in the residual until they cross the
        threshold — no information is permanently lost."""
        h = EncodingHandler(threshold=0.1)
        g = np.full(4, 0.03, np.float32)
        sent = np.zeros(4)
        for _ in range(10):
            sent += np.asarray(h.encode(g))
        # after 10 rounds of 0.03, ~0.3 worth must have been transmitted
        assert np.all(sent >= 0.2)
        total = sent + np.asarray(h._residual)
        assert np.allclose(total, 0.3, atol=1e-6)

    def test_sparse_wire_roundtrip(self):
        msg = np.array([0.01, 0.0, -0.01, 0.0, 0.01], np.float32)
        idx, signs = sparsify(msg, 0.01)
        assert list(idx) == [0, 2, 4]
        back = unsparsify(idx, signs, 0.01, 5)
        assert np.allclose(back, msg)

    def test_accumulator_matches_uncompressed_mean_over_time(self):
        """Error-feedback compressed mean converges to the true mean of the
        per-worker gradients over repeated rounds."""
        rs = np.random.RandomState(7)
        W, D = 4, 64
        # threshold must exceed the per-round gradient magnitude for the
        # error-feedback transmission to keep up (1-bit-SGD regime: each
        # round moves at most +-threshold per coordinate)
        theta = 0.05
        grads = [np.clip(rs.randn(D) * 0.01, -0.04, 0.04).astype(np.float32)
                 for _ in range(W)]
        acc_c = BasicGradientsAccumulator(W, threshold=theta, compress=True)
        total_c = np.zeros(D)
        rounds = 30
        for _ in range(rounds):
            for w in range(W):
                acc_c.store_update(w, grads[w])
            total_c += np.asarray(acc_c.get_update())
        true_total = np.mean(grads, axis=0) * rounds
        # error bounded by ~threshold per coordinate (final residuals)
        assert np.all(np.abs(total_c - true_total) <= 2 * theta + 1e-6)
