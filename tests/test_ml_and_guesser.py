"""Estimator wrappers (spark-ml analog), ModelGuesser, and the
Keras-backend entry-point server (py4j analog)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.ml import DL4JClassifier, DL4JRegressor
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.updater import Adam


def _clf_conf():
    return (NeuralNetConfiguration.builder()
            .seed(7).updater(Adam(learning_rate=0.05))
            .list(DenseLayer(n_out=16, activation="relu"),
                  OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())


def _reg_conf():
    return (NeuralNetConfiguration.builder()
            .seed(7).updater(Adam(learning_rate=0.05))
            .list(DenseLayer(n_out=16, activation="relu"),
                  OutputLayer(n_out=1, activation="identity", loss="mse"))
            .set_input_type(InputType.feed_forward(3)).build())


class TestEstimators:
    def test_classifier_fit_predict_score(self):
        rs = np.random.RandomState(0)
        y = rs.randint(0, 3, 256)
        x = (rs.randn(256, 4) + 2 * y[:, None]).astype(np.float32)
        clf = DL4JClassifier(_clf_conf, epochs=30, batch_size=64)
        clf.fit(x, y)
        assert clf.score(x, y) > 0.9
        proba = clf.predict_proba(x[:5])
        assert proba.shape == (5, 3)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)

    def test_classifier_string_labels(self):
        rs = np.random.RandomState(1)
        names = np.array(["cat", "dog", "fox"])
        yi = rs.randint(0, 3, 128)
        x = (rs.randn(128, 4) + 2 * yi[:, None]).astype(np.float32)
        clf = DL4JClassifier(_clf_conf, epochs=25, batch_size=64)
        clf.fit(x, names[yi])
        pred = clf.predict(x[:10])
        assert set(pred) <= set(names)
        assert clf.score(x, names[yi]) > 0.8

    def test_regressor_r2(self):
        rs = np.random.RandomState(2)
        x = rs.randn(256, 3).astype(np.float32)
        y = (x @ np.array([1.0, -2.0, 0.5])).astype(np.float32)
        reg = DL4JRegressor(_reg_conf, epochs=60, batch_size=64)
        reg.fit(x, y)
        assert reg.score(x, y) > 0.8
        assert reg.predict(x[:7]).shape == (7,)

    def test_params_protocol_and_unfitted(self):
        clf = DL4JClassifier(_clf_conf, epochs=3)
        p = clf.get_params()
        assert p["epochs"] == 3
        clf.set_params(epochs=5)
        assert clf.epochs == 5
        with pytest.raises(ValueError):
            clf.set_params(nope=1)
        with pytest.raises(RuntimeError):
            clf.predict(np.zeros((1, 4)))


class TestModelGuesser:
    def test_guesses_all_formats(self, tmp_path):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.utils.model_guesser import (guess_format,
                                                            load_model_guess)
        from deeplearning4j_tpu.utils.model_serializer import save_model

        # dl4j zip
        net = MultiLayerNetwork(_clf_conf()).init()
        zp = str(tmp_path / "net.zip")
        save_model(net, zp)
        assert guess_format(zp) == "dl4j-zip"
        loaded = load_model_guess(zp)
        np.testing.assert_allclose(loaded.params_flat(), net.params_flat())

        # word2vec binary + text
        from deeplearning4j_tpu.nlp import (CollectionSentenceIterator,
                                            Word2Vec)
        from deeplearning4j_tpu.nlp.serde import (write_word2vec_binary,
                                                  write_word_vectors_text)
        rs = np.random.RandomState(0)
        sents = [" ".join(f"w{rs.randint(20)}" for _ in range(8))
                 for _ in range(100)]
        w2v = Word2Vec(layer_size=8, window=2, min_word_frequency=1,
                       epochs=1, seed=1)
        w2v.fit(CollectionSentenceIterator(sents))
        bp, tp = str(tmp_path / "v.bin"), str(tmp_path / "v.txt")
        write_word2vec_binary(w2v, bp)
        write_word_vectors_text(w2v, tp)
        assert guess_format(bp) == "word2vec-binary"
        assert guess_format(tp) == "word-vectors-text"
        words, vecs = load_model_guess(bp)
        assert len(words) == vecs.shape[0] == w2v.vocab.num_words()

    def test_keras_h5_detected(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        from deeplearning4j_tpu.utils.model_guesser import guess_format
        p = str(tmp_path / "m.h5")
        with h5py.File(p, "w"):
            pass
        assert guess_format(p) == "keras-h5"

    def test_unknown_rejected(self, tmp_path):
        from deeplearning4j_tpu.utils.model_guesser import guess_format
        p = tmp_path / "junk.bin"
        p.write_bytes(b"\x00\x01\x02\x03garbage")
        with pytest.raises(ValueError):
            guess_format(str(p))


class TestKerasBackendServer:
    def test_import_fit_evaluate_predict_over_http(self, tmp_path):
        keras = pytest.importorskip("keras")
        from keras import layers

        from deeplearning4j_tpu.modelimport.server import KerasBackendServer

        m = keras.Sequential([
            layers.Input((4,)),
            layers.Dense(16, activation="relu"),
            layers.Dense(3, activation="softmax"),
        ])
        m.compile(loss="categorical_crossentropy")
        h5 = str(tmp_path / "m.h5")
        m.save(h5)

        rs = np.random.RandomState(0)
        paths = []
        for i in range(4):
            labels = rs.randint(0, 3, 32)
            p = str(tmp_path / f"b{i}.npz")
            np.savez(p,
                     features=(rs.randn(32, 4) + 2 * labels[:, None])
                     .astype(np.float32),
                     labels=np.eye(3, dtype=np.float32)[labels])
            paths.append(p)

        srv = KerasBackendServer()
        port = srv.start()
        base = f"http://127.0.0.1:{port}"

        def post(path, payload):
            req = urllib.request.Request(
                base + path, json.dumps(payload).encode(),
                {"Content-Type": "application/json"})
            try:
                return json.loads(urllib.request.urlopen(req).read())
            except urllib.error.HTTPError as e:
                return json.loads(e.read())

        try:
            mid = post("/import", {"path": h5})["model"]
            r = post("/fit", {"model": mid, "batches": paths, "epochs": 20})
            assert r["iterations"] == 80
            ev = post("/evaluate", {"model": mid, "batches": paths})
            assert ev["accuracy"] > 0.8
            out = post("/predict", {"model": mid,
                                    "features": [[0.0, 0.0, 0.0, 0.0]]})
            assert len(out["output"][0]) == 3
            models = json.loads(
                urllib.request.urlopen(base + "/models").read())
            assert mid in models["models"]
            err = post("/fit", {"model": "nope", "batches": []})
            assert "error" in err
        finally:
            srv.stop()
