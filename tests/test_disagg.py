"""Disaggregated prefill/decode serving-tier tests (parallel/fleet.py +
parallel/generation.py role modes).

Covers the tier boundary end to end on the CPU mesh: a prefill-role
server exporting freshly prefilled requests as KVSnapshots (first token
included), decode-tier adoption finishing the stream bit-exactly vs a
unified single-tier server (greedy + sampled, f32 + int8), remaining
deadline budget crossing the wire as a duration, role-aware fleet
routing behind the same ``submit() -> Future`` surface with TTFT and
inter-token latency in separate histograms, and the robustness core:
mid-handoff kills on either side of the boundary, corrupt / truncated /
dropped transfers falling back without losing a future, and the
decode-tier-dark degraded mode with automatic recovery.
"""

import time
from contextlib import contextmanager

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import (TransformerLM, greedy_generate,
                                           sample_generate)
from deeplearning4j_tpu.parallel.fleet import ReplicaFleet
from deeplearning4j_tpu.parallel.generation import GenerationServer
from deeplearning4j_tpu.parallel.handoff import (KVSnapshot,
                                                 SnapshotUnsupported,
                                                 export_request)
from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy, Deadline,
                                                    DeadlineExceeded,
                                                    ResilienceError,
                                                    TransientDispatchError)

V = 17


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(num_labels=V, max_length=16, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


@contextmanager
def serving(*args, **kwargs):
    srv = GenerationServer(*args, **kwargs)
    try:
        yield srv
    finally:
        srv.close()


@contextmanager
def fleet_of(factory, replicas, **kw):
    fl = ReplicaFleet(factory, replicas=replicas, **kw)
    try:
        yield fl
    finally:
        fl.close()


def _tier_factory(lm, roles, chaos_by_rid=None, **gen_kw):
    kw = dict(slots=2, page_size=4, steps_per_dispatch=1)
    kw.update(gen_kw)

    def factory(rid):
        extra = {}
        if chaos_by_rid and rid in chaos_by_rid:
            extra["chaos"] = chaos_by_rid[rid]
        return GenerationServer(lm, V, role=roles[rid], **kw, **extra)

    return factory


def _mixed_specs(n, rng, shapes=((3, 4), (5, 5), (4, 6))):
    specs = []
    for i in range(n):
        plen, steps = shapes[i % len(shapes)]
        p = rng.integers(1, V, size=plen).astype(np.int64)
        if i % 2 == 0:
            specs.append((p, steps, 0.0, 0, 0))
        else:
            specs.append((p, steps, 0.9, 5, 2000 + i))
    return specs


def _serial_refs(lm, specs):
    refs = []
    for p, steps, temp, top_k, seed in specs:
        if temp == 0.0:
            refs.append(greedy_generate(lm, p[None], steps, V)[0])
        else:
            refs.append(sample_generate(lm, p[None], steps, V,
                                        temperature=temp, top_k=top_k,
                                        seed=seed)[0])
    return refs


def _submit_with_backoff(fleet, spec, deadline_s=240.0, budget_s=60.0):
    p, steps, temp, top_k, seed = spec
    t_end = time.monotonic() + budget_s
    while True:
        try:
            return fleet.submit(p, steps, temperature=temp, top_k=top_k,
                                seed=seed, deadline_s=deadline_s)
        except ResilienceError:
            if time.monotonic() > t_end:
                raise
            time.sleep(0.02)


def _assert_zero_lost(st):
    """The cross-tier ledger: once idle, every accepted request is
    accounted for — nothing vanished in a handoff."""
    assert st["submitted"] == (st["completed"] + st["failed"]
                               + st["expired"] + st["rejected_submits"]), st
    assert st["inflight"] == 0 and st["parked"] == 0


GREEDY = (np.array([1, 2, 3, 4], np.int64), 12, 0.0, 0, 0)
SAMPLED = (np.array([1, 2, 3, 4], np.int64), 12, 0.9, 5, 77)


@pytest.mark.disagg
class TestPrefillExport:
    def test_export_and_adopt_bitexact(self, lm):
        """A prefill-role server resolves the future to a KVSnapshot
        holding exactly the first token; adopting it on a separate
        decode-role server finishes byte-identical to the serial
        reference — greedy and sampled."""
        for spec in (GREEDY, SAMPLED):
            p, steps, temp, top_k, seed = spec
            ref = _serial_refs(lm, [spec])[0]
            with serving(lm, V, slots=2, page_size=4,
                         role="prefill") as pre:
                snap = pre.submit(p, steps, temperature=temp, top_k=top_k,
                                  seed=seed).result(timeout=120)
                assert isinstance(snap, KVSnapshot)
                assert snap.count == 1 and snap.tokens == [int(ref[0])]
                st = pre.stats()
                assert st["role"] == "prefill"
                assert st["handoff"]["prefill_exports"] == 1
                # the slot frees at export: short slot residency is the
                # whole point of the prefill tier
                assert st["active_slots"] == 0 and st["queued"] == 0
            with serving(lm, V, slots=2, page_size=4,
                         role="decode") as dec:
                out = dec.adopt_request(snap).result(timeout=120)
                np.testing.assert_array_equal(np.asarray(out), ref)
                assert dec.stats()["role"] == "decode"

    def test_export_int8_bitexact_vs_unified_int8(self, lm):
        """int8 tier transfer: prefill-export from an int8 pool adopted
        into an int8 decode pool matches the unified int8 server's own
        completion token-for-token."""
        p, steps, temp, top_k, seed = SAMPLED
        with serving(lm, V, slots=2, page_size=4,
                     kv_dtype="int8") as uni:
            ref = np.asarray(uni.submit(
                p, steps, temperature=temp, top_k=top_k,
                seed=seed).result(timeout=120))
        with serving(lm, V, slots=2, page_size=4, kv_dtype="int8",
                     role="prefill") as pre:
            snap = pre.submit(p, steps, temperature=temp, top_k=top_k,
                              seed=seed).result(timeout=120)
        assert snap.kv_dtype == "int8"
        with serving(lm, V, slots=2, page_size=4, kv_dtype="int8",
                     role="decode") as dec:
            out = dec.adopt_request(snap).result(timeout=120)
        np.testing.assert_array_equal(np.asarray(out), ref)

    def test_single_token_request_returns_tokens(self, lm):
        """max_tokens=1 finishes ON the prefill token: the request
        retires normally with a token array — never a snapshot of an
        already-complete stream."""
        p = np.array([1, 2, 3], np.int64)
        ref = greedy_generate(lm, p[None], 1, V)[0]
        with serving(lm, V, slots=2, page_size=4, role="prefill") as pre:
            out = pre.submit(p, 1).result(timeout=120)
            assert not isinstance(out, KVSnapshot)
            np.testing.assert_array_equal(np.asarray(out), ref)
            assert pre.stats()["handoff"]["prefill_exports"] == 0

    def test_role_validation(self, lm):
        with pytest.raises(ValueError):
            GenerationServer(lm, V, role="bogus")
        with pytest.raises(ValueError):
            ReplicaFleet(lambda rid: GenerationServer(lm, V), replicas=2,
                         roles=("prefill",))  # length mismatch
        with pytest.raises(ValueError):
            ReplicaFleet(lambda rid: GenerationServer(lm, V), replicas=2,
                         roles=("prefill", "prefill"))  # no decode tier
        with pytest.raises(ValueError):
            # declared roles must match what the factory builds
            ReplicaFleet(lambda rid: GenerationServer(lm, V), replicas=2,
                         roles=("prefill", "decode"))


@pytest.mark.disagg
class TestDeadlineAcrossTiers:
    def test_snapshot_carries_remaining_budget(self, lm):
        """The wire format ships the request's REMAINING deadline budget
        as a duration (never a timestamp): present after export, bounded
        by the original budget, and preserved by a byte round-trip."""
        p, steps, _, _, _ = GREEDY
        with serving(lm, V, slots=2, page_size=4, role="prefill") as pre:
            snap = pre.submit(p, steps, deadline_s=120.0).result(
                timeout=120)
        assert snap.deadline_remaining is not None
        assert 0.0 < snap.deadline_remaining <= 120.0
        back = KVSnapshot.from_bytes(snap.to_bytes())
        assert back.deadline_remaining == snap.deadline_remaining
        # a request submitted WITHOUT a deadline exports None
        with serving(lm, V, slots=2, page_size=4, role="prefill") as pre:
            snap2 = pre.submit(p, steps).result(timeout=120)
        assert snap2.deadline_remaining is None
        assert KVSnapshot.from_bytes(
            snap2.to_bytes()).deadline_remaining is None

    def test_adopting_exhausted_budget_fails_typed(self, lm):
        """A snapshot whose carried budget is already spent is rejected
        with the typed DeadlineExceeded at adoption — the decode tier
        never burns slots on a request that cannot meet its SLO."""
        p, steps, _, _, _ = GREEDY
        with serving(lm, V, slots=2, page_size=4, role="prefill") as pre:
            snap = pre.submit(p, steps).result(timeout=120)
        kw = {s: getattr(snap, s) for s in KVSnapshot.__slots__
              if s != "checksum"}
        kw["deadline_remaining"] = 1e-4
        expired = KVSnapshot(**kw)
        with serving(lm, V, slots=2, page_size=4, role="decode") as dec:
            with pytest.raises(DeadlineExceeded):
                dec.adopt_request(expired).result(timeout=120)

    def test_export_request_clamps_to_deadline(self, lm):
        """``export_request`` waits ``min(timeout, remaining)`` and
        raises the typed expiry: an exhausted budget fails fast even
        with the default 30 s timeout."""
        p = np.array([1, 2, 3, 4], np.int64)
        with serving(lm, V, slots=2, page_size=4) as srv:
            fut = srv.submit(p, 12)
            fut._deadline = Deadline(1e-4)  # budget already spent
            time.sleep(0.005)
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceeded):
                export_request(srv, fut, timeout=30.0)
            assert time.monotonic() - t0 < 5.0
            fut._deadline = None  # let the request finish normally
            fut.result(timeout=120)


@pytest.mark.disagg
class TestTieredFleet:
    def test_mixed_bitexact_ledger_and_slos(self, lm):
        """The full tier pipeline behind one submit(): every completion
        bit-exact vs serial, every request crossing the boundary exactly
        once, zero lost futures, and TTFT / inter-token latency observed
        in SEPARATE registry histograms."""
        rng = np.random.default_rng(42)
        specs = _mixed_specs(8, rng)
        refs = _serial_refs(lm, specs)
        roles = ("prefill", "decode")
        with fleet_of(_tier_factory(lm, roles), 2, roles=roles) as fl:
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            for fut, ref in zip(futs, refs):
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=240)), ref)
            st = fl.stats()
            assert st["tier_handoffs"] >= len(specs)
            assert st["degraded_mode"] is False
            assert st["tiers"]["prefill"]["replicas"] == 1
            assert st["tiers"]["decode"]["replicas"] == 1
            assert st["completed"] == len(specs)
            _assert_zero_lost(st)
            assert fl.ttft_hist.count == len(specs)
            assert fl.itl_hist.count == len(specs)
            assert fl.ttft_hist.sum > 0 and fl.itl_hist.sum > 0
            # per-tier levers move capacity independently
            assert fl.set_tier_active_slots("decode", 1) == 1
            assert fl.tier_stats("decode")["active_slots"] == 1
            assert fl.tier_stats("prefill")["active_slots"] == 2
            assert fl.set_tier_active_slots("decode", 2) == 2

    def test_int8_tiered_matches_unified(self, lm):
        specs = [GREEDY, SAMPLED]
        with serving(lm, V, slots=2, page_size=4, kv_dtype="int8") as uni:
            refs = [np.asarray(uni.submit(
                p, steps, temperature=t, top_k=k, seed=s).result(
                    timeout=120))
                for p, steps, t, k, s in specs]
        roles = ("prefill", "decode")
        with fleet_of(_tier_factory(lm, roles, kv_dtype="int8"), 2,
                      roles=roles) as fl:
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            for fut, ref in zip(futs, refs):
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=240)), ref)
            _assert_zero_lost(fl.stats())

    def test_decode_tier_dark_degraded_and_recovery(self, lm):
        """Kill the only decode replica: the fleet flips the
        degraded_mode gauge, serves every request co-located on the
        prefill tier (bit-exact), then clears the flag automatically
        when the supervised restart heals the tier."""
        ref = _serial_refs(lm, [GREEDY])[0]
        roles = ("prefill", "decode")
        # a long restart backoff keeps the tier dark across the whole
        # degraded pass, so the assertions race nothing
        with fleet_of(_tier_factory(lm, roles), 2, roles=roles,
                      restart_backoff_s=5.0) as fl:
            assert fl.kill_replica(1)
            futs = [_submit_with_backoff(fl, GREEDY) for _ in range(3)]
            for fut in futs:
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=240)), ref)
            st = fl.stats()
            assert st["degraded_mode"] is True
            assert st["degraded_submits"] >= 3
            # supervised restart brings the tier back -> flag clears
            t_end = time.monotonic() + 90.0
            while fl.stats()["degraded_mode"]:
                assert time.monotonic() < t_end, "degraded mode stuck"
                time.sleep(0.02)
            before = fl.stats()["tier_handoffs"]
            fut = _submit_with_backoff(fl, GREEDY)
            np.testing.assert_array_equal(
                np.asarray(fut.result(timeout=240)), ref)
            st = fl.stats()
            assert st["tier_handoffs"] > before  # pipeline is back
            _assert_zero_lost(st)

    def test_no_recompile_on_tier_churn(self):
        """Zero-retrace across the boundary: after one greedy and one
        sampled request have crossed the tiers, further tiered traffic
        adds ZERO compiled programs."""
        net = TransformerLM(num_labels=V, max_length=16, d_model=8,
                            n_heads=2, n_blocks=1, seed=9).init()
        roles = ("prefill", "decode")
        with fleet_of(_tier_factory(net, roles), 2, roles=roles) as fl:
            for sp in (GREEDY, SAMPLED):
                _submit_with_backoff(fl, sp).result(timeout=240)
            warmed = len(net._output_cache)
            specs = _mixed_specs(4, np.random.default_rng(5))
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            for fut in futs:
                fut.result(timeout=240)
            assert len(net._output_cache) == warmed


@pytest.mark.disagg
class TestTierChaos:
    def test_midhandoff_prefill_kill(self, lm):
        """Killing a prefill replica with requests in flight re-prefills
        them on the sibling: all complete bit-exact, zero lost."""
        rng = np.random.default_rng(7)
        specs = _mixed_specs(6, rng)
        refs = _serial_refs(lm, specs)
        roles = ("prefill", "prefill", "decode")
        with fleet_of(_tier_factory(lm, roles), 3, roles=roles) as fl:
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            fl.kill_replica(0)  # mid-prefill for whatever it holds
            for fut, ref in zip(futs, refs):
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=240)), ref)
            st = fl.stats()
            assert st["completed"] == len(specs)
            _assert_zero_lost(st)

    def test_midhandoff_decode_kill(self, lm):
        """Killing a decode replica mid-stream re-adopts (or token-0
        regenerates) its requests elsewhere: all complete bit-exact,
        zero lost."""
        rng = np.random.default_rng(11)
        specs = _mixed_specs(6, rng, shapes=((3, 12), (4, 12), (3, 13)))
        refs = _serial_refs(lm, specs)
        roles = ("prefill", "decode", "decode")
        with fleet_of(_tier_factory(lm, roles, snapshot_every=4), 3,
                      roles=roles) as fl:
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            # event-driven: kill a decode replica once it is visibly
            # streaming (poll, don't sleep-calibrate)
            victim = None
            t_end = time.monotonic() + 90.0
            while victim is None and time.monotonic() < t_end:
                for blk in fl.stats()["replicas"]:
                    srv = blk["server"] or {}
                    if (blk["role"] == "decode" and blk["state"] == "ready"
                            and srv.get("active_slots", 0) >= 1):
                        victim = blk["rid"]
                        break
                else:
                    time.sleep(0.005)
            if victim is not None:
                fl.kill_replica(victim)
            for fut, ref in zip(futs, refs):
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=240)), ref)
            st = fl.stats()
            assert st["completed"] == len(specs)
            _assert_zero_lost(st)

    def test_corrupt_transfer_token0_fallback(self, lm):
        """A corrupted tier transfer (checksum breaks in flight) is
        dropped at adoption and the request regenerates from token 0 on
        the decode tier — bit-exact, typed, never lost."""
        self._faulty_transfer_case(lm, ChaosPolicy(
            seed=5, snapshot_corrupt_rate=1.0))

    def test_truncated_transfer_token0_fallback(self, lm):
        """A truncated transfer (partial wire bytes) fails checksum
        verification exactly like corruption: token-0 fallback."""
        self._faulty_transfer_case(lm, ChaosPolicy(
            seed=6, handoff_truncate_rate=1.0))

    @staticmethod
    def _faulty_transfer_case(lm, chaos):
        specs = [GREEDY, SAMPLED]
        refs = _serial_refs(lm, specs)
        roles = ("prefill", "decode")
        factory = _tier_factory(lm, roles, chaos_by_rid={0: chaos})
        with fleet_of(factory, 2, roles=roles) as fl:
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            for fut, ref in zip(futs, refs):
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=240)), ref)
            st = fl.stats()
            assert st["handoff_fallbacks"] >= len(specs)
            assert st["completed"] == len(specs) and st["failed"] == 0
            _assert_zero_lost(st)

    def test_dropped_transfer_reprefills_on_sibling(self, lm):
        """A transfer that vanishes in flight fails the attempt typed
        (SnapshotUnavailable, no snapshot) and the fleet re-prefills on
        the clean sibling prefill replica."""
        specs = [GREEDY, SAMPLED, (np.array([2, 5, 1], np.int64),
                                   10, 0.0, 0, 0)]
        refs = _serial_refs(lm, specs)
        chaos = ChaosPolicy(seed=8, handoff_drop_rate=1.0)
        roles = ("prefill", "prefill", "decode")
        factory = _tier_factory(lm, roles, chaos_by_rid={0: chaos})
        with fleet_of(factory, 3, roles=roles) as fl:
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            for fut, ref in zip(futs, refs):
                np.testing.assert_array_equal(
                    np.asarray(fut.result(timeout=240)), ref)
            st = fl.stats()
            assert st["completed"] == len(specs) and st["failed"] == 0
            if chaos.injected_handoff_drop:  # routing hit the faulty rep
                assert st["redispatched"] >= 1
            _assert_zero_lost(st)

    def test_speculative_prefill_role_rejected(self, lm):
        """Speculative decoding cannot export mid-stream KV: a
        prefill-role server with a draft net is a config error, typed
        at construction."""
        draft = TransformerLM(num_labels=V, max_length=16, d_model=8,
                              n_heads=2, n_blocks=1, seed=4).init()
        with pytest.raises((ValueError, SnapshotUnsupported)):
            GenerationServer(lm, V, role="prefill", draft_net=draft)


@pytest.mark.disagg
class TestChaosPinning:
    def test_handoff_fault_modes_deterministic_and_exclusive(self):
        """Same seed -> same corrupt/stall/drop/truncate sequence; at
        most one fault per draw; counters match the emitted modes."""
        def run():
            sleeps = []
            ch = ChaosPolicy(seed=7, snapshot_corrupt_rate=0.1,
                             handoff_stall_rate=0.1, handoff_stall_s=0.5,
                             handoff_drop_rate=0.1,
                             handoff_truncate_rate=0.1,
                             sleep=sleeps.append)
            modes = [ch.handoff_fault_mode() for _ in range(400)]
            return modes, sleeps, ch

        m1, s1, c1 = run()
        m2, s2, c2 = run()
        assert m1 == m2 and s1 == s2
        assert m1.count("corrupt") == c1.injected_snapshot_corrupt > 0
        assert m1.count("drop") == c1.injected_handoff_drop > 0
        assert m1.count("truncate") == c1.injected_handoff_truncate > 0
        assert len(s1) == c1.injected_handoff_stall > 0
        assert c1.injected_handoff_drop == c2.injected_handoff_drop
        assert c1.injected_handoff_truncate == c2.injected_handoff_truncate

    def test_legacy_sequences_pinned(self):
        """Zero-rate drop/truncate knobs draw NOTHING from the chaos
        RNG: a seeded policy's replica-fault sequence is byte-identical
        with the new parameters present and interleaved fault checks."""
        def pattern(**kw):
            ch = ChaosPolicy(seed=11, transient_rate=0.3, hard_rate=0.1,
                             **kw)
            fn = ch.wrap(lambda: "ok")
            seq = []
            for _ in range(200):
                if kw:
                    assert ch.handoff_fault() is False
                    assert ch.handoff_fault_mode() is None
                try:
                    seq.append(fn() is not None)
                except TransientDispatchError:
                    seq.append("transient")
                except RuntimeError:
                    seq.append("hard")
            return seq

        assert pattern() == pattern(handoff_drop_rate=0.0,
                                    handoff_truncate_rate=0.0)
        # and the PR-11 knobs stay pinned alongside the new ones
        assert pattern() == pattern(snapshot_corrupt_rate=0.0,
                                    handoff_stall_rate=0.0,
                                    handoff_drop_rate=0.0,
                                    handoff_truncate_rate=0.0)
