"""Native data-loader tests: C CSV parser parity with the Python reader,
fallback behavior, and edge cases (the DataVec-ingestion native-path
analog)."""

import csv as _csv
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datavec.records import CSVRecordReader
from deeplearning4j_tpu.native import native_available, parse_numeric_csv


def _write(path, rows, delimiter=",", header=None, crlf=False):
    nl = "\r\n" if crlf else "\n"
    with open(path, "w", newline="") as f:
        if header:
            f.write(delimiter.join(header) + nl)
        for r in rows:
            f.write(delimiter.join(str(v) for v in r) + nl)


needs_native = pytest.mark.skipif(not native_available(),
                                  reason="no C toolchain")


class TestNativeParser:
    @needs_native
    def test_parity_with_python_reader(self, tmp_path):
        rs = np.random.RandomState(0)
        rows = rs.randn(500, 12).round(6).tolist()
        p = str(tmp_path / "data.csv")
        _write(p, rows)
        arr = parse_numeric_csv(p)
        assert arr is not None and arr.shape == (500, 12)
        py_rows = list(CSVRecordReader(p))
        np.testing.assert_allclose(arr, np.asarray(py_rows), rtol=1e-12)

    @needs_native
    def test_skip_lines_and_delimiters(self, tmp_path):
        p = str(tmp_path / "d.csv")
        _write(p, [[1, 2], [3, 4]], delimiter=";", header=["a", "b"])
        arr = parse_numeric_csv(p, delimiter=";", skip_lines=1)
        np.testing.assert_array_equal(arr, [[1.0, 2.0], [3.0, 4.0]])

    @needs_native
    def test_crlf_and_blank_lines(self, tmp_path):
        p = str(tmp_path / "d.csv")
        with open(p, "w", newline="") as f:
            f.write("1,2\r\n\r\n3,4\r\n")
        np.testing.assert_array_equal(parse_numeric_csv(p),
                                      [[1.0, 2.0], [3.0, 4.0]])

    @needs_native
    def test_non_numeric_returns_none(self, tmp_path):
        p = str(tmp_path / "d.csv")
        _write(p, [["1", "x"], ["2", "3"]])
        assert parse_numeric_csv(p) is None

    @needs_native
    def test_ragged_returns_none(self, tmp_path):
        p = str(tmp_path / "d.csv")
        with open(p, "w") as f:
            f.write("1,2\n3,4,5\n")
        assert parse_numeric_csv(p) is None

    @needs_native
    def test_empty_field_returns_none(self, tmp_path):
        p = str(tmp_path / "d.csv")
        with open(p, "w") as f:
            f.write("1,,3\n")
        assert parse_numeric_csv(p) is None

    @needs_native
    def test_empty_field_does_not_eat_next_line(self, tmp_path):
        # strtod skips newlines as whitespace; the guard must reject the
        # empty trailing field instead of consuming the next line's value
        p = str(tmp_path / "d.csv")
        with open(p, "w") as f:
            f.write("1, \n2,3\n")
        assert parse_numeric_csv(p) is None

    @needs_native
    def test_whitespace_only_line_declines(self, tmp_path):
        # the Python path yields a one-string record for '   ' — the fast
        # path must decline so output never depends on toolchain presence
        p = str(tmp_path / "d.csv")
        with open(p, "w") as f:
            f.write("1,2\n   \n3,4\n")
        assert parse_numeric_csv(p) is None

    @needs_native
    def test_hex_floats_decline(self, tmp_path):
        # strtod accepts 0x10; Python float() does not — must fall back
        p = str(tmp_path / "d.csv")
        with open(p, "w") as f:
            f.write("0x10,2\n3,4\n")
        assert parse_numeric_csv(p) is None

    @needs_native
    def test_tab_delimited_takes_fast_path(self, tmp_path):
        p = str(tmp_path / "d.tsv")
        with open(p, "w") as f:
            f.write("1.5\t2.5\n3.5\t4.5\n")
        arr = parse_numeric_csv(p, delimiter="\t")
        np.testing.assert_array_equal(arr, [[1.5, 2.5], [3.5, 4.5]])

    @needs_native
    def test_space_delimited_empty_field_declines(self, tmp_path):
        p = str(tmp_path / "d.txt")
        with open(p, "w") as f:
            f.write("1  2\n3 4\n")  # '1  2' has an empty middle field
        assert parse_numeric_csv(p, delimiter=" ") is None
        with open(p, "w") as f:
            f.write("1 2\n3 4\n")
        np.testing.assert_array_equal(parse_numeric_csv(p, delimiter=" "),
                                      [[1.0, 2.0], [3.0, 4.0]])


class TestReaderIntegration:
    def test_reader_yields_same_rows_either_path(self, tmp_path):
        # mixed file -> python path; numeric file -> native path (when
        # available); both yield identical record structure
        pn = str(tmp_path / "n.csv")
        _write(pn, [[1.5, 2.5], [3.5, 4.5]])
        assert list(CSVRecordReader(pn)) == [[1.5, 2.5], [3.5, 4.5]]
        pm = str(tmp_path / "m.csv")
        _write(pm, [["a", 1], ["b", 2]])
        assert list(CSVRecordReader(pm)) == [["a", 1.0], ["b", 2.0]]

    @needs_native
    def test_native_is_faster_on_bulk(self, tmp_path):
        rs = np.random.RandomState(1)
        rows = rs.randn(20000, 20).round(6).tolist()
        p = str(tmp_path / "big.csv")
        _write(p, rows)
        t0 = time.perf_counter()
        arr = parse_numeric_csv(p)
        t_native = time.perf_counter() - t0
        t0 = time.perf_counter()
        with open(p, newline="") as f:
            py = [[float(v) for v in row] for row in _csv.reader(f)]
        t_py = time.perf_counter() - t0
        np.testing.assert_allclose(arr, np.asarray(py), rtol=1e-12)
        # not a strict perf assert (CI noise) — just record the ratio and
        # require the native path to not be pathologically slower
        print(f"native {t_native * 1e3:.1f} ms vs python {t_py * 1e3:.1f} ms")
        assert t_native < t_py * 2
