"""Keras import tests (reference: modelimport test intent — import a fixture
h5 and compare forward outputs).

Two fixture paths:
- real Keras 3 legacy-h5 files (keras/tensorflow are in the image) — strict
  numerical parity of predict() vs our output()
- a hand-built Keras-1-style h5 (th dim ordering, Convolution2D spellings)
  written directly with h5py — exercises the K1 config/weight layout without
  needing Keras 1.
"""

import json

import numpy as np
import pytest

h5py = pytest.importorskip("h5py")


@pytest.fixture(scope="module")
def keras():
    return pytest.importorskip("keras")


def _assert_forward_parity(keras_model, path, x, atol=1e-4):
    from deeplearning4j_tpu.modelimport import \
        import_keras_sequential_model_and_weights

    keras_model.save(path)
    net = import_keras_sequential_model_and_weights(path)
    expected = np.asarray(keras_model.predict(x, verbose=0))
    got = np.asarray(net.output(x))
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, atol=atol, rtol=1e-3)
    return net


class TestKeras3Import:
    def test_cnn_sequential_parity(self, keras, tmp_path):
        from keras import layers

        m = keras.Sequential([
            keras.Input((8, 8, 2)),
            layers.Conv2D(4, (3, 3), padding="same", activation="relu"),
            layers.MaxPooling2D((2, 2)),
            layers.Conv2D(6, (3, 3), padding="valid", activation="tanh"),
            layers.Flatten(),
            layers.Dense(5, activation="softmax"),
        ])
        x = np.random.RandomState(0).randn(3, 8, 8, 2).astype(np.float32)
        net = _assert_forward_parity(m, str(tmp_path / "cnn.h5"), x)
        assert len(net.conf.layers) == 4  # flatten absorbed as preprocessor

    def test_mlp_with_bn_dropout_parity(self, keras, tmp_path):
        from keras import layers

        m = keras.Sequential([
            keras.Input((6,)),
            layers.Dense(10, activation="relu"),
            layers.BatchNormalization(),
            layers.Dropout(0.5),
            layers.Dense(3, activation="softmax"),
        ])
        # give BN non-trivial moving stats
        m.compile(loss="categorical_crossentropy", optimizer="sgd")
        rs = np.random.RandomState(1)
        m.fit(rs.randn(64, 6) * 3 + 1,
              np.eye(3)[rs.randint(0, 3, 64)], epochs=1, verbose=0)
        x = rs.randn(4, 6).astype(np.float32)
        _assert_forward_parity(m, str(tmp_path / "mlp.h5"), x)

    def test_lstm_parity(self, keras, tmp_path):
        from keras import layers

        m = keras.Sequential([
            keras.Input((7, 5)),
            layers.LSTM(6, activation="tanh",
                        recurrent_activation="sigmoid",
                        return_sequences=True),
            layers.Dense(3, activation="softmax"),
        ])
        x = np.random.RandomState(2).randn(2, 7, 5).astype(np.float32)
        _assert_forward_parity(m, str(tmp_path / "lstm.h5"), x)

    def test_global_pooling_parity(self, keras, tmp_path):
        from keras import layers

        m = keras.Sequential([
            keras.Input((6, 6, 3)),
            layers.Conv2D(8, (3, 3), padding="same", activation="relu"),
            layers.GlobalAveragePooling2D(),
            layers.Dense(4, activation="softmax"),
        ])
        x = np.random.RandomState(3).randn(2, 6, 6, 3).astype(np.float32)
        _assert_forward_parity(m, str(tmp_path / "gap.h5"), x)


class TestKeras1StyleImport:
    """Hand-written Keras-1-format h5 (th ordering, nb_filter/nb_row
    spellings) — the reference's primary target format
    (KerasModel.java:419-598)."""

    def _write_k1_fixture(self, path):
        rs = np.random.RandomState(4)
        cin, cout, h, w = 2, 3, 6, 6
        kernel_th = rs.randn(cout, cin, 3, 3).astype(np.float32) * 0.3
        conv_b = rs.randn(cout).astype(np.float32) * 0.1
        dense_W = rs.randn(cout * 3 * 3, 4).astype(np.float32) * 0.3
        dense_b = rs.randn(4).astype(np.float32) * 0.1
        config = {
            "class_name": "Sequential",
            "config": [
                {"class_name": "Convolution2D", "config": {
                    "name": "conv1", "nb_filter": cout, "nb_row": 3,
                    "nb_col": 3, "subsample": [1, 1],
                    "border_mode": "same", "activation": "relu",
                    "dim_ordering": "th",
                    "batch_input_shape": [None, cin, h, w]}},
                {"class_name": "MaxPooling2D", "config": {
                    "name": "pool1", "pool_size": [2, 2],
                    "strides": [2, 2], "border_mode": "valid",
                    "dim_ordering": "th"}},
                {"class_name": "Flatten", "config": {"name": "flat"}},
                {"class_name": "Dense", "config": {
                    "name": "dense1", "output_dim": 4,
                    "activation": "softmax"}},
            ],
        }
        with h5py.File(path, "w") as f:
            f.attrs["model_config"] = json.dumps(config)
            mw = f.create_group("model_weights")
            g = mw.create_group("conv1")
            g.attrs["weight_names"] = [b"conv1_W", b"conv1_b"]
            g.create_dataset("conv1_W", data=kernel_th)
            g.create_dataset("conv1_b", data=conv_b)
            mw.create_group("pool1").attrs["weight_names"] = []
            mw.create_group("flat").attrs["weight_names"] = []
            g2 = mw.create_group("dense1")
            g2.attrs["weight_names"] = [b"dense1_W", b"dense1_b"]
            g2.create_dataset("dense1_W", data=dense_W)
            g2.create_dataset("dense1_b", data=dense_b)
        return kernel_th, conv_b, dense_W, dense_b, (cin, h, w)

    def test_th_model_imports_and_matches_manual_forward(self, tmp_path):
        from deeplearning4j_tpu.modelimport import \
            import_keras_sequential_model_and_weights

        path = str(tmp_path / "k1.h5")
        kernel_th, conv_b, dense_W, dense_b, (cin, h, w) = \
            self._write_k1_fixture(path)
        net = import_keras_sequential_model_and_weights(path)
        rs = np.random.RandomState(5)
        x_th = rs.randn(2, cin, h, w).astype(np.float32)  # keras th layout
        x_nhwc = np.transpose(x_th, (0, 2, 3, 1))

        # manual keras-1 th forward in numpy: true convolution, same padding
        from scipy.signal import convolve2d  # available via scipy
        B = x_th.shape[0]
        cout = kernel_th.shape[0]
        conv = np.zeros((B, cout, h, w), np.float32)
        for b in range(B):
            for o in range(cout):
                acc = np.zeros((h, w))
                for ci in range(cin):
                    acc += convolve2d(x_th[b, ci], kernel_th[o, ci],
                                      mode="same")
                conv[b, o] = acc + conv_b[o]
        conv = np.maximum(conv, 0)
        pooled = conv.reshape(B, cout, 3, 2, 3, 2).max(axis=(3, 5))
        flat = pooled.reshape(B, -1)  # (c, h, w) flatten order
        logits = flat @ dense_W + dense_b
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        expected = e / e.sum(axis=1, keepdims=True)

        got = np.asarray(net.output(x_nhwc))
        np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-3)

    def test_unsupported_layer_raises(self, tmp_path):
        from deeplearning4j_tpu.modelimport import \
            import_keras_sequential_model_and_weights

        path = str(tmp_path / "bad.h5")
        config = {"class_name": "Sequential", "config": [
            {"class_name": "Lambda", "config": {
                "name": "l", "batch_input_shape": [None, 4]}}]}
        with h5py.File(path, "w") as f:
            f.attrs["model_config"] = json.dumps(config)
        with pytest.raises(ValueError, match="Unsupported Keras layer"):
            import_keras_sequential_model_and_weights(path)


class TestFunctionalBranchedImport:
    """Branched functional-API DAGs -> ComputationGraph (reference:
    KerasModel.java:419-495 GraphBuilder construction, layers/KerasMerge.java
    merge-vertex mapping). Forward parity against keras.predict, plus the
    legacy [[name, node, tensor]] inbound format hand-written."""

    def _residual_model(self, keras):
        from keras import layers

        inp = keras.Input((8, 8, 3), name="in0")
        x = layers.Conv2D(4, (3, 3), padding="same", activation="relu",
                          name="c1")(inp)
        y = layers.Conv2D(4, (3, 3), padding="same", name="c2")(x)
        z = layers.Add(name="add1")([x, y])
        z = layers.Activation("relu", name="act1")(z)
        w = layers.Conv2D(2, (1, 1), padding="same", name="c3")(z)
        cat = layers.Concatenate(name="cat1")([z, w])
        f = layers.Flatten(name="fl")(cat)
        out = layers.Dense(5, activation="softmax", name="d1")(f)
        return keras.Model(inp, out)

    def test_residual_add_concat_parity(self, keras, tmp_path):
        from deeplearning4j_tpu.modelimport import \
            import_keras_model_and_weights
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        m = self._residual_model(keras)
        path = str(tmp_path / "residual.h5")
        m.save(path)
        net = import_keras_model_and_weights(path)
        assert isinstance(net, ComputationGraph)  # branched => graph
        x = np.random.RandomState(0).randn(3, 8, 8, 3).astype(np.float32)
        expected = np.asarray(m.predict(x, verbose=0))
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-3)

    def test_branched_bn_pool_parity(self, keras, tmp_path):
        from keras import layers

        from deeplearning4j_tpu.modelimport import \
            import_keras_model_and_weights

        inp = keras.Input((8, 8, 2), name="in0")
        a = layers.Conv2D(3, (3, 3), padding="same", name="ca")(inp)
        a = layers.BatchNormalization(name="bn_a")(a)
        b = layers.AveragePooling2D((1, 1), name="pb")(inp)
        b = layers.Conv2D(3, (1, 1), padding="same", name="cb")(b)
        s = layers.Average(name="avg")([a, b])
        s = layers.GlobalAveragePooling2D(name="gap")(s)
        out = layers.Dense(4, activation="softmax", name="d1")(s)
        m = keras.Model(inp, out)
        # non-identity BN running stats so eval-mode parity is a real check
        m.get_layer("bn_a").set_weights([
            np.random.RandomState(1).rand(3).astype(np.float32) + 0.5,
            np.random.RandomState(2).randn(3).astype(np.float32) * 0.1,
            np.random.RandomState(3).randn(3).astype(np.float32) * 0.2,
            np.random.RandomState(4).rand(3).astype(np.float32) + 0.5,
        ])
        path = str(tmp_path / "bnbranch.h5")
        m.save(path)
        net = import_keras_model_and_weights(path)
        x = np.random.RandomState(5).randn(4, 8, 8, 2).astype(np.float32)
        expected = np.asarray(m.predict(x, verbose=0))
        got = np.asarray(net.output(x))
        np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-3)

    def test_two_input_model_parity(self, keras, tmp_path):
        from keras import layers

        from deeplearning4j_tpu.modelimport import \
            import_keras_model_and_weights

        in_a = keras.Input((6,), name="in_a")
        in_b = keras.Input((6,), name="in_b")
        ha = layers.Dense(5, activation="tanh", name="da")(in_a)
        hb = layers.Dense(5, activation="relu", name="db")(in_b)
        merged = layers.Concatenate(name="cat")([ha, hb])
        out = layers.Dense(3, activation="softmax", name="out")(merged)
        m = keras.Model([in_a, in_b], out)
        path = str(tmp_path / "twoin.h5")
        m.save(path)
        net = import_keras_model_and_weights(path)
        rs = np.random.RandomState(7)
        xa = rs.randn(3, 6).astype(np.float32)
        xb = rs.randn(3, 6).astype(np.float32)
        expected = np.asarray(m.predict([xa, xb], verbose=0))
        got = np.asarray(net.output(xa, xb))
        np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-3)

    def test_linear_functional_stays_sequential(self, keras, tmp_path):
        from keras import layers

        from deeplearning4j_tpu.modelimport import \
            import_keras_model_and_weights
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        inp = keras.Input((6,), name="in0")
        h = layers.Dense(8, activation="relu", name="h1")(inp)
        out = layers.Dense(3, activation="softmax", name="o1")(h)
        m = keras.Model(inp, out)
        path = str(tmp_path / "linear.h5")
        m.save(path)
        net = import_keras_model_and_weights(path)
        assert isinstance(net, MultiLayerNetwork)
        x = np.random.RandomState(1).randn(4, 6).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(net.output(x)),
            np.asarray(m.predict(x, verbose=0)), atol=1e-4, rtol=1e-3)

    def test_legacy_triple_inbound_format(self, tmp_path):
        """Keras-1/2 style inbound_nodes [[[name, node, tensor]]] with an
        Add branch, hand-written h5; forward checked against numpy."""
        from deeplearning4j_tpu.modelimport import \
            import_keras_model_and_weights

        rs = np.random.RandomState(11)
        W1 = rs.randn(4, 4).astype(np.float32) * 0.4
        b1 = rs.randn(4).astype(np.float32) * 0.1
        W2 = rs.randn(4, 3).astype(np.float32) * 0.4
        b2 = rs.randn(3).astype(np.float32) * 0.1
        config = {
            "class_name": "Model",
            "config": {
                "name": "m",
                "layers": [
                    {"class_name": "InputLayer", "name": "in0",
                     "config": {"name": "in0",
                                "batch_input_shape": [None, 4]},
                     "inbound_nodes": []},
                    {"class_name": "Dense", "name": "d1",
                     "config": {"name": "d1", "units": 4,
                                "activation": "tanh"},
                     "inbound_nodes": [[["in0", 0, 0]]]},
                    {"class_name": "Add", "name": "add",
                     "config": {"name": "add"},
                     "inbound_nodes": [[["in0", 0, 0], ["d1", 0, 0]]]},
                    {"class_name": "Dense", "name": "d2",
                     "config": {"name": "d2", "units": 3,
                                "activation": "softmax"},
                     "inbound_nodes": [[["add", 0, 0]]]},
                ],
                "input_layers": [["in0", 0, 0]],
                "output_layers": [["d2", 0, 0]],
            },
        }
        path = str(tmp_path / "legacy.h5")
        with h5py.File(path, "w") as f:
            f.attrs["model_config"] = json.dumps(config)
            mw = f.create_group("model_weights")
            g = mw.create_group("d1")
            g.attrs["weight_names"] = [b"d1_W", b"d1_b"]
            g.create_dataset("d1_W", data=W1)
            g.create_dataset("d1_b", data=b1)
            g2 = mw.create_group("d2")
            g2.attrs["weight_names"] = [b"d2_W", b"d2_b"]
            g2.create_dataset("d2_W", data=W2)
            g2.create_dataset("d2_b", data=b2)
        net = import_keras_model_and_weights(path)
        x = rs.randn(5, 4).astype(np.float32)
        h = np.tanh(x @ W1 + b1)
        logits = (x + h) @ W2 + b2
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        expected = e / e.sum(axis=1, keepdims=True)
        np.testing.assert_allclose(np.asarray(net.output(x)), expected,
                                   atol=1e-5, rtol=1e-4)

    def test_add_same_tensor_twice_imports_as_graph(self, tmp_path):
        """``Add()([x, x])`` — a merge fed the SAME tensor twice. Inbound
        counting must not dedup by name: two connections means branched
        topology (-> ComputationGraph), and the forward doubles x."""
        from deeplearning4j_tpu.modelimport import \
            import_keras_model_and_weights
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        rs = np.random.RandomState(17)
        W = rs.randn(4, 3).astype(np.float32) * 0.4
        b = rs.randn(3).astype(np.float32) * 0.1
        config = {
            "class_name": "Model",
            "config": {
                "name": "m",
                "layers": [
                    {"class_name": "InputLayer", "name": "in0",
                     "config": {"name": "in0",
                                "batch_input_shape": [None, 4]},
                     "inbound_nodes": []},
                    {"class_name": "Add", "name": "dbl",
                     "config": {"name": "dbl"},
                     "inbound_nodes": [[["in0", 0, 0], ["in0", 0, 0]]]},
                    {"class_name": "Dense", "name": "out",
                     "config": {"name": "out", "units": 3,
                                "activation": "softmax"},
                     "inbound_nodes": [[["dbl", 0, 0]]]},
                ],
                "input_layers": [["in0", 0, 0]],
                "output_layers": [["out", 0, 0]],
            },
        }
        path = str(tmp_path / "add_same.h5")
        with h5py.File(path, "w") as f:
            f.attrs["model_config"] = json.dumps(config)
            mw = f.create_group("model_weights")
            g = mw.create_group("out")
            g.attrs["weight_names"] = [b"out_W", b"out_b"]
            g.create_dataset("out_W", data=W)
            g.create_dataset("out_b", data=b)
        net = import_keras_model_and_weights(path)
        assert isinstance(net, ComputationGraph)
        x = rs.randn(5, 4).astype(np.float32)
        logits = (2.0 * x) @ W + b
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        expected = e / e.sum(axis=1, keepdims=True)
        np.testing.assert_allclose(np.asarray(net.output(x)), expected,
                                   atol=1e-5, rtol=1e-4)

    def test_shared_layer_rejected(self, tmp_path):
        from deeplearning4j_tpu.modelimport import \
            import_keras_model_and_weights

        config = {
            "class_name": "Model",
            "config": {
                "layers": [
                    {"class_name": "InputLayer", "name": "in0",
                     "config": {"name": "in0",
                                "batch_input_shape": [None, 4]},
                     "inbound_nodes": []},
                    {"class_name": "Dense", "name": "d1",
                     "config": {"name": "d1", "units": 4},
                     "inbound_nodes": [[["in0", 0, 0]], [["d2", 0, 0]]]},
                    {"class_name": "Dense", "name": "d2",
                     "config": {"name": "d2", "units": 4},
                     "inbound_nodes": [[["d1", 0, 0]]]},
                ],
                "input_layers": [["in0", 0, 0]],
                "output_layers": [["d1", 0, 0]],
            },
        }
        path = str(tmp_path / "shared.h5")
        with h5py.File(path, "w") as f:
            f.attrs["model_config"] = json.dumps(config)
        with pytest.raises(ValueError, match="shared"):
            import_keras_model_and_weights(path)

    def test_two_input_disjoint_chains_not_flattened(self, keras, tmp_path):
        """Two inputs with fully DISJOINT chains to two outputs — every
        layer is single-input and nothing fans out, so only the
        multi-InputLayer guard keeps this off the sequential path, which
        would silently mis-wire the chains into one stack."""
        from keras import layers

        from deeplearning4j_tpu.modelimport import \
            import_keras_model_and_weights
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        in_a = keras.Input((4,), name="ia")
        in_b = keras.Input((6,), name="ib")
        oa = layers.Dense(3, activation="softmax", name="oa")(in_a)
        ob = layers.Dense(2, activation="softmax", name="ob")(in_b)
        m = keras.Model([in_a, in_b], [oa, ob])
        path = str(tmp_path / "disjoint.h5")
        m.save(path)
        net = import_keras_model_and_weights(path)
        assert isinstance(net, ComputationGraph)
        rs = np.random.RandomState(3)
        xa = rs.randn(3, 4).astype(np.float32)
        xb = rs.randn(3, 6).astype(np.float32)
        got = net.output(xa, xb)
        exp = m.predict([xa, xb], verbose=0)
        assert len(got) == len(exp) == 2
        for g, e in zip(got, exp):
            np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                       atol=1e-4, rtol=1e-3)


    def test_single_input_multi_output_stays_functional(self, keras,
                                                        tmp_path):
        """One input, TWO outputs on a linear chain: must import as a
        two-output ComputationGraph, not a flattened stack that silently
        drops the intermediate output."""
        from keras import layers

        from deeplearning4j_tpu.modelimport import \
            import_keras_model_and_weights
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        inp = keras.Input((5,), name="in0")
        mid = layers.Dense(4, activation="softmax", name="mid")(inp)
        fin = layers.Dense(2, activation="softmax", name="fin")(mid)
        m = keras.Model(inp, [mid, fin])
        path = str(tmp_path / "multiout.h5")
        m.save(path)
        net = import_keras_model_and_weights(path)
        assert isinstance(net, ComputationGraph)
        x = np.random.RandomState(4).randn(3, 5).astype(np.float32)
        got = net.output(x)
        exp = m.predict(x, verbose=0)
        assert len(got) == len(exp) == 2
        for g, e in zip(got, exp):
            np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                       atol=1e-4, rtol=1e-3)
