"""Keras import tests (reference: modelimport test intent — import a fixture
h5 and compare forward outputs).

Two fixture paths:
- real Keras 3 legacy-h5 files (keras/tensorflow are in the image) — strict
  numerical parity of predict() vs our output()
- a hand-built Keras-1-style h5 (th dim ordering, Convolution2D spellings)
  written directly with h5py — exercises the K1 config/weight layout without
  needing Keras 1.
"""

import json

import numpy as np
import pytest

h5py = pytest.importorskip("h5py")


@pytest.fixture(scope="module")
def keras():
    return pytest.importorskip("keras")


def _assert_forward_parity(keras_model, path, x, atol=1e-4):
    from deeplearning4j_tpu.modelimport import \
        import_keras_sequential_model_and_weights

    keras_model.save(path)
    net = import_keras_sequential_model_and_weights(path)
    expected = np.asarray(keras_model.predict(x, verbose=0))
    got = np.asarray(net.output(x))
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, atol=atol, rtol=1e-3)
    return net


class TestKeras3Import:
    def test_cnn_sequential_parity(self, keras, tmp_path):
        from keras import layers

        m = keras.Sequential([
            keras.Input((8, 8, 2)),
            layers.Conv2D(4, (3, 3), padding="same", activation="relu"),
            layers.MaxPooling2D((2, 2)),
            layers.Conv2D(6, (3, 3), padding="valid", activation="tanh"),
            layers.Flatten(),
            layers.Dense(5, activation="softmax"),
        ])
        x = np.random.RandomState(0).randn(3, 8, 8, 2).astype(np.float32)
        net = _assert_forward_parity(m, str(tmp_path / "cnn.h5"), x)
        assert len(net.conf.layers) == 4  # flatten absorbed as preprocessor

    def test_mlp_with_bn_dropout_parity(self, keras, tmp_path):
        from keras import layers

        m = keras.Sequential([
            keras.Input((6,)),
            layers.Dense(10, activation="relu"),
            layers.BatchNormalization(),
            layers.Dropout(0.5),
            layers.Dense(3, activation="softmax"),
        ])
        # give BN non-trivial moving stats
        m.compile(loss="categorical_crossentropy", optimizer="sgd")
        rs = np.random.RandomState(1)
        m.fit(rs.randn(64, 6) * 3 + 1,
              np.eye(3)[rs.randint(0, 3, 64)], epochs=1, verbose=0)
        x = rs.randn(4, 6).astype(np.float32)
        _assert_forward_parity(m, str(tmp_path / "mlp.h5"), x)

    def test_lstm_parity(self, keras, tmp_path):
        from keras import layers

        m = keras.Sequential([
            keras.Input((7, 5)),
            layers.LSTM(6, activation="tanh",
                        recurrent_activation="sigmoid",
                        return_sequences=True),
            layers.Dense(3, activation="softmax"),
        ])
        x = np.random.RandomState(2).randn(2, 7, 5).astype(np.float32)
        _assert_forward_parity(m, str(tmp_path / "lstm.h5"), x)

    def test_global_pooling_parity(self, keras, tmp_path):
        from keras import layers

        m = keras.Sequential([
            keras.Input((6, 6, 3)),
            layers.Conv2D(8, (3, 3), padding="same", activation="relu"),
            layers.GlobalAveragePooling2D(),
            layers.Dense(4, activation="softmax"),
        ])
        x = np.random.RandomState(3).randn(2, 6, 6, 3).astype(np.float32)
        _assert_forward_parity(m, str(tmp_path / "gap.h5"), x)


class TestKeras1StyleImport:
    """Hand-written Keras-1-format h5 (th ordering, nb_filter/nb_row
    spellings) — the reference's primary target format
    (KerasModel.java:419-598)."""

    def _write_k1_fixture(self, path):
        rs = np.random.RandomState(4)
        cin, cout, h, w = 2, 3, 6, 6
        kernel_th = rs.randn(cout, cin, 3, 3).astype(np.float32) * 0.3
        conv_b = rs.randn(cout).astype(np.float32) * 0.1
        dense_W = rs.randn(cout * 3 * 3, 4).astype(np.float32) * 0.3
        dense_b = rs.randn(4).astype(np.float32) * 0.1
        config = {
            "class_name": "Sequential",
            "config": [
                {"class_name": "Convolution2D", "config": {
                    "name": "conv1", "nb_filter": cout, "nb_row": 3,
                    "nb_col": 3, "subsample": [1, 1],
                    "border_mode": "same", "activation": "relu",
                    "dim_ordering": "th",
                    "batch_input_shape": [None, cin, h, w]}},
                {"class_name": "MaxPooling2D", "config": {
                    "name": "pool1", "pool_size": [2, 2],
                    "strides": [2, 2], "border_mode": "valid",
                    "dim_ordering": "th"}},
                {"class_name": "Flatten", "config": {"name": "flat"}},
                {"class_name": "Dense", "config": {
                    "name": "dense1", "output_dim": 4,
                    "activation": "softmax"}},
            ],
        }
        with h5py.File(path, "w") as f:
            f.attrs["model_config"] = json.dumps(config)
            mw = f.create_group("model_weights")
            g = mw.create_group("conv1")
            g.attrs["weight_names"] = [b"conv1_W", b"conv1_b"]
            g.create_dataset("conv1_W", data=kernel_th)
            g.create_dataset("conv1_b", data=conv_b)
            mw.create_group("pool1").attrs["weight_names"] = []
            mw.create_group("flat").attrs["weight_names"] = []
            g2 = mw.create_group("dense1")
            g2.attrs["weight_names"] = [b"dense1_W", b"dense1_b"]
            g2.create_dataset("dense1_W", data=dense_W)
            g2.create_dataset("dense1_b", data=dense_b)
        return kernel_th, conv_b, dense_W, dense_b, (cin, h, w)

    def test_th_model_imports_and_matches_manual_forward(self, tmp_path):
        from deeplearning4j_tpu.modelimport import \
            import_keras_sequential_model_and_weights

        path = str(tmp_path / "k1.h5")
        kernel_th, conv_b, dense_W, dense_b, (cin, h, w) = \
            self._write_k1_fixture(path)
        net = import_keras_sequential_model_and_weights(path)
        rs = np.random.RandomState(5)
        x_th = rs.randn(2, cin, h, w).astype(np.float32)  # keras th layout
        x_nhwc = np.transpose(x_th, (0, 2, 3, 1))

        # manual keras-1 th forward in numpy: true convolution, same padding
        from scipy.signal import convolve2d  # available via scipy
        B = x_th.shape[0]
        cout = kernel_th.shape[0]
        conv = np.zeros((B, cout, h, w), np.float32)
        for b in range(B):
            for o in range(cout):
                acc = np.zeros((h, w))
                for ci in range(cin):
                    acc += convolve2d(x_th[b, ci], kernel_th[o, ci],
                                      mode="same")
                conv[b, o] = acc + conv_b[o]
        conv = np.maximum(conv, 0)
        pooled = conv.reshape(B, cout, 3, 2, 3, 2).max(axis=(3, 5))
        flat = pooled.reshape(B, -1)  # (c, h, w) flatten order
        logits = flat @ dense_W + dense_b
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        expected = e / e.sum(axis=1, keepdims=True)

        got = np.asarray(net.output(x_nhwc))
        np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-3)

    def test_unsupported_layer_raises(self, tmp_path):
        from deeplearning4j_tpu.modelimport import \
            import_keras_sequential_model_and_weights

        path = str(tmp_path / "bad.h5")
        config = {"class_name": "Sequential", "config": [
            {"class_name": "Lambda", "config": {
                "name": "l", "batch_input_shape": [None, 4]}}]}
        with h5py.File(path, "w") as f:
            f.attrs["model_config"] = json.dumps(config)
        with pytest.raises(ValueError, match="Unsupported Keras layer"):
            import_keras_sequential_model_and_weights(path)
