"""Cross-process broker streaming (deeplearning4j_tpu/streaming/ — the
dl4j-streaming Kafka/Camel analog: CamelKafkaRouteBuilder.java:16,
kafka/NDArrayPublisher.java, kafka/NDArrayConsumer.java).

The headline test is the reference's end-to-end contract: a producer in a
SEPARATE PROCESS publishes minibatches to a broker topic while this
process trains ``net.fit`` on the subscribed route."""

import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.streaming import (
    NDArrayConsumer,
    NDArrayPublisher,
    NDArrayRoute,
    StreamingBroker,
    StreamStalled,
    dataset_from_bytes,
    dataset_to_bytes,
)


def _net():
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers.core import (DenseLayer,
                                                        OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updater import Adam

    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(learning_rate=1e-2))
            .list(DenseLayer(n_out=8, activation="relu"),
                  OutputLayer(n_out=2, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


class TestSerde:
    def test_roundtrip_with_masks(self):
        rs = np.random.RandomState(0)
        ds = DataSet(rs.randn(3, 5, 7).astype(np.float32),
                     rs.randn(3, 5, 2).astype(np.float32),
                     features_mask=(rs.rand(3, 7) > 0.3).astype(np.float32),
                     labels_mask=(rs.rand(3, 7) > 0.3).astype(np.float32))
        back = dataset_from_bytes(dataset_to_bytes(ds))
        np.testing.assert_array_equal(back.features, ds.features)
        np.testing.assert_array_equal(back.labels, ds.labels)
        np.testing.assert_array_equal(back.features_mask, ds.features_mask)
        np.testing.assert_array_equal(back.labels_mask, ds.labels_mask)

    def test_roundtrip_without_masks(self):
        ds = DataSet(np.ones((2, 3), np.float32), np.eye(2, dtype=np.float32))
        back = dataset_from_bytes(dataset_to_bytes(ds))
        np.testing.assert_array_equal(back.features, ds.features)
        assert back.features_mask is None and back.labels_mask is None


class TestBrokerInProcess:
    def test_pub_sub_roundtrip(self):
        broker = StreamingBroker(port=0).start()
        try:
            with NDArrayConsumer("127.0.0.1", broker.port, "t1") as cons, \
                    NDArrayPublisher("127.0.0.1", broker.port, "t1") as pub:
                sent = [DataSet(np.full((2, 3), i, np.float32),
                                np.eye(2, dtype=np.float32))
                        for i in range(5)]
                for ds in sent:
                    pub.publish(ds)
                pub.end()
                got = list(cons)
            assert len(got) == 5
            for i, ds in enumerate(got):
                assert float(ds.features[0, 0]) == i
        finally:
            broker.stop()

    def test_fan_out_two_subscribers(self):
        """Every subscriber sees every frame (Kafka
        consumer-group-per-subscriber semantics)."""
        import threading

        broker = StreamingBroker(port=0).start()
        try:
            c1 = NDArrayConsumer("127.0.0.1", broker.port, "t2")
            c2 = NDArrayConsumer("127.0.0.1", broker.port, "t2")
            out1, out2 = [], []
            t1 = threading.Thread(target=lambda: out1.extend(c1))
            t2 = threading.Thread(target=lambda: out2.extend(c2))
            t1.start()
            t2.start()
            with NDArrayPublisher("127.0.0.1", broker.port, "t2") as pub:
                for i in range(4):
                    pub.publish_arrays(np.full((1, 2), i, np.float32),
                                       np.ones((1, 1), np.float32))
                pub.end()
            t1.join(10)
            t2.join(10)
            assert len(out1) == 4 and len(out2) == 4
        finally:
            broker.stop()

    def test_thread_registry_bounded_over_reconnect_cycles(self):
        """A long-lived broker serving many connect/disconnect cycles must
        not accumulate one dead Thread object per connection: the registry
        prunes finished threads, keeping O(live) entries after 50 cycles."""
        broker = StreamingBroker(port=0).start()
        try:
            for i in range(50):
                with NDArrayPublisher("127.0.0.1", broker.port,
                                      "tb") as pub:
                    pub.publish_arrays(np.full((1, 2), i, np.float32),
                                       np.ones((1, 1), np.float32))
            # pruning happens as threads are tracked, so the registry
            # holds the accept thread plus at most the last few
            # connections still winding down — never all 50
            assert len(broker._threads) < 10, len(broker._threads)
            assert any(t.name == "broker-accept" and t.is_alive()
                       for t in broker._threads)
        finally:
            broker.stop()

    def test_topics_are_isolated(self):
        broker = StreamingBroker(port=0).start()
        try:
            ca = NDArrayConsumer("127.0.0.1", broker.port, "a")
            with NDArrayPublisher("127.0.0.1", broker.port, "a") as pa, \
                    NDArrayPublisher("127.0.0.1", broker.port, "b") as pb:
                pb.publish_arrays(np.zeros((1, 1), np.float32),
                                  np.zeros((1, 1), np.float32))
                pb.end()
                pa.publish_arrays(np.ones((1, 1), np.float32),
                                  np.ones((1, 1), np.float32))
                pa.end()
            got = list(ca)
            assert len(got) == 1 and float(got[0].features[0, 0]) == 1.0
        finally:
            broker.stop()


_PRODUCER_SCRIPT = r"""
import sys
import numpy as np
from deeplearning4j_tpu.streaming import NDArrayPublisher

port, n_batches = int(sys.argv[1]), int(sys.argv[2])
rs = np.random.RandomState(3)
with NDArrayPublisher("127.0.0.1", port, "train") as pub:
    for i in range(n_batches):
        x = rs.randn(16, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rs.randint(0, 2, 16)]
        pub.publish_arrays(x, y)
    pub.end()
print("published", n_batches, flush=True)
"""


class TestCrossProcess:
    def test_fit_from_separate_producer_process(self, tmp_path):
        """The reference's end-to-end route: another PROCESS publishes
        NDArray minibatches to the broker while this process trains on
        the subscribed topic (CamelKafkaRouteBuilder semantics)."""
        n_batches = 12
        broker = StreamingBroker(port=0).start()
        try:
            route = NDArrayRoute("127.0.0.1", broker.port, "train")
            producer = subprocess.Popen(
                [sys.executable, "-c", _PRODUCER_SCRIPT,
                 str(broker.port), str(n_batches)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            net = _net()
            net.fit(route.iterator())  # trains WHILE the producer runs
            out, err = producer.communicate(timeout=60)
            assert producer.returncode == 0, err
            assert f"published {n_batches}" in out
            assert net.iteration == n_batches
            assert np.isfinite(net.score_value)
        finally:
            broker.stop()


@pytest.mark.serving
class TestSlowSubscriber:
    """A slow consumer no longer stalls the topic forever: past the
    publish-patience window its frames are dropped (counted in
    ``broker.stats()``) and after ``drop_limit`` consecutive drops it is
    evicted — while a healthy subscriber keeps seeing every frame."""

    def test_drops_are_counted_and_persistent_laggard_evicted(self):
        n_frames = 20
        broker = StreamingBroker(port=0, subscriber_buffer=2, drop_limit=3,
                                 publish_patience_s=0.05).start()
        try:
            # a subscriber that handshakes, then never reads another byte;
            # big frames fill its socket buffer fast, then its queue
            slow = NDArrayConsumer("127.0.0.1", broker.port, "lag")
            fast_out = []
            fast = NDArrayConsumer("127.0.0.1", broker.port, "lag")
            t = threading.Thread(target=lambda: fast_out.extend(fast))
            t.start()
            big = np.zeros((64, 1024), np.float32)  # ~256 KB per frame
            labels = np.ones((64, 1), np.float32)
            with NDArrayPublisher("127.0.0.1", broker.port, "lag") as pub:
                for _ in range(n_frames):
                    pub.publish_arrays(big, labels)
                pub.end()
            t.join(30)
            st = broker.stats()
            assert st["frames_dropped"] > 0
            assert st["dropped_by_topic"].get("lag", 0) \
                == st["frames_dropped"]
            assert st["subscribers_disconnected"] == 1
            # the healthy subscriber missed nothing
            assert len(fast_out) == n_frames
            slow.close()
        finally:
            broker.stop()

    def test_fast_subscribers_never_drop(self):
        broker = StreamingBroker(port=0, subscriber_buffer=2, drop_limit=3,
                                 publish_patience_s=0.05).start()
        try:
            out = []
            cons = NDArrayConsumer("127.0.0.1", broker.port, "ok")
            t = threading.Thread(target=lambda: out.extend(cons))
            t.start()
            with NDArrayPublisher("127.0.0.1", broker.port, "ok") as pub:
                for i in range(10):
                    pub.publish_arrays(np.full((1, 2), i, np.float32),
                                       np.ones((1, 1), np.float32))
                pub.end()
            t.join(10)
            assert len(out) == 10
            st = broker.stats()
            assert st["frames_dropped"] == 0
            assert st["subscribers_disconnected"] == 0
        finally:
            broker.stop()


@pytest.mark.serving
class TestIdleTimeout:
    def test_silent_topic_raises_stream_stalled(self):
        """A consumer with an idle budget fails typed instead of hanging
        forever on a topic nobody publishes to."""
        broker = StreamingBroker(port=0).start()
        try:
            with NDArrayConsumer("127.0.0.1", broker.port, "dead",
                                 idle_timeout_s=0.3) as cons:
                start = time.monotonic()
                with pytest.raises(StreamStalled, match="dead"):
                    list(cons)
                assert time.monotonic() - start < 5.0
        finally:
            broker.stop()

    def test_timely_frames_do_not_stall(self):
        """The timeout is per-frame idle time, not total stream time: a
        stream longer than the budget flows as long as gaps stay under."""
        broker = StreamingBroker(port=0).start()
        try:
            cons = NDArrayConsumer("127.0.0.1", broker.port, "live",
                                   idle_timeout_s=2.0)
            out = []
            t = threading.Thread(target=lambda: out.extend(cons))
            t.start()
            with NDArrayPublisher("127.0.0.1", broker.port, "live") as pub:
                for i in range(5):
                    pub.publish_arrays(np.full((1, 2), i, np.float32),
                                       np.ones((1, 1), np.float32))
                    time.sleep(0.05)
                pub.end()
            t.join(10)
            assert len(out) == 5
        finally:
            broker.stop()


class TestLargeFrames:
    def test_multi_megabyte_batch_roundtrip(self):
        """Image-sized batches (a ~12 MB frame) survive framing and npz
        serde intact — length-prefixed frames, not line-based."""
        rs = np.random.RandomState(0)
        big = rs.randn(16, 224, 224, 3).astype(np.float32)  # ~9.6 MB
        labels = np.eye(10, dtype=np.float32)[rs.randint(0, 10, 16)]
        broker = StreamingBroker(port=0).start()
        try:
            with NDArrayConsumer("127.0.0.1", broker.port, "img") as c, \
                    NDArrayPublisher("127.0.0.1", broker.port, "img") as p:
                p.publish(DataSet(big, labels))
                p.end()
                got = list(c)
            assert len(got) == 1
            np.testing.assert_array_equal(got[0].features, big)
            np.testing.assert_array_equal(got[0].labels, labels)
        finally:
            broker.stop()
