"""Accelerated-kernel (Pallas flash attention) vs stock-XLA parity.

Ports the reference's helper-vs-stock test pattern
(deeplearning4j-cuda/src/test/: cuDNN helper output must equal the pure
ND4J layer output) to the TPU build's one accelerated kernel: the
flash-attention forward (ops/pallas_attention.py) behind
SelfAttentionLayer's ``helper`` switch. On the CPU test mesh the kernel
runs in interpreter mode; the driver's TPU bench measures the speedup
(bench.py bench_attention).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.layers.attention import (
    SelfAttentionLayer,
    scaled_dot_attention,
)
from deeplearning4j_tpu.ops.pallas_attention import flash_attention, supports


def _qkv(B=2, H=3, T=256, d=64, seed=0):
    rs = np.random.RandomState(seed)
    return tuple(jnp.asarray(rs.randn(B, H, T, d), jnp.float32)
                 for _ in range(3))


class TestFlashAttentionKernel:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_stock(self, causal):
        q, k, v = _qkv()
        ref = scaled_dot_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=128,
                              block_k=128)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_stock(self, causal):
        q, k, v = _qkv(T=128, d=32)

        def loss_ref(q, k, v):
            return jnp.sum(scaled_dot_attention(q, k, v, causal=causal) ** 2)

        def loss_new(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_new = jax.grad(loss_new, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_new):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_key_mask_matches_stock(self, causal):
        """[B, T] key-mask parity, forward (round-5 mask support)."""
        q, k, v = _qkv(T=256)
        rs = np.random.RandomState(9)
        mask = jnp.asarray(rs.rand(2, 256) > 0.3, jnp.float32)
        # every row keeps at least its first key valid so the softmax
        # row is well-defined in both implementations
        mask = mask.at[:, 0].set(1.0)
        ref = scaled_dot_attention(q, k, v, causal=causal, mask=mask)
        out = flash_attention(q, k, v, causal=causal, mask=mask,
                              block_q=128, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_key_mask_gradients_match_stock(self, causal):
        q, k, v = _qkv(T=128, d=32)
        mask = jnp.ones((2, 128), jnp.float32).at[0, 96:].set(0.0) \
            .at[1, 64:].set(0.0)

        def loss_ref(q, k, v):
            return jnp.sum(scaled_dot_attention(
                q, k, v, causal=causal, mask=mask) ** 2)

        def loss_new(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=causal, mask=mask, block_q=64,
                block_k=64) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_new = jax.grad(loss_new, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_new):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_nonzero_is_valid_mask_semantics(self):
        """Stock treats mask.astype(bool): ANY nonzero value is a valid
        key. The kernel must match — negative validity markers included."""
        q, k, v = _qkv(T=128, d=32)
        mask = jnp.where(jnp.asarray(
            np.random.RandomState(4).rand(2, 128) > 0.4), -1.0, 0.0) \
            .at[:, 0].set(-1.0)
        ref = scaled_dot_attention(q, k, v, mask=mask)
        out = flash_attention(q, k, v, mask=mask, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_wrong_mask_shape_raises(self):
        """A transposed / wrong-sized mask must fail loudly, not be
        silently reshaped into wrong attention."""
        q, k, v = _qkv(T=128, d=32)
        with pytest.raises(ValueError, match="key mask shape"):
            flash_attention(q, k, v, mask=jnp.ones((128, 2)))
        with pytest.raises(ValueError, match="key mask shape"):
            flash_attention(q, k, v, mask=jnp.ones((2, 64)))

    def test_uneven_q_k_blocks_causal(self):
        # block_q != block_k exercises the diagonal-block arithmetic
        q, k, v = _qkv(T=256)
        ref = scaled_dot_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_blocks_clamp_to_short_sequences(self):
        q, k, v = _qkv(T=64)
        ref = scaled_dot_attention(q, k, v)
        out = flash_attention(q, k, v)  # default blocks 512 -> clamped
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_supports_gating(self):
        ok = dict(mask=None, backend="tpu")
        assert supports((2, 3, 256, 64), **ok)
        assert supports((2, 3, 250, 64), **ok)  # clamps to one block
        # larger than a block but not divisible -> stock fallback
        assert not supports((2, 3, 600, 64), **ok)
        # [B, T] key masks route to the kernel since round 5; any other
        # mask shape still falls back to stock
        assert supports((2, 3, 256, 64), mask=np.ones((2, 256)),
                        backend="tpu")
        assert not supports((2, 3, 256, 64), mask=np.ones((2, 3, 256)),
                            backend="tpu")
        assert not supports((2, 3, 256, 64), mask=np.ones((2, 128)),
                            backend="tpu")
        # f32-accumulating kernel must decline float64 networks, but
        # narrower dtypes only gain precision through it
        assert not supports((2, 3, 256, 64), dtype=jnp.float64, **ok)
        assert supports((2, 3, 256, 64), dtype=jnp.bfloat16, **ok)
        # off-TPU the kernel would run in interpret mode: decline
        assert not supports((2, 3, 256, 64), mask=None, backend="cpu")
        # full K/V live in VMEM per program: decline past the ceiling
        # (empirical on v5e: 4096x128 compiles, 8192x128 does not)
        assert supports((2, 3, 4096, 128), **ok)
        assert supports((2, 3, 8192, 64), **ok)
        assert not supports((2, 3, 8192, 128), **ok)
        assert not supports((2, 3, 16384, 128), **ok)


class TestSelfAttentionHelperSwitch:
    def _layer(self, helper, causal=False):
        lyr = SelfAttentionLayer(n_in=32, n_out=32, n_heads=4,
                                 causal=causal, helper=helper,
                                 bias_init=0.0)
        params = lyr.init_params(jax.random.PRNGKey(0))
        return lyr, params

    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_equals_stock(self, causal):
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(2, 128, 32), jnp.float32)
        l_stock, p = self._layer("stock", causal)
        l_pallas, _ = self._layer("pallas", causal)
        out_s, _ = l_stock.forward(p, {}, x)
        out_p, _ = l_pallas.forward(p, {}, x)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_s),
                                   atol=1e-5, rtol=1e-5)

    def test_auto_falls_back_on_mask(self):
        rs = np.random.RandomState(2)
        x = jnp.asarray(rs.randn(2, 64, 32), jnp.float32)
        mask = jnp.ones((2, 64), jnp.float32).at[:, 40:].set(0.0)
        l_auto, p = self._layer("auto")
        l_stock, _ = self._layer("stock")
        out_a, _ = l_auto.forward(p, {}, x, mask=mask)
        out_s, _ = l_stock.forward(p, {}, x, mask=mask)
        np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_s),
                                   atol=1e-6)

    def test_pallas_with_mask_equals_stock(self):
        """Round 5: masked workloads route through the kernel — the layer
        output must equal the stock path's, masked rows included."""
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.randn(2, 128, 32), jnp.float32)
        mask = jnp.ones((2, 128), jnp.float32).at[0, 100:].set(0.0) \
            .at[1, 64:].set(0.0)
        l_pallas, p = self._layer("pallas")
        l_stock, _ = self._layer("stock")
        out_p, _ = l_pallas.forward(p, {}, x, mask=mask)
        out_s, _ = l_stock.forward(p, {}, x, mask=mask)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_s),
                                   atol=1e-5, rtol=1e-5)
