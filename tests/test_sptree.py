"""SPTree / QuadTree tests (ports the intent of SPTreeTest / QuadTreeTest
in deeplearning4j-core: construction correctness, counts, BH force
approximation vs exact)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering.sptree import QuadTree, SPTree


def _exact_forces(y, i):
    """Exact t-SNE repulsion terms for point i (the theta=0 ground truth)."""
    diff = y[i] - y
    d2 = (diff ** 2).sum(axis=1)
    q = 1.0 / (1.0 + d2)
    q[i] = 0.0
    neg = (q[:, None] ** 2 * diff).sum(axis=0)
    return neg, q.sum()


class TestConstruction:
    @pytest.mark.parametrize("d", [2, 3, 4])
    def test_all_points_counted_and_contained(self, d):
        rs = np.random.RandomState(0)
        x = rs.randn(200, d)
        t = SPTree(x)
        assert t.cum_size == 200
        assert t.is_correct()
        assert t.depth() >= 2

    def test_duplicates_terminate(self):
        x = np.vstack([np.ones((50, 2)), np.zeros((3, 2))])
        t = SPTree(x)
        assert t.cum_size == 53  # stacked duplicates still counted

    def test_quadtree_requires_2d(self):
        with pytest.raises(ValueError):
            QuadTree(np.zeros((5, 3)))
        assert QuadTree(np.random.RandomState(1).randn(20, 2)).cum_size == 20


class TestForces:
    def test_theta_zero_matches_exact(self):
        rs = np.random.RandomState(2)
        y = rs.randn(120, 2)
        t = QuadTree(y)
        for i in (0, 17, 119):
            neg, sq = t.compute_non_edge_forces(i, theta=0.0)
            neg_e, sq_e = _exact_forces(y, i)
            assert np.allclose(neg, neg_e, atol=1e-9)
            assert sq == pytest.approx(sq_e, abs=1e-9)

    @pytest.mark.parametrize("d", [2, 3])
    def test_bh_approximates_exact(self, d):
        rs = np.random.RandomState(3)
        y = rs.randn(400, d) * 3
        t = SPTree(y)
        rel_errs = []
        for i in range(0, 400, 37):
            neg, sq = t.compute_non_edge_forces(i, theta=0.5)
            neg_e, sq_e = _exact_forces(y, i)
            rel_errs.append(abs(sq - sq_e) / sq_e)
        assert np.mean(rel_errs) < 0.03  # BH-quality approximation

    def test_duplicate_leaf_excludes_self_only(self):
        y = np.vstack([np.zeros((4, 2)), np.array([[3.0, 3.0]])])
        t = QuadTree(y)
        far_q = 1.0 / (1.0 + 18.0)
        # EVERY coincident point must exclude exactly itself — not just
        # the one whose index the stacked leaf happens to store
        for i in range(4):
            neg, sq = t.compute_non_edge_forces(i, theta=0.0)
            assert sq == pytest.approx(3.0 + far_q, abs=1e-9), i
