"""Barnes-Hut t-SNE: ladder-vs-exact force parity and end-to-end embedding.

Parity target: plot/BarnesHutTsne.java:65 + clustering/sptree/SpTree.java
(computeNonEdgeForces / computeEdgeForces). The grid-ladder repulsion must
match the exact O(N^2) forces to BH-class accuracy, and the full pipeline
must separate clusters like the exact implementation does.
"""

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne
from deeplearning4j_tpu.plot.barnes_hut import (
    _bh_repulsion,
    _knn,
    _ladder_config,
    _perplexity_search,
    build_sparse_p,
)


class TestLadderRepulsion:
    def _exact(self, yn):
        d2 = ((yn[:, None, :] - yn[None, :, :]) ** 2).sum(-1)
        num = 1.0 / (1.0 + d2)
        np.fill_diagonal(num, 0.0)
        rep = ((num ** 2)[..., None]
               * (yn[:, None, :] - yn[None, :, :])).sum(1)
        return rep, num.sum(1)

    def test_matches_exact_forces(self):
        rs = np.random.RandomState(0)
        y = jnp.asarray(rs.randn(800, 2) * 5, jnp.float32)
        R, l0, L = _ladder_config(800, 0.5)
        rep, z = _bh_repulsion(y, R=R, l0=l0, L=L)
        rep_ex, z_ex = self._exact(np.asarray(y))
        # Z within ~2%, forces within ~5% of the mean force magnitude —
        # the BH accuracy class at theta=0.5
        np.testing.assert_allclose(np.asarray(z), z_ex, rtol=0.02)
        fmag = np.linalg.norm(rep_ex, axis=1).mean()
        err = np.linalg.norm(np.asarray(rep) - rep_ex, axis=1) / fmag
        assert err.mean() < 0.05, err.mean()

    def test_smaller_theta_is_more_accurate(self):
        rs = np.random.RandomState(1)
        y = jnp.asarray(rs.randn(600, 2) * 3, jnp.float32)
        rep_ex, z_ex = self._exact(np.asarray(y))

        def mean_err(theta):
            R, l0, L = _ladder_config(600, theta)
            rep, _ = _bh_repulsion(y, R=R, l0=l0, L=L)
            fmag = np.linalg.norm(rep_ex, axis=1).mean()
            return (np.linalg.norm(np.asarray(rep) - rep_ex, axis=1)
                    / fmag).mean()

        assert mean_err(0.3) <= mean_err(1.0) + 1e-6


class TestSparseP:
    def test_knn_finds_true_neighbors(self):
        rs = np.random.RandomState(2)
        x = rs.randn(200, 5).astype(np.float32)
        idx, d2 = _knn(x, 10)
        d_full = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        np.fill_diagonal(d_full, np.inf)
        expect = np.sort(d_full, axis=1)[:, :10]
        np.testing.assert_allclose(np.sort(d2, axis=1), expect, rtol=1e-4,
                                   atol=1e-4)

    def test_perplexity_entropy_hits_target(self):
        rs = np.random.RandomState(3)
        d2 = np.abs(rs.randn(50, 30)) * 3
        p = _perplexity_search(d2, 10.0)
        h = -np.sum(p * np.log(np.maximum(p, 1e-12)), axis=1)
        np.testing.assert_allclose(np.exp(h), 10.0, rtol=0.05)

    def test_edges_sum_to_one_and_symmetric(self):
        rs = np.random.RandomState(4)
        x = rs.randn(120, 8).astype(np.float32)
        ei, ej, ep = build_sparse_p(x, 15.0)
        np.testing.assert_allclose(ep.sum(), 1.0, rtol=1e-6)
        dense = np.zeros((120, 120))
        np.add.at(dense, (ei, ej), ep)
        np.testing.assert_allclose(dense, dense.T, atol=1e-9)


class TestEndToEnd:
    def test_bh_separates_clusters(self):
        rs = np.random.RandomState(5)
        a = rs.randn(150, 10) * 0.3
        b = rs.randn(150, 10) * 0.3 + 5.0
        x = np.concatenate([a, b])
        tsne = BarnesHutTsne(perplexity=15, theta=0.5, max_iter=300,
                             learning_rate=100.0, seed=0)
        y = tsne.fit(x)
        assert y.shape == (300, 2)
        assert np.isfinite(tsne.kl)
        ca, cb = y[:150].mean(0), y[150:].mean(0)
        intra = max(np.linalg.norm(y[:150] - ca, axis=1).mean(),
                    np.linalg.norm(y[150:] - cb, axis=1).mean())
        assert np.linalg.norm(ca - cb) > 2 * intra

    def test_bh_embedding_close_to_exact_quality(self):
        """Same data through exact Tsne and BH: both must reach comparable
        sparse-KL / separation — BH is an approximation of the same
        objective, not a different algorithm."""
        rs = np.random.RandomState(6)
        a = rs.randn(100, 6) * 0.4
        b = rs.randn(100, 6) * 0.4 + 4.0
        x = np.concatenate([a, b])
        kw = dict(perplexity=12, max_iter=250, learning_rate=100.0, seed=0)
        y_bh = BarnesHutTsne(theta=0.5, **kw).fit(x)

        def sep(y):
            ca, cb = y[:100].mean(0), y[100:].mean(0)
            intra = max(np.linalg.norm(y[:100] - ca, axis=1).mean(),
                        np.linalg.norm(y[100:] - cb, axis=1).mean())
            return np.linalg.norm(ca - cb) / intra

        y_ex = Tsne(num_dimension=2, **kw).fit(x)
        assert sep(y_bh) > 2.0
        assert sep(y_ex) > 2.0
