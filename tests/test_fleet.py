"""ReplicaFleet serving tests (parallel/fleet.py).

Covers the fleet contract end to end on the CPU mesh: health-weighted
routing over N replicas, typed load shedding at submit
(ReplicaUnavailable / CircuitOpen / ServerOverloaded), failover
re-dispatch with bit-exact deterministic regeneration (the fold_in key
schedule makes a re-dispatched generation identical on any replica),
supervised restart with backoff after replica death, request hedging
(first-result-wins, loser cancelled), the replica-targeted ChaosPolicy
fault modes, the KerasBackendServer fleet wiring, and the headline chaos
soak: 200 mixed greedy+sampled requests at ~10% injected replica faults
including a mid-generation kill — zero lost futures, every completion
bit-exact vs serial.
"""

import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo import (TransformerLM, greedy_generate,
                                           sample_generate)
from deeplearning4j_tpu.parallel.fleet import (DEAD, READY, RETIRED,
                                               ReplicaFleet)
from deeplearning4j_tpu.parallel.generation import GenerationServer
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.resilience import (ChaosPolicy,
                                                    CircuitOpen,
                                                    DeadlineExceeded,
                                                    ReplicaKilled,
                                                    ReplicaUnavailable,
                                                    ResilienceError,
                                                    ServerOverloaded,
                                                    TransientDispatchError)

V = 17


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(num_labels=V, max_length=16, d_model=16,
                         n_heads=2, n_blocks=1, seed=3).init()


def _gen_factory(lm, **chaos_kw):
    """Factory of GenerationServer replicas; chaos_kw seeds each replica's
    own deterministic fault injector (seed derived from the rid)."""
    def factory(rid):
        chaos = (ChaosPolicy(seed=1000 + rid, **chaos_kw)
                 if chaos_kw else None)
        return GenerationServer(lm, V, slots=4, chaos=chaos)
    return factory


@contextmanager
def fleet_of(factory, replicas=2, **kw):
    fl = ReplicaFleet(factory, replicas=replicas, **kw)
    try:
        yield fl
    finally:
        fl.close()


def _mixed_specs(n, rng):
    """n mixed greedy+sampled request specs over three prompt shapes (so
    the serial references compile a bounded program set)."""
    shapes = [(3, 4), (5, 5), (4, 6)]
    specs = []
    for i in range(n):
        plen, steps = shapes[i % len(shapes)]
        p = rng.integers(1, V, size=plen).astype(np.int64)
        if i % 2 == 0:
            specs.append((p, steps, 0.0, 0, 0))
        else:
            specs.append((p, steps, 0.9, 5, 2000 + i))
    return specs


def _serial_refs(lm, specs):
    refs = []
    for p, steps, temp, top_k, seed in specs:
        if temp == 0.0:
            refs.append(greedy_generate(lm, p[None], steps, V)[0])
        else:
            refs.append(sample_generate(lm, p[None], steps, V,
                                        temperature=temp, top_k=top_k,
                                        seed=seed)[0])
    return refs


def _submit_with_backoff(fleet, spec, deadline_s=240.0, budget_s=60.0):
    """Client-side 429/503 handling: typed shed at submit means back off
    and resubmit, exactly what an HTTP client does with Retry-After."""
    p, steps, temp, top_k, seed = spec
    t_end = time.monotonic() + budget_s
    while True:
        try:
            return fleet.submit(p, steps, temperature=temp, top_k=top_k,
                                seed=seed, deadline_s=deadline_s)
        except ResilienceError:
            if time.monotonic() > t_end:
                raise
            time.sleep(0.02)


@pytest.mark.fleet
class TestChaosPolicyReplicaModes:
    def test_modes_deterministic_and_exclusive(self):
        """Same seed -> same injected fault sequence; at most one
        replica-targeted fault per call."""
        def run():
            sleeps = []
            ch = ChaosPolicy(seed=7, kill_rate=0.1, stall_rate=0.2,
                             stall_s=0.5, slow_rate=0.2, slow_factor=3.0,
                             sleep=sleeps.append)
            fn = ch.wrap(lambda: "ok")
            outcomes = []
            for _ in range(200):
                try:
                    outcomes.append(fn() is not None)
                except ReplicaKilled:
                    outcomes.append("killed")
            return outcomes, sleeps, ch

        o1, s1, c1 = run()
        o2, s2, c2 = run()
        assert o1 == o2                       # same fault sequence
        assert len(s1) == len(s2)             # same injection points
        # stall sleeps are the fixed duration; slow-mode pads scale with
        # the measured run time and are timing-dependent by design
        assert [v for v in s1 if v == 0.5] == [v for v in s2 if v == 0.5]
        assert c1.injected_kill == c2.injected_kill > 0
        assert c1.injected_stall == c2.injected_stall > 0
        assert c1.injected_slow == c2.injected_slow > 0
        assert (c1.injected_kill + c1.injected_stall + c1.injected_slow
                <= 200)

    def test_legacy_sequences_unchanged(self):
        """With the replica rates at zero, the rng draw sequence is the
        pre-extension one: same seed reproduces the same transient/hard
        pattern as before the replica modes existed."""
        def pattern(**kw):
            ch = ChaosPolicy(seed=11, transient_rate=0.3, hard_rate=0.1,
                             **kw)
            fn = ch.wrap(lambda: 0)
            out = []
            for _ in range(100):
                try:
                    fn()
                    out.append("ok")
                except TransientDispatchError:
                    out.append("t")
                except RuntimeError:
                    out.append("h")
            return out

        assert pattern() == pattern(kill_rate=0.0, stall_rate=0.0,
                                    slow_rate=0.0)

    def test_slow_mode_runs_fn_then_pads(self):
        calls = []
        sleeps = []
        ch = ChaosPolicy(seed=0, slow_rate=1.0, slow_factor=4.0,
                         sleep=sleeps.append)
        fn = ch.wrap(lambda: calls.append(1) or 42)
        assert fn() == 42
        assert calls == [1]          # slow mode still runs the dispatch
        assert len(sleeps) == 1      # ... then pads it out
        assert ch.injected_slow == 1


@pytest.mark.fleet
class TestFleetRouting:
    def test_routes_spread_and_results_bitexact(self, lm):
        rng = np.random.default_rng(5)
        specs = _mixed_specs(12, rng)
        refs = _serial_refs(lm, specs)
        with fleet_of(_gen_factory(lm), replicas=2) as fl:
            futs = [fl.submit(p, s, temperature=t, top_k=k, seed=sd,
                              deadline_s=120.0)
                    for p, s, t, k, sd in specs]
            outs = [f.result(timeout=180) for f in futs]
            st = fl.stats()
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(np.asarray(got), ref)
        assert st["completed"] == len(specs)
        assert st["failed"] == 0 and st["expired"] == 0
        # both replicas took traffic (least-loaded routing spreads a burst)
        assert all(r["dispatched"] > 0 for r in st["replicas"])

    def test_sick_replica_sheds_into_healthy_one(self, lm):
        """A replica that fails every dispatch trips its breaker; traffic
        re-dispatches to the survivor and every completion stays correct."""
        def factory(rid):
            chaos = (ChaosPolicy(seed=9, hard_rate=1.0) if rid == 0
                     else None)
            return GenerationServer(lm, V, slots=4, chaos=chaos)

        rng = np.random.default_rng(6)
        specs = _mixed_specs(8, rng)
        refs = _serial_refs(lm, specs)
        with fleet_of(factory, replicas=2) as fl:
            futs = [_submit_with_backoff(fl, sp) for sp in specs]
            outs = [f.result(timeout=180) for f in futs]
            st = fl.stats()
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(np.asarray(got), ref)
        sick = st["replicas"][0]
        assert sick["failed"] > 0
        assert st["redispatched"] > 0
        assert st["completed"] == len(specs)

    def test_submit_sheds_typed_when_everything_is_down(self, lm):
        with fleet_of(_gen_factory(lm), replicas=2, restart=False) as fl:
            fl.kill_replica(0)
            fl.kill_replica(1)
            deadline = time.monotonic() + 30.0
            with pytest.raises(ReplicaUnavailable):
                while time.monotonic() < deadline:
                    # the kill is async (monitor closes the corpse): poll
                    # until both replicas report dead, then submit
                    st = fl.stats()
                    if all(r["state"] != READY for r in st["replicas"]):
                        fl.submit(np.array([1, 2], np.int64), 2)
                        break
                    time.sleep(0.01)

    def test_validation_error_propagates_sync(self, lm):
        with fleet_of(_gen_factory(lm), replicas=2) as fl:
            with pytest.raises(ValueError):
                fl.submit(np.array([1, 2], np.int64), 2, deadline_s=-1.0)
            with pytest.raises(ValueError):
                # empty prompt: server-side caller-error validation
                fl.submit(np.array([], np.int64), 2)
            with pytest.raises(ServerOverloaded):
                # infeasible page budget rejects typed on every replica
                fl.submit(np.array([1, 2], np.int64), 10_000)
            st = fl.stats()
        assert st["inflight"] == 0 and fl.admission.pending == 0
        # sync rejections (caller error + typed shed) never count as
        # failures — they land in rejected_submits
        assert st["rejected_submits"] == 2 and st["failed"] == 0


@pytest.mark.fleet
class TestFleetLifecycle:
    def test_kill_restarts_with_counters(self, lm):
        rng = np.random.default_rng(7)
        specs = _mixed_specs(10, rng)
        refs = _serial_refs(lm, specs)
        with fleet_of(_gen_factory(lm), replicas=2,
                      restart_backoff_s=0.02) as fl:
            futs = [fl.submit(p, s, temperature=t, top_k=k, seed=sd,
                              deadline_s=180.0)
                    for p, s, t, k, sd in specs]
            time.sleep(0.2)           # let generation get going
            assert fl.kill_replica(0)
            outs = [f.result(timeout=240) for f in futs]
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                st = fl.stats()
                if st["replicas"][0]["state"] == READY \
                        and st["replicas"][0]["restarts"] >= 1:
                    break
                time.sleep(0.02)
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(np.asarray(got), ref)
        assert st["deaths"] >= 1
        assert st["restarts"] >= 1
        assert st["replicas"][0]["restarts"] >= 1

    def test_retire_drains_for_good(self, lm):
        with fleet_of(_gen_factory(lm), replicas=2) as fl:
            assert fl.retire_replica(0)
            st = fl.stats()
            assert st["replicas"][0]["state"] == RETIRED
            # retired replicas never restart; the survivor still serves
            out = fl.submit(np.array([1, 2, 3], np.int64), 3).result(
                timeout=120)
            assert len(out) == 3
            st = fl.stats()
            assert st["replicas"][0]["state"] == RETIRED
            assert st["replicas"][1]["dispatched"] >= 1

    def test_close_never_leaves_hung_futures(self, lm):
        fl = ReplicaFleet(_gen_factory(lm), replicas=2)
        futs = [fl.submit(np.array([1, 2, 3], np.int64), 4)
                for _ in range(6)]
        fl.close(timeout=120.0)
        done = [f for f in futs if f.done()]
        assert len(done) == len(futs)       # zero lost futures at close
        fl.close()                          # idempotent

    def test_spawn_failure_backs_off_exponentially(self):
        calls = []

        class _Dud:
            def close(self, timeout=0.0):
                pass

            def submit(self, *a, **k):
                raise ReplicaKilled("dud replica")

            def drain(self, timeout=None):
                return True

            def stats(self):
                return {}

        def factory(rid):
            calls.append(time.monotonic())
            if len(calls) >= 4:
                return _Dud()
            if len(calls) > 1:
                raise RuntimeError("spawn flake")
            return _Dud()

        fl = ReplicaFleet(factory, replicas=1, restart_backoff_s=0.02,
                          restart_backoff_cap_s=0.08)
        try:
            fl.kill_replica(0)
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                st = fl.stats()
                if st["replicas"][0]["state"] == READY \
                        and st["replicas"][0]["restarts"] >= 1:
                    break
                time.sleep(0.01)
            st = fl.stats()
            assert st["replicas"][0]["spawn_failures"] >= 2
            assert st["replicas"][0]["restarts"] >= 1
        finally:
            fl.close()


@pytest.mark.fleet
class TestFleetHedging:
    def test_straggler_hedged_first_result_wins(self, lm):
        """Replica 0 stalls every dispatch; with hedging on, parked tail
        requests duplicate onto the healthy replica and finish fast."""
        def factory(rid):
            chaos = (ChaosPolicy(seed=3, stall_rate=1.0, stall_s=0.25)
                     if rid == 0 else None)
            return GenerationServer(lm, V, slots=4, chaos=chaos)

        rng = np.random.default_rng(8)
        specs = _mixed_specs(6, rng)
        refs = _serial_refs(lm, specs)
        with fleet_of(factory, replicas=2, hedge_after_s=0.15,
                      max_hedges=1) as fl:
            futs = [fl.submit(p, s, temperature=t, top_k=k, seed=sd,
                              deadline_s=180.0)
                    for p, s, t, k, sd in specs]
            outs = [f.result(timeout=240) for f in futs]
            st = fl.stats()
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(np.asarray(got), ref)
        assert st["completed"] == len(specs)
        # the stalled replica forced at least one hedge; the duplicate's
        # loser was cancelled, not leaked
        assert st["hedged"] >= 1
        assert st["losers_cancelled"] >= 1


@pytest.mark.fleet
class TestFleetOverParallelInference:
    def test_failover_and_bitexact_rows(self):
        from tests.test_inference_server import _features, _mln

        net = _mln()
        x = _features(24, seed=11)
        ref = np.asarray(net.output(x))

        def factory(rid):
            chaos = ChaosPolicy(seed=50 + rid, stall_rate=0.1,
                                stall_s=0.01)
            return ParallelInference(net, workers=8, max_batch=8,
                                     max_wait_ms=1.0, chaos=chaos)

        with fleet_of(factory, replicas=2, restart_backoff_s=0.02) as fl:
            futs = [fl.submit(x[i:i + 1], deadline_s=60.0)
                    for i in range(12)]
            fl.kill_replica(0)
            futs += [fl.submit(x[i:i + 1], deadline_s=60.0)
                     for i in range(12, 24)]
            outs = [np.asarray(f.result(timeout=120))[0] for f in futs]
            st = fl.stats()
        for i, row in enumerate(outs):
            np.testing.assert_allclose(row, ref[i], rtol=0, atol=0)
        assert st["completed"] == 24
        assert st["deaths"] >= 1


@pytest.mark.fleet
class TestKerasBackendServerFleet:
    def test_generate_predict_and_stats_through_fleet(self, lm):
        import json
        from urllib.request import Request, urlopen

        from tests.test_inference_server import _features, _mln
        from deeplearning4j_tpu.modelimport.server import KerasBackendServer

        net = _mln()
        x = _features(4, seed=12)
        ref = np.asarray(net.output(x))
        gref = greedy_generate(lm, np.array([[1, 2, 3]], np.int64), 4, V)[0]

        srv = KerasBackendServer()
        try:
            gmid = srv.attach_generation(lm, vocab=V, slots=4, replicas=2)
            pmid = srv.attach_inference(net, replicas=2,
                                        max_batch=8, max_wait_ms=1.0)
            port = srv.start()

            def post(path, body):
                req = Request(f"http://127.0.0.1:{port}{path}",
                              data=json.dumps(body).encode(),
                              headers={"Content-Type": "application/json"})
                with urlopen(req, timeout=120) as r:
                    return json.loads(r.read())

            out = post("/generate", {"model": gmid,
                                     "prompt_ids": [1, 2, 3],
                                     "max_tokens": 4})
            np.testing.assert_array_equal(np.asarray(out["tokens"]), gref)

            out = post("/predict", {"model": pmid,
                                    "features": x.tolist()})
            np.testing.assert_allclose(np.asarray(out["output"]), ref,
                                       rtol=1e-6, atol=1e-6)

            with urlopen(f"http://127.0.0.1:{port}/stats",
                         timeout=60) as r:
                st = json.loads(r.read())
            for block in (st["generation"][gmid], st["inference"][pmid]):
                reps = block["replicas"]
                assert len(reps) == 2
                for rep in reps:
                    assert {"health_score", "breaker", "inflight",
                            "restarts", "state"} <= set(rep)
        finally:
            srv.stop()

    def test_all_replicas_down_maps_to_503(self, lm):
        import json
        from urllib.error import HTTPError
        from urllib.request import Request, urlopen

        from deeplearning4j_tpu.modelimport.server import KerasBackendServer

        srv = KerasBackendServer()
        try:
            gmid = srv.attach_generation(lm, vocab=V, slots=4, replicas=2,
                                         fleet_kw={"restart": False})
            port = srv.start()
            gen = srv._generators[gmid]
            gen.kill_replica(0)
            gen.kill_replica(1)
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if all(r["state"] != READY
                       for r in gen.stats()["replicas"]):
                    break
                time.sleep(0.01)
            req = Request(f"http://127.0.0.1:{port}/generate",
                          data=json.dumps({
                              "model": gmid, "prompt_ids": [1, 2],
                              "max_tokens": 2}).encode(),
                          headers={"Content-Type": "application/json"})
            with pytest.raises(HTTPError) as ei:
                urlopen(req, timeout=60)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["type"] in ("ReplicaUnavailable", "CircuitOpen")
        finally:
            srv.stop()


@pytest.mark.fleet
class TestGenerationFailAllCloseRace:
    """Satellite regression: a chaos kill racing close() must not rebuild
    the device pools on a server that is already shutting down."""

    def test_fail_all_after_close_skips_rebuild(self, lm):
        srv = GenerationServer(lm, V, slots=2)
        srv.submit(np.array([1, 2], np.int64), 2).result(timeout=120)
        srv.close()
        pool_before = srv._pool
        page_pool_before = srv._page_pool
        srv._fail_all(RuntimeError("late chaos fault"))
        assert srv._pool is pool_before          # no resurrection
        assert srv._page_pool is page_pool_before
        assert srv.stats()["pool_rebuilds"] == 0

    def test_chaos_kill_racing_close_resolves_everything(self, lm):
        chaos = ChaosPolicy(seed=13, kill_rate=0.25)
        srv = GenerationServer(lm, V, slots=4, chaos=chaos)
        futs = [srv.submit(np.array([1, 2, 3], np.int64), 5)
                for _ in range(8)]
        closer = threading.Thread(target=srv.close, kwargs={"timeout": 60})
        closer.start()
        for f in futs:
            try:
                f.result(timeout=120)
            except Exception:
                pass                              # typed failure is fine
        closer.join(timeout=120)
        assert not closer.is_alive()
        assert all(f.done() for f in futs)        # zero hung futures
        assert srv._runtime.alive_workers == 0    # loop truly stopped

    def test_fail_all_still_rebuilds_on_live_server(self, lm):
        """Complement of the guard: on a server that is NOT shutting
        down, a hard fault still rebuilds the pools and later requests
        keep serving from the fresh state."""
        srv = GenerationServer(lm, V, slots=2)
        try:
            srv.submit(np.array([1, 2], np.int64), 2).result(timeout=120)
            srv._fail_all(RuntimeError("injected hard fault"))
            assert srv.stats()["pool_rebuilds"] == 1
            out = srv.submit(np.array([1, 2, 3], np.int64),
                             3).result(timeout=120)
            assert len(out) == 3
        finally:
            srv.close()


@pytest.mark.fleet
class TestFleetChaosSoak:
    def test_soak_200_mixed_requests_zero_lost_bitexact(self, lm):
        """The headline invariant: 200 mixed greedy+sampled requests at
        ~10% injected replica faults (transient, stall, slow-decode, and
        seeded kills) plus one guaranteed mid-generation replica kill —
        zero lost futures, every completion bit-exact vs the serial
        reference, and the breaker/restart counters consistent."""
        rng = np.random.default_rng(42)
        specs = _mixed_specs(200, rng)
        refs = _serial_refs(lm, specs)
        factory = _gen_factory(lm, transient_rate=0.04, kill_rate=0.015,
                               stall_rate=0.02, stall_s=0.005,
                               slow_rate=0.025, slow_factor=2.0)
        with fleet_of(factory, replicas=2, max_pending=256,
                      restart_backoff_s=0.02) as fl:
            futs = []
            for i, sp in enumerate(specs):
                futs.append(_submit_with_backoff(fl, sp))
                if i == 60:
                    time.sleep(0.05)          # requests mid-generation...
                    fl.kill_replica(0)        # ...then kill under them
            outs = [f.result(timeout=600) for f in futs]
            st = fl.stats()

        # zero lost futures: every single request resolved with a result
        assert len(outs) == 200
        for got, ref in zip(outs, refs):
            np.testing.assert_array_equal(np.asarray(got), ref)

        # counters consistent: every accepted request completed exactly
        # once; typed sheds the client retried count as rejected_submits,
        # never as failed/expired — zero lost futures
        assert st["completed"] == 200
        assert st["submitted"] == (st["completed"] + st["failed"]
                                   + st["expired"] + st["rejected_submits"])
        assert st["failed"] == 0 and st["expired"] == 0
        assert st["inflight"] == 0 and st["parked"] == 0
        # the explicit kill (plus any seeded ones) died and restarted
        assert st["deaths"] >= 1
        assert st["restarts"] >= 1
        per = st["replicas"]
        assert sum(r["restarts"] for r in per) == st["restarts"]
        # each fleet completion had >= 1 successful replica attempt (a
        # cancelled hedge loser may also have completed server-side)
        assert sum(r["completed"] for r in per) >= st["completed"]
        for r in per:
            assert r["breaker_trips"] >= 0
            assert r["state"] in (READY, DEAD)  # nothing wedged mid-state
        assert fl.admission.pending == 0
