"""Reproducible FLOP audit for the bench models (round-5 correction).

Round 4 recorded ResNet50 train = 12.8 GFLOP/img by assuming XLA's
``cost_analysis()['flops']`` counts 1 per MAC and doubling it. That
assumption was WRONG, and it hid a real architecture fact:

1. XLA (CPU backend) counts **2 flops per MAC** for spatial convolutions —
   verified here by a single-conv microcheck whose analytic MAC count is
   known exactly (ratio measured 1.99).
2. The reference's zoo ResNet50 is ~2x LIGHTER than canonical
   torchvision ResNet50: ``ResNet50.java`` applies stride 2 in the
   stage-2a convBlock (after the stem maxpool already reached 56x56), so
   every residual stage runs at half the canonical spatial size
   (28->14->7->4 instead of 56->28->14->7). The repo matches it
   (models/zoo.py stage-2a stride (2,2)) — parity, not a bug. Canonical
   "4.1 GFLOP forward" therefore does NOT apply to this model.

This script computes, per bench model:
- exact conv+dot MACs/img of the forward pass, walked from the jaxpr
  (shape-exact, counting-convention-free);
- XLA cost_analysis flops/img for forward and full train step;
- the train-step GFLOP/img figure the MFU numbers should use
  (XLA count at 2/MAC == multiply+add, the same convention as the
  v5e 197 TFLOP/s bf16 peak).

Usage: python profiles/flop_audit.py   (CPU backend; writes the summary
to stdout; numbers are recorded in profiles/README.md and bench.py)
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _conv_dot_macs(jaxpr):
    """Exact MACs of every conv_general_dilated / dot_general in a jaxpr."""
    macs = 0

    def walk(jx):
        nonlocal macs
        for eq in jx.eqns:
            if eq.primitive.name == "conv_general_dilated":
                kh, kw, cin, cout = eq.invars[1].aval.shape  # HWIO
                n, h, w, c = eq.outvars[0].aval.shape        # NHWC
                macs += n * h * w * c * kh * kw * cin
            elif eq.primitive.name == "dot_general":
                a = eq.invars[0].aval.shape
                b = eq.invars[1].aval.shape
                (lc, rc), _ = eq.params["dimension_numbers"]
                keep_b = [b[i] for i in range(len(b)) if i not in rc]
                macs += int(np.prod(a)) * int(np.prod(keep_b, dtype=np.int64))
            for sub in eq.params.values():
                if hasattr(sub, "jaxpr"):
                    walk(sub.jaxpr)

    walk(jaxpr.jaxpr)
    return macs


def single_conv_check():
    """XLA flop-counting convention vs an analytically known conv."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers.convolution import ConvolutionLayer
    from deeplearning4j_tpu.nn.conf.layers.core import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updater import Sgd

    conf = (NeuralNetConfiguration.builder().seed(0)
            .updater(Sgd(learning_rate=0.1)).activation("relu")
            .list(ConvolutionLayer(n_out=64, kernel_size=(3, 3),
                                   stride=(1, 1), padding=(1, 1)),
                  OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(56, 56, 64)).build())
    net = MultiLayerNetwork(conf).init()
    B = 4
    x = jnp.zeros((B, 56, 56, 64), jnp.float32)

    def fwd(params, state):
        out, _, _, _ = net._forward(params, state, x, None, train=True,
                                    rng=jax.random.PRNGKey(0))
        return jnp.mean(out)

    ca = jax.jit(fwd).lower(net.params, net.state).compile().cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    theory_macs = 56 * 56 * 64 * 64 * 9 + 56 * 56 * 64 * 10  # conv + dense
    ratio = ca["flops"] / B / theory_macs
    print(f"single-conv check: XLA flops/analytic MACs = {ratio:.2f} "
          "(2.0 => XLA counts multiply+add separately)")
    return ratio


def audit(name, net, graph: bool):
    import jax
    import jax.numpy as jnp

    B = 2
    x = jnp.zeros((B, 224, 224, 3), jnp.float32)
    y = jnp.zeros((B, 1000), jnp.float32)

    if graph:
        def fwd(params, state):
            outs, _, _, _, _ = net._forward(params, state, [x], None,
                                            train=True,
                                            rng=jax.random.PRNGKey(0))
            return jnp.mean(outs[0])
    else:
        def fwd(params, state):
            out, _, _, _ = net._forward(params, state, x, None, train=True,
                                        rng=jax.random.PRNGKey(0))
            return jnp.mean(out)

    macs = _conv_dot_macs(jax.make_jaxpr(fwd)(net.params, net.state)) / B

    ca = jax.jit(fwd).lower(net.params, net.state).compile().cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    fwd_flops = ca["flops"] / B

    step = net._get_step((x.shape, y.shape, False, False, False))
    args = (net.params, net.updater_state, net.state, jax.random.PRNGKey(0),
            jnp.float32(1), x, y, None, None, {})
    ca2 = jax.jit(lambda *a: step(*a)).lower(*args).compile().cost_analysis()
    ca2 = ca2[0] if isinstance(ca2, list) else ca2
    step_flops = ca2["flops"] / B

    print(f"{name}: fwd {macs / 1e9:.2f} GMACs/img (jaxpr-exact), "
          f"XLA fwd {fwd_flops / 1e9:.2f} G, "
          f"XLA train step {step_flops / 1e9:.2f} GFLOP/img "
          f"(multiply+add; use THIS for MFU)")
    return step_flops


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    single_conv_check()
    from deeplearning4j_tpu.models import VGG16, ResNet50

    audit("resnet50 (zoo/DL4J variant, stride-2 stage2a)",
          ResNet50(num_labels=1000, dtype="float32").init(), graph=True)
    audit("vgg16 (conv-only head)",
          VGG16(num_labels=1000, dtype="float32").init(), graph=False)


if __name__ == "__main__":
    main()
