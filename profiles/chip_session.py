"""Round-4 real-chip measurement chain (run manually when the TPU tunnel
is up; results land in profiles/ and inform bench defaults).

1. word2vec A/B: segment_updates {True, False} x batch {8k, 16k, 32k, 64k}
   on the real chip — the sorted-segment path exists because XLA serializes
   duplicate-index scatter-adds on TPU; only chip numbers can pick the
   default.
2. flash-attention fwd and fwd+bwd timings.
3. ResNet50 bf16 jax.profiler trace -> profiles/resnet50_bf16_trace/.

Usage: python profiles/chip_session.py [w2v|attn|resnet|all]
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def w2v_ab():
    import bench
    from deeplearning4j_tpu.nlp import learning, sequence_vectors

    orig = learning.skipgram_corpus_epoch
    results = {}
    for seg in (True, False):
        sequence_vectors.skipgram_corpus_epoch = functools.partial(
            orig, segment_updates=seg)
        for batch in (8192, 16384, 32768, 65536):
            t0 = time.time()
            wps = _w2v_once(batch)
            results[f"seg={seg} batch={batch}"] = round(wps)
            print(f"# w2v seg={seg} batch={batch}: {wps:,.0f} words/s "
                  f"({time.time() - t0:.0f}s)", flush=True)
    sequence_vectors.skipgram_corpus_epoch = orig
    return results


def _w2v_once(batch_size: int, n_sentences: int = 50000):
    from deeplearning4j_tpu.nlp import CollectionSentenceIterator, Word2Vec

    rs = np.random.RandomState(3)
    vocab = [f"w{i}" for i in range(30000)]
    zipf = np.minimum(rs.zipf(1.3, size=n_sentences * 20) - 1,
                      len(vocab) - 1)
    sentences = [" ".join(vocab[z] for z in zipf[i * 20:(i + 1) * 20])
                 for i in range(n_sentences)]
    w2v = Word2Vec(layer_size=128, window=5, min_word_frequency=2,
                   negative=5, use_hierarchic_softmax=False, epochs=1,
                   batch_size=batch_size)
    w2v.build_vocab(sentences)
    w2v.reset_weights()
    w2v.fit(CollectionSentenceIterator(sentences))  # warmup/compile
    w2v.reset_weights()
    t0 = time.perf_counter()
    w2v.fit(CollectionSentenceIterator(sentences))
    import bench as _b
    _b._sync(w2v.syn0)
    return n_sentences * 20 / (time.perf_counter() - t0)


def attn():
    import bench

    s, f = bench.bench_attention()
    print(f"# attention T=4096 fwd: stock {s:.2f} ms, flash {f:.2f} ms "
          f"({s / f:.2f}x)", flush=True)
    sb, fb = bench.bench_attention_bwd()
    print(f"# attention T=2048 fwd+bwd: stock {sb:.2f} ms, flash {fb:.2f} "
          f"ms ({sb / fb:.2f}x)", flush=True)
    return {"fwd_stock_ms": s, "fwd_flash_ms": f,
            "bwd_stock_ms": sb, "bwd_flash_ms": fb}


def resnet_profile():
    import jax

    import bench

    out = {}
    with jax.profiler.trace("profiles/resnet50_bf16_trace"):
        med, windows = bench.bench_resnet50(compute_dtype="bfloat16")
    out["bf16_img_s"], out["bf16_windows"] = med, windows
    print(f"# resnet50 bf16 (traced): {med:.0f} img/s median of {windows}",
          flush=True)
    med, windows = bench.bench_resnet50()
    out["f32_img_s"], out["f32_windows"] = med, windows
    print(f"# resnet50 f32: {med:.0f} img/s median of {windows}", flush=True)
    return out


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    res = {}
    if which in ("w2v", "all"):
        res["w2v"] = w2v_ab()
    if which in ("attn", "all"):
        res["attn"] = attn()
    if which in ("resnet", "all"):
        res["resnet"] = resnet_profile()
    # read-merge-write so partial runs (w2v|attn|resnet) don't clobber
    # previously recorded sections
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "chip_session_results.json")
    merged = {}
    if os.path.exists(path):
        with open(path) as fh:
            merged = json.load(fh)
    merged.update(res)
    with open(path, "w") as fh:
        json.dump(merged, fh, indent=1)
    print(json.dumps(merged))
