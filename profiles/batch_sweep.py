"""Round-5 batch sweep: ResNet50 + VGG16 bf16 throughput vs batch size.

VERDICT r4 weak #2/#3: batch 64 (ResNet50) and batch 32 (VGG16) were never
swept upward; the unclaimed MFU lives there. The tunneled chip's throughput
swings ~3.5x on a minutes timescale (profiles/README.md variance table), so
a naive A-then-B sweep measures contention, not batch effects. This sweep
INTERLEAVES: each round measures every config once, and configs are compared
within-round (plus median across rounds).

Usage: python profiles/batch_sweep.py [rounds]
Results land in profiles/chip_session_results.json under "batch_sweep_r5"
(replacing any previous sweep under that key; other keys are preserved).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# round-5 corrected audit (profiles/flop_audit.py): XLA-counted train-step
# flops at multiply+add, the same convention as the peak figure
RESNET_FLOP_PER_IMG = 6.6e9
VGG16_FLOP_PER_IMG = 89.35e9
PEAK_BF16_FLOPS = 197e12       # v5e


def _prepare(model_cls, batch, seed):
    """One bench-identical timer per config (the sweep must measure with
    the SAME methodology the bench reports, or sweep-picked defaults and
    bench numbers drift apart)."""
    import bench

    timer = bench._imagenet_model_timer(
        model_cls, batch=batch, steps=10, seed=seed,
        compute_dtype="bfloat16")
    return timer.window


def main(rounds=3):
    from deeplearning4j_tpu.models import VGG16, ResNet50

    configs = []
    for b in (64, 128, 256):
        configs.append((f"resnet50_b{b}", ResNet50, b, RESNET_FLOP_PER_IMG))
    for b in (32, 64, 128, 192):
        configs.append((f"vgg16_b{b}", VGG16, b, VGG16_FLOP_PER_IMG))

    samplers = {}
    for name, cls, b, _ in configs:
        try:
            t0 = time.time()
            samplers[name] = _prepare(cls, b, seed=b)
            print(f"# prepared {name} ({time.time() - t0:.0f}s)", flush=True)
        except Exception as e:  # noqa: BLE001 — OOM at big batch is data
            print(f"# {name} PREP FAILED: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    results = {name: [] for name in samplers}
    for r in range(rounds):
        for name, s in samplers.items():
            try:
                v = s()
                if v is not None:
                    results[name].append(round(v))
                print(f"# round {r} {name}: {v and round(v)} img/s",
                      flush=True)
            except Exception as e:  # noqa: BLE001
                print(f"# round {r} {name} FAILED: {e}", flush=True)

    summary = {}
    for name, _, b, flop in configs:
        if results.get(name):
            med = float(np.median(results[name]))
            summary[name] = {
                "windows_img_s": results[name],
                "median_img_s": round(med),
                "mfu_pct": round(100 * med * flop / PEAK_BF16_FLOPS, 1),
            }
    print(json.dumps(summary, indent=1), flush=True)

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "chip_session_results.json")
    merged = {}
    if os.path.exists(path):
        with open(path) as fh:
            merged = json.load(fh)
    merged["batch_sweep_r5"] = summary
    with open(path, "w") as fh:
        json.dump(merged, fh, indent=1)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 3)
