"""Round-5 batch sweep: ResNet50 + VGG16 bf16 throughput vs batch size.

VERDICT r4 weak #2/#3: batch 64 (ResNet50) and batch 32 (VGG16) were never
swept upward; the unclaimed MFU lives there. The tunneled chip's throughput
swings ~3.5x on a minutes timescale (profiles/README.md variance table), so
a naive A-then-B sweep measures contention, not batch effects. This sweep
INTERLEAVES: each round measures every config once, and configs are compared
within-round (plus median across rounds).

Usage: python profiles/batch_sweep.py [rounds]
Results land in profiles/chip_session_results.json under "batch_sweep_r5"
(replacing any previous sweep under that key; other keys are preserved).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESNET_FLOP_PER_IMG = 12.8e9   # profiles/README.md FLOP audit (train step)
VGG16_FLOP_PER_IMG = 23.3e9    # 3x fwd 7.75 GFLOP (MAC=2) at 224^2
PEAK_BF16_FLOPS = 197e12       # v5e


def _prepare(model_cls, batch, seed, image=224, labels=1000):
    """Build net + device data + compiled step; return a sampler closure."""
    import bench
    import jax
    import jax.numpy as jnp

    net = model_cls(num_labels=labels, dtype="float32",
                    compute_dtype="bfloat16").init()
    rs = np.random.RandomState(seed)
    x = rs.randn(batch, image, image, 3).astype(np.float32)
    y = np.eye(labels, dtype=np.float32)[rs.randint(0, labels, batch)]
    xd, yd = jnp.asarray(x), jnp.asarray(y)
    key = (xd.shape, yd.shape, False, False, False)
    step = net._get_step(key)
    rng = jax.random.PRNGKey(0)
    tree0 = jax.tree_util.tree_map(
        lambda a: a.copy(), (net.params, net.updater_state, net.state))

    def run(n):
        params, opt, state = jax.tree_util.tree_map(
            lambda a: a.copy(), tree0)
        bench._sync(params)
        t0 = time.perf_counter()
        for i in range(n):
            params, opt, state, _, loss = step(
                params, opt, state, rng, jnp.float32(i + 1), xd, yd, None,
                None, {})
        bench._sync(params)
        return time.perf_counter() - t0

    run(1)  # compile + warm

    def sample(steps=10):
        t1 = run(steps)
        t2 = run(2 * steps)
        dt = t2 - t1
        if dt < bench.MIN_MARGINAL_WINDOW_S:
            return None
        return batch / (dt / steps)

    return sample


def main(rounds=3):
    from deeplearning4j_tpu.models import VGG16, ResNet50

    configs = []
    for b in (64, 128, 256):
        configs.append((f"resnet50_b{b}", ResNet50, b, RESNET_FLOP_PER_IMG))
    for b in (32, 64, 128, 192):
        configs.append((f"vgg16_b{b}", VGG16, b, VGG16_FLOP_PER_IMG))

    samplers = {}
    for name, cls, b, _ in configs:
        try:
            t0 = time.time()
            samplers[name] = _prepare(cls, b, seed=b)
            print(f"# prepared {name} ({time.time() - t0:.0f}s)", flush=True)
        except Exception as e:  # noqa: BLE001 — OOM at big batch is data
            print(f"# {name} PREP FAILED: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    results = {name: [] for name in samplers}
    for r in range(rounds):
        for name, s in samplers.items():
            try:
                v = s()
                if v is not None:
                    results[name].append(round(v))
                print(f"# round {r} {name}: {v and round(v)} img/s",
                      flush=True)
            except Exception as e:  # noqa: BLE001
                print(f"# round {r} {name} FAILED: {e}", flush=True)

    summary = {}
    for name, _, b, flop in configs:
        if results.get(name):
            med = float(np.median(results[name]))
            summary[name] = {
                "windows_img_s": results[name],
                "median_img_s": round(med),
                "mfu_pct": round(100 * med * flop / PEAK_BF16_FLOPS, 1),
            }
    print(json.dumps(summary, indent=1), flush=True)

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "chip_session_results.json")
    merged = {}
    if os.path.exists(path):
        with open(path) as fh:
            merged = json.load(fh)
    merged["batch_sweep_r5"] = summary
    with open(path, "w") as fh:
        json.dump(merged, fh, indent=1)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 3)
