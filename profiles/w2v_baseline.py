"""Measured local baseline for the reference's Word2Vec rate (VERDICT r4
weak #6 / next-step #5): the reference's skip-gram hot op is a native
libnd4j kernel (SkipGram.java:215-272 dispatches AggregateSkipGram); the
stand-in is the same inner loop in C (native/skipgram.c), -O3, run on
this host's CPU over the EXACT bench corpus/config (1M words, 30k vocab,
layer 128, window 5, negative 5 — bench.bench_word2vec). nproc=1 in this
image, so the reference's multi-thread fan-out adds nothing here; the
single-thread rate IS the host ceiling.

Usage: python profiles/w2v_baseline.py
Merges {"w2v_native_baseline": {...}} into chip_session_results.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from deeplearning4j_tpu.native import (
        skipgram_native_available,
        skipgram_train,
    )
    from deeplearning4j_tpu.nlp import Word2Vec

    assert skipgram_native_available(), "C toolchain missing"

    # the bench corpus, verbatim (bench.bench_word2vec)
    n_sentences = 50000
    rs = np.random.RandomState(3)
    vocab = [f"w{i}" for i in range(30000)]
    zipf = np.minimum(rs.zipf(1.3, size=n_sentences * 20) - 1,
                      len(vocab) - 1)
    sentences = [" ".join(vocab[z] for z in zipf[i * 20:(i + 1) * 20])
                 for i in range(n_sentences)]

    # build the same vocab/filtering the device path trains with
    w2v = Word2Vec(layer_size=128, window=5, min_word_frequency=2,
                   negative=5, use_hierarchic_softmax=False, epochs=1,
                   batch_size=8192)
    w2v.build_vocab(sentences)
    w2v.reset_weights()
    cache = w2v.vocab
    corpus = []
    for s in sentences:
        for tok in s.split():
            i = cache.index_of(tok)
            if i >= 0:
                corpus.append(i)
        corpus.append(-1)
    corpus = np.asarray(corpus, np.int32)
    n_words = int((corpus >= 0).sum())

    # unigram^0.75 table, classic size
    counts = cache.counts_array()
    p = counts ** 0.75
    p /= p.sum()
    table = np.repeat(np.arange(len(p), dtype=np.int32),
                      np.maximum(1, (p * 1_000_000).astype(np.int64)))

    syn0 = np.asarray(w2v.syn0, np.float32).copy()
    syn1 = np.asarray(w2v.syn1neg, np.float32).copy()

    t0 = time.perf_counter()
    pairs, syn0, syn1 = skipgram_train(
        syn0, syn1, corpus, table, window=5, negative=5,
        alpha=0.025, min_alpha=1e-4, epochs=1, seed=7)
    dt = time.perf_counter() - t0
    rate = n_words / dt
    out = {
        "native_words_s": round(rate),
        "trained_pairs": int(pairs),
        "corpus_words": n_words,
        "seconds": round(dt, 2),
        "threads": 1,
        "note": "C -O3 AggregateSkipGram stand-in, bench corpus/config, "
                "single core (nproc=1 on this image)",
    }
    print(json.dumps(out), flush=True)

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "chip_session_results.json")
    merged = {}
    if os.path.exists(path):
        with open(path) as fh:
            merged = json.load(fh)
    merged["w2v_native_baseline"] = out
    with open(path, "w") as fh:
        json.dump(merged, fh, indent=1)


if __name__ == "__main__":
    main()
