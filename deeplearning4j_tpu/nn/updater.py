"""Updaters (optimizers) + learning-rate schedules.

Reference: the per-layer IUpdater configs (Sgd/Adam/Nesterovs/RmsProp/AdaGrad/
AdaDelta/Adamax/Nadam/NoOp) bridged by nn/updater/BaseMultiLayerUpdater.java into one
contiguous state buffer applied blockwise (UpdaterBlock.java:104-134). Here updater
state is a pytree mirroring the param pytree, and the whole update is one fused
tree_map inside the jitted train step — the TPU equivalent of the reference's
single-op UpdaterBlock application.

Each updater computes the STEP to subtract: ``params_new = params - step``.
Learning-rate schedules mirror nn/conf/LearningRatePolicy.java (Exponential, Inverse,
Poly, Sigmoid, Step, Schedule map).

Per-leaf learning rates (reference: BaseLayer.learningRate/biasLearningRate resolved
per-parameter by BaseMultiLayerUpdater): ``lr_mult`` may be a scalar OR a pytree with
the same structure as the gradients, giving each leaf its own multiplier. The
effective learning rate enters the update formula itself (not a post-scale), so
momentum-style updaters (Nesterovs) keep exact per-leaf semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.utils.serde import register_serializable


def _tree_zeros(params):
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


@register_serializable
@dataclass
class LearningRateSchedule:
    """lr(iteration). policy: none|exponential|inverse|poly|sigmoid|step|schedule."""

    policy: str = "none"
    decay_rate: float = 0.0
    power: float = 1.0
    steps: float = 1.0
    max_iterations: int = 10000
    schedule: Optional[dict] = None  # {iteration(str|int): lr}

    def __call__(self, base_lr, iteration):
        it = iteration
        p = self.policy
        if p == "none":
            return base_lr
        if p == "exponential":
            return base_lr * self.decay_rate ** it
        if p == "inverse":
            return base_lr / (1.0 + self.decay_rate * it) ** self.power
        if p == "poly":
            frac = jnp.clip(it / self.max_iterations, 0.0, 1.0)
            return base_lr * (1.0 - frac) ** self.power
        if p == "sigmoid":
            return base_lr / (1.0 + jnp.exp(-self.decay_rate * (it - self.steps)))
        if p == "step":
            return base_lr * self.decay_rate ** jnp.floor(it / self.steps)
        if p == "schedule":
            # piecewise-constant: applied outside jit (python int iteration) or via
            # nested where; schedule keys are iteration thresholds
            lr = base_lr
            for k in sorted(self.schedule or {}, key=lambda s: int(s)):
                lr = jnp.where(it >= int(k), self.schedule[k], lr)
            return lr
        raise ValueError(f"Unknown LR policy '{p}'")


@register_serializable
@dataclass
class Updater:
    """Base updater config. State: dict of pytrees keyed by slot name."""

    learning_rate: float = 0.1
    lr_schedule: LearningRateSchedule = field(default_factory=LearningRateSchedule)

    def init(self, params):
        return {}

    def lr(self, iteration):
        return self.lr_schedule(self.learning_rate, iteration)

    def scale_lr(self, factor: float) -> float:
        """Rescale the base learning rate in place (the whole schedule
        shifts with it) and return the new value. This is the health
        guard's LR-backoff hook (optimize/health.py): the base lr is a
        trace-time constant of every compiled step program, so callers
        MUST invalidate cached jitted steps afterwards — HealthPolicy
        clears ``net._step_cache`` (and ParallelWrapper's round cache)."""
        if not factor > 0:
            raise ValueError(f"scale_lr factor must be > 0, got {factor}")
        self.learning_rate = self.learning_rate * factor
        return self.learning_rate

    def lr_tree(self, grads, iteration, lr_mult):
        """Per-leaf effective learning rate: schedule(base_lr) * multiplier."""
        lr = self.lr(iteration)
        if isinstance(lr_mult, dict):
            return _tmap(lambda m: lr * m, lr_mult)
        return _tmap(lambda g: lr * lr_mult, grads)

    def step(self, grads, state, iteration, lr_mult=1.0):
        raise NotImplementedError


@register_serializable
@dataclass
class Sgd(Updater):
    def step(self, grads, state, iteration, lr_mult=1.0):
        lrs = self.lr_tree(grads, iteration, lr_mult)
        return _tmap(lambda g, lr: lr * g, grads, lrs), state


@register_serializable
@dataclass
class NoOp(Updater):
    def step(self, grads, state, iteration, lr_mult=1.0):
        return _tmap(jnp.zeros_like, grads), state


@register_serializable
@dataclass
class Nesterovs(Updater):
    momentum: float = 0.9

    def init(self, params):
        return {"v": _tree_zeros(params)}

    def step(self, grads, state, iteration, lr_mult=1.0):
        lrs = self.lr_tree(grads, iteration, lr_mult)
        mu = self.momentum
        v_old = state["v"]
        v_new = _tmap(lambda v, g, lr: mu * v - lr * g, v_old, grads, lrs)
        # param += -mu*v_old + (1+mu)*v_new  (nd4j NesterovsUpdater form)
        steps = _tmap(lambda vo, vn: mu * vo - (1.0 + mu) * vn, v_old, v_new)
        return steps, {"v": v_new}


@register_serializable
@dataclass
class Adam(Updater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init(self, params):
        return {"m": _tree_zeros(params), "v": _tree_zeros(params)}

    def step(self, grads, state, iteration, lr_mult=1.0):
        lrs = self.lr_tree(grads, iteration, lr_mult)
        t = iteration + 1.0
        b1, b2 = self.beta1, self.beta2
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        bias_corr = jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        steps = _tmap(
            lambda m, v, lr: lr * bias_corr * m / (jnp.sqrt(v) + self.epsilon), m, v,
            lrs)
        return steps, {"m": m, "v": v}


@register_serializable
@dataclass
class AdaMax(Updater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init(self, params):
        return {"m": _tree_zeros(params), "u": _tree_zeros(params)}

    def step(self, grads, state, iteration, lr_mult=1.0):
        lrs = self.lr_tree(grads, iteration, lr_mult)
        t = iteration + 1.0
        b1, b2 = self.beta1, self.beta2
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        u = _tmap(lambda u, g: jnp.maximum(b2 * u, jnp.abs(g)), state["u"], grads)
        corr = 1.0 / (1 - b1 ** t)
        steps = _tmap(lambda m, u, lr: lr * corr * m / (u + self.epsilon), m, u, lrs)
        return steps, {"m": m, "u": u}


@register_serializable
@dataclass
class Nadam(Updater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init(self, params):
        return {"m": _tree_zeros(params), "v": _tree_zeros(params)}

    def step(self, grads, state, iteration, lr_mult=1.0):
        lrs = self.lr_tree(grads, iteration, lr_mult)
        t = iteration + 1.0
        b1, b2 = self.beta1, self.beta2
        m = _tmap(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = _tmap(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        steps = _tmap(
            lambda m, v, g, lr: lr / (jnp.sqrt(v / (1 - b2 ** t)) + self.epsilon)
            * (b1 * m / (1 - b1 ** (t + 1)) + (1 - b1) * g / (1 - b1 ** t)),
            m, v, grads, lrs)
        return steps, {"m": m, "v": v}


@register_serializable
@dataclass
class AdaGrad(Updater):
    epsilon: float = 1e-6

    def init(self, params):
        return {"h": _tree_zeros(params)}

    def step(self, grads, state, iteration, lr_mult=1.0):
        lrs = self.lr_tree(grads, iteration, lr_mult)
        h = _tmap(lambda h, g: h + g * g, state["h"], grads)
        steps = _tmap(lambda h, g, lr: lr * g / (jnp.sqrt(h) + self.epsilon), h,
                      grads, lrs)
        return steps, {"h": h}


@register_serializable
@dataclass
class RmsProp(Updater):
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def init(self, params):
        return {"h": _tree_zeros(params)}

    def step(self, grads, state, iteration, lr_mult=1.0):
        lrs = self.lr_tree(grads, iteration, lr_mult)
        d = self.rms_decay
        h = _tmap(lambda h, g: d * h + (1 - d) * g * g, state["h"], grads)
        steps = _tmap(lambda h, g, lr: lr * g / (jnp.sqrt(h + self.epsilon)), h,
                      grads, lrs)
        return steps, {"h": h}


@register_serializable
@dataclass
class AdaDelta(Updater):
    rho: float = 0.95
    epsilon: float = 1e-6

    def init(self, params):
        return {"eg": _tree_zeros(params), "ex": _tree_zeros(params)}

    def step(self, grads, state, iteration, lr_mult=1.0):
        # AdaDelta has no learning rate (reference: nd4j AdaDeltaUpdater);
        # lr_mult is intentionally ignored.
        rho, eps = self.rho, self.epsilon
        eg = _tmap(lambda e, g: rho * e + (1 - rho) * g * g, state["eg"], grads)
        dx = _tmap(lambda g, e, x: g * jnp.sqrt(x + eps) / jnp.sqrt(e + eps),
                   grads, eg, state["ex"])
        ex = _tmap(lambda x, d: rho * x + (1 - rho) * d * d, state["ex"], dx)
        return dx, {"eg": eg, "ex": ex}


_BY_NAME = {"sgd": Sgd, "adam": Adam, "adamax": AdaMax, "nadam": Nadam,
            "nesterovs": Nesterovs, "adagrad": AdaGrad, "rmsprop": RmsProp,
            "adadelta": AdaDelta, "none": NoOp, "noop": NoOp}


def get_updater(u, learning_rate=None) -> Updater:
    if isinstance(u, Updater):
        return u
    cls = _BY_NAME.get(str(u).lower())
    if cls is None:
        raise ValueError(f"Unknown updater '{u}'. Known: {sorted(_BY_NAME)}")
    return cls(learning_rate=learning_rate) if learning_rate is not None else cls()
